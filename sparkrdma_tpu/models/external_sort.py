"""External TeraSort: sortByKey for datasets larger than device memory.

The reference's headline job sorts 175 GB across 16 workers — far more
than any single worker holds — by streaming shuffle files through
registered memory (SURVEY.md §6).  The device-plane analog: a two-pass
sample sort whose working set per device step is ONE chunk or ONE
bucket, never the whole dataset:

1. **Partition pass** — each input chunk is locally sorted ON DEVICE
   (the fast path: one unstable multi-operand ``lax.sort``), sampled,
   and split by global range splitters into per-bucket runs appended to
   bucket spill files (sequential host IO; the
   ``shuffleWriteBlockSize``-style chunking of
   RdmaMappedFile.java:95-171, with disk standing in for registered
   memory).  Splitters come from a first sampling sweep, so buckets are
   equal-frequency ranges.
2. **Merge pass** — bucket files are loaded in range order and sorted
   ON DEVICE (each bucket fits by construction when ``num_buckets``
   ≳ total/chunk); concatenating the bucket outputs yields the global
   sort.

Peak device memory: O(max(chunk, bucket)); disk holds the rest — the
SURVEY §5 "chunked, memory-bounded exchange of larger-than-HBM
shuffles" template realized for the sort job.
"""

from __future__ import annotations

import os
import tempfile
from typing import Iterable, Iterator, Optional, Tuple

import numpy as np

from sparkrdma_tpu.models.terasort import TeraSorter
from sparkrdma_tpu.parallel.mesh import make_mesh


class ExternalTeraSorter:
    """Streaming sortByKey: ``sort_chunks`` consumes (keys, vals) numpy
    chunk pairs and yields globally sorted (keys, vals) chunks, one per
    range bucket."""

    def __init__(
        self,
        mesh=None,
        num_buckets: int = 64,
        sample_per_chunk: int = 4096,
        spill_dir: Optional[str] = None,
        max_split_depth: int = 4,
        direct_io: str = "auto",
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.sorter = TeraSorter(self.mesh)
        self.num_buckets = int(num_buckets)
        self.sample_per_chunk = int(sample_per_chunk)
        self.spill_dir = spill_dir
        # conf.directIO analog for this model-level API ("off" keeps
        # bucket spills buffered)
        self.direct_io = direct_io
        # recursion guard for oversized-bucket re-splitting
        self.max_split_depth = int(max_split_depth)
        # stats (observability parity: spill volumes, bucket skew)
        self.chunks_in = 0
        self.bytes_spilled = 0
        self.max_bucket_records = 0
        self.buckets_resplit = 0

    # -- pass 1 helpers -----------------------------------------------------
    def _device_sort(self, keys: np.ndarray, vals: np.ndarray):
        sk, sv = self.sorter.sort(keys, vals)
        return np.asarray(sk), np.asarray(sv)

    def sort_chunks(
        self, chunks: Iterable[Tuple[np.ndarray, np.ndarray]],
        preset_splitters: Optional[np.ndarray] = None,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Two-pass external sort.  ``chunks`` may be a one-shot
        generator: chunk data is retained in per-bucket spill files, so
        nothing is iterated twice.  Yields (sorted_keys, sorted_vals)
        per bucket in ascending global range order.

        ``preset_splitters`` skips the sampling sweep — used by the
        oversized-bucket re-split, where the data is already on disk and
        a whole-file sample is available up front."""
        from concurrent.futures import ThreadPoolExecutor

        from sparkrdma_tpu.memory.direct_io import (
            DirectAppender,
            direct_supported,
        )

        with tempfile.TemporaryDirectory(
            prefix="sparkrdma_tpu_extsort_", dir=self.spill_dir
        ) as tmp, ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="extsort-io"
        ) as io:
            paths = [os.path.join(tmp, f"bucket_{r}.bin")
                     for r in range(self.num_buckets)]
            # bucket spills ride O_DIRECT (buffered writeback throttles
            # to ~1/6 device bandwidth on virtualized hosts); small
            # bounce buffers — many buckets share one flush thread
            use_direct = self.direct_io != "off" and (
                self.direct_io == "on" or direct_supported(tmp)
            )
            files = [
                DirectAppender(
                    p, use_direct=use_direct, buf_bytes=256 << 10,
                    executor=io,
                )
                for p in paths
            ]
            samples = []
            staged = []  # sorted chunks awaiting splitters
            dtype = None
            try:
                # One subtlety: splitters need a GLOBAL sample, so the
                # first chunks are staged (sorted, in memory) until the
                # sample stabilizes.  To keep memory bounded we fix the
                # splitters after the FIRST chunk's sample plus any
                # staged chunks — for uniformly shuffled inputs one
                # chunk's quantiles are already unbiased; pathological
                # (sorted/clustered) orderings skew bucket fill, which
                # pass 2 repairs by recursively re-splitting any bucket
                # that outgrew the per-step working-set bound.
                splitters = preset_splitters
                max_chunk_records = 0  # per-call (reuse must not inflate)
                total_records = 0
                for keys, vals in chunks:
                    keys = np.asarray(keys)
                    vals = np.asarray(vals)
                    if dtype is None:
                        dtype = (keys.dtype, vals.dtype)
                    self.chunks_in += 1
                    max_chunk_records = max(max_chunk_records, len(keys))
                    total_records += len(keys)
                    sk, sv = self._device_sort(keys, vals)
                    n = len(sk)
                    if n and splitters is None:
                        # samples are only ever consumed to MAKE the
                        # splitters; once fixed (or preset) skip the work
                        step = max(1, n // self.sample_per_chunk)
                        samples.append(sk[::step])
                    if splitters is None:
                        staged.append((sk, sv))
                        if sum(len(s) for s, _ in staged) >= 1:
                            splitters = self._make_splitters(samples)
                            for s, v in staged:
                                self._spill(files, s, v, splitters)
                            staged = []
                    else:
                        self._spill(files, sk, sv, splitters)
                if splitters is None:
                    # zero or empty chunks only
                    splitters = self._make_splitters(samples)
                    for s, v in staged:
                        self._spill(files, s, v, splitters)
            finally:
                for f in files:
                    f.finish()
            if dtype is None:
                return
            # pass 2: per-bucket device sort, in range order.  A bucket
            # that outgrew the working-set bound (adversarial input order
            # froze the splitters on an unrepresentative sample) is NOT
            # loaded whole: it is recursively re-split with splitters
            # sampled from its own data, keeping every device step at
            # O(max(chunk, balanced bucket)).
            kd, vd = dtype
            item = np.dtype([("k", kd), ("v", vd)])
            # the promised working-set bound: a balanced bucket (with 2x
            # slack for benign imbalance) or one chunk, whichever is
            # larger — balanced buckets never re-split, only skew does
            cap = max(
                max_chunk_records,
                2 * total_records // self.num_buckets,
                1,
            )
            for p in paths:
                size = os.path.getsize(p)
                if size == 0:
                    continue
                n_rec = size // item.itemsize
                if (n_rec > cap and self.num_buckets > 1
                        and self.max_split_depth > 0):
                    yield from self._resplit_bucket(p, item, cap)
                    continue
                rec = np.fromfile(p, dtype=item)
                self.max_bucket_records = max(
                    self.max_bucket_records, len(rec)
                )
                yield self._device_sort(rec["k"], rec["v"])

    def _resplit_bucket(
        self, path: str, item: np.dtype, cap: int
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Re-sort one oversized bucket file through a child sorter,
        streaming it back in ≤cap-record chunks.  Unlike the parent
        (which froze splitters on its first chunk's sample), the child
        gets splitters from a strided sample of the ENTIRE file — the
        data is already on disk, so a representative sample is one
        sequential scan away and re-split buckets come out balanced even
        for sorted/clustered input."""
        child = ExternalTeraSorter(
            self.mesh,
            num_buckets=self.num_buckets,
            sample_per_chunk=self.sample_per_chunk,
            spill_dir=self.spill_dir,
            max_split_depth=self.max_split_depth - 1,
            direct_io=self.direct_io,
        )
        n_rec = os.path.getsize(path) // item.itemsize
        want = self.sample_per_chunk * self.num_buckets
        stride = max(1, n_rec // max(want, 1))
        # memmap so sampling pages in only the touched records, not the
        # whole oversized file (that being too big is why we're here)
        mm = np.memmap(path, dtype=item, mode="r")
        keys = np.array(mm["k"][::stride])
        del mm
        splitters = child._make_splitters([np.sort(keys)])
        if len(splitters) == 0 or (splitters == splitters[0]).all():
            # duplicate-heavy bucket: identical splitters would route
            # everything into one child bucket again — recursion makes
            # no progress, so load-and-sort whole without burning
            # max_split_depth passes of disk churn first
            rec = np.fromfile(path, dtype=item)
            self.max_bucket_records = max(self.max_bucket_records, len(rec))
            yield self._device_sort(rec["k"], rec["v"])
            return
        self.buckets_resplit += 1

        def chunk_reader():
            with open(path, "rb") as f:
                while True:
                    raw = f.read(cap * item.itemsize)
                    if not raw:
                        return
                    rec = np.frombuffer(raw, dtype=item)
                    yield rec["k"], rec["v"]

        yield from child.sort_chunks(
            chunk_reader(), preset_splitters=splitters
        )
        self.max_bucket_records = max(
            self.max_bucket_records, child.max_bucket_records
        )
        self.bytes_spilled += child.bytes_spilled
        self.buckets_resplit += child.buckets_resplit

    def _make_splitters(self, samples) -> np.ndarray:
        if not samples:
            return np.zeros(0, np.int64)
        cat = np.sort(np.concatenate(samples))
        idx = (np.arange(1, self.num_buckets) * len(cat)) // self.num_buckets
        return cat[np.clip(idx, 0, len(cat) - 1)]

    def _spill(self, files, sk: np.ndarray, sv: np.ndarray,
               splitters: np.ndarray) -> None:
        """Append each splitter range of the SORTED chunk to its bucket
        file (ranges are contiguous slices — sequential IO only)."""
        edges = np.concatenate([
            [0], np.searchsorted(sk, splitters, side="right"), [len(sk)]
        ]).astype(np.int64)
        # an empty sample (all chunks empty so far) yields no splitters:
        # everything lands in bucket 0
        for r in range(len(edges) - 1):
            lo, hi = edges[r], edges[r + 1]
            if hi <= lo:
                continue
            item = np.dtype([("k", sk.dtype), ("v", sv.dtype)])
            rec = np.empty(hi - lo, dtype=item)
            rec["k"] = sk[lo:hi]
            rec["v"] = sv[lo:hi]
            files[r].append(rec.view(np.uint8).reshape(-1))
            self.bytes_spilled += rec.nbytes

    def sort(self, keys, vals) -> Tuple[np.ndarray, np.ndarray]:
        """Convenience non-streaming wrapper (array in, array out)."""
        keys = np.asarray(keys)
        vals = np.asarray(vals)
        outs = list(self.sort_chunks([(keys, vals)]))
        if not outs:
            return keys[:0], vals[:0]
        return (
            np.concatenate([k for k, _ in outs]),
            np.concatenate([v for _, v in outs]),
        )
