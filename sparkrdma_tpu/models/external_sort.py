"""External TeraSort: sortByKey for datasets larger than device memory.

The reference's headline job sorts 175 GB across 16 workers — far more
than any single worker holds — by streaming shuffle files through
registered memory (SURVEY.md §6).  The device-plane analog: a two-pass
sample sort whose working set per device step is ONE chunk or ONE
bucket, never the whole dataset:

1. **Partition pass** — each input chunk is locally sorted ON DEVICE
   (the fast path: one unstable multi-operand ``lax.sort``), sampled,
   and split by global range splitters into per-bucket runs appended to
   bucket spill files (sequential host IO; the
   ``shuffleWriteBlockSize``-style chunking of
   RdmaMappedFile.java:95-171, with disk standing in for registered
   memory).  Splitters come from a first sampling sweep, so buckets are
   equal-frequency ranges.
2. **Merge pass** — bucket files are loaded in range order and sorted
   ON DEVICE (each bucket fits by construction when ``num_buckets``
   ≳ total/chunk); concatenating the bucket outputs yields the global
   sort.

Peak device memory: O(max(chunk, bucket)); disk holds the rest — the
SURVEY §5 "chunked, memory-bounded exchange of larger-than-HBM
shuffles" template realized for the sort job.
"""

from __future__ import annotations

import os
import tempfile
from typing import Iterable, Iterator, Optional, Tuple

import numpy as np

from sparkrdma_tpu.models.terasort import TeraSorter
from sparkrdma_tpu.parallel.mesh import make_mesh


class ExternalTeraSorter:
    """Streaming sortByKey: ``sort_chunks`` consumes (keys, vals) numpy
    chunk pairs and yields globally sorted (keys, vals) chunks, one per
    range bucket."""

    def __init__(
        self,
        mesh=None,
        num_buckets: int = 64,
        sample_per_chunk: int = 4096,
        spill_dir: Optional[str] = None,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.sorter = TeraSorter(self.mesh)
        self.num_buckets = int(num_buckets)
        self.sample_per_chunk = int(sample_per_chunk)
        self.spill_dir = spill_dir
        # stats (observability parity: spill volumes, bucket skew)
        self.chunks_in = 0
        self.bytes_spilled = 0
        self.max_bucket_records = 0

    # -- pass 1 helpers -----------------------------------------------------
    def _device_sort(self, keys: np.ndarray, vals: np.ndarray):
        sk, sv = self.sorter.sort(keys, vals)
        return np.asarray(sk), np.asarray(sv)

    def sort_chunks(
        self, chunks: Iterable[Tuple[np.ndarray, np.ndarray]]
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Two-pass external sort.  ``chunks`` may be a one-shot
        generator: chunk data is retained in per-bucket spill files, so
        nothing is iterated twice.  Yields (sorted_keys, sorted_vals)
        per bucket in ascending global range order."""
        with tempfile.TemporaryDirectory(
            prefix="sparkrdma_tpu_extsort_", dir=self.spill_dir
        ) as tmp:
            paths = [os.path.join(tmp, f"bucket_{r}.bin")
                     for r in range(self.num_buckets)]
            files = [open(p, "wb") for p in paths]
            samples = []
            staged = []  # sorted chunks awaiting splitters
            dtype = None
            try:
                # One subtlety: splitters need a GLOBAL sample, so the
                # first chunks are staged (sorted, in memory) until the
                # sample stabilizes.  To keep memory bounded we fix the
                # splitters after the FIRST chunk's sample plus any
                # staged chunks — for uniformly shuffled inputs one
                # chunk's quantiles are already unbiased; pathological
                # orderings degrade bucket balance, not correctness.
                splitters = None
                for keys, vals in chunks:
                    keys = np.asarray(keys)
                    vals = np.asarray(vals)
                    if dtype is None:
                        dtype = (keys.dtype, vals.dtype)
                    self.chunks_in += 1
                    sk, sv = self._device_sort(keys, vals)
                    n = len(sk)
                    if n:
                        step = max(1, n // self.sample_per_chunk)
                        samples.append(sk[::step])
                    if splitters is None:
                        staged.append((sk, sv))
                        if sum(len(s) for s, _ in staged) >= 1:
                            splitters = self._make_splitters(samples)
                            for s, v in staged:
                                self._spill(files, s, v, splitters)
                            staged = []
                    else:
                        self._spill(files, sk, sv, splitters)
                if splitters is None:
                    # zero or empty chunks only
                    splitters = self._make_splitters(samples)
                    for s, v in staged:
                        self._spill(files, s, v, splitters)
            finally:
                for f in files:
                    f.close()
            if dtype is None:
                return
            # pass 2: per-bucket device sort, in range order
            kd, vd = dtype
            item = np.dtype([("k", kd), ("v", vd)])
            for p in paths:
                size = os.path.getsize(p)
                if size == 0:
                    continue
                rec = np.fromfile(p, dtype=item)
                self.max_bucket_records = max(
                    self.max_bucket_records, len(rec)
                )
                yield self._device_sort(rec["k"], rec["v"])

    def _make_splitters(self, samples) -> np.ndarray:
        if not samples:
            return np.zeros(0, np.int64)
        cat = np.sort(np.concatenate(samples))
        idx = (np.arange(1, self.num_buckets) * len(cat)) // self.num_buckets
        return cat[np.clip(idx, 0, len(cat) - 1)]

    def _spill(self, files, sk: np.ndarray, sv: np.ndarray,
               splitters: np.ndarray) -> None:
        """Append each splitter range of the SORTED chunk to its bucket
        file (ranges are contiguous slices — sequential IO only)."""
        edges = np.concatenate([
            [0], np.searchsorted(sk, splitters, side="right"), [len(sk)]
        ]).astype(np.int64)
        # an empty sample (all chunks empty so far) yields no splitters:
        # everything lands in bucket 0
        for r in range(len(edges) - 1):
            lo, hi = edges[r], edges[r + 1]
            if hi <= lo:
                continue
            item = np.dtype([("k", sk.dtype), ("v", sv.dtype)])
            rec = np.empty(hi - lo, dtype=item)
            rec["k"] = sk[lo:hi]
            rec["v"] = sv[lo:hi]
            rec.tofile(files[r])
            self.bytes_spilled += rec.nbytes

    def sort(self, keys, vals) -> Tuple[np.ndarray, np.ndarray]:
        """Convenience non-streaming wrapper (array in, array out)."""
        keys = np.asarray(keys)
        vals = np.asarray(vals)
        outs = list(self.sort_chunks([(keys, vals)]))
        if not outs:
            return keys[:0], vals[:0]
        return (
            np.concatenate([k for k, _ in outs]),
            np.concatenate([v for _, v in outs]),
        )
