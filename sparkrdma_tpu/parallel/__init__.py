"""Device-mesh parallel layer: meshes, collectives, the byte exchange engine."""

from sparkrdma_tpu.parallel.mesh import make_mesh, mesh_devices
from sparkrdma_tpu.parallel.exchange import (
    DestRowView,
    ExchangePlan,
    TileExchange,
    row_offsets,
)

__all__ = ["make_mesh", "mesh_devices", "ExchangePlan", "TileExchange",
           "DestRowView", "row_offsets"]
