"""Ring exchange: ppermute-based alternative data plane.

Two reasons this exists alongside the all_to_all engine
(sparkrdma_tpu.parallel.exchange):

1. **Memory ceiling.**  An all_to_all round holds every peer's tile at
   once (D × tile per chip).  The ring moves one neighbor-hop per step
   (``ppermute`` shift by 1), so peak exchange memory is 2 × tile per
   chip regardless of D — the knob that lets shuffles larger than HBM
   stream through, the way the reference's ``maxBytesInFlight`` window
   bounds NIC buffer usage (RdmaShuffleFetcherIterator.scala:241-251).

2. **Sequence/context parallelism.**  Ring attention and ring
   sequence-parallel schedules are exactly this communication pattern:
   each chip consumes one remote shard per step while computing on the
   previous one.  ``ring_exchange_step`` is the reusable primitive; the
   shuffle data plane and a ring-attention consumer share it.

After D-1 hops every chip has seen every source shard once; a consumer
callback receives ``(source_index, shard)`` per hop and never needs the
whole exchange resident.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS, make_mesh


def ring_shift(x: jax.Array, axis_name: str = EXCHANGE_AXIS) -> jax.Array:
    """One ring hop: device i's block goes to device (i+1) mod D.
    Must run inside shard_map/pjit over the mesh axis."""
    n = jax.lax.axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


@functools.lru_cache(maxsize=1)
def supports_pallas_partition_id() -> bool:
    """Can this backend compile the ring-attention schedule's hot
    pattern — ``jax.lax.axis_index`` feeding a Pallas kernel's block
    offsets inside a ``lax.scan`` over ring hops?

    ``axis_index`` under SPMD lowers to a ``PartitionId`` HLO; the CPU
    backend's SPMD partitioner rejects the instruction when the scan
    keeps it alive past DCE ("PartitionId instruction is not supported
    for SPMD partitioning"), which was a documented seed failure of the
    pallas ring test.  Probed ONCE by compiling a miniature (D=2,
    8×128) replica of exactly that pattern; callers route to the
    data-carried device-index fallback when it answers False.  A
    1-device process has no SPMD partitioning to trip — True."""
    if len(jax.devices()) < 2:
        return True
    from sparkrdma_tpu.ops.attention import block_attention

    mesh = make_mesh(2)
    spec = P(EXCHANGE_AXIS, None, None)

    def body(q_):
        q = q_[0]
        my = jax.lax.axis_index(EXCHANGE_AXIS)

        def step(carry, j):
            k = carry
            _m, _l, o = block_attention(
                q, k, k, q_offset=my * 8, k_offset=((my - j) % 2) * 8,
                causal=False, scale=0.5, impl="pallas",
            )
            return ring_shift(k), o

        _, outs = jax.lax.scan(step, q, jnp.arange(2))
        return outs.sum(0)[None]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )
    # 8×128: lane-aligned so the probe also compiles on real TPU
    # backends (where it should answer True, keeping the native path)
    x = jnp.zeros((2, 8, 128), jnp.float32)
    try:
        jax.jit(mapped)(x).block_until_ready()
        return True
    except Exception:
        return False


@functools.lru_cache(maxsize=32)
def _ring_scan_fn(mesh: Mesh, n_local_shape, dtype_str: str, reverse: bool):
    """Jitted full-ring pass: returns [D, ...] where slot j holds the
    shard originating at device (i - j) mod D (i = my index) — i.e. the
    scan collects every source's shard at every device in D steps."""
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS)

    def body(x):  # local shard [1, ...] under shard_map of [D, ...]
        shard = x[0]

        def step(carry, _):
            nxt = ring_shift(carry) if not reverse else _ring_shift_back(carry)
            return nxt, carry

        _, seen = jax.lax.scan(step, shard, None, length=D)
        # seen[j] = shard after j hops = block of source (i - j) mod D
        return seen[None]  # [1, D, ...]

    mapped = jax.shard_map(body, mesh=mesh, in_specs=spec, out_specs=spec)
    return jax.jit(mapped)


def _ring_shift_back(x: jax.Array, axis_name: str = EXCHANGE_AXIS) -> jax.Array:
    n = jax.lax.axis_size(axis_name)
    perm = [(i, (i - 1) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


class RingExchange:
    """Ring data plane over the exchange mesh."""

    def __init__(self, mesh: Optional[Mesh] = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_devices = len(list(self.mesh.devices.flat))
        self.sharding = NamedSharding(self.mesh, P(EXCHANGE_AXIS))

    def all_shards(self, x: jax.Array, reverse: bool = False) -> jax.Array:
        """Ring-collect: input [D, ...] sharded on axis 0; output
        [D, D, ...] where out[i, j] = shard of source (i - j) mod D —
        every device ends holding all shards, having moved only one
        shard per hop (an all_gather that never exceeds 2 shards of
        in-flight memory)."""
        if x.shape[0] != self.n_devices:
            raise ValueError(
                f"leading dim {x.shape[0]} != D={self.n_devices}"
            )
        fn = _ring_scan_fn(
            self.mesh, tuple(x.shape[1:]), str(x.dtype), reverse
        )
        x = jax.device_put(x, self.sharding)
        return fn(x)

    def ring_reduce(
        self, x: jax.Array, init_fn: Callable, consume: Callable
    ) -> jax.Array:
        """Streaming consume: fold ``consume(acc, src_index, shard)``
        over every source's shard without ever materializing [D, D, ...].

        ``init_fn(local_shard) -> acc`` builds the accumulator;
        ``consume(acc, src_index, shard) -> acc`` folds one hop.  Runs
        as one jitted scan — the ring-attention-shaped schedule.

        The jitted program is cached on (mesh, shape, dtype, init_fn,
        consume) — callables compare by identity, so pass the SAME
        function objects across calls to reuse the compilation.
        """
        fn = _ring_reduce_fn(
            self.mesh, tuple(x.shape[1:]), str(x.dtype), init_fn, consume
        )
        return fn(jax.device_put(x, self.sharding))


@functools.lru_cache(maxsize=32)
def _ring_reduce_fn(mesh: Mesh, shard_shape, dtype_str: str,
                    init_fn: Callable, consume: Callable):
    """Cached jitted ring_reduce program (mirrors _ring_scan_fn; without
    this every call would pay a fresh XLA compile)."""
    D = len(list(mesh.devices.flat))
    spec = P(EXCHANGE_AXIS)

    def body(x):
        shard = x[0]
        my = jax.lax.axis_index(EXCHANGE_AXIS)

        def step(carry, j):
            acc, cur = carry
            src = (my - j) % D
            acc = consume(acc, src, cur)
            return (acc, ring_shift(cur)), None

        (acc, _), _ = jax.lax.scan(
            step, (init_fn(shard), shard), jnp.arange(D)
        )
        return jax.tree.map(lambda a: a[None], acc)

    mapped = jax.shard_map(body, mesh=mesh, in_specs=spec, out_specs=spec)
    return jax.jit(mapped)
