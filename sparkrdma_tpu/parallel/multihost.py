"""Multi-host (multi-slice / DCN) support.

The reference scales across nodes with one RdmaNode per JVM and a
full mesh of RC connections (SURVEY.md §1 deployment topology).  The
TPU-native equivalent is JAX's multi-controller runtime: one process
per host, ``jax.distributed.initialize`` for rendezvous (the
hello/announce analog at the runtime layer), and a global mesh whose
collectives ride ICI within a slice and DCN across slices — XLA picks
the transport per hop, exactly the RoCE/IB duality DiSNI gave the
reference.

What this module provides:

- :func:`initialize` — rendezvous wrapper (driver coordinator analog).
- :func:`global_mesh` — a mesh over every device in the job.
- :func:`host_local_indices` — which rows of a leading-axis-sharded
  global array live on this host; the TileExchange already consumes
  per-host shards via ``addressable_shards``, so host code only ever
  touches its local slice (the "executor owns its blocks" invariant).

Single-host jobs never need to call anything here.
"""

from __future__ import annotations

import functools
import os
import subprocess
import sys
from typing import List, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-controller job (idempotent).

    With no arguments JAX autodetects the environment (TPU pods publish
    topology via metadata).  Mirrors the reference's driver hello path:
    every process must call this before building the global mesh — in
    particular BEFORE anything touches a backend (jax.devices() etc.),
    which is why this guard must not query process_count() itself.
    """
    if _distributed_client() is not None:
        return  # rendezvous already done
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except (RuntimeError, ValueError):
        # The degenerate cases are fine: a no-arg call on a plain single
        # host (autodetection finds no cluster) or a second initialize.
        # Explicit-argument failures (bad coordinator address, rendezvous
        # timeout) must surface — swallowing them would silently run N
        # independent single-host jobs.
        if kwargs and _distributed_client() is None:
            raise


def _distributed_client():
    """The live rendezvous client, or None if initialize never ran.
    Checked via jax's distributed global state so the probe does NOT
    initialize a backend the way jax.process_count() would."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client
    except Exception:
        return None


def global_mesh(axis_name: str = EXCHANGE_AXIS) -> Mesh:
    """1-D exchange mesh over EVERY device in the job (all hosts)."""
    return Mesh(np.array(jax.devices()), (axis_name,))


def host_local_indices(mesh: Mesh) -> List[int]:
    """Mesh-axis positions whose device is addressable from this
    process — the rows of a leading-axis-sharded array this host owns."""
    local = set(d.id for d in jax.local_devices())
    return [
        i for i, dev in enumerate(mesh.devices.flat) if dev.id in local
    ]


def is_multihost() -> bool:
    return jax.process_count() > 1


@functools.lru_cache(maxsize=1)
def supports_multiprocess_collectives() -> bool:
    """Can a multi-controller job's workers actually run cross-process
    collectives on the backend they would initialize?

    The CPU backend cannot ("Multiprocess computations aren't
    implemented on the CPU backend" at collective dispatch) — the
    documented seed failures of the multi-process tests.  Because a
    worker process chooses its backend WITHOUT the parent's
    ``JAX_PLATFORMS``/``XLA_FLAGS`` test-harness pins, this probe asks
    an unconstrained subprocess for its default backend instead of
    reading this process's (already-pinned) one.  Cached: one
    subprocess jax import per process lifetime, no backend
    initialization here.  Probe failures answer False — callers gate
    multi-process work, and skipping beats hanging a rendezvous."""
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_PLATFORM_NAME")
    }
    code = "import jax, sys; sys.stdout.write(jax.default_backend())"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], env=env, timeout=120,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
        backend = out.stdout.decode().strip()
    except Exception:
        return False
    return out.returncode == 0 and backend not in ("", "cpu")
