"""Mesh construction helpers.

The TPU analog of the reference's endpoint topology: where SparkRDMA
discovers a full mesh of RC queue pairs lazily via hello/announce RPCs
(RdmaShuffleManager.scala:70-118), a TPU pod's topology is known up
front — we fix a ``jax.sharding.Mesh`` at job start and the control
plane only tracks *logical* membership on top of it (SURVEY.md §7
"Dynamic membership" hard part).

One mesh axis ``"x"`` carries the shuffle exchange: ``all_to_all`` over
"x" rides ICI within a slice and DCN across slices — XLA picks the
transport per hop, exactly the RoCE/IB duality the reference gets from
ibverbs.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

EXCHANGE_AXIS = "x"


def mesh_devices(
    n_devices: Optional[int] = None, device_list: Optional[Sequence[int]] = None
):
    """Pick the devices serving the exchange (conf.device_list analog of
    the reference's cpuList pinning, RdmaNode.java:216-273)."""
    devs = jax.devices()
    if device_list:
        devs = [devs[i] for i in device_list if i < len(devs)]
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}"
            )
        devs = devs[:n_devices]
    return devs


def make_mesh(
    n_devices: Optional[int] = None,
    device_list: Optional[Sequence[int]] = None,
    axis_name: str = EXCHANGE_AXIS,
) -> Mesh:
    """1-D exchange mesh over the chosen devices."""
    devs = mesh_devices(n_devices, device_list)
    return Mesh(np.array(devs), (axis_name,))
