"""Tile-round byte exchange over the device mesh.

This is the data-plane inversion at the core of the TPU-native design
(SURVEY.md §7 "Hard parts"): the reference's reducers *pull* exactly the
bytes they want with one-sided RDMA READs (RdmaChannel.java:441-474);
SPMD collectives instead need every chip participating in lockstep with
static shapes.  The resolution:

- The control plane still resolves exact block locations (unchanged).
- The data plane buckets each (src → dst) byte stream into fixed-size
  padded *tiles* and executes synchronized ``all_to_all`` rounds over the
  mesh axis; the host-side :class:`ExchangePlan` knows exactly which
  slice of which stream rides in which round, so no in-band framing is
  needed.
- Round count is the global max over pairs (lockstep), tile size is the
  ``shuffle_read_block_size`` analog (``conf.exchange_tile_bytes``), and
  the bounded number of in-flight rounds is the ``maxBytesInFlight``
  window (RdmaShuffleFetcherIterator.scala:241-251) — here it bounds
  HBM staging memory and lets JAX's async dispatch overlap host staging
  of round r+1 with the collective of round r (double buffering).

Single-host it runs on the spoofed CPU mesh; on a pod the same code
rides ICI (and DCN across slices) because the mesh carries real devices.
"""

from __future__ import annotations

import functools
import math
import zlib
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.metrics import counter
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS, make_mesh
from sparkrdma_tpu.transport.channel import TransportError


class ExchangeIntegrityError(TransportError):
    """A received stream failed its end-to-end checksum.

    The collective analog of a CQ completion with error status
    (RdmaChannel.java:611-615): a chip/link fault inside a collective
    corrupts silently instead of failing a channel.  Subclasses
    :class:`TransportError` so any layer that converts transport
    failures to stage-retryable fetch failures (the reader's
    FetchFailedError bridge) handles corruption the same way
    (SURVEY.md §7 failure-semantics hard part).  Opt in via the
    ``verify_integrity`` constructor flag, or
    ``spark.shuffle.tpu.verifyExchangeIntegrity`` through
    :meth:`TileExchange.from_conf` — the comparison costs O(payload)
    host time, and healthy ICI links have hardware CRC."""

    def __init__(self, src: int, dst: int, expected: int, got: int):
        super().__init__(
            f"stream {src}->{dst} corrupt: crc32 {got:#010x} != "
            f"expected {expected:#010x}"
        )
        self.src = src
        self.dst = dst
        self.expected = expected
        self.got = got

# tiles are padded to lane multiples so uint8 rows lay out cleanly
TILE_ALIGN = 128


def row_offsets(lengths_1d) -> np.ndarray:
    """Exclusive prefix sums of one lengths row/column: stream ``i`` of
    a contiguous exchange row occupies ``[offs[i], offs[i + 1])``.
    Returns int64 ``[D + 1]``."""
    lengths_1d = np.asarray(lengths_1d, np.int64)
    offs = np.zeros(len(lengths_1d) + 1, np.int64)
    np.cumsum(lengths_1d, out=offs[1:])
    return offs


class DestRowView:
    """One destination's received streams as ZERO-COPY slices of one
    contiguous row buffer: ``row[s]`` is the uint8 view of the stream
    from source ``s`` (the copy-free replacement for the legacy
    per-pair ``bytes`` lists — consumers slice blocks out of the view
    without ever materializing a ``bytes`` object)."""

    __slots__ = ("buf", "offsets")

    def __init__(self, buf: np.ndarray, offsets: np.ndarray):
        self.buf = buf
        self.offsets = offsets

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(self, s: int) -> np.ndarray:
        return self.buf[int(self.offsets[s]):int(self.offsets[s + 1])]

    @property
    def nbytes(self) -> int:
        return int(self.offsets[-1])


class PaddedSourceRow:
    """One source's exchange payload in the DEVICE framing: a flat
    uint8 buffer of ``D * cols`` bytes where the stream to destination
    ``d`` occupies ``[d * cols, d * cols + lengths[s, d])`` and the
    tail of each span is zero padding.

    This is the marker type the staged-assembly path hands the session
    barrier when the device plane is on: assembly writes blocks ONCE at
    their padded offsets, the collective consumes the row via a single
    ``device_put`` (no per-round [D, D, tile] host staging matrices),
    and ``stream(d, n)`` recovers the compact view any host-staged
    consumer (or a mixed-capability barrier peer) expects."""

    __slots__ = ("buf", "cols")

    def __init__(self, buf: np.ndarray, cols: int):
        self.buf = buf
        self.cols = int(cols)

    def stream(self, d: int, n: int) -> np.ndarray:
        """Zero-copy view of the payload bytes headed to destination
        ``d`` (``n`` = that stream's true length, excluding padding)."""
        o = d * self.cols
        return self.buf[o : o + n]

    @property
    def nbytes(self) -> int:
        return int(self.buf.nbytes)


class PaddedDestRowView:
    """One destination's received streams as rows of one padded
    ``[S, cols]`` matrix: ``row[s]`` is the uint8 view of the first
    ``lengths[s]`` bytes of source ``s``'s row — the device-plane
    sibling of :class:`DestRowView` (same consumer protocol, different
    backing layout).

    ``keepalive`` pins whatever owns the matrix memory (the collective
    output's device buffer on the zero-copy full-shot path) for the
    life of the views handed out."""

    __slots__ = ("mat", "lengths", "keepalive")

    def __init__(self, mat: np.ndarray, lengths: np.ndarray,
                 keepalive=None):
        self.mat = mat
        self.lengths = np.asarray(lengths, np.int64)
        self.keepalive = keepalive

    def __len__(self) -> int:
        return len(self.lengths)

    def __getitem__(self, s: int) -> np.ndarray:
        return self.mat[s, : int(self.lengths[s])]

    @property
    def nbytes(self) -> int:
        return int(self.lengths.sum())


class NonAddressableStreamError(TransportError):
    """A caller touched a destination row that lives on another host.

    ``exchange_bytes`` is host-local by construction (each process only
    holds its own devices' shards) — silently returning empty streams
    for remote destinations made the API *look* total while dropping
    data, so those rows now fail loudly on access."""

    def __init__(self, dst: int):
        super().__init__(
            f"destination {dst} is not addressable from process "
            f"{jax.process_index()}: exchange_bytes results are "
            f"host-local; read this row on the process that owns "
            f"device {dst}"
        )
        self.dst = dst


class HostLocalStreams:
    """Result of a multi-host ``exchange_bytes`` (rows are per-source
    ``bytes`` lists) or any ``exchange_into`` (rows are
    :class:`DestRowView` zero-copy views): list-like [D][S] with only
    this host's destination rows present.  Indexing a remote
    destination raises :class:`NonAddressableStreamError` instead of
    returning empty bytes; ``addressable`` lists the valid rows.

    There is deliberately no ``__iter__``: plain iteration falls back to
    ``__getitem__(0..)`` and raises the moment it touches a remote row,
    so single-host code (`for row in result`) that silently assumed the
    full matrix fails LOUDLY on a multi-host mesh instead of consuming a
    partial one.  Multi-host code iterates ``items()`` explicitly."""

    def __init__(self, rows: List[List[bytes]], filled: frozenset):
        self._rows = rows
        self.addressable = frozenset(filled)

    def __len__(self) -> int:
        return len(self._rows)

    def __getitem__(self, d: int):
        if d not in self.addressable:
            raise NonAddressableStreamError(d)
        return self._rows[d]

    def items(self):
        """(destination, row) pairs for this host's rows — the explicit
        multi-host iteration idiom."""
        for d in sorted(self.addressable):
            yield d, self._rows[d]


class ExchangePlan:
    """Static plan for one exchange of per-pair streams of known length.

    lengths[s, d] = bytes queued from source s to destination d.
    """

    def __init__(self, lengths: np.ndarray, tile_bytes: int):
        lengths = np.asarray(lengths, dtype=np.int64)
        if lengths.ndim != 2 or lengths.shape[0] != lengths.shape[1]:
            raise ValueError(f"lengths must be [D, D], got {lengths.shape}")
        if (lengths < 0).any():
            raise ValueError("negative stream length")
        self.lengths = lengths
        self.n_devices = lengths.shape[0]
        max_len = int(lengths.max()) if lengths.size else 0
        if max_len == 0:
            self.tile_bytes = 0
            self.rounds = 0
            self.total_cols = 0
            return
        # tile: lane-aligned, no larger than needed for a single round,
        # QUANTIZED to a power-of-two ladder of TILE_ALIGN units below
        # the configured tile — the collective's compiled shape is
        # (D, D, tile), so an exact-fit tile recompiles for every
        # distinct stream size (20-40s per novel shape on a real chip);
        # the ladder bounds distinct shapes to ~log2(tile_bytes/128)
        # for ≤2x padding on sub-tile exchanges
        cap = max(
            TILE_ALIGN,
            (int(tile_bytes) + TILE_ALIGN - 1) // TILE_ALIGN * TILE_ALIGN,
        )
        if max_len >= cap:
            tile = cap
        else:
            units = (max_len + TILE_ALIGN - 1) // TILE_ALIGN
            tile = min(cap, TILE_ALIGN * (1 << (units - 1).bit_length()))
        self.tile_bytes = tile
        self.rounds = math.ceil(max_len / tile)
        self.total_cols = self.rounds * tile

    @property
    def payload_bytes(self) -> int:
        return int(self.lengths.sum())

    @property
    def moved_bytes(self) -> int:
        """Bytes actually moved per full exchange incl. padding."""
        return self.n_devices * self.n_devices * self.total_cols

    def round_slice(self, r: int) -> Tuple[int, int]:
        """[start, end) byte range of round r within each pair stream."""
        return r * self.tile_bytes, (r + 1) * self.tile_bytes


def _make_row_collect(plan: "ExchangePlan", lengths: np.ndarray,
                      col_offs, get_dst):
    """The ONE per-round destination scatter both byte paths share:
    received tile slices land at their final offsets inside the
    per-destination contiguous rows (a second copy of this slicing
    loop drifting on round/offset math would silently misalign
    stream boundaries)."""
    D = lengths.shape[0]

    def collect(r: int, d: int, local: np.ndarray) -> None:
        lo, hi = plan.round_slice(r)
        buf = get_dst(d)
        offs = col_offs[d]
        for s in range(D):
            take = min(hi, int(lengths[s, d])) - lo
            if take > 0:
                o = int(offs[s]) + lo
                buf[o : o + take] = local[s, :take]

    return collect


@functools.lru_cache(maxsize=64)
def _a2a_fn(mesh: Mesh, n_devices: int, cols: int, donate: bool):
    """Jitted all_to_all: S[s, d, c] → R[d, s, c] over the mesh axis.

    The one XLA program that *is* the shuffle data plane: each device
    contributes its row of destination tiles and receives its row of
    source tiles; XLA lowers the permutation onto ICI links.

    ``donate`` lets XLA reuse the input buffer (halves HBM pressure) —
    only safe when the caller owns the array and won't touch it again.
    """
    spec = P(EXCHANGE_AXIS, None, None)
    sharding = NamedSharding(mesh, spec)

    def body(x):  # local view: [1, D, C]
        y = jax.lax.all_to_all(
            x, EXCHANGE_AXIS, split_axis=1, concat_axis=0, tiled=False
        )  # → [D, 1, C], row s = tile from source s
        return jnp.swapaxes(y, 0, 1)  # → [1, D, C]

    mapped = jax.shard_map(body, mesh=mesh, in_specs=spec, out_specs=spec)
    fn = jax.jit(mapped, donate_argnums=(0,) if donate else ())
    return fn, sharding


@functools.lru_cache(maxsize=64)
def _padded_full_fn(mesh: Mesh, n_devices: int, cols_w: int,
                    dtype_str: str):
    """Jitted ONE-SHOT padded exchange: each device's flat source row
    ``[1, D * cols_w]`` reshapes in-program to ``[1, D, cols_w]`` and
    goes through the same all_to_all permutation as :func:`_a2a_fn` —
    the entire exchange is a single donated XLA program, no per-round
    host staging, no host-side tile slicing.  Elements are uint32 words
    (4x fewer lanes through the permutation at identical bytes) with a
    uint8 fallback for unaligned buffers."""
    spec = P(EXCHANGE_AXIS, None)

    def body(x):  # local view: [1, D * cols_w]
        y = x.reshape(1, n_devices, cols_w)
        z = jax.lax.all_to_all(
            y, EXCHANGE_AXIS, split_axis=1, concat_axis=0, tiled=False
        )
        return jnp.swapaxes(z, 0, 1)  # [1, S, cols_w]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=spec,
        out_specs=P(EXCHANGE_AXIS, None, None),
    )
    # the caller always owns the staged row array: donate it so XLA
    # reuses the input HBM for the permutation
    return jax.jit(mapped, donate_argnums=(0,)), NamedSharding(mesh, spec)


@functools.lru_cache(maxsize=64)
def _padded_round_fn(mesh: Mesh, n_devices: int, rounds: int,
                     tile_w: int, dtype_str: str):
    """Jitted PER-ROUND padded exchange: the flat source row reshapes
    to ``[1, D, rounds, tile_w]`` and ``dynamic_index_in_dim`` selects
    round ``r``'s tile ON DEVICE — the host never re-slices or
    re-stages between rounds, it just feeds round indices while the
    in-flight window overlaps collectives with downstream decode.  NOT
    donated: the same device-resident row feeds every round."""
    spec = P(EXCHANGE_AXIS, None)

    def body(x, r):  # x: [1, D * rounds * tile_w]
        y = x.reshape(1, n_devices, rounds, tile_w)
        y = jax.lax.dynamic_index_in_dim(y, r, axis=2, keepdims=False)
        z = jax.lax.all_to_all(
            y, EXCHANGE_AXIS, split_axis=1, concat_axis=0, tiled=False
        )
        return jnp.swapaxes(z, 0, 1)  # [1, S, tile_w]

    mapped = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, P()),
        out_specs=P(EXCHANGE_AXIS, None, None),
    )
    return jax.jit(mapped), NamedSharding(mesh, spec)


class TileExchange:
    """The exchange engine: pack → all_to_all rounds → unpack.

    ``exchange_bytes(streams)`` moves ``streams[s][d]`` (bytes from
    source s to destination d) and returns ``out[d][s]``.  Large
    exchanges run as multiple rounds with at most
    ``max_rounds_in_flight`` outstanding device computations.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        tile_bytes: int = 4 << 20,
        max_rounds_in_flight: int = 2,
        verify_integrity: bool = False,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.devices = list(self.mesh.devices.flat)
        self.n_devices = len(self.devices)
        self.tile_bytes = int(tile_bytes)
        self.max_rounds_in_flight = max(1, int(max_rounds_in_flight))
        self.verify_integrity = verify_integrity
        # stats (reader-stats analog for the collective plane)
        self.rounds_executed = 0
        self.payload_bytes_moved = 0
        self.padded_bytes_moved = 0
        self.integrity_failures = 0
        self.device_exchanges = 0

    @classmethod
    def from_conf(cls, conf, mesh: Optional[Mesh] = None) -> "TileExchange":
        """Build from a :class:`TpuShuffleConf`: wires
        ``exchangeTileBytes``, ``exchangeMaxRoundsInFlight``, and
        ``verifyExchangeIntegrity``."""
        return cls(
            mesh,
            tile_bytes=conf.exchange_tile_bytes,
            max_rounds_in_flight=conf.exchange_max_rounds_in_flight,
            verify_integrity=conf.verify_exchange_integrity,
        )

    # -- planning -----------------------------------------------------------
    def plan(self, lengths: np.ndarray) -> ExchangePlan:
        return ExchangePlan(lengths, self.tile_bytes)

    # -- host-driven byte exchange ------------------------------------------
    def exchange_bytes(
        self, streams: Sequence[Sequence[bytes]],
        lengths: Optional[np.ndarray] = None,
        local_sources: Optional[frozenset] = None,
    ):
        """Move ``streams[s][d]`` → ``out[d][s]``.  Single-host (every
        destination addressable) returns plain ``[D][S]`` lists; on a
        multi-host mesh the return is a :class:`HostLocalStreams` whose
        remote destination rows raise on access (each process holds
        only its own devices' shards).

        Multi-host contract: every process must call with the SAME
        ``lengths`` matrix (the plan's tile/round shapes derive from
        it — divergent shapes would compile different programs and
        deadlock the collective), but only needs real data for its own
        sources' rows; remote sources' streams may be empty — their
        shards are not addressable here and never read.

        ``local_sources`` names the source rows THIS caller vouches for
        (default: the devices of this process).  Bulk-synchronous
        callers that represent a single executor on a shared mesh pass
        just their own row — empty rows outside the set are legal, an
        empty row INSIDE it with a nonzero length is a caller bug."""
        D = self.n_devices
        if len(streams) != D or any(len(row) != D for row in streams):
            raise ValueError(
                f"streams must be [{D}][{D}], got "
                f"[{len(streams)}][{[len(r) for r in streams]}]"
            )
        if lengths is None:
            lengths = np.array(
                [[len(streams[s][d]) for d in range(D)] for s in range(D)],
                dtype=np.int64,
            )
        else:
            lengths = np.asarray(lengths, dtype=np.int64)
            if lengths.shape != (D, D):
                raise ValueError(
                    f"lengths must be [{D}, {D}], got {lengths.shape}"
                )
            if local_sources is None:
                proc = jax.process_index()
                local_sources = frozenset(
                    s for s, dev in enumerate(self.devices)
                    if dev.process_index == proc
                )
            for s in range(D):
                # only sources this caller does NOT vouch for may omit
                # their data; a vouched-for empty row with a nonzero
                # length is a caller bug that would silently exchange
                # zeros
                for d in range(D):
                    n = len(streams[s][d])
                    if (n or s in local_sources) and n != int(lengths[s, d]):
                        raise ValueError(
                            f"stream [{s}][{d}] is {n}B but lengths says "
                            f"{int(lengths[s, d])}B (only rows outside "
                            f"local_sources may be empty)"
                        )
        plan = self.plan(lengths)
        if plan.rounds == 0:
            return [[b""] * D for _ in range(D)]

        col_offs = [row_offsets(lengths[:, d]) for d in range(D)]
        # destination rows preallocated ONCE at their exact payload
        # size: the per-round collect slice-assigns into them instead
        # of growing per-pair bytearrays round by round (the old
        # ``out[d][s] += local[s].tobytes()`` accumulation reallocated
        # and re-copied every pair every round)
        dst_rows: Dict[int, np.ndarray] = {}

        def get_dst(d: int) -> np.ndarray:
            buf = dst_rows.get(d)
            if buf is None:
                buf = dst_rows[d] = np.empty(
                    int(lengths[:, d].sum()), np.uint8
                )
            return buf

        def fill(r: int) -> np.ndarray:
            lo, hi = plan.round_slice(r)
            # np.zeros, not np.empty: calloc's zero pages make the
            # untouched padding free until faulted, and everything the
            # collective ships stays deterministic — np.empty would
            # transmit stale heap memory in the pad spans (a cross-host
            # disclosure on a real mesh).  Omitted rows outside
            # local_sources read as zeros, as before.
            mat = np.zeros((D, D, plan.tile_bytes), dtype=np.uint8)
            for s in range(D):
                row = streams[s]
                for d in range(D):
                    take = min(hi, int(lengths[s, d])) - lo
                    if take <= 0:
                        continue
                    chunk = row[d][lo : lo + take]
                    if len(chunk):
                        mat[s, d, : len(chunk)] = np.frombuffer(
                            chunk, np.uint8
                        )
            return mat

        collect = _make_row_collect(plan, lengths, col_offs, get_dst)
        filled_dsts = self._run_tile_rounds(plan, fill, collect)
        result = [
            [
                bytes(memoryview(
                    dst_rows[d][col_offs[d][s]:col_offs[d][s + 1]]
                )) if d in filled_dsts else b""
                for s in range(D)
            ]
            for d in range(D)
        ]
        if self.verify_integrity:
            self._verify(streams, result, filled_dsts, local_sources)
        if len(filled_dsts) < D:
            # multi-host: only this process's destination rows hold
            # data — hand back a guarded view so a remote row fails
            # loudly instead of reading as empty streams
            return HostLocalStreams(result, frozenset(filled_dsts))
        return result

    def exchange_into(
        self,
        lengths: np.ndarray,
        src_rows,
        local_sources: Optional[frozenset] = None,
        out_alloc=None,
    ) -> HostLocalStreams:
        """Zero-copy exchange over preallocated contiguous rows.

        ``src_rows`` maps source index → one contiguous uint8 buffer
        (ndarray / memoryview) laid out per ``lengths[s]``: the stream
        to destination ``d`` occupies ``[row_offsets(lengths[s])[d],
        row_offsets(lengths[s])[d + 1])``.  Assembly writes map-output
        blocks into that row ONCE; the round loop stages tile slices
        straight out of it (no per-destination ``bytes`` joins, no
        ``frombuffer`` round-trips).

        Returns a :class:`HostLocalStreams` whose addressable rows are
        :class:`DestRowView` objects — ``result[d][s]`` is a uint8 VIEW
        of the received stream from source ``s``, sliced out of one
        per-destination buffer that ``out_alloc(nbytes)`` provides
        (default ``np.empty``; pass a pooled allocator such as
        ``StagingPool.alloc_gc`` to recycle the buffers).  Same
        multi-host contract as :meth:`exchange_bytes`: every process
        passes the same ``lengths``; ``local_sources`` names the rows
        this caller vouches for (their buffers must be present and
        exactly sized; other sources' rows may be omitted)."""
        D = self.n_devices
        lengths = np.asarray(lengths, dtype=np.int64)
        if lengths.shape != (D, D):
            raise ValueError(
                f"lengths must be [{D}, {D}], got {lengths.shape}"
            )
        if (lengths < 0).any():
            raise ValueError("negative stream length")
        if local_sources is None:
            proc = jax.process_index()
            local_sources = frozenset(
                s for s, dev in enumerate(self.devices)
                if dev.process_index == proc
            )
        src: Dict[int, np.ndarray] = {}
        src_offs: Dict[int, np.ndarray] = {}
        for s in sorted(local_sources):
            row = src_rows[s] if not hasattr(src_rows, "get") \
                else src_rows.get(s)
            if row is None:
                raise ValueError(f"no source row for vouched source {s}")
            arr = row if isinstance(row, np.ndarray) \
                else np.frombuffer(row, np.uint8)
            if arr.dtype != np.uint8 or arr.ndim != 1:
                raise ValueError(
                    f"source row {s} must be a flat uint8 buffer, got "
                    f"{arr.dtype} ndim={arr.ndim}"
                )
            need = int(lengths[s].sum())
            if arr.shape[0] != need:
                raise ValueError(
                    f"source row {s} is {arr.shape[0]}B but its lengths "
                    f"row sums to {need}B"
                )
            src[s] = arr
            src_offs[s] = row_offsets(lengths[s])

        plan = self.plan(lengths)
        col_offs = [row_offsets(lengths[:, d]) for d in range(D)]
        alloc = out_alloc if out_alloc is not None else (
            lambda n: np.empty(n, np.uint8)
        )
        dst_rows: Dict[int, np.ndarray] = {}

        def get_dst(d: int) -> np.ndarray:
            buf = dst_rows.get(d)
            if buf is None:
                n = int(lengths[:, d].sum())
                buf = np.empty(0, np.uint8) if n == 0 else alloc(n)[:n]
                dst_rows[d] = buf
            return buf

        if plan.rounds == 0:
            rows = [
                DestRowView(get_dst(d), col_offs[d]) for d in range(D)
            ]
            return HostLocalStreams(rows, frozenset(range(D)))

        def fill(r: int) -> np.ndarray:
            lo, hi = plan.round_slice(r)
            # np.zeros for the same reason as exchange_bytes: pad spans
            # and unvouched sources' cells must ship deterministic
            # zeros, never stale heap memory
            mat = np.zeros((D, D, plan.tile_bytes), dtype=np.uint8)
            for s, row in src.items():
                offs = src_offs[s]
                for d in range(D):
                    take = min(hi, int(lengths[s, d])) - lo
                    if take > 0:
                        o = int(offs[d]) + lo
                        mat[s, d, :take] = row[o : o + take]
            return mat

        collect = _make_row_collect(plan, lengths, col_offs, get_dst)
        filled_dsts = self._run_tile_rounds(plan, fill, collect)
        sent = sum(int(lengths[s].sum()) for s in src)
        received = sum(
            int(lengths[:, d].sum()) for d in filled_dsts
        )
        # vs the legacy bytes path: assembly skipped the per-destination
        # join of the source payload; consumption skipped the per-pair
        # tobytes + trim materializations of the received payload
        counter("exchange_copy_bytes_avoided_total").inc(
            sent + 2 * received
        )
        rows: List[Optional[DestRowView]] = [None] * D
        for d in filled_dsts:
            rows[d] = DestRowView(get_dst(d), col_offs[d])
        if self.verify_integrity:
            self._verify_rows(
                src, src_offs, rows, filled_dsts, lengths
            )
        return HostLocalStreams(rows, frozenset(filled_dsts))

    # -- device-native padded exchange --------------------------------------
    def exchange_padded(
        self,
        lengths: np.ndarray,
        src_rows,
        local_sources: Optional[frozenset] = None,
        out_alloc=None,
        on_round=None,
        window_rounds: int = 0,
    ) -> HostLocalStreams:
        """Device-native exchange over :class:`PaddedSourceRow` buffers:
        each source row goes to its mesh device with ONE ``device_put``
        and the collective consumes it directly — no per-round host
        [D, D, tile] staging matrices, no ``bytes`` materialization
        anywhere between assembly and the destination views.

        Two execution shapes, selected by ``window_rounds``:

        - ``window_rounds <= 0`` (or a single-round plan): ONE donated
          XLA program moves the whole padded payload; destination
          matrices are ZERO-COPY views of the collective's output
          shards (``out_alloc`` is ignored — pooling can't beat not
          copying), and ``on_round(0, 0, total_cols, rows)`` fires
          once.
        - ``window_rounds > 0``: tile rounds with at most that many
          collectives in flight; round ``r``'s tile is selected ON
          DEVICE (``dynamic_index_in_dim``) from the one resident row
          array, landed slabs are copied into pooled ``out_alloc``
          matrices, and ``on_round(r, lo, hi, rows)`` fires after each
          landing so decode can overlap round ``r + 1``'s collective —
          the ``maxBytesInFlight`` window with deserialization riding
          inside it.

        Single-controller only: a multi-process mesh stages through
        :meth:`exchange_into` (each process owns only its devices'
        shards; the padded row layout would need cross-process
        assembly).  Returns :class:`HostLocalStreams` of
        :class:`PaddedDestRowView` rows — the same consumer protocol as
        the host-staged path, bit-for-bit identical payloads."""
        from sparkrdma_tpu.memory.device_arena import DeviceStagingBridge

        if jax.process_count() > 1:
            raise NotImplementedError(
                "exchange_padded is single-controller: multi-process "
                "meshes stage through exchange_into"
            )
        D = self.n_devices
        # plan metadata, not payload
        lengths = np.asarray(lengths, dtype=np.int64)  # noqa: PY13
        if lengths.shape != (D, D):
            raise ValueError(
                f"lengths must be [{D}, {D}], got {lengths.shape}"
            )
        if (lengths < 0).any():
            raise ValueError("negative stream length")
        if local_sources is None:
            local_sources = frozenset(range(D))
        plan = self.plan(lengths)
        C = plan.total_cols
        if plan.rounds == 0:
            empty = np.zeros((D, 0), np.uint8)
            rows = [
                PaddedDestRowView(empty, lengths[:, d]) for d in range(D)
            ]
            return HostLocalStreams(rows, frozenset(range(D)))

        src: Dict[int, PaddedSourceRow] = {}
        for s in sorted(local_sources):
            row = src_rows[s] if not hasattr(src_rows, "get") \
                else src_rows.get(s)
            if row is None:
                raise ValueError(f"no source row for vouched source {s}")
            if not isinstance(row, PaddedSourceRow):
                arr = row if isinstance(row, np.ndarray) \
                    else np.frombuffer(row, np.uint8)
                row = PaddedSourceRow(arr, C)
            if row.cols != C:
                raise ValueError(
                    f"source row {s} framed for cols={row.cols}, "
                    f"plan needs {C}"
                )
            if row.buf.dtype != np.uint8 or row.buf.ndim != 1 \
                    or row.buf.shape[0] != D * C:
                raise ValueError(
                    f"source row {s} must be flat uint8 [{D * C}], got "
                    f"{row.buf.dtype} shape={row.buf.shape}"
                )
            src[s] = row

        # word framing: every vouched row must sustain the uint32 view
        # or the program shape diverges per source — fall back to uint8
        # lanes for the whole exchange on the first unaligned buffer
        words = {
            s: DeviceStagingBridge.as_words(pr.buf)
            for s, pr in src.items()
        }
        use_words = all(w is not None for w in words.values())
        itemsize = DeviceStagingBridge.WORD if use_words else 1
        elem = np.uint32 if use_words else np.uint8
        dtype_str = "uint32" if use_words else "uint8"
        C_e = C // itemsize

        full = window_rounds <= 0 or plan.rounds <= 1
        if full:
            fn, sharding = _padded_full_fn(self.mesh, D, C_e, dtype_str)
        else:
            fn, sharding = _padded_round_fn(
                self.mesh, D, plan.rounds,
                plan.tile_bytes // itemsize, dtype_str,
            )

        # per-device H2D: one put per source row straight onto its mesh
        # device — never a stacked [D, D*C] host matrix
        bridge = DeviceStagingBridge()
        zeros = None
        shards = []
        for s in range(D):
            pr = src.get(s)
            if pr is None:
                # unvouched sources ship deterministic zeros (the
                # exchange_bytes omitted-row contract)
                if zeros is None:
                    zeros = np.zeros(D * C_e, elem)
                row_e, avoided = zeros, 0
            else:
                row_e = words[s] if use_words else pr.buf
                # the host-staged path would have copied this row's
                # payload through D*C bytes of per-round staging matrix
                avoided = D * C
            shards.append(
                bridge.to_device(row_e[None], self.devices[s], avoided)
            )
        garr = jax.make_array_from_single_device_arrays(
            (D, D * C_e), sharding, shards
        )

        def shard_pos(shard) -> int:
            return shard.index[0].start \
                if shard.index[0].start is not None else 0

        if full:
            out = fn(garr)
            rows = [None] * D
            for shard in out.addressable_shards:
                d = shard_pos(shard)
                # zero-copy alias of the CPU shard  # noqa below
                mat = np.asarray(shard.data)[0]  # noqa: PY13
                if use_words:
                    mat = mat.view(np.uint8)
                # zero-copy on CPU shards; keepalive pins the device
                # buffer the views alias
                rows[d] = PaddedDestRowView(
                    mat, lengths[:, d], keepalive=shard.data
                )
            self.rounds_executed += 1
            if on_round is not None:
                on_round(0, 0, C, rows)
        else:
            alloc = out_alloc if out_alloc is not None else (
                lambda n: np.empty(n, np.uint8)
            )
            dest = []
            rows = []
            for d in range(D):
                mat = alloc(D * C)[: D * C].reshape(D, C)
                dest.append(mat)
                rows.append(PaddedDestRowView(mat, lengths[:, d]))
            inflight: deque = deque()

            def collect(r, done):
                lo, hi = plan.round_slice(r)
                for shard in done.addressable_shards:
                    # zero-copy alias of the CPU shard
                    local = np.asarray(shard.data)[0]  # noqa: PY13
                    if use_words:
                        local = local.view(np.uint8)
                    dest[shard_pos(shard)][:, lo:hi] = local
                self.rounds_executed += 1
                if on_round is not None:
                    on_round(r, lo, hi, rows)

            window = max(1, int(window_rounds))
            for r in range(plan.rounds):
                inflight.append((r, fn(garr, np.int32(r))))
                if len(inflight) >= window:
                    collect(*inflight.popleft())
            while inflight:
                collect(*inflight.popleft())

        if self.verify_integrity:
            for d in range(D):
                row = rows[d]
                for s in sorted(src):
                    n = int(lengths[s, d])
                    sent = src[s].stream(d, n)
                    got = row[s]
                    if not np.array_equal(got, sent):
                        self.integrity_failures += 1
                        raise ExchangeIntegrityError(
                            s, d,
                            zlib.crc32(memoryview(sent)),
                            zlib.crc32(memoryview(got)),
                        )
        # the device path avoids everything the zero-copy host path
        # avoided (assembly joins + per-pair tobytes on receive), so it
        # carries that counter too — plus its own H2D counter above for
        # the staging matrices only this path eliminates
        sent = sum(int(lengths[s].sum()) for s in src)
        counter("exchange_copy_bytes_avoided_total").inc(
            sent + 2 * int(lengths.sum())
        )
        self.device_exchanges += 1
        self.payload_bytes_moved += plan.payload_bytes
        self.padded_bytes_moved += plan.moved_bytes
        return HostLocalStreams(rows, frozenset(range(D)))

    def _run_tile_rounds(self, plan: ExchangePlan, fill_round,
                         collect_round) -> set:
        """The ONE tile-round engine both byte paths share:
        ``fill_round(r)`` stages round ``r``'s [D, D, tile] host
        matrix, ``collect_round(r, d, local)`` consumes destination
        ``d``'s received [D, tile] slab for round ``r``.  Keeps the
        bounded in-flight window (rounds collect FIFO, so round
        indices pair correctly with completions) and returns the set
        of destinations addressable on this host."""
        D = self.n_devices
        # our own staging arrays: safe to donate, halves HBM per round
        fn, sharding = _a2a_fn(self.mesh, D, plan.tile_bytes, True)
        inflight: deque = deque()
        filled_dsts: set = set()

        def collect(r, done):
            # pull each destination's local shard (on a pod each host
            # pulls only its own shard)
            for shard in done.addressable_shards:
                d = shard.index[0].start \
                    if shard.index[0].start is not None else 0
                filled_dsts.add(d)
                local = np.asarray(shard.data)[0]  # [D, tile]
                collect_round(r, d, local)

        multi = jax.process_count() > 1
        if multi:
            local_rows = np.array([
                i for i, dev in enumerate(self.devices)
                if dev.process_index == jax.process_index()
            ])
        for r in range(plan.rounds):
            mat = fill_round(r)
            if multi:
                # multi-controller: a process may only place its own
                # devices' shards (device_put of a global array would
                # reject the non-addressable ones)
                garr = jax.make_array_from_process_local_data(
                    sharding, mat[local_rows], (D, D, plan.tile_bytes)
                )
            else:
                garr = jax.device_put(mat, sharding)
            inflight.append((r, fn(garr)))
            self.rounds_executed += 1
            if len(inflight) >= self.max_rounds_in_flight:
                collect(*inflight.popleft())
        while inflight:
            collect(*inflight.popleft())
        self.payload_bytes_moved += plan.payload_bytes
        self.padded_bytes_moved += plan.moved_bytes
        return filled_dsts

    def _verify_rows(self, src, src_offs, rows, filled_dsts,
                     lengths) -> None:
        """Integrity check for the zero-copy path: same scope as
        :meth:`_verify` (pairs whose source AND destination are
        addressable here), comparing views without materializing."""
        for d in sorted(filled_dsts):
            row = rows[d]
            for s in sorted(src):
                o = int(src_offs[s][d])
                n = int(lengths[s, d])
                sent = src[s][o : o + n]
                got = row[s]
                if not np.array_equal(got, sent):
                    self.integrity_failures += 1
                    raise ExchangeIntegrityError(
                        s, d,
                        zlib.crc32(memoryview(sent)),
                        zlib.crc32(memoryview(got)),
                    )

    def _verify(self, streams, result, filled_dsts,
                local_sources=None) -> None:
        """End-to-end integrity: a chip/link fault inside a collective
        corrupts silently (no per-channel CQ error to observe), so
        received streams are compared against what the source enqueued
        and mismatches surface as retryable transport failures.  Direct
        comparison beats hashing both sides (early exit, no
        collisions); CRCs are computed only for the error message.
        Scope: pairs whose source AND destination are addressable from
        this process — for a cross-host pair neither endpoint holds
        both byte strings (verifying those would need the CRC to ride
        the exchange)."""
        local_srcs = local_sources if local_sources is not None else {
            i for i, dev in enumerate(self.devices)
            if dev.process_index == jax.process_index()
        }
        for d in sorted(filled_dsts):
            for s in sorted(local_srcs):
                if result[d][s] != streams[s][d]:
                    self.integrity_failures += 1
                    raise ExchangeIntegrityError(
                        s, d,
                        zlib.crc32(streams[s][d]),
                        zlib.crc32(result[d][s]),
                    )

    # -- on-device exchange (arrays already in HBM) -------------------------
    def a2a(self, x: jax.Array, donate: bool = False) -> jax.Array:
        """All-to-all a device-resident [D, D, C] uint8 array (sharded or
        shardable over the mesh): returns [D, S, C] with out[d, s] =
        x[s, d].  No host round-trip — the pure ICI path used when map
        outputs already live in HBM arenas.

        Pass ``donate=True`` ONLY when the caller gives up ``x``: XLA
        then reuses its buffer and ``x`` becomes invalid afterwards."""
        D = self.n_devices
        if x.ndim != 3 or x.shape[0] != D or x.shape[1] != D:
            raise ValueError(f"expected [D={D}, D, C] array, got {x.shape}")
        fn, sharding = _a2a_fn(self.mesh, D, int(x.shape[2]), donate)
        if not hasattr(x, "sharding") or x.sharding != sharding:
            x = jax.device_put(x, sharding)
        return fn(x)

    def stats(self) -> Dict[str, int]:
        return {
            "rounds_executed": self.rounds_executed,
            "payload_bytes_moved": self.payload_bytes_moved,
            "padded_bytes_moved": self.padded_bytes_moved,
            "integrity_failures": self.integrity_failures,
            "device_exchanges": self.device_exchanges,
        }
