"""Block resolver: commits map outputs into HBM arenas, serves local reads.

Analog of RdmaShuffleBlockResolver + RdmaMappedFile + RdmaWrapperShuffleData
(SURVEY.md §2 rows 3, 5, 6): where the reference intercepts
``writeIndexFileAndCommit`` to mmap+register the shuffle data file and
build the per-reduce-partition location table
(RdmaMappedFile.java:99-171), here ``commit_map_output`` stages the
serialized partition bytes into a registered device segment and fills
the ``MapTaskOutput`` table with (offset, length, mkey) entries.

Local partitions are served straight from the arena without touching the
transport (reference: getLocalRdmaPartition,
RdmaShuffleBlockResolver.scala:73-78).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from sparkrdma_tpu.memory.arena import ArenaManager, DeviceSegment
from sparkrdma_tpu.memory.device_arena import ROW_BYTES as _ROW_BYTES
from sparkrdma_tpu.shuffle.map_output import MapTaskOutput
from sparkrdma_tpu.skew.splitter import (
    collapse_sub_locations,
    is_split_marker,
    make_marker,
)
from sparkrdma_tpu.transport.node import Node
from sparkrdma_tpu.utils.dbglock import dbg_lock
from sparkrdma_tpu.utils.types import BlockLocation

logger = logging.getLogger(__name__)

# skew sub-block table layout (skew/splitter.py): a split partition's
# primary row is a marker naming aux rows past the logical partition
# count; these helpers keep every commit path emitting that shape
# identically.


def _split_extra(split_spans) -> int:
    """How many aux table rows a commit's split plan needs."""
    return sum(len(v) for v in split_spans.values()) if split_spans else 0


def _put_partition_entry(
    mto: MapTaskOutput, pid: int, off: int, n: int, mkey: int,
    spans, aux: int,
) -> int:
    """Install partition ``pid``'s table entry at payload (off, n) in
    segment ``mkey``: an ordinary location, or — when ``spans`` carries
    the partition's sub-block plan — a marker plus one aux row per
    sub-span.  Returns the advanced aux cursor."""
    if n == 0:
        mto.put(pid, BlockLocation.EMPTY)
        return aux
    if spans:
        mto.put(pid, make_marker(aux, len(spans)))
        for rel, ln in spans:
            mto.put(aux, BlockLocation(off + rel, ln, mkey))
            aux += 1
        return aux
    mto.put(pid, BlockLocation(off, n, mkey))
    return aux


def _resolve_marker(mto: MapTaskOutput, loc: BlockLocation) -> BlockLocation:
    """Collapse a sub-block marker for LOCAL serving: the sub-spans
    tile the partition payload contiguously in one segment, so the
    local read is exactly the unsplit block."""
    if not is_split_marker(loc):
        return loc
    subs = mto.get_locations(loc.address, loc.address + loc.length - 1)
    return collapse_sub_locations(subs)


class ChunkedPayload:
    """Lazily-produced partition bytes for the commit path: total
    length known up front, chunks materialized one at a time.  Lets a
    spilled map output stream into the commit target (host buffer or
    data file) without ever being fully resident in RAM."""

    __slots__ = ("length", "chunks_fn")

    def __init__(self, length: int, chunks_fn):
        self.length = length
        self.chunks_fn = chunks_fn  # () -> Iterator[bytes]


def _payload_len(p) -> int:
    return p.length if isinstance(p, ChunkedPayload) else len(p)


def _payload_chunks(p):
    if isinstance(p, ChunkedPayload):
        yield from p.chunks_fn()
    elif len(p):  # bytes OR ndarray views (no ndarray bool())
        yield p


class _ShuffleData:
    """Per-shuffle write-side state on one executor (the
    RdmaWrapperShuffleData analog)."""

    def __init__(self, shuffle_id: int, num_partitions: int):
        self.shuffle_id = shuffle_id
        self.num_partitions = num_partitions
        # map_id -> (output table, device segment)
        self.outputs: Dict[int, Tuple[MapTaskOutput, DeviceSegment]] = {}


class ShuffleBlockResolver:
    """Executor-local registry of committed map outputs."""

    def __init__(self, arena: ArenaManager, node: Optional[Node] = None,
                 stage_to_device: bool = True, staging_pool=None,
                 file_backed_threshold: int = 0,
                 spill_dir: Optional[str] = None,
                 lazy_staging: bool = False,
                 write_block_size: int = 8 << 20,
                 direct_io: str = "auto",
                 tier_store=None):
        self.arena = arena
        self.node = node
        self.stage_to_device = stage_to_device
        # residency manager for file-backed commits (memory/tier.py):
        # when wired, those commits register lazily per span and serve
        # through the hot/cold tiers; None keeps the eager whole-output
        # mmap registration
        self.tier_store = tier_store
        # conf directIO: "off" keeps file-backed READS on the page-
        # cache mmap path too (O_DIRECT bypasses the cache; repeated
        # reads of one block would hit disk every time)
        self.direct_io = direct_io
        # ODP analog (RdmaShuffleConf.scala:68-83,
        # RdmaBufferManager.java:103-110): commits stay in host memory;
        # the first device-plane touch stages the segment into the HBM
        # arena on demand (ensure_staged), optionally swept ahead by
        # prefetch_shuffle (RdmaMappedFile.java:158-168's odp prefetch)
        self.lazy_staging = lazy_staging
        # ranks BELOW the arena/device-arena locks it calls into while
        # staging a segment (ensure_staged holds it across the
        # alloc + write + replace sequence)
        self._stage_lock = dbg_lock("resolver.stage", 32)
        self.staging_pool = staging_pool  # pooled host buffers for concat
        # persistent per-device HBM arena (set when the executor is
        # attached to a collective network); commits then land as arena
        # spans with ROW_BYTES-aligned partitions so the exchange
        # coordinator can row-gather them
        self.device_arena = None
        # commits >= this many bytes go to an mmapped file segment (the
        # RdmaMappedFile path); 0 disables the size trigger — but a
        # writer whose output spilled still commits file-backed via
        # ``prefer_file_backed`` (its data is already on disk)
        self.file_backed_threshold = file_backed_threshold
        self.spill_dir = spill_dir
        # arena-path commits split into segments of at most this many
        # bytes (the reference's chunked mmap+MR registration,
        # RdmaMappedFile.java:95-171): bounded span sizes keep a
        # fragmented arena allocatable and large map outputs from
        # needing one contiguous extent
        self.write_block_size = max(int(write_block_size), 1)
        self._shuffles: Dict[int, _ShuffleData] = {}  # guarded-by: _lock
        self._lock = dbg_lock("resolver.shuffles", 34)

    @property
    def commit_align(self) -> int:
        """Partition-offset alignment writers must honor in assembled
        commits: arena-resident blocks are row-gathered by the
        collective plane, so their offsets must be ROW_BYTES-aligned
        (unaligned blocks still read correctly — they just fall back to
        the host path).  Lazy commits align too: they may be staged
        into the arena later."""
        if self.device_arena is not None and (
                self.stage_to_device or self.lazy_staging):
            return _ROW_BYTES
        return 1

    def _alloc_span_or_none(self, total: int, shuffle_id: int,
                            map_id: int):
        """Arena span for a commit, or None when the budget is
        exhausted — the commit then degrades to a host-resident
        segment instead of failing the write (the larger-than-HBM
        shuffle contract; lazy staging may promote it later)."""
        try:
            return self.device_arena.alloc(max(total, 1))
        except MemoryError:
            logger.warning(
                "device arena full: committing shuffle=%d map=%d "
                "(%dB) host-resident", shuffle_id, map_id, total,
            )
            return None

    # -- lazy staging (the ODP page-fault path) ------------------------------
    def ensure_staged(self, mkey: int):
        """Stage a host-committed segment into the device arena on
        demand, keeping its mkey (published locations stay valid).
        Returns the (possibly already) arena-backed segment, or None
        when this block cannot ride the device plane."""
        if not self.lazy_staging or self.device_arena is None:
            return None
        with self._stage_lock:
            seg = self.arena.get(mkey)
            if seg is None:
                return None
            if getattr(seg, "span", None) is not None:
                return seg  # already staged (racing reader won)
            arr = getattr(seg, "array", None)
            if not isinstance(arr, np.ndarray) or arr.dtype != np.uint8:
                return None  # not host bytes (already a device array)
            span = self.device_arena.alloc(max(int(arr.shape[0]), 1))
            try:
                self.device_arena.write(span, arr)
                new_seg = self.arena.replace_with_span(mkey, span)
            except BaseException:
                span.free()
                raise
            if new_seg is not None:
                # swap the shuffle-output entry too, dropping the last
                # reference to the host copy (local reads now serve from
                # the arena; the host bytes free once views die)
                with self._lock:
                    sd = self._shuffles.get(new_seg.shuffle_id)
                    if sd is not None:
                        for _mid, (_mto, segs) in sd.outputs.items():
                            if mkey in segs:
                                segs[mkey] = new_seg
                                break
            return new_seg

    def prefetch_shuffle(self, shuffle_id: int) -> int:
        """Stage every host-resident segment of one shuffle ahead of
        the reads (the ODP prefetch sweep, RdmaMappedFile.java:158-168).
        Returns how many of the shuffle's segments are arena-resident
        after the sweep."""
        if not self.lazy_staging or self.device_arena is None:
            return 0
        with self._lock:
            sd = self._shuffles.get(shuffle_id)
            mkeys = (
                [mk for _, segs in sd.outputs.values() for mk in segs]
                if sd else []
            )
        staged = 0
        for mkey in mkeys:
            try:
                seg = self.ensure_staged(mkey)
            except MemoryError:
                # arena full: skip — the segment keeps serving from
                # host, exactly like the on-demand path's fallback
                logger.warning(
                    "prefetch: staging mkey=%d skipped (arena full)", mkey
                )
                continue
            if seg is not None:
                staged += 1
        return staged

    def _get_or_create(self, shuffle_id: int, num_partitions: int) -> _ShuffleData:
        with self._lock:
            sd = self._shuffles.get(shuffle_id)
            if sd is None:
                sd = self._shuffles.setdefault(
                    shuffle_id, _ShuffleData(shuffle_id, num_partitions)
                )
            return sd

    # -- write side ---------------------------------------------------------
    def commit_map_output(
        self,
        shuffle_id: int,
        map_id: int,
        partition_bytes: Sequence,
        prefer_file_backed: bool = False,
        split_spans: Optional[Dict[int, List[Tuple[int, int]]]] = None,
    ) -> MapTaskOutput:
        """Stage one map task's serialized partitions into a registered
        segment and build its location table.  Each partition payload is
        ``bytes`` or a :class:`ChunkedPayload` (spill-merge commits
        stream their chunks — nothing is pre-joined in RAM).

        ``prefer_file_backed`` routes the commit to the mmap path even
        below ``file_backed_threshold`` — set by writers whose output
        already spilled to disk, so the commit never re-materializes in
        one in-memory buffer what spilling was bounding.

        ``split_spans`` is the writer's skew split plan
        (:func:`sparkrdma_tpu.skew.splitter.plan_commit_splits`):
        ``{pid: [(rel_off, rel_len), ...]}`` sub-block spans within the
        partition payload.  Those partitions register a marker entry
        plus one aux table row per sub-block; the payload bytes land
        exactly where they would have anyway."""
        num_partitions = len(partition_bytes)
        sd = self._get_or_create(shuffle_id, num_partitions)
        use_arena = self.stage_to_device and self.device_arena is not None
        # collective plane: partition starts row-aligned for the gather
        align = self.commit_align
        sizes = [_payload_len(b) for b in partition_bytes]
        total = 0
        for n in sizes:
            total = (total + align - 1) // align * align + n
        if prefer_file_backed or (
            self.file_backed_threshold and total >= self.file_backed_threshold
        ):
            return self._commit_file_backed(
                sd, shuffle_id, map_id, partition_bytes, total,
                split_spans=split_spans,
            )
        # arena commits split into write-block-sized segments (chunked
        # registration, RdmaMappedFile.java:95-171): greedy groups of
        # whole partitions, a partition larger than the block gets its
        # own segment.  Host/jnp commits keep one segment.
        if use_arena and total > self.write_block_size:
            groups: List[List[int]] = [[]]
            gsize = 0
            for pid, n in enumerate(sizes):
                an = (gsize + align - 1) // align * align + n - gsize
                if groups[-1] and gsize + an > self.write_block_size:
                    groups.append([pid])
                    gsize = n
                else:
                    groups[-1].append(pid)
                    gsize += an
        else:
            groups = [list(range(num_partitions))]
        mto = MapTaskOutput(num_partitions + _split_extra(split_spans))
        aux = num_partitions  # sub-block rows allocated in pid order
        segs: Dict[int, DeviceSegment] = {}
        try:
            for pids in groups:
                g_bytes = [partition_bytes[p] for p in pids]
                g_offsets: List[Tuple[int, int]] = []
                g_total = 0
                for p in pids:
                    g_total = (g_total + align - 1) // align * align
                    g_offsets.append((g_total, sizes[p]))
                    g_total += sizes[p]
                seg = self._commit_partitions_segment(
                    shuffle_id, map_id, g_bytes, g_offsets, g_total,
                    use_arena,
                )
                segs[seg.mkey] = seg
                for p, (o, n) in zip(pids, g_offsets):
                    aux = _put_partition_entry(
                        mto, p, o, n, seg.mkey,
                        split_spans.get(p) if split_spans else None, aux,
                    )
        except BaseException:
            for seg in segs.values():
                if self.node is not None:
                    self.node.unregister_block_store(seg.mkey)
                self.arena.release(seg.mkey)
            raise
        # install, releasing any superseded segments from a task retry
        self._install(sd, map_id, mto, segs)
        return mto

    def _commit_partitions_segment(
        self, shuffle_id: int, map_id: int, partition_bytes: Sequence,
        offsets: List[Tuple[int, int]], total: int, use_arena: bool,
    ) -> DeviceSegment:
        """Assemble one group of partitions into a buffer and register
        it (arena span, device array, or host bytes — with arena-full
        and pool-exhausted fallbacks)."""
        staging_buf = None
        if self.stage_to_device and self.staging_pool is not None and total > 0:
            # serialize through the pooled, page-aligned native buffer —
            # the registered-staging path (RdmaBuffer analog).  Host-only
            # commits deliberately AVOID the pool: their segments serve
            # zero-copy read views, and pooled memory may be recycled
            # while a view is still alive; plain numpy buffers are kept
            # alive by the views themselves.
            try:
                staging_buf = self.staging_pool.alloc(total)
                buf = staging_buf.view
            except MemoryError:
                # pool budget exhausted (keepalives pin buffers for the
                # shuffle's lifetime): fall back to a plain host buffer
                # rather than failing the commit
                buf = np.empty(max(total, 1), dtype=np.uint8)
        else:
            buf = np.empty(max(total, 1), dtype=np.uint8)
        for (off, _n), b in zip(offsets, partition_bytes):
            for chunk in _payload_chunks(b):
                m = len(chunk)
                buf[off : off + m] = np.frombuffer(chunk, np.uint8)
                off += m
        span = (
            self._alloc_span_or_none(total, shuffle_id, map_id)
            if use_arena else None
        )
        arena_full = use_arena and span is None
        use_arena = span is not None
        if arena_full and staging_buf is not None:
            # nothing zero-copy aliases a host fallback segment, so
            # copy once and release the pooled buffer now instead of
            # pinning it for the shuffle's lifetime
            buf = buf[: max(total, 1)].copy()
            staging_buf.free()
            staging_buf = None
        try:
            if use_arena:
                try:
                    self.device_arena.write(span, buf[: max(total, 1)])
                    seg = self.arena.register_arena_span(
                        span, shuffle_id=shuffle_id
                    )
                except BaseException:
                    span.free()
                    raise
                if staging_buf is not None:
                    staging_buf.free()
                    staging_buf = None
            else:
                if self.stage_to_device and not arena_full:
                    import jax.numpy as jnp

                    array = jnp.asarray(buf[: max(total, 1)])
                else:
                    # arena-full commits stay on the HOST (an unbudgeted
                    # device_put would defeat the arena's HBM budget)
                    array = np.asarray(buf[: max(total, 1)])
                # PJRT may zero-copy alias page-aligned host buffers: the
                # staging buffer must live until the segment is released,
                # not be returned to the pool while the device array can
                # still read through it
                seg = self.arena.register(
                    array, shuffle_id=shuffle_id, keepalive=staging_buf,
                    # host commits are plain numpy (never pooled): reads
                    # may serve refcount-protected views
                    zero_copy_ok=(
                        not self.stage_to_device and staging_buf is None
                    ),
                )
        except BaseException:
            # register never took ownership: return the buffer ourselves
            if staging_buf is not None:
                staging_buf.free()
            raise
        if self.node is not None:
            self.node.register_block_store(seg.mkey, self.arena)
        return seg

    def commit_assembled(
        self, shuffle_id: int, map_id: int, buf: np.ndarray,
        ranges: Sequence[Tuple[int, int]],
        split_spans: Optional[Dict[int, List[Tuple[int, int]]]] = None,
    ) -> MapTaskOutput:
        """Commit a writer-assembled contiguous buffer: ``ranges[pid] =
        (offset, length)`` within ``buf``.  The writer gathered records
        straight into ``buf``, so this path adds NO further copy on the
        host plane (the buffer itself becomes the registered segment);
        device staging is the one ``jnp.asarray`` transfer.
        ``split_spans`` as in :meth:`commit_map_output`."""
        sd = self._get_or_create(shuffle_id, len(ranges))
        total = int(buf.shape[0])
        if self.file_backed_threshold and total >= self.file_backed_threshold:
            return self._commit_file_backed(
                sd, shuffle_id, map_id,
                [buf[off : off + n] for off, n in ranges], total,
                split_spans=split_spans,
            )
        span = (
            self._alloc_span_or_none(total, shuffle_id, map_id)
            if self.stage_to_device and self.device_arena is not None
            else None
        )
        if span is not None:
            try:
                self.device_arena.write(span, buf)
                seg = self.arena.register_arena_span(
                    span, shuffle_id=shuffle_id
                )
            except BaseException:
                span.free()
                raise
        else:
            if self.stage_to_device and self.device_arena is None:
                import jax.numpy as jnp

                array = jnp.asarray(buf if total else buf[:1])
                zero_copy = False
            else:
                # host plane, or arena-full fallback (an unbudgeted
                # device_put would defeat the arena's HBM budget): the
                # writer hands buf over, so views may serve zero-copy
                array = buf if total else np.zeros(1, np.uint8)
                zero_copy = True
            seg = self.arena.register(
                array, shuffle_id=shuffle_id, zero_copy_ok=zero_copy
            )
        if self.node is not None:
            self.node.register_block_store(seg.mkey, self.arena)
        mto = MapTaskOutput(len(ranges) + _split_extra(split_spans))
        aux = len(ranges)
        for pid, (off, n) in enumerate(ranges):
            aux = _put_partition_entry(
                mto, pid, off, n, seg.mkey,
                split_spans.get(pid) if split_spans else None, aux,
            )
        self._install(sd, map_id, mto, seg)
        return mto

    def _commit_file_backed(
        self, sd: "_ShuffleData", shuffle_id: int, map_id: int,
        partition_bytes: Sequence, total: int,
        split_spans: Optional[Dict[int, List[Tuple[int, int]]]] = None,
    ) -> MapTaskOutput:
        """Large-output commit: stream the map task's partitions into
        one data file and serve it through the tiered block store
        (memory/tier.py) when one is wired — the file stays UNMAPPED
        until a span is resolved or prefetched, hot blocks live in
        budgeted pooled rows, cold reads hit the disk.  Without a tier
        store, the legacy eager path registers the whole read-only
        mmap up front (the RdmaMappedFile mmap+register shape).
        Streamed chunk-by-chunk either way, and NOT debited against
        the arena byte budget — the whole point is holding shuffles
        larger than the in-memory arena."""
        from sparkrdma_tpu.memory.mapped_file import MappedFile

        tiered = self.tier_store is not None
        mf = MappedFile(
            (chunk for b in partition_bytes for chunk in _payload_chunks(b)),
            directory=self.spill_dir,
            direct_write=self.direct_io != "off",
            defer_map=tiered,
        )
        mf.direct_read_enabled = self.direct_io != "off"
        spans: List[Tuple[int, int]] = []
        off = 0
        for b in partition_bytes:
            n = _payload_len(b)
            spans.append((off, n))
            off += n
        try:
            if tiered:
                seg = self.tier_store.adopt(
                    mf, spans, max(total, 1), shuffle_id, self.arena
                )
            else:
                # mmap reads may serve views: MappedFile.free defers
                # closing the mapping while views are exported
                # (BufferError path)
                seg = self.arena.register(
                    mf.array, shuffle_id=shuffle_id, keepalive=mf,
                    budgeted=False, zero_copy_ok=True,
                )
        except BaseException:
            mf.free()
            raise
        if self.node is not None:
            self.node.register_block_store(seg.mkey, self.arena)
        # the tier store keeps whole partitions as its residency blocks
        # (sub-block reads are in-block sub-ranges, which it already
        # serves with promotion), so split plans change only the table
        mto = MapTaskOutput(len(partition_bytes) + _split_extra(split_spans))
        aux = len(partition_bytes)
        for pid, (off, n) in enumerate(spans):
            aux = _put_partition_entry(
                mto, pid, off, n, seg.mkey,
                split_spans.get(pid) if split_spans else None, aux,
            )
        self._install(sd, map_id, mto, seg)
        return mto

    def commit_spilled_files(
        self, shuffle_id: int, map_id: int, files: Sequence,
    ) -> MapTaskOutput:
        """ZERO-COPY commit of per-partition spill files: each file
        registers directly as that partition's mapped segment (the
        spill file IS the shuffle file — no consolidation rewrite, the
        round-4 answer to the writeback-throttled double write).
        ``files[pid]`` is ``(path, logical_length)`` or None for an
        empty partition.  Takes ownership of every path (unlinked on
        segment release, or here on failure/emptiness)."""
        from sparkrdma_tpu.memory.mapped_file import MappedFile

        sd = self._get_or_create(shuffle_id, len(files))
        mto = MapTaskOutput(len(files))
        segs: Dict[int, DeviceSegment] = {}
        done = 0
        try:
            for pid, ent in enumerate(files):
                done = pid + 1
                if ent is None:
                    mto.put(pid, BlockLocation.EMPTY)
                    continue
                path, length = ent
                if length == 0:
                    mto.put(pid, BlockLocation.EMPTY)
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue
                tiered = self.tier_store is not None
                mf = MappedFile.from_path(path, length, defer_map=tiered)
                mf.direct_read_enabled = self.direct_io != "off"
                try:
                    if tiered:
                        # one block per spill file: residency (and the
                        # lazy per-span registration) managed by the
                        # tier store like any file-backed commit
                        seg = self.tier_store.adopt(
                            mf, [(0, length)], length, shuffle_id,
                            self.arena,
                        )
                    else:
                        seg = self.arena.register(
                            mf.array, shuffle_id=shuffle_id, keepalive=mf,
                            budgeted=False, zero_copy_ok=True,
                        )
                except BaseException:
                    mf.free()
                    raise
                if self.node is not None:
                    self.node.register_block_store(seg.mkey, self.arena)
                segs[seg.mkey] = seg
                mto.put(pid, BlockLocation(0, length, seg.mkey))
        except BaseException:
            for seg in segs.values():
                if self.node is not None:
                    self.node.unregister_block_store(seg.mkey)
                self.arena.release(seg.mkey)
            # ownership contract: unlink the files this commit never
            # reached (the failed one cleans itself up via mf.free)
            for ent in files[done:]:
                if ent is not None:
                    try:
                        os.unlink(ent[0])
                    except OSError:
                        pass
            raise
        self._install(sd, map_id, mto, segs)
        return mto

    def _install(self, sd: "_ShuffleData", map_id: int,
                 mto: MapTaskOutput, segs) -> None:
        """Publish (mto, {mkey: segment}) as map_id's output, releasing
        any superseded segments from a task retry/speculation.  A
        single segment may be passed bare."""
        if not isinstance(segs, dict):
            segs = {segs.mkey: segs}
        with self._lock:
            prior = sd.outputs.get(map_id)
            sd.outputs[map_id] = (mto, segs)
        if prior is not None:
            for old_seg in prior[1].values():
                if self.node is not None:
                    self.node.unregister_block_store(old_seg.mkey)
                self.arena.release(old_seg.mkey)

    # -- read side (local short-circuit) ------------------------------------
    def get_local_block(self, shuffle_id: int, map_id: int, reduce_id: int):
        """Serve one partition block as a bytes-LIKE payload — host
        segments hand back zero-copy chunk views (read-only ndarray /
        memoryview over the registered buffer), device segments the
        landed host array; nothing on the serve path materializes
        ``bytes`` (the transport sends any buffer view scatter-gather,
        and the deserializers consume views directly)."""
        with self._lock:
            sd = self._shuffles.get(shuffle_id)
            entry = sd.outputs.get(map_id) if sd else None
        if entry is None:
            raise KeyError(
                f"no committed output for shuffle={shuffle_id} map={map_id}"
            )
        mto, segs = entry
        loc = _resolve_marker(mto, mto.get_location(reduce_id))
        if loc.is_empty:
            return b""
        return segs[loc.mkey].read(loc.address, loc.length)

    def get_local_blocks(
        self, shuffle_id: int, map_id: int, reduce_ids
    ) -> List:
        """Serve many of one map output's partition blocks with ONE
        backing-store read (``Segment.read_many`` batches the
        device→host transfer — the bulk plane reads every partition of
        every map, and a per-block fetch pays a device round-trip
        each).  Blocks are chunk VIEWS of the landed cluster buffers,
        never per-block ``bytes`` joins (see
        :func:`sparkrdma_tpu.memory.arena._read_spans_clustered`).
        Empty partitions come back as ``b""``."""
        with self._lock:
            sd = self._shuffles.get(shuffle_id)
            entry = sd.outputs.get(map_id) if sd else None
        if entry is None:
            raise KeyError(
                f"no committed output for shuffle={shuffle_id} map={map_id}"
            )
        mto, segs = entry
        locs = [
            _resolve_marker(mto, mto.get_location(r)) for r in reduce_ids
        ]
        # one batched read per backing segment (multi-segment map
        # outputs exist under write_block_size splitting)
        by_seg: Dict[int, List[Tuple[int, int]]] = {}
        for i, loc in enumerate(locs):
            if not loc.is_empty:
                by_seg.setdefault(loc.mkey, []).append(
                    (i, loc.address, loc.length)
                )
        out: List = [b""] * len(locs)
        for mkey, items in by_seg.items():
            blocks = segs[mkey].read_many([(a, ln) for _i, a, ln in items])
            for (i, _a, _ln), blk in zip(items, blocks):
                out[i] = blk
        return out

    def num_partitions(self, shuffle_id: int) -> int:
        with self._lock:
            sd = self._shuffles.get(shuffle_id)
        if sd is None:
            raise KeyError(f"shuffle {shuffle_id} has no committed outputs")
        return sd.num_partitions

    def map_ids(self, shuffle_id: int) -> List[int]:
        """This executor's committed map ids for one shuffle, sorted
        (the canonical order of the bulk-exchange stream builder)."""
        with self._lock:
            sd = self._shuffles.get(shuffle_id)
            return sorted(sd.outputs.keys()) if sd else []

    def get_map_output(self, shuffle_id: int, map_id: int) -> Optional[MapTaskOutput]:
        with self._lock:
            sd = self._shuffles.get(shuffle_id)
            entry = sd.outputs.get(map_id) if sd else None
        return entry[0] if entry else None

    # -- lifecycle ----------------------------------------------------------
    def remove_shuffle(self, shuffle_id: int) -> None:
        """Dispose segments + tables (reference: removeDataByMap/dispose)."""
        with self._lock:
            sd = self._shuffles.pop(shuffle_id, None)
        if sd is not None:
            for _mto, segs in sd.outputs.values():
                for seg in segs.values():
                    if self.node is not None:
                        self.node.unregister_block_store(seg.mkey)
            self.arena.release_shuffle(shuffle_id)

    def stop(self) -> None:
        with self._lock:
            ids = list(self._shuffles.keys())
        for sid in ids:
            self.remove_shuffle(sid)
