"""Bulk-synchronous collective shuffle: the multi-host data plane.

The in-process collective plane (tests/collective_read_fixture.py) batches
reader fetches into all_to_all rounds opportunistically; across HOSTS
that requires every process to launch identical collectives, so this
module runs the exchange bulk-synchronously instead — the natural mode
for mesh-resident SPMD jobs (SURVEY.md §7 "pull → collective
inversion"):

1. map phase: every executor writes + publishes normally (the TCP
   control plane carries publishes to the driver across processes),
2. barrier: each host asks the driver for the exchange PLAN
   (FetchExchangePlanMsg); the driver answers once every registered map
   has published — with the canonical host order, the full
   (src × dst) stream-length matrix, and the requester's destination
   manifest,
3. one collective: every host concatenates its local blocks into
   per-destination streams and calls ``TileExchange.exchange_bytes``
   with the agreed lengths — all processes compile the same programs
   and the bytes ride ICI/DCN,
4. each host slices its destination row by the manifest and feeds the
   blocks to the serializer.

Partition ownership is ``reduce_id % n_hosts`` over the plan's
canonical host order — the bulk-mode convention the driver and every
executor share.

The reference has no analog mode (its reducers pull asynchronously);
this is the TPU-native answer to scaling the shuffle the way NCCL/MPI
backends scale — symmetric collectives instead of per-pair streams.
"""

from __future__ import annotations

import threading
import time
from typing import Iterator, List, Optional

import numpy as np

from sparkrdma_tpu.metrics import counter, gauge
from sparkrdma_tpu.parallel.exchange import (
    PaddedSourceRow,
    TileExchange,
    row_offsets,
)
from sparkrdma_tpu.utils.dbglock import dbg_condition, dbg_lock
from sparkrdma_tpu.rpc.messages import FetchExchangePlanMsg
from sparkrdma_tpu.shuffle.reader import (
    FetchFailedError,
    MetadataFetchFailedError,
    flush_read_metrics,
)


class BulkShuffleSession:
    """In-process contribution barrier: when several participating
    executors share ONE process (tests, local[*] mode), their rows must
    ride a single collective — each contributes its source row, the
    last contributor runs the exchange, everyone shares the result.

    Across processes this object is unnecessary: the collective itself
    is the barrier (each process fills only its own addressable rows).
    """

    def __init__(self, exchange: TileExchange, n_hosts: int,
                 timeout_s: float = 120.0, out_alloc=None,
                 window_rounds: int = 0):
        self.exchange = exchange
        self.n_hosts = n_hosts
        self.timeout_s = timeout_s
        # optional pooled allocator for destination rows (e.g. a
        # StagingPool.alloc_gc): zero-copy results then recycle their
        # buffers once the last consumer view dies
        self.out_alloc = out_alloc
        # in-flight collective window for PADDED (device-native) rounds
        # (conf deviceExchangeWindowRounds; 0 = one fused program)
        self.window_rounds = int(window_rounds)
        self._cv = dbg_condition("bulk.session", 26)
        self._rows = {}  # guarded-by: _cv
        self._cbs: list = []  # per-generation on_round callbacks
        self._lengths = None  # guarded-by: _cv
        # results keyed by ROUND generation: a waiter descheduled
        # across a whole subsequent round must still read its own
        # round's outcome, not the latest
        self._results = {}
        self._gen = 0
        # explicitly keyed rounds ((shuffle_id, window) from the
        # windowed plane): CONCURRENT shuffles on one session each get
        # their own barrier instead of cross-contributing rows into a
        # shared generation
        self._keyed: dict = {}
        self._aborted = None  # sticky: a failed participant poisons all

    def abort(self, error: BaseException) -> None:
        """A participant failed before contributing: poison the
        session so waiters (and future contributors) fail immediately
        instead of riding out the barrier timeout."""
        with self._cv:
            self._aborted = error
            self._cv.notify_all()

    def run(self, me: int, row: List[bytes], lengths: np.ndarray,
            round_key=None, on_round=None):
        """Contribute source row ``me``; blocks until every host
        contributed and the one exchange ran.  Returns the shared
        result.

        ``round_key`` (e.g. ``(shuffle_id, window)``) isolates this
        round's barrier: callers that may run several shuffles
        concurrently through ONE session MUST pass it — unkeyed rounds
        share a single generation counter and would cross-contribute.

        ``on_round`` (device-native rounds only) is this contributor's
        per-round landing callback: every contributor may register one
        and the exchange fans each landed round out to ALL of them —
        that is how each in-process executor's decode overlap sees its
        own destination's completed blocks while the next round's
        collective is still in flight."""
        if round_key is not None:
            return self._run_keyed(me, row, lengths, round_key, on_round)
        with self._cv:
            if self._aborted is not None:
                raise RuntimeError(
                    "bulk exchange aborted by a failed participant"
                ) from self._aborted
            gen = self._gen
            if self._lengths is None:
                self._lengths = np.asarray(lengths)
            elif not np.array_equal(self._lengths, lengths):
                raise ValueError(
                    "contributors disagree on the lengths matrix"
                )
            if me in self._rows:
                raise ValueError(f"row {me} contributed twice")
            self._rows[me] = row
            if on_round is not None:
                self._cbs.append(on_round)
            if len(self._rows) == self.n_hosts:
                cbs, self._cbs = self._cbs, []
                try:
                    self._results[gen] = (
                        self._exchange_contributed(
                            self._rows, self._lengths,
                            on_round=_fanout(cbs),
                        ),
                        None,
                    )
                except BaseException as e:
                    self._results[gen] = (None, e)
                self._rows = {}
                self._lengths = None
                self._gen += 1
                # keep only recent rounds (waiters of gen and gen-1
                # may still be draining)
                for g in [g for g in self._results if g < gen - 1]:
                    del self._results[g]
                self._cv.notify_all()
            else:
                while self._gen == gen and self._aborted is None:
                    if not self._cv.wait(timeout=self.timeout_s):
                        raise TimeoutError(
                            f"bulk exchange barrier: not every host "
                            f"contributed within {self.timeout_s:.0f}s "
                            f"(conf spark.shuffle.tpu.bulkBarrierTimeout)"
                        )
                if self._aborted is not None:
                    raise RuntimeError(
                        "bulk exchange aborted by a failed participant"
                    ) from self._aborted
            result, error = self._results[gen]
            if error is not None:
                raise error
            return result

    def _run_keyed(self, me: int, row: List[bytes], lengths: np.ndarray,
                   key, on_round=None) -> object:
        with self._cv:
            if self._aborted is not None:
                raise RuntimeError(
                    "bulk exchange aborted by a failed participant"
                ) from self._aborted
            st = self._keyed.get(key)
            if st is None:
                st = self._keyed[key] = {
                    "rows": {}, "lengths": np.asarray(lengths),
                    "result": None, "error": None, "done": False,
                    "delivered": 0, "cbs": [],
                }
            elif not np.array_equal(st["lengths"], lengths):
                raise ValueError(
                    f"contributors disagree on the lengths matrix "
                    f"(round {key})"
                )
            if me in st["rows"]:
                raise ValueError(
                    f"row {me} contributed twice (round {key})"
                )
            st["rows"][me] = row
            if on_round is not None:
                st["cbs"].append(on_round)
            if len(st["rows"]) == self.n_hosts:
                try:
                    st["result"] = self._exchange_contributed(
                        st["rows"], st["lengths"],
                        on_round=_fanout(st["cbs"]),
                    )
                except BaseException as e:
                    st["error"] = e
                st["done"] = True
                self._cv.notify_all()
            else:
                deadline = time.monotonic() + self.timeout_s
                while not st["done"] and self._aborted is None:
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._cv.wait(timeout=left):
                        raise TimeoutError(
                            f"bulk exchange barrier (round {key}): not "
                            f"every host contributed within "
                            f"{self.timeout_s:.0f}s (conf "
                            f"spark.shuffle.tpu.bulkBarrierTimeout)"
                        )
                if self._aborted is not None:
                    raise RuntimeError(
                        "bulk exchange aborted by a failed participant"
                    ) from self._aborted
            result, error = st["result"], st["error"]
            st["delivered"] += 1
            if st["delivered"] >= self.n_hosts:
                self._keyed.pop(key, None)  # all participants served
            if error is not None:
                raise error
            return result

    def _exchange_contributed(self, rows: dict, lengths,
                              on_round=None) -> object:
        """Run the one collective over the contributed rows.  Rows come
        in three shapes: :class:`PaddedSourceRow` (the DEVICE-NATIVE
        path — one ``device_put`` per source, the collective consumes
        the padded framing directly via ``exchange_padded``),
        contiguous uint8 arrays (the host zero-copy path —
        ``exchange_into`` into destination row VIEWS), or the legacy
        per-destination ``bytes`` lists (``exchange_bytes``).  Mixed
        contributions (a mid-upgrade cluster) downgrade padded/array
        rows to the least capable shape aboard so one legacy
        participant never deadlocks the round."""
        E = self.n_hosts
        if rows and all(
            isinstance(r, PaddedSourceRow) for r in rows.values()
        ):
            return self.exchange.exchange_padded(
                lengths, dict(rows), local_sources=frozenset(rows),
                out_alloc=self._dst_alloc, on_round=on_round,
                window_rounds=self.window_rounds,
            )
        if rows and all(
            isinstance(r, np.ndarray) for r in rows.values()
        ):
            return self.exchange.exchange_into(
                lengths, dict(rows), local_sources=frozenset(rows),
                out_alloc=self._dst_alloc,
            )
        streams: list = [[b""] * E for _ in range(E)]
        for s, r in rows.items():
            if isinstance(r, PaddedSourceRow):
                streams[s] = [
                    bytes(memoryview(r.stream(d, int(lengths[s, d]))))
                    for d in range(E)
                ]
            elif isinstance(r, np.ndarray):
                offs = row_offsets(lengths[s])
                streams[s] = [
                    bytes(memoryview(
                        r[int(offs[d]):int(offs[d + 1])]
                    ))
                    for d in range(E)
                ]
            else:
                streams[s] = list(r)
        return self.exchange.exchange_bytes(
            streams, lengths=lengths, local_sources=frozenset(rows),
        )

    def _dst_alloc(self, nbytes: int) -> np.ndarray:
        """Destination-row buffer: pooled when the session was given an
        allocator, fresh numpy memory otherwise (or when the pool's
        budget is exhausted — an exchange must not fail on pool
        pressure when plain memory would serve)."""
        if self.out_alloc is not None:
            try:
                return self.out_alloc(nbytes)
            except MemoryError:
                counter("exchange_row_pool_fallbacks_total").inc()
        return np.empty(nbytes, np.uint8)


def iter_plan_blocks(plan, E: int, row):
    """Walk one exchange result row by its plan manifest: yields
    ``(source, map_id, reduce_id, block payload)`` for every block this
    host received — the ONE offset-slicing loop shared by the windowed
    pump and both bulk consumption paths (a second copy drifting on
    manifest layout would silently misalign block boundaries).  Block
    payloads are zero-copy slices of the row (uint8 views on the
    ``exchange_into`` path, ``bytes`` slices on the legacy one); every
    consumer downstream takes bytes-likes."""
    for s in range(E):
        data = row[s]
        off = 0
        for map_id, reduce_id, n in plan.manifest[s]:
            yield s, map_id, reduce_id, data[off : off + n]
            off += n


def _fanout(cbs: list):
    """Compose contributors' on_round callbacks into the ONE callback
    the exchange takes (None when nobody registered)."""
    cbs = [cb for cb in cbs if cb is not None]
    if not cbs:
        return None
    if len(cbs) == 1:
        return cbs[0]

    def on_round(rnd, lo, hi, rows):
        for cb in cbs:
            cb(rnd, lo, hi, rows)

    return on_round


def _make_round_emitter(plan, E: int, me: int, lengths, sink):
    """Per-round block emitter: the collective/decode overlap of the
    device-native exchange.

    ``exchange_padded`` calls the returned ``on_round(rnd, lo, hi,
    rows)`` after each tile round LANDS; every manifest block of this
    host's destination row that is now fully received (the valid
    prefix ``[0, hi)`` covers it) goes to the plane's round ``sink``
    as a zero-copy view — so the DecodePool deserializes round
    ``rnd``'s blocks while round ``rnd + 1``'s collective is still in
    flight.  The LAST round (``hi`` covering the longest incoming
    stream — also the fused full-shot program) is deliberately left to
    the pump: it delivers the residual as the plan window's own event,
    keeping window accounting and ``final`` semantics exactly where
    they were."""
    manifest = plan.manifest
    next_block = [0] * E      # blocks already emitted, per source
    done_off = [0] * E        # byte offset those blocks covered
    # lengths is [E, E] plan metadata, not payload
    max_len = int(np.asarray(lengths)[:, me].max()) if E else 0  # noqa: PY13

    def on_round(rnd, lo, hi, rows):
        if hi >= max_len:
            return  # final round: the pump owns this window's deliver
        view = rows[me]
        blocks = []
        for s in range(E):
            data = view[s]
            lim = min(hi, len(data))
            off = done_off[s]
            i = next_block[s]
            man = manifest[s]
            while i < len(man):
                map_id, reduce_id, n = man[i]
                if off + n > lim:
                    break
                blocks.append(
                    (s, map_id, reduce_id, data[off : off + n])
                )
                off += n
                i += 1
            next_block[s] = i
            done_off[s] = off
        if blocks:
            payload = sum(len(b) for _s, _m, _r, b in blocks)
            sink(plan, blocks, payload, next_block)

    return on_round


def _iter_residual_blocks(plan, E: int, row, emitted):
    """The blocks :func:`_make_round_emitter` did NOT deliver early
    (``emitted[s]`` = count of source ``s``'s already-emitted manifest
    prefix) — the pump delivers these as the plan window's event."""
    for s in range(E):
        data = row[s]
        off = 0
        for i, (map_id, reduce_id, n) in enumerate(plan.manifest[s]):
            if i >= emitted[s]:
                yield s, map_id, reduce_id, data[off : off + n]
            off += n


class _ShuffleWindows:
    """Per-shuffle receive state shared by every reader on one executor:
    windows of (map_id, reduce_id, block bytes) delivered by the pump,
    a final flag, and a sticky error."""

    def __init__(self):
        self._cv = dbg_condition("bulk.windows", 28)
        self._windows: List[List[tuple]] = []  # guarded-by: _cv
        # (window, t, bytes) per deliver
        self._events: List[tuple] = []  # guarded-by: _cv
        self.hosts = None   # canonical host order, pinned at window 0
        self.me = -1        # this executor's index in hosts
        self._done = False
        self._error: Optional[BaseException] = None

    def deliver(self, blocks: List[tuple], final: bool, hosts,
                me: int, payload_bytes: int) -> None:
        with self._cv:
            if self.hosts is None:
                self.hosts = tuple(hosts)
                self.me = me
            self._windows.append(blocks)
            self._events.append(
                (len(self._windows) - 1, time.monotonic(), payload_bytes)
            )
            if final:
                self._done = True
            self._cv.notify_all()
        counter("shuffle_windows_total").inc()
        counter("shuffle_window_payload_bytes_total").inc(payload_bytes)
        # resident until the plane forgets the shuffle — the occupancy
        # gauge tracks buffered windows across every active pump
        gauge("shuffle_window_occupancy").inc()

    def fail(self, err: BaseException) -> None:
        with self._cv:
            if self._error is None:
                self._error = err
            self._done = True
            self._cv.notify_all()

    def wait_beyond(self, idx: int, timeout_s: float):
        """Block until there are windows past ``idx`` (or the shuffle
        finished/failed); returns (new windows, done)."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while len(self._windows) <= idx and not self._done:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(timeout=left):
                    raise TimeoutError(
                        f"no exchange window beyond {idx} within "
                        f"{timeout_s:.0f}s"
                    )
            if self._error is not None:
                raise self._error
            return list(self._windows[idx:]), self._done

    @property
    def window_events(self) -> List[tuple]:
        with self._cv:
            return list(self._events)


class WindowedReadPlane:
    """The unified reactive device plane (readPlane=windowed).

    Reducers issue partition reads through ``manager.get_reader`` —
    the reference's reactive pull model
    (RdmaShuffleFetcherIterator.scala:241-251) — and the bytes move as
    the driver's incremental window plans land: ONE symmetric
    TileExchange collective per window per shuffle, shared by every
    reader on this executor (the window pump).  Reactive AND
    multi-process: the same plan RPCs + collectives the bulk plane
    uses across OS processes, with blocks surfacing to readers
    window-by-window while straggler maps still write.

    This supersedes the in-process-only opportunistic coordinator
    (tests/collective_read_fixture.py, now a test fixture): cross-process
    agreement on collective launches comes from the driver's window
    plans instead of per-process batching heuristics."""

    def __init__(self, manager, exchange: Optional[TileExchange] = None,
                 mesh=None, session: Optional[BulkShuffleSession] = None):
        self.manager = manager
        self._bulk = BulkExchangeReader(
            manager, exchange=exchange, mesh=mesh, session=session
        )
        self._lock = dbg_lock("bulk.plane", 24)
        self._shuffles = {}  # guarded-by: _lock

    # -- reader factory (manager.get_reader hook) ---------------------------
    def reader(self, handle, start_partition: int, end_partition: int):
        return WindowedShuffleReader(
            self, handle, start_partition, end_partition
        )

    def join(self, shuffle_id: int) -> None:
        """Start this executor's window pump for a shuffle even when it
        owns no partitions: every host in the plan must join each
        window's collective (symmetric participation), reader or not."""
        self._state(shuffle_id)

    def forget(self, shuffle_id: int) -> None:
        with self._lock:
            st = self._shuffles.pop(shuffle_id, None)
        if st is not None:
            resident = len(st.window_events)
            if resident:
                gauge("shuffle_window_occupancy").dec(resident)

    def window_events(self, shuffle_id: int) -> List[tuple]:
        """(window, completion time, payload bytes) per landed window —
        the straggler-overlap observability hook."""
        with self._lock:
            st = self._shuffles.get(shuffle_id)
        return st.window_events if st is not None else []

    def stats(self) -> dict:
        """Exchange counters + active pump count (the coordinator-plane
        stats() analog for this plane)."""
        out = dict(self._bulk.exchange.stats())
        with self._lock:
            out["active_shuffles"] = len(self._shuffles)
        return out

    # -- the pump -----------------------------------------------------------
    def _state(self, shuffle_id: int) -> _ShuffleWindows:
        with self._lock:
            st = self._shuffles.get(shuffle_id)
            if st is None:
                st = self._shuffles[shuffle_id] = _ShuffleWindows()
                t = threading.Thread(
                    target=self._pump, args=(shuffle_id, st),
                    name=f"windowed-read-{shuffle_id}", daemon=True,
                )
                t.start()
            return st

    def _pump(self, shuffle_id: int, st: _ShuffleWindows) -> None:
        """One thread per (executor, shuffle): runs the windowed
        exchanges in order (next window's plan fetch overlapping the
        current collective) and feeds received blocks to the readers.

        While a device-native exchange runs MULTI-ROUND, the installed
        round sink delivers each landed round's completed blocks as an
        extra window immediately (decode overlaps the next round's
        collective); this loop then delivers only that plan's RESIDUAL
        blocks, so single-round exchanges — and the host-staged path —
        behave exactly as before."""
        mgr = self.manager
        delivered: dict = {}  # id(plan) -> per-source emitted counts

        def sink(plan, blocks, payload, emitted):
            delivered[id(plan)] = emitted
            me = list(plan.hosts).index(mgr.local_smid)
            st.deliver(blocks, False, plan.hosts, me, payload)

        self._bulk.round_block_sinks[shuffle_id] = sink
        try:
            if mgr.conf.bulk_window_maps <= 0:
                exchanges = iter(
                    [self._bulk._exchange_rows(shuffle_id, window=-1)]
                )
            else:
                exchanges = self._bulk._iter_windowed_exchanges(
                    shuffle_id
                )
            legacy = mgr.conf.bulk_window_maps <= 0
            for plan, E, row in exchanges:
                me = list(plan.hosts).index(mgr.local_smid)
                emitted = delivered.pop(id(plan), None)
                if emitted is None:
                    blocks = list(iter_plan_blocks(plan, E, row))
                else:
                    blocks = list(
                        _iter_residual_blocks(plan, E, row, emitted)
                    )
                payload = sum(len(b) for _s, _m, _r, b in blocks)
                final = legacy or plan.final
                st.deliver(blocks, final, plan.hosts, me, payload)
                if final:
                    return
        except BaseException as e:
            st.fail(e)
        finally:
            self._bulk.round_block_sinks.pop(shuffle_id, None)


class WindowedShuffleReader:
    """Reactive reader over the windowed plane: same ``read()``
    contract as the pull :class:`~sparkrdma_tpu.shuffle.reader
    .ShuffleReader` (deserialize → aggregate → sort), with block
    payloads arriving window-by-window.  Partition ownership follows
    the plan convention ``reduce_id % n_hosts == my index``; asking
    for a partition another host owns fails loudly."""

    def __init__(self, plane: WindowedReadPlane, handle,
                 start_partition: int, end_partition: int):
        from sparkrdma_tpu.shuffle.reader import ReadMetrics

        self.plane = plane
        self.handle = handle
        self.start_partition = start_partition
        self.end_partition = end_partition
        self.metrics = ReadMetrics()

    def _iter_block_bytes(self):
        try:
            yield from self._iter_block_bytes_inner()
        finally:
            # normal exhaustion, fetch failure AND abandoned iteration
            # all flush exactly once
            flush_read_metrics(
                self.plane.manager, self.handle.shuffle_id,
                self.metrics, self,
            )

    def _iter_block_bytes_inner(self):
        mgr = self.plane.manager
        st = self.plane._state(self.handle.shuffle_id)
        timeout_s = max(
            mgr.conf.partition_location_fetch_timeout_ms,
            mgr.conf.bulk_barrier_timeout_ms,
        ) / 1000.0
        idx = 0
        checked = False
        while True:
            t0 = time.monotonic()
            try:
                wins, done = st.wait_beyond(idx, timeout_s)
            except FetchFailedError:
                raise
            except BaseException as e:
                raise FetchFailedError(
                    mgr.local_smid.host, self.handle.shuffle_id, str(e)
                ) from e
            # blocked-on-window time is the plane's fetch-wait analog
            # (RdmaShuffleReaderStats' latency accounting)
            self.metrics.fetch_wait_ms += (
                time.monotonic() - t0
            ) * 1000
            if not checked:
                E = len(st.hosts)
                for rid in range(self.start_partition,
                                 self.end_partition):
                    if rid % E != st.me:
                        raise FetchFailedError(
                            mgr.local_smid.host, self.handle.shuffle_id,
                            f"partition {rid} belongs to host "
                            f"{rid % E} in the exchange plan, not this "
                            f"host ({st.me}) — windowed readers must "
                            f"follow reduce_id % n_hosts ownership",
                        )
                checked = True
            for blocks in wins:
                for s, _map_id, rid, data in blocks:
                    if not (
                        self.start_partition <= rid < self.end_partition
                    ):
                        continue
                    if s == st.me:
                        self.metrics.local_blocks += 1
                        self.metrics.local_bytes += len(data)
                    else:
                        self.metrics.remote_blocks += 1
                        self.metrics.remote_bytes += len(data)
                    yield data
            idx += len(wins)
            if done:
                return

    def read(self):
        """fetch (window-by-window) → deserialize → aggregate → sort.

        With ``decodeThreads`` > 0 the windowed plane reuses the
        manager's decode pool for its assembly-side deserialization:
        a landed window's blocks fan out to the workers while the task
        thread is still draining earlier windows (and while the pump's
        next collective runs), through the same decode-ahead stream
        the pull reader uses — serial fallback and output stay
        bit-exact."""
        from sparkrdma_tpu.shuffle.decode import (
            iter_decoded_ahead,
            open_decode_stream,
        )
        from sparkrdma_tpu.shuffle.manager import ColumnarAggregator
        from sparkrdma_tpu.shuffle.reader import (
            postprocess_column_batches,
            postprocess_record_runs,
            postprocess_records,
        )

        mgr = self.plane.manager
        agg = self.handle.aggregator
        columnar = getattr(
            mgr.serializer, "supports_columns", False
        ) and (agg is None or isinstance(agg, ColumnarAggregator))
        stream = open_decode_stream(mgr, self.handle, columnar)

        def _decoded_runs():
            try:
                for t in iter_decoded_ahead(
                    stream, self._iter_block_bytes(),
                    mgr.conf.decode_ahead_bytes,
                ):
                    t0 = time.monotonic()
                    items, n = t.get()
                    self.metrics.decode_wait_ms += (
                        time.monotonic() - t0
                    ) * 1000
                    self.metrics.records_read += n
                    yield items
            finally:
                stream.close()

        if columnar:
            batches = []
            if stream is not None:
                for items in _decoded_runs():
                    batches.extend(items)
            else:
                deser = mgr.serializer.deserialize_columns
                for data in self._iter_block_bytes():
                    t0 = time.monotonic()
                    got = list(deser(data))
                    self.metrics.decode_wait_ms += (
                        time.monotonic() - t0
                    ) * 1000
                    for b in got:
                        self.metrics.records_read += len(b)
                    batches.extend(got)
            return postprocess_column_batches(batches, self.handle)

        if stream is not None:
            return postprocess_record_runs(
                _decoded_runs(), self.handle, presorted=True,
            )

        def _records():
            deser = mgr.serializer.deserialize
            for data in self._iter_block_bytes():
                t0 = time.monotonic()
                recs = list(deser(data))
                self.metrics.decode_wait_ms += (
                    time.monotonic() - t0
                ) * 1000
                self.metrics.records_read += len(recs)
                yield from recs

        return postprocess_records(_records(), self.handle)


class _StagedWindow:
    """One window's assembled exchange inputs: the plan, this host's
    index, the [E, E] lengths matrix, and the contiguous pooled source
    row — everything the collective stage needs, produced off the
    critical path by the pipelined assembler."""

    __slots__ = ("plan", "E", "me", "lengths", "row")

    def __init__(self, plan, E: int, me: int, lengths: np.ndarray,
                 row: np.ndarray):
        self.plan = plan
        self.E = E
        self.me = me
        self.lengths = lengths
        self.row = row


class _StagingTask:
    """Background plan-wait + assembly for one window (the pipelined
    loop's second buffer).  A daemon thread owns the blocking work;
    ``result()`` joins it, ``cancel()`` unblocks a plan wait in flight
    (the waiter's cancel poisons its event) so an abandoned pipeline
    never strands the assembler until the plan timeout."""

    def __init__(self, reader: "BulkExchangeReader", shuffle_id: int,
                 window: int, overlapped: bool):
        from sparkrdma_tpu.utils.trace import get_tracer

        self._tracer = get_tracer()
        self._reader = reader
        self._shuffle_id = shuffle_id
        self._window = window
        self._overlapped = overlapped
        self._waiter = reader._fetch_plan_async(
            shuffle_id, window=window
        )
        self._done = threading.Event()
        self._out: dict = {}
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"window-stage-{shuffle_id}-{window}",
        )
        self._thread.start()

    def _run(self) -> None:
        try:
            with self._tracer.span(
                "shuffle.windowed.plan_wait",
                shuffle=self._shuffle_id, window=self._window,
            ):
                plan = self._waiter.wait()
            self._out["staged"] = self._reader._assemble(
                self._shuffle_id, plan, window=self._window,
                overlapped=self._overlapped,
            )
        except BaseException as e:
            self._out["error"] = e
        finally:
            self._done.set()

    def result(self) -> _StagedWindow:
        # the plan wait bounds itself (partitionLocationFetchTimeout /
        # cancel); assembly is local work — no extra timer here
        self._done.wait()
        if "error" in self._out:
            raise self._out["error"]
        return self._out["staged"]

    def cancel(self) -> None:
        self._waiter.cancel()


class BulkExchangeReader:
    """Runs steps 2-4 for one executor (one per participating host)."""

    def __init__(self, manager, exchange: Optional[TileExchange] = None,
                 mesh=None, session: Optional[BulkShuffleSession] = None):
        self.manager = manager
        self.session = session
        if session is not None:
            self.exchange = session.exchange
        elif exchange is not None:
            self.exchange = exchange
        else:
            self.exchange = TileExchange(
                mesh, tile_bytes=manager.conf.exchange_tile_bytes,
                max_rounds_in_flight=(
                    manager.conf.exchange_max_rounds_in_flight
                ),
            )
        # (window, monotonic completion time, payload bytes) per
        # completed window exchange — lets tests/metrics observe bytes
        # landing while straggler maps are still writing
        self.window_events: List[tuple] = []
        # shuffle_id -> round sink installed by the windowed pump: the
        # device exchange's per-round landings deliver through it
        # (multiple concurrent shuffles share this reader, hence a
        # dict, not a slot)
        self.round_block_sinks: dict = {}

    # -- step 2: the plan barrier -------------------------------------------
    def _fetch_plan_async(self, shuffle_id: int, window: int = -1):
        """Issue the plan RPC WITHOUT blocking and return a one-shot
        waiter object.  The windowed loops use this to overlap the
        NEXT window's plan barrier (driver-side wait for its maps to
        publish) with the CURRENT window's collective — the
        maxBytesInFlight spirit applied to plans
        (RdmaShuffleFetcherIterator.scala:241-251)."""
        mgr = self.manager
        event = threading.Event()
        box = {}

        def on_plan(plan):
            box["plan"] = plan
            event.set()

        def on_failed(reason):
            box["error"] = reason
            event.set()

        cb_id = mgr.register_plan_callback(on_plan, on_failed)
        try:
            # _send_driver_msg re-resolves once if the cached driver
            # channel was evicted from the bounded cache between
            # lookup and post
            mgr._send_driver_msg(
                FetchExchangePlanMsg(
                    mgr.local_smid, shuffle_id, cb_id, window=window
                ),
                on_failure=lambda e: (
                    box.setdefault("error", str(e)), event.set()
                ),
            )
        except BaseException:
            mgr.unregister_plan_callback(cb_id)
            raise

        class _PlanWaiter:
            def wait(self):
                timeout = (
                    mgr.conf.partition_location_fetch_timeout_ms / 1000.0
                )
                try:
                    if not event.wait(timeout):
                        raise MetadataFetchFailedError(
                            mgr.local_smid.host, shuffle_id,
                            f"no exchange plan within {timeout:.0f}s",
                        )
                finally:
                    mgr.unregister_plan_callback(cb_id)
                if "error" in box:
                    raise MetadataFetchFailedError(
                        mgr.local_smid.host, shuffle_id, str(box["error"])
                    )
                return box["plan"]

            def cancel(self):
                # also unblocks a wait() in flight on another thread
                # (the pipelined assembler): a cancelled waiter must
                # fail NOW, not ride out the full plan timeout
                box.setdefault("error", "plan waiter cancelled")
                event.set()
                mgr.unregister_plan_callback(cb_id)

        return _PlanWaiter()

    def _fetch_plan(self, shuffle_id: int, window: int = -1):
        from sparkrdma_tpu.utils.trace import get_tracer

        with get_tracer().span(
            "shuffle.windowed.plan_wait", shuffle=shuffle_id,
            window=window,
        ):
            return self._fetch_plan_async(shuffle_id, window).wait()

    def _run_exchange(self, shuffle_id: int, me: int, row,
                      lengths, window: int = -1, on_round=None):
        """One collective over this host's contiguous source ``row``
        (laid out per ``lengths[me]``, or a :class:`PaddedSourceRow`
        in the device framing when the device plane staged it)."""
        if self.session is not None:
            # key the in-process barrier by (shuffle, window) so
            # concurrent shuffles through one shared session never
            # cross-contribute rows
            return self.session.run(
                me, row, lengths,
                round_key=(shuffle_id, window), on_round=on_round,
            )
        import jax

        dev = self.exchange.devices[me]
        if (jax.process_count() > 1
                and dev.process_index != jax.process_index()):
            # the exchange only stages THIS process's device rows: a
            # mesh whose device order disagrees with the canonical host
            # order would silently exchange zeros
            raise MetadataFetchFailedError(
                self.manager.local_smid.host, shuffle_id,
                f"mesh device {me} (this host's canonical row) "
                f"belongs to process {dev.process_index}, not this "
                f"process {jax.process_index()} — order the mesh "
                f"devices like the plan's host order",
            )
        if isinstance(row, PaddedSourceRow):
            return self.exchange.exchange_padded(
                lengths, {me: row}, local_sources=frozenset({me}),
                out_alloc=self._alloc_row, on_round=on_round,
                window_rounds=(
                    self.manager.conf.device_exchange_window_rounds
                ),
            )
        return self.exchange.exchange_into(
            lengths, {me: row}, local_sources=frozenset({me}),
            out_alloc=self._alloc_row,
        )

    # -- steps 3-4: exchange + consume --------------------------------------
    def _exchange_all(self, shuffle_id: int):
        """Run the shuffle's exchange(s) eagerly and return a list of
        (plan, E, row) — ONE entry for the legacy full barrier, one
        per window when ``bulkWindowMaps`` > 0 (each window's exchange
        runs as soon as its plan lands, overlapping straggler maps)."""
        if self.manager.conf.bulk_window_maps <= 0:
            return [self._exchange_rows(shuffle_id, window=-1)]
        out = []
        for plan, E, row in self._iter_windowed_exchanges(shuffle_id):
            out.append((plan, E, row))
        return out

    def _iter_windowed_exchanges(self, shuffle_id: int):
        """Run each plan window's exchange in order.  With
        ``bulkPipelineWindows`` (the default) the NEXT window's plan
        fetch AND stream assembly both overlap the current collective
        (double-buffered: window N+1 assembles into a second pooled
        row while window N's bytes ride the mesh); disabling the knob
        keeps only the plan-fetch overlap — output is bit-identical
        either way."""
        mgr = getattr(self, "manager", None)
        if mgr is not None and mgr.conf.bulk_pipeline_windows:
            yield from self._iter_windowed_pipelined(shuffle_id)
        else:
            yield from self._iter_windowed_serial(shuffle_id)

    def _iter_windowed_serial(self, shuffle_id: int):
        """The non-pipelined window loop: only the next window's plan
        FETCH overlaps the current collective (the plan barrier
        includes waiting for that window's maps to publish —
        serializing it behind the exchange doubled the per-window
        latency at fine window settings); assembly stays on the
        critical path.

        The whole loop — INCLUDING the yields — runs under one
        try/finally: when the consumer abandons the generator
        mid-iteration (GeneratorExit), or any step raises, the
        prefetched next-window waiter is cancelled instead of leaking
        its registered plan callback on the manager."""
        from sparkrdma_tpu.utils.trace import get_tracer

        w = 0
        waiter = self._fetch_plan_async(shuffle_id, window=0)
        nxt = None
        try:
            while True:
                with get_tracer().span(
                    "shuffle.windowed.plan_wait", shuffle=shuffle_id,
                    window=w,
                ):
                    plan = waiter.wait()
                waiter = None
                if not plan.final:
                    nxt = self._fetch_plan_async(
                        shuffle_id, window=w + 1
                    )
                result = self._exchange_rows(
                    shuffle_id, window=w, plan=plan
                )
                waiter, nxt = nxt, None
                yield result
                if plan.final:
                    return
                w += 1
        finally:
            cancelled = 0
            for pending in (waiter, nxt):
                if pending is not None:
                    pending.cancel()
                    cancelled += 1
            if cancelled:
                counter(
                    "shuffle_plan_waiters_cancelled_total"
                ).inc(cancelled)

    def _iter_windowed_pipelined(self, shuffle_id: int):
        """The double-buffered window loop: while window N's collective
        runs, window N+1's plan barrier AND stream assembly proceed on
        a background stage into a second pooled source row — the
        maxBytesInFlight overlap applied to the whole host-side data
        path, not just the plan RPC.

        Abort/poison semantics are preserved: a poisoned session fails
        the in-flight exchange immediately (session.run re-checks
        under its condition), the error unwinds this generator, and
        the finally cancels the being-assembled window's stage — its
        plan waiter is unblocked by cancel(), so the assembler thread
        exits promptly instead of riding out the plan timeout."""
        w = 0
        prep = _StagingTask(self, shuffle_id, 0, overlapped=False)
        nxt = None
        try:
            while True:
                staged = prep.result()
                prep = None
                if not staged.plan.final:
                    # window w+1 stages (plan barrier + assembly into
                    # the second buffer) while window w exchanges
                    nxt = _StagingTask(
                        self, shuffle_id, w + 1, overlapped=True
                    )
                    counter("exchange_windows_pipelined_total").inc()
                result = self._exchange_staged(
                    shuffle_id, staged, window=w
                )
                prep, nxt = nxt, None
                yield result
                if staged.plan.final:
                    return
                w += 1
        finally:
            cancelled = 0
            for pending in (prep, nxt):
                if pending is not None:
                    pending.cancel()
                    cancelled += 1
            if cancelled:
                counter(
                    "shuffle_plan_waiters_cancelled_total"
                ).inc(cancelled)

    def _exchange_rows(self, shuffle_id: int, window: int = -1,
                       plan=None):
        """Plan barrier + stream assembly + ONE collective exchange;
        all EAGER (a lazily-deferred exchange would leave every other
        participant blocked in the collective).  Returns (plan, E,
        row) where row[s] is the received stream from source s (a
        zero-copy view of this host's destination row)."""
        if plan is None:
            plan = self._fetch_plan(shuffle_id, window=window)
        staged = self._assemble(shuffle_id, plan, window=window)
        return self._exchange_staged(shuffle_id, staged, window=window)

    def _alloc_row(self, nbytes: int) -> np.ndarray:
        """One pooled contiguous source row (memory/staging.py): the
        pool recycles the buffer once the last view of it dies, which
        is what makes the double-buffered windows a TWO-buffer steady
        state instead of an allocation per window."""
        from sparkrdma_tpu.memory.staging import alloc_row_gc

        return alloc_row_gc(
            getattr(self.manager, "staging_pool", None), nbytes,
            "exchange_row_pool_fallbacks_total",
        )

    def _assemble(self, shuffle_id: int, plan, window: int = -1,
                  overlapped: bool = False) -> "_StagedWindow":
        """Stage this host's source row for one exchange: map-output
        blocks are gathered ONCE into a single preallocated uint8 row
        laid out per the plan's lengths (map_id asc, reduce_id asc,
        empties skipped — the exact order the driver's plan assumed).
        No per-destination ``bytes`` join, no per-block
        materialization: block views copy straight into their final
        offset.  A host that ran no map tasks still participates (the
        collective needs every member) with an all-empty row.  A
        windowed plan names exactly which of my maps belong to THIS
        window (the driver assigns maps to windows as fills land)."""
        from sparkrdma_tpu.utils.trace import get_tracer

        mgr = self.manager
        hosts = list(plan.hosts)
        E = len(hosts)
        try:
            me = hosts.index(mgr.local_smid)
        except ValueError:
            raise MetadataFetchFailedError(
                mgr.local_smid.host, shuffle_id,
                "this host is not in the exchange plan "
                "(did it hello the driver?)",
            )
        # [E, E] plan metadata, not payload
        lengths = np.asarray(plan.lengths, np.int64).reshape(E, E)  # noqa: PY13
        if window >= 0:
            my_maps = sorted(plan.my_maps)
        else:
            my_maps = mgr.resolver.map_ids(shuffle_id)
        offs = row_offsets(lengths[me])
        total = int(offs[-1])
        # device plane: stage straight into the PADDED framing the
        # collective consumes (stream d at [d*C, d*C+len]) — assembly
        # is the ONLY host pass over the payload; the exchange then
        # does one device_put per source row and never builds the
        # per-round [E, E, tile] staging matrices.  Single-controller
        # only: across OS processes the padded row layout would need
        # cross-process agreement the host-staged path already gives.
        dev_cols = 0
        if mgr.conf.device_exchange_enabled:
            import jax

            if jax.process_count() == 1:
                xplan = self.exchange.plan(lengths)
                if xplan.rounds:
                    dev_cols = xplan.total_cols
        if dev_cols:
            row = self._alloc_row(E * dev_cols)
            starts = [d * dev_cols for d in range(E)]
            limits = [
                d * dev_cols + int(lengths[me, d]) for d in range(E)
            ]
        else:
            row = self._alloc_row(total)
            starts = [int(offs[d]) for d in range(E)]
            limits = [int(offs[d + 1]) for d in range(E)]
        cursors = list(starts)
        t0 = time.monotonic()
        with get_tracer().span(
            "shuffle.windowed.stream_build", shuffle=shuffle_id,
            window=window, maps=len(my_maps),
        ):
            if my_maps and total:
                from sparkrdma_tpu.memory.staging import (
                    native_gather_blocks,
                )

                num_parts = mgr.resolver.num_partitions(shuffle_id)
                # one batched backing-store read per map output (every
                # partition ships somewhere, so fetch each segment
                # ONCE instead of a device round-trip per block), then
                # gather every block view to its destination offset in
                # ONE native memcpy batch (slice assignment dispatches
                # ~1 us of numpy machinery per block; `keep` pins the
                # views until the copies land)
                addrs: list = []
                lens_l: list = []
                offs_l: list = []
                keep: list = []
                for map_id in my_maps:
                    blocks = mgr.resolver.get_local_blocks(
                        shuffle_id, map_id, range(num_parts)
                    )
                    for d in range(E):
                        cur = cursors[d]
                        for r in range(d, num_parts, E):
                            blk = blocks[r]
                            n = len(blk)
                            if not n:
                                continue
                            if isinstance(blk, np.ndarray) \
                                    and blk.dtype == np.uint8:
                                src = blk
                            else:
                                try:
                                    src = np.frombuffer(blk, np.uint8)
                                except (TypeError, ValueError):
                                    # exotic block store: materialize
                                    # once and COUNT it — the zero-copy
                                    # smoke test pins this at zero
                                    counter(
                                        "exchange_assembly_"
                                        "materialized_blocks_total"
                                    ).inc()
                                    src = np.frombuffer(
                                        bytes(blk), np.uint8
                                    )
                            end = cur + n
                            if end > limits[d]:
                                raise MetadataFetchFailedError(
                                    mgr.local_smid.host, shuffle_id,
                                    f"local stream to dst {d} "
                                    f"overflows its planned "
                                    f"{int(lengths[me, d])}B",
                                )
                            addrs.append(src.ctypes.data)
                            lens_l.append(n)
                            offs_l.append(cur)
                            keep.append(src)
                            cur = end
                        cursors[d] = cur
                if not native_gather_blocks(row, addrs, lens_l, offs_l):
                    for src, cur, n in zip(keep, offs_l, lens_l):
                        row[cur:cur + n] = src
                del keep
        for d in range(E):
            got = cursors[d] - starts[d]
            if got != int(lengths[me, d]):
                raise MetadataFetchFailedError(
                    mgr.local_smid.host, shuffle_id,
                    f"local stream to dst {d} is {got}B, plan says "
                    f"{int(lengths[me, d])}B",
                )
        if dev_cols:
            # pooled rows recycle: the pad spans must ship
            # deterministic zeros, never a previous window's bytes
            for d in range(E):
                row[limits[d] : (d + 1) * dev_cols] = 0
            row = PaddedSourceRow(row, dev_cols)
        # microseconds: whole-ms granularity truncated fast windows to
        # zero and zeroed the overlap ratio on fine window settings
        us = int((time.monotonic() - t0) * 1e6)
        counter("exchange_assembly_us_total").inc(us)
        counter("exchange_assembly_bytes_total").inc(total)
        if overlapped:
            # staged while another window's collective was in flight:
            # this host-side work left the critical path entirely
            counter("exchange_assembly_overlapped_us_total").inc(us)
        return _StagedWindow(plan, E, me, lengths, row)

    def _exchange_staged(self, shuffle_id: int,
                         staged: "_StagedWindow", window: int = -1):
        """Run the one collective for an assembled window; returns
        (plan, E, row) with row = this host's destination-row view."""
        from sparkrdma_tpu.utils.trace import get_tracer

        lengths = staged.lengths
        sink = self.round_block_sinks.get(shuffle_id)
        on_round = None
        if sink is not None and isinstance(staged.row, PaddedSourceRow):
            on_round = _make_round_emitter(
                staged.plan, staged.E, staged.me, lengths, sink
            )
        with get_tracer().span(
            "shuffle.bulk.exchange", shuffle=shuffle_id,
            hosts=staged.E, window=window,
            payload_bytes=int(lengths.sum()),
        ):
            result = self._run_exchange(
                shuffle_id, staged.me, staged.row, lengths,
                window=window, on_round=on_round,
            )
        self.window_events.append(
            (window, time.monotonic(), int(lengths.sum()))
        )
        return staged.plan, staged.E, result[staged.me]

    def read(self, shuffle_id: int) -> Iterator:
        """Blocking bulk read of this host's partitions (the
        exchange(s) run eagerly in this call; the returned iterator
        only deserializes).  Yields records."""
        exchanged = self._exchange_all(shuffle_id)
        deser = self.manager.serializer.deserialize

        def _records():
            for plan, E, row in exchanged:
                for _s, _m, _r, block in iter_plan_blocks(plan, E, row):
                    yield from deser(block)

        return _records()

    def read_partitioned(self, shuffle_id: int) -> dict:
        """Like :meth:`read` but returns ``{reduce_id: [records]}`` for
        every partition this host owns — the shape the job layer's
        per-partition reduce tasks want."""
        deser = self.manager.serializer.deserialize
        out: dict = {}
        for reduce_id, block in self.read_partitioned_blocks(shuffle_id):
            out.setdefault(reduce_id, []).extend(deser(block))
        return out

    def read_partitioned_blocks(self, shuffle_id: int):
        """Lowest-level consumption: yields (reduce_id, raw block
        bytes) pairs after the exchange — lets columnar consumers feed
        blocks straight to ``deserialize_columns`` (the vectorized
        path) instead of per-record tuples.  The exchange(s) run
        eagerly before the first yield."""
        exchanged = self._exchange_all(shuffle_id)

        def _blocks():
            for plan, E, row in exchanged:
                for _s, _m, reduce_id, block in iter_plan_blocks(
                    plan, E, row
                ):
                    yield reduce_id, block

        return _blocks()
