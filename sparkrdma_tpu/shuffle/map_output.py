"""Per-map-task output location table with partial-fill futures.

Analog of the reference's RdmaMapTaskOutput (RdmaMapTaskOutput.scala:26-104):
a compact off-object index of one 16-byte entry ``(address: i64, length: i32,
mkey: i32)`` per reduce partition, supporting partial fills with a
completion future so the driver can await full publication before
answering fetch-status queries (the reference's ``fillFuture``).

Backed by one contiguous ``bytearray`` rather than per-entry objects so a
100k-partition table costs 1.6 MB, not millions of boxed tuples.

Delta sync: the writer side tracks which entries changed since the last
publish (``take_delta`` returns epoch-tagged dirty runs), so a
REpublish after a location change ships O(changed) entry bytes instead
of the whole table — at 256-executor fan-out the driver's publish
inbox scales with churn, not fleet size.  The driver side applies
segments with a per-entry epoch guard (``put_range``'s ``epoch``), so
segments of different publishes may land out of order (the receive
dispatcher is a pool) without a stale segment clobbering newer
locations.
"""

from __future__ import annotations

from array import array
from concurrent.futures import Future, InvalidStateError
from typing import List, Optional, Tuple

from sparkrdma_tpu.utils.dbglock import dbg_lock
from sparkrdma_tpu.utils.types import (
    LOCATION_ENTRY_SIZE,
    BlockLocation,
    _LOCATION_STRUCT,
)


class MapTaskOutput:
    """Location table for one map task: partitions [0, num_partitions)."""

    def __init__(self, num_partitions: int):
        if num_partitions <= 0:
            raise ValueError(f"num_partitions must be > 0: {num_partitions}")
        self.num_partitions = num_partitions
        self._buf = bytearray(num_partitions * LOCATION_ENTRY_SIZE)
        # distinct-partition fill tracking: re-delivered publish segments
        # (RPC retries, overlapping ranges) must not double-count
        self._filled_flags = bytearray(num_partitions)  # guarded-by: _lock
        self._filled = 0  # guarded-by: _lock
        # entries changed since the last take_delta (writer-side
        # publish cursor; 1 byte per partition like the fill flags)
        self._dirty = bytearray(num_partitions)  # guarded-by: _lock
        self._publish_epoch = 0  # guarded-by: _lock
        # receiver-side per-entry applied epoch, allocated lazily on
        # the first epoch-tagged segment (> 0): single-publish tables —
        # the overwhelmingly common case — never pay the 4B/partition
        self._entry_epochs: Optional[array] = None  # guarded-by: _lock
        self._lock = dbg_lock("map_output.fill", 36)
        self._fill_future: Future = Future()

    # -- write side ---------------------------------------------------------
    def put(self, partition_id: int, location: BlockLocation) -> None:
        self._check_range(partition_id, partition_id)
        _LOCATION_STRUCT.pack_into(
            self._buf,
            partition_id * LOCATION_ENTRY_SIZE,
            location.address,
            location.length,
            location.mkey,
        )
        self._mark_filled(partition_id, partition_id)

    def put_range(self, first: int, last: int, raw: bytes,
                  epoch: int = 0) -> None:
        """Install serialized entries for partitions [first, last]
        (inclusive), e.g. one segment of a publish RPC
        (reference: RdmaMapTaskOutput.putRange).

        ``epoch`` is the sender's publish generation: segments of a
        later publish carry a higher epoch, and an entry is only
        overwritten by a segment of equal-or-newer epoch — so a delta
        republish racing (or re-delivered after) the original full
        publish through the dispatcher pool can never be clobbered by
        the stale full-range entries."""
        self._check_range(first, last)
        n = last - first + 1
        expect = n * LOCATION_ENTRY_SIZE
        if len(raw) != expect:
            raise ValueError(f"putRange payload {len(raw)}B != expected {expect}B")
        start = first * LOCATION_ENTRY_SIZE
        with self._lock:
            if epoch > 0 and self._entry_epochs is None:
                self._entry_epochs = array(
                    "i", bytes(4 * self.num_partitions)
                )
            eps = self._entry_epochs
            if eps is None:
                # no epoch-tagged segment ever seen: bulk fast path
                self._buf[start : start + expect] = raw
            elif epoch >= max(eps[first : last + 1]):
                # whole segment passes the guard (the common case —
                # in-order delivery): one bulk copy, not a 16-byte
                # slice-assign per entry on the RPC dispatch thread
                self._buf[start : start + expect] = raw
                eps[first : last + 1] = array("i", [epoch]) * n
            else:
                for i in range(n):
                    p = first + i
                    if epoch >= eps[p]:
                        eps[p] = epoch
                        lo = p * LOCATION_ENTRY_SIZE
                        ro = i * LOCATION_ENTRY_SIZE
                        self._buf[lo : lo + LOCATION_ENTRY_SIZE] = (
                            raw[ro : ro + LOCATION_ENTRY_SIZE]
                        )
        self._mark_filled(first, last)

    def take_delta(self) -> Tuple[int, List[Tuple[int, int, bytes]]]:
        """Pop the entries changed since the last call as contiguous
        ``(first, last, raw)`` runs, tagged with this publish's epoch —
        the delta-sync publish cursor.  The first call after a fresh
        commit returns the whole table (everything is dirty); a later
        call after relocating a few blocks returns just those runs, so
        republish bytes scale with churn, not partition count."""
        with self._lock:
            d = self._dirty
            runs: List[Tuple[int, int]] = []
            pos = 0
            while True:
                lo = d.find(b"\x01", pos)
                if lo < 0:
                    break
                hi = d.find(b"\x00", lo + 1)
                if hi < 0:
                    hi = self.num_partitions
                runs.append((lo, hi - 1))
                pos = hi
            epoch = self._publish_epoch
            if not runs:
                return epoch, []
            d[:] = bytes(self.num_partitions)
            self._publish_epoch += 1
            out = [
                (
                    lo, hi,
                    bytes(self._buf[
                        lo * LOCATION_ENTRY_SIZE:
                        (hi + 1) * LOCATION_ENTRY_SIZE
                    ]),
                )
                for lo, hi in runs
            ]
        return epoch, out

    def ensure_capacity(self, num_partitions: int) -> None:
        """Grow the table to at least ``num_partitions`` rows (never
        shrinks).  Skew-split map outputs publish EXTRA sub-block rows
        past the logical partition count, but the driver may have
        pre-created this table at the logical size from an early
        fetch-status query — the publish handler calls this with the
        sender's row count before installing segments, so the fill
        threshold is raised to the extended count BEFORE any row can
        land (a table must never complete at the narrow size and then
        widen)."""
        with self._lock:
            extra = num_partitions - self.num_partitions
            if extra <= 0:
                return
            # replace rather than resize in place: readers snapshot
            # memoryview(self._buf) outside the lock, and resizing a
            # bytearray with a live export raises BufferError
            buf = bytearray(num_partitions * LOCATION_ENTRY_SIZE)
            buf[: len(self._buf)] = self._buf
            self._buf = buf
            self._filled_flags = self._filled_flags + bytes(extra)
            self._dirty = self._dirty + bytes(extra)
            if self._entry_epochs is not None:
                self._entry_epochs = self._entry_epochs + array(
                    "i", bytes(4 * extra)
                )
            self.num_partitions = num_partitions

    def mark_dirty(self, first: int, last: int) -> None:
        """Re-flag [first, last] for the next ``take_delta`` — the
        publish path calls this from a send-failure callback so a
        delta run lost on the wire is re-shipped (at a newer epoch) by
        the next publish instead of staying stale forever."""
        self._check_range(first, last)
        with self._lock:
            self._dirty[first : last + 1] = b"\x01" * (last - first + 1)

    def _mark_filled(self, first: int, last: int) -> None:
        n = last - first + 1
        with self._lock:
            # dirty tracking rides the fill path: put() and put_range()
            # both funnel here AFTER the entry bytes are in _buf, so a
            # concurrent take_delta never snapshots a half-written run
            self._dirty[first : last + 1] = b"\x01" * n
            already = self._filled_flags.count(1, first, last + 1)
            complete = False
            if already < n:
                self._filled_flags[first : last + 1] = b"\x01" * n
                self._filled += n - already
                complete = self._filled >= self.num_partitions
        if complete:
            # OUTSIDE the lock: set_result runs done-callbacks inline
            # (the driver's window-plan retrigger takes manager locks
            # ranked far ABOVE this leaf) — firing it under _lock was a
            # latent order inversion, caught by the rank sanitizer.
            # Only the thread that crossed the threshold gets here
            # (fills are monotonic under _lock), so the only possible
            # race is remove_executor's set_exception — tolerate it the
            # same way it tolerates us.
            try:
                self._fill_future.set_result(self)
            except InvalidStateError:
                pass  # lost the race; the failed future stands

    # -- read side ----------------------------------------------------------
    def get_location(self, partition_id: int) -> BlockLocation:
        self._check_range(partition_id, partition_id)
        return BlockLocation.read(
            memoryview(self._buf), partition_id * LOCATION_ENTRY_SIZE
        )

    def get_locations(self, first: int, last: int) -> List[BlockLocation]:
        self._check_range(first, last)
        view = memoryview(self._buf)
        return [
            BlockLocation.read(view, p * LOCATION_ENTRY_SIZE)
            for p in range(first, last + 1)
        ]

    def get_range_bytes(self, first: int, last: int) -> bytes:
        """Raw serialized entries for [first, last] inclusive — the publish
        RPC's segment payload (reference: getByteBuffer range slices)."""
        self._check_range(first, last)
        return bytes(
            self._buf[first * LOCATION_ENTRY_SIZE : (last + 1) * LOCATION_ENTRY_SIZE]
        )

    @property
    def fill_future(self) -> Future:
        """Resolves once every partition entry has been installed."""
        return self._fill_future

    @property
    def is_complete(self) -> bool:
        return self._fill_future.done()

    def total_bytes(self) -> int:
        view = memoryview(self._buf)
        return sum(
            _LOCATION_STRUCT.unpack_from(view, p * LOCATION_ENTRY_SIZE)[1]
            for p in range(self.num_partitions)
        )

    def _check_range(self, first: int, last: int) -> None:
        if not (0 <= first <= last < self.num_partitions):
            raise IndexError(
                f"partition range [{first},{last}] out of bounds "
                f"[0,{self.num_partitions})"
            )
