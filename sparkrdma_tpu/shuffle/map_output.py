"""Per-map-task output location table with partial-fill futures.

Analog of the reference's RdmaMapTaskOutput (RdmaMapTaskOutput.scala:26-104):
a compact off-object index of one 16-byte entry ``(address: i64, length: i32,
mkey: i32)`` per reduce partition, supporting partial fills with a
completion future so the driver can await full publication before
answering fetch-status queries (the reference's ``fillFuture``).

Backed by one contiguous ``bytearray`` rather than per-entry objects so a
100k-partition table costs 1.6 MB, not millions of boxed tuples.
"""

from __future__ import annotations

from concurrent.futures import Future, InvalidStateError
from typing import List

from sparkrdma_tpu.utils.dbglock import dbg_lock
from sparkrdma_tpu.utils.types import (
    LOCATION_ENTRY_SIZE,
    BlockLocation,
    _LOCATION_STRUCT,
)


class MapTaskOutput:
    """Location table for one map task: partitions [0, num_partitions)."""

    def __init__(self, num_partitions: int):
        if num_partitions <= 0:
            raise ValueError(f"num_partitions must be > 0: {num_partitions}")
        self.num_partitions = num_partitions
        self._buf = bytearray(num_partitions * LOCATION_ENTRY_SIZE)
        # distinct-partition fill tracking: re-delivered publish segments
        # (RPC retries, overlapping ranges) must not double-count
        self._filled_flags = bytearray(num_partitions)  # guarded-by: _lock
        self._filled = 0  # guarded-by: _lock
        self._lock = dbg_lock("map_output.fill", 36)
        self._fill_future: Future = Future()

    # -- write side ---------------------------------------------------------
    def put(self, partition_id: int, location: BlockLocation) -> None:
        self._check_range(partition_id, partition_id)
        _LOCATION_STRUCT.pack_into(
            self._buf,
            partition_id * LOCATION_ENTRY_SIZE,
            location.address,
            location.length,
            location.mkey,
        )
        self._mark_filled(partition_id, partition_id)

    def put_range(self, first: int, last: int, raw: bytes) -> None:
        """Install serialized entries for partitions [first, last]
        (inclusive), e.g. one segment of a publish RPC
        (reference: RdmaMapTaskOutput.putRange)."""
        self._check_range(first, last)
        n = last - first + 1
        expect = n * LOCATION_ENTRY_SIZE
        if len(raw) != expect:
            raise ValueError(f"putRange payload {len(raw)}B != expected {expect}B")
        start = first * LOCATION_ENTRY_SIZE
        self._buf[start : start + expect] = raw
        self._mark_filled(first, last)

    def _mark_filled(self, first: int, last: int) -> None:
        n = last - first + 1
        with self._lock:
            already = self._filled_flags.count(1, first, last + 1)
            complete = False
            if already < n:
                self._filled_flags[first : last + 1] = b"\x01" * n
                self._filled += n - already
                complete = self._filled >= self.num_partitions
        if complete:
            # OUTSIDE the lock: set_result runs done-callbacks inline
            # (the driver's window-plan retrigger takes manager locks
            # ranked far ABOVE this leaf) — firing it under _lock was a
            # latent order inversion, caught by the rank sanitizer.
            # Only the thread that crossed the threshold gets here
            # (fills are monotonic under _lock), so the only possible
            # race is remove_executor's set_exception — tolerate it the
            # same way it tolerates us.
            try:
                self._fill_future.set_result(self)
            except InvalidStateError:
                pass  # lost the race; the failed future stands

    # -- read side ----------------------------------------------------------
    def get_location(self, partition_id: int) -> BlockLocation:
        self._check_range(partition_id, partition_id)
        return BlockLocation.read(
            memoryview(self._buf), partition_id * LOCATION_ENTRY_SIZE
        )

    def get_locations(self, first: int, last: int) -> List[BlockLocation]:
        self._check_range(first, last)
        view = memoryview(self._buf)
        return [
            BlockLocation.read(view, p * LOCATION_ENTRY_SIZE)
            for p in range(first, last + 1)
        ]

    def get_range_bytes(self, first: int, last: int) -> bytes:
        """Raw serialized entries for [first, last] inclusive — the publish
        RPC's segment payload (reference: getByteBuffer range slices)."""
        self._check_range(first, last)
        return bytes(
            self._buf[first * LOCATION_ENTRY_SIZE : (last + 1) * LOCATION_ENTRY_SIZE]
        )

    @property
    def fill_future(self) -> Future:
        """Resolves once every partition entry has been installed."""
        return self._fill_future

    @property
    def is_complete(self) -> bool:
        return self._fill_future.done()

    def total_bytes(self) -> int:
        view = memoryview(self._buf)
        return sum(
            _LOCATION_STRUCT.unpack_from(view, p * LOCATION_ENTRY_SIZE)[1]
            for p in range(self.num_partitions)
        )

    def _check_range(self, first: int, last: int) -> None:
        if not (0 <= first <= last < self.num_partitions):
            raise IndexError(
                f"partition range [{first},{last}] out of bounds "
                f"[0,{self.num_partitions})"
            )
