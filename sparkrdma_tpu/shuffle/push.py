"""Push-based merged shuffle: the per-reduce-partition merger.

The magnet idiom applied to this stack's pull plane: at commit, every
writer pushes its per-partition payload — cut at serializer frame
boundaries into sub-blocks — to the reduce partition's deterministic
merger executor.  The merger assembles each map's partition from its
``(offset, bytes)`` spans, appends completed partitions into ONE
merged per-reduce span, and commits that span through the same
file-backed / tier-store write-through path the resolver's large
commits use (memory/mapped_file.py + memory/tier.py), so readers fetch
one large sequential run instead of M small random blocks.

Correctness contract — best-effort push, bit-exact always:

* **Dedup under retries.**  A retried/speculated map task pushes the
  same partition twice; the merger keeps the FIRST completed copy per
  ``map_id`` and drops the rest (``push_drops_total{reason="dup"}``).
  Map output bytes are deterministic per (shuffle, map, reduce), so
  first-wins is bit-exact.
* **Provenance.**  The merged span records ``(map_id, rel_off,
  rel_len)`` rows, so the reader knows exactly which map outputs the
  span covers — everything else (never pushed, dropped, arrived after
  seal, over the byte cap) rides the unchanged pull path — and can
  slice the span back into per-map blocks for the k-way merge.
* **Seal on first query.**  A merge-status query seals the
  (shuffle, reduce) state: what is complete is committed and served;
  partial assemblies are discarded and later arrivals dropped
  (``reason="late"``), so a span's provenance can never change after a
  reader planned against it.
* **Bounded.**  ``pushMaxMergedBytes`` caps a merger's per-reduce
  footprint; over-cap partitions drop to the pull path
  (``reason="cap"``).

No reference analog: RdmaShuffleWriter commits then serves pulls; this
is the LinkedIn-magnet restructuring of the same commit point, pushed
over the existing RPC channels behind the v3 wire handshake.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from sparkrdma_tpu.faults.injector import FAULTS
from sparkrdma_tpu.metrics import counter
from sparkrdma_tpu.utils.dbglock import dbg_lock
from sparkrdma_tpu.utils.statemachine import StateMachine

logger = logging.getLogger(__name__)

#: Provenance row: (map_id, rel_off, rel_len) within the merged span.
ProvRow = Tuple[int, int, int]


class MergeUnavailable(Exception):
    """This merger cannot answer a merge-status query (fault-injected
    dead-merger drill, or teardown race).  The manager converts it to
    the failed-reply the reader treats as no-coverage → pull."""


class _ReduceMerge(StateMachine):
    """Merge state of ONE (shuffle, reduce partition) on this merger:
    ``accepting`` sub-blocks until the first status query seals it,
    ``committed`` once the merged span is registered and servable (a
    failed commit stays ``sealed`` with no segment — pure pull)."""

    __slots__ = ("pending", "totals", "payloads", "done", "nbytes",
                 "_state", "seg", "length", "provenance")

    MACHINE = "push.merge"
    STATES = ("accepting", "sealed", "committed")
    INITIAL = "accepting"
    TERMINAL = ("committed",)
    TRANSITIONS = {
        "accepting": ("sealed",),
        "sealed": ("committed",),
    }

    def __init__(self):
        self.pending: Dict[int, Dict[int, bytes]] = {}  # map -> off -> bytes
        self.totals: Dict[int, int] = {}                # map -> total_len
        self.payloads: List[Tuple[int, bytes]] = []     # completed, in order
        self.done: set = set()       # map_ids no longer accepted
        self.nbytes = 0              # merged bytes (completed payloads)
        self._state = "accepting"  # state: push.merge guarded-by: PushMerger._lock
        self.seg = None              # registered segment once sealed
        self.length = 0
        self.provenance: Tuple[ProvRow, ...] = ()


class PushMerger:
    """Per-executor merger endpoint: receives pushed sub-blocks, seals
    merged per-reduce spans on first query, serves their locations.

    All handlers run on the manager's receive paths; the single lock
    covers assembly state only — the one slow operation under it (the
    seal's streaming file write + registration) happens once per
    (shuffle, reduce) and keeps seal idempotent under concurrent
    queries from retried reduce tasks."""

    def __init__(self, conf, arena, tier_store=None, node=None,
                 spill_dir: Optional[str] = None, direct_io: str = "off"):
        self.arena = arena
        self.tier_store = tier_store
        self.node = node
        self.spill_dir = spill_dir
        self.direct_io = direct_io
        self.max_merged_bytes = conf.push_max_merged_bytes
        self._lock = dbg_lock("push.merger", 26)
        self._shuffles: Dict[int, Dict[int, _ReduceMerge]] = {}  # guarded-by: _lock

    # -- push side (writer → merger) ----------------------------------------
    def on_sub_block(self, shuffle_id: int, map_id: int, reduce_id: int,
                     total_len: int, offset: int, data: bytes) -> None:
        """Accept one pushed span of a map's partition payload.  Drops
        are silent by design (counted, never raised): push is advisory
        and the pull path serves whatever never merges."""
        counter("push_sub_blocks_total").inc()
        if FAULTS.enabled and FAULTS.fires("push_merge"):
            counter("push_drops_total", reason="fault").inc()
            return
        with self._lock:
            st = self._shuffles.setdefault(shuffle_id, {}).setdefault(
                reduce_id, _ReduceMerge()
            )
            if st._state != "accepting":
                counter("push_drops_total", reason="late").inc()
                return
            if map_id in st.done:
                counter("push_drops_total", reason="dup").inc()
                return
            if st.totals.get(map_id, total_len) != total_len:
                # a retried map re-pushing with a different length can
                # only mean corruption upstream — restart its assembly
                # from the latest generation (last-writer-wins)
                st.pending.pop(map_id, None)
            st.totals[map_id] = total_len
            parts = st.pending.setdefault(map_id, {})
            parts[offset] = bytes(data)
            if not self._complete(parts, total_len):
                return
            st.pending.pop(map_id)
            st.totals.pop(map_id)
            st.done.add(map_id)
            if st.nbytes + total_len > self.max_merged_bytes:
                counter("push_drops_total", reason="cap").inc()
                return
            payload = b"".join(parts[o] for o in sorted(parts))
            st.payloads.append((map_id, payload))
            st.nbytes += total_len
        counter("push_merged_blocks_total").inc()
        counter("push_merged_bytes_total").inc(total_len)

    @staticmethod
    def _complete(parts: Dict[int, bytes], total_len: int) -> bool:
        """Do the spans tile [0, total_len) contiguously?  Offset-keyed
        parts dedup identical resends; a gap means more spans are in
        flight."""
        end = 0
        for off in sorted(parts):
            if off > end:
                return False
            end = max(end, off + len(parts[off]))
        return end >= total_len

    # -- query side (reader → merger) ---------------------------------------
    def merge_status(
        self, shuffle_id: int, reduce_ids
    ) -> List[Tuple[int, int, int, Tuple[ProvRow, ...]]]:
        """Seal and answer: ``(reduce_id, mkey, length, provenance)``
        per queried id; ``mkey == 0`` means no merged data (pull
        everything).  Raises :class:`MergeUnavailable` under the
        dead-merger fault drill."""
        if FAULTS.enabled and FAULTS.fires("merge_status"):
            raise MergeUnavailable("fault-injected merge_status failure")
        out = []
        for rid in reduce_ids:
            mkey, length, prov = self.local_merged(shuffle_id, rid)
            out.append((rid, mkey, length, prov))
        return out

    def local_merged(
        self, shuffle_id: int, reduce_id: int
    ) -> Tuple[int, int, Tuple[ProvRow, ...]]:
        """Seal ONE reduce partition and return ``(mkey, length,
        provenance)`` — ``(0, 0, ())`` when nothing merged.  Idempotent:
        the first call commits, every later call re-reads the sealed
        answer (retried reduce tasks must plan against the same span)."""
        with self._lock:
            st = self._shuffles.get(shuffle_id, {}).get(reduce_id)
            if st is None:
                # seal-by-absence: record the miss so late pushes drop
                st = self._shuffles.setdefault(shuffle_id, {}).setdefault(
                    reduce_id, _ReduceMerge()
                )
            if st._state == "accepting":
                st._transition("sealed")
                st.pending.clear()
                st.totals.clear()
                if st.payloads:
                    try:
                        self._commit_locked(st, shuffle_id)
                    except Exception:
                        logger.warning(
                            "merged-span commit failed for shuffle=%d "
                            "reduce=%d; serving via pull",
                            shuffle_id, reduce_id, exc_info=True,
                        )
                        st.payloads = []
                        st.seg = None
            if st.seg is None:
                return (0, 0, ())
            return (st.seg.mkey, st.length, st.provenance)

    def _commit_locked(self, st: _ReduceMerge, shuffle_id: int) -> None:
        """Commit the completed payloads as one registered merged span —
        the resolver's file-backed commit shape: stream to a spill file,
        adopt into the tier store when one is wired (deferred mapping,
        disk-resident cold tier), else register the read-only mmap."""
        from sparkrdma_tpu.memory.mapped_file import MappedFile

        prov: List[ProvRow] = []
        off = 0
        for map_id, payload in st.payloads:
            prov.append((map_id, off, len(payload)))
            off += len(payload)
        tiered = self.tier_store is not None
        mf = MappedFile(
            (payload for _m, payload in st.payloads),
            directory=self.spill_dir,
            prefix="sparkrdma_tpu_merged_",
            direct_write=self.direct_io != "off",
            defer_map=tiered,
        )
        mf.direct_read_enabled = self.direct_io != "off"
        try:
            if tiered:
                seg = self.tier_store.adopt(
                    mf, [(o, n) for _m, o, n in prov], max(off, 1),
                    shuffle_id, self.arena,
                )
            else:
                seg = self.arena.register(
                    mf.array, shuffle_id=shuffle_id, keepalive=mf,
                    budgeted=False, zero_copy_ok=True,
                )
        except BaseException:
            mf.free()
            raise
        if self.node is not None:
            self.node.register_block_store(seg.mkey, self.arena)
        st.seg = seg
        st.length = off
        st.provenance = tuple(prov)
        st._transition("committed", frm="sealed")
        # assembled payloads now live in the committed file
        st.payloads = []

    # -- lifecycle ----------------------------------------------------------
    def remove_shuffle(self, shuffle_id: int) -> None:
        """Release one shuffle's merged segments + assembly state.  Runs
        BEFORE the resolver's ``remove_shuffle`` in the manager's sweep,
        so ``arena.release_shuffle`` never finds these twice."""
        with self._lock:
            states = self._shuffles.pop(shuffle_id, None)
        if not states:
            return
        for st in states.values():
            seg = st.seg
            st.seg = None
            if seg is None:
                continue
            if self.node is not None:
                self.node.unregister_block_store(seg.mkey)
            self.arena.release(seg.mkey)

    def stop(self) -> None:
        with self._lock:
            ids = list(self._shuffles.keys())
        for sid in ids:
            self.remove_shuffle(sid)
