"""Reduce-side reader: async location resolution + windowed block fetch.

Analog of RdmaShuffleReader + RdmaShuffleFetcherIterator
(RdmaShuffleReader.scala:31-127, RdmaShuffleFetcherIterator.scala:39-425),
the reference's critical path (SURVEY.md §3.4):

- local partitions short-circuit to the arena (no transport),
- per remote host: a fetch-status RPC resolves exact block locations
  (with a timeout timer → metadata fetch failure),
- locations are grouped into pending fetches of ≤ shuffle_read_block_size
  (and ≤ max_agg_block), throttled by the max_bytes_in_flight window,
- completions land in a blocking results queue consumed by the record
  iterator; failures convert to :class:`FetchFailedError` so the job
  layer can retry the stage (the reference's FetchFailedException
  bridge),
- then deserialization → aggregation → optional key sort
  (RdmaShuffleReader.scala:82-113).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Sequence

from sparkrdma_tpu.faults.injector import FAULTS
from sparkrdma_tpu.faults.retry import RetryPolicy, is_transient
from sparkrdma_tpu.metrics import counter, histogram
from sparkrdma_tpu.obs import RECORDER, TRACING, fr_event
from sparkrdma_tpu.qos import BULK, INTERACTIVE
from sparkrdma_tpu.shuffle.manager import ShuffleHandle
from sparkrdma_tpu.skew import is_split_marker
from sparkrdma_tpu.transport.channel import FnCompletionListener
from sparkrdma_tpu.rpc.messages import FetchMapStatusMsg, FetchMergeStatusMsg
from sparkrdma_tpu.utils.dbglock import dbg_lock
from sparkrdma_tpu.utils.ledger import NOOP_TICKET, ledger_acquire
from sparkrdma_tpu.utils.serde import Record
from sparkrdma_tpu.utils.trace import get_tracer
from sparkrdma_tpu.utils.types import BlockLocation, ShuffleManagerId

logger = logging.getLogger(__name__)


class FetchFailedError(Exception):
    """Remote block fetch failed; the stage should be retried
    (reference: FetchFailedException conversion,
    RdmaShuffleFetcherIterator.scala:368-373)."""

    def __init__(self, host: str, shuffle_id: int, reason: str):
        super().__init__(
            f"fetch failed from {host} (shuffle {shuffle_id}): {reason}"
        )
        self.host = host
        self.shuffle_id = shuffle_id


class MetadataFetchFailedError(FetchFailedError):
    """Location resolution timed out / failed
    (reference: MetadataFetchFailedException)."""


@dataclass
class ReadMetrics:
    local_blocks: int = 0
    remote_blocks: int = 0
    local_bytes: int = 0
    remote_bytes: int = 0
    records_read: int = 0
    # the fetch-wait split: wire-wait is time the task thread blocked
    # on bytes (the results queue / local backing-store reads);
    # decode-wait is time it spent in — or blocked on — deserialize/
    # decompress (inline on the serial path, ticket waits on the
    # pipelined one).  fetch_wait_ms stays the wire-side series the
    # pre-split consumers (stats, telemetry dashboards) already read.
    fetch_wait_ms: float = 0.0
    decode_wait_ms: float = 0.0


def flush_read_metrics(manager, shuffle_id: int, m: ReadMetrics,
                       owner) -> None:
    """Flush one reduce task's read metrics into the registry and the
    manager's per-shuffle telemetry — at most once per reader (shared
    by the pull and windowed readers; ``owner`` carries the guard)."""
    if getattr(owner, "_metrics_flushed", False):
        return
    owner._metrics_flushed = True
    counter("shuffle_read_bytes_total", source="local").inc(m.local_bytes)
    counter("shuffle_read_bytes_total", source="remote").inc(m.remote_bytes)
    counter("shuffle_blocks_read_total", source="local").inc(m.local_blocks)
    counter("shuffle_blocks_read_total", source="remote").inc(
        m.remote_blocks)
    counter("shuffle_records_read_total").inc(m.records_read)
    counter("shuffle_fetch_wait_ms_total").inc(int(m.fetch_wait_ms))
    counter("shuffle_decode_wait_ms_total").inc(int(m.decode_wait_ms))
    counter("shuffle_reduce_tasks_total").inc()
    manager.record_shuffle_read(shuffle_id, m)


@dataclass
class _PendingFetch:
    """One grouped fetch against one host
    (reference: PendingFetch, RdmaShuffleFetcherIterator.scala:112-127).
    ``qos_granted`` is the brokered in-flight credit this fetch holds
    (qos/) — released per landed stripe, remainder at settle."""

    host: ShuffleManagerId
    locations: List[BlockLocation]
    total_bytes: int
    # aligned with ``locations`` when the group carries skew sub-blocks:
    # ``(map_id, reduce_id, sub_idx, num_subs)`` per split entry, None
    # per ordinary block; None for an all-ordinary group (the default
    # path allocates nothing)
    tags: Optional[List[Any]] = None
    qos_granted: int = 0
    # resource-ledger tickets (utils/ledger.py) for the window bytes /
    # brokered credits this fetch holds while on the wire
    win_tkt: Any = NOOP_TICKET
    qos_tkt: Any = NOOP_TICKET
    # in-task retry state (faults/retry.py): failures observed so far
    # and the monotonic stamp of the first one (the deadline anchor)
    attempts: int = 0
    first_failure_at: float = 0.0
    # push-based merged shuffle (shuffle/push.py): ``(reduce_id,
    # provenance_rows)`` when this fetch is ONE merged per-reduce span.
    # The rows — ``(map_id, rel_off, rel_len)`` — slice the landed span
    # back into per-map blocks, and a failure degrades exactly those
    # (map, reduce) pairs to the pull path instead of failing the stage.
    merged: Optional[Any] = None


class _Result:
    __slots__ = ("blocks", "host", "error", "latency_ms", "tags")

    def __init__(self, blocks=None, host=None, error=None, latency_ms=0.0,
                 tags=None):
        self.blocks = blocks
        self.host = host
        self.error = error
        self.latency_ms = latency_ms
        self.tags = tags  # sub-block tags aligned with blocks (or None)


class ShuffleReader:
    """Reads partitions [start_partition, end_partition) of one shuffle."""

    def __init__(
        self,
        manager,
        handle: ShuffleHandle,
        start_partition: int,
        end_partition: int,
        maps_by_host: Dict[ShuffleManagerId, List[int]],
    ):
        self.manager = manager
        self.handle = handle
        self.start_partition = start_partition
        self.end_partition = end_partition
        self.maps_by_host = maps_by_host
        self.metrics = ReadMetrics()
        self._results: "queue.Queue[_Result]" = queue.Queue()
        self._pending: List[_PendingFetch] = []  # guarded-by: _pending_lock
        self._pending_lock = dbg_lock("reader.pending", 30)
        # resource: reader.inflight_bytes (windowed fetch bytes on the wire)
        self._bytes_in_flight = 0  # guarded-by: _pending_lock
        # non-empty remote blocks not yet delivered
        self._outstanding_blocks = 0  # guarded-by: _pending_lock
        # hosts whose locations are unresolved
        self._awaiting_hosts = 0  # guarded-by: _pending_lock
        self._failed: Optional[FetchFailedError] = None
        # (host, mkey, address) triples already hinted to their serving
        # peer — each upcoming block is announced at most once, and the
        # key MUST carry the host: every executor's arena numbers mkeys
        # from 1 and symmetric outputs land at identical offsets, so a
        # host-less key would collide across peers and silently
        # suppress their hints (memory/tier.py prefetch;
        # guarded-by: _pending_lock)
        self._hinted: set = set()
        self._timers: List[threading.Timer] = []
        self._callback_ids: List[int] = []
        self._metrics_flushed = False
        # decode-ahead stream (shuffle/decode.py): opened by read()
        # when decodeThreads > 0; on_success then submits landed blocks
        # to the pool and the consumer sees tickets instead of raw
        # payloads.  None = the legacy serial task-thread decode.
        self._decode_stream = None
        # multi-tenant QoS (qos/): this reader's tenant and the
        # manager-wide brokered in-flight window — every concurrent
        # reader's fetch bytes share one weighted budget instead of
        # each holding a private maxBytesInFlight (None = QoS off,
        # the per-reader window alone throttles, exactly as before)
        self._tenant = manager.qos_tenant_for(handle)
        # resource: reader.qos_inflight_bytes (brokered fetch credits)
        self._inflight = manager.qos_inflight_broker()
        self._pump_registered = False
        # skew sub-block sequencing (skew/): a split partition's
        # sub-blocks are interleaved across the fetch plan on purpose,
        # but the merge must see the partition as one contiguous
        # in-sub-order stream for the bit-exactness argument to hold —
        # landed sub-blocks park here until the set completes.  Peak
        # residency is bounded by what the unsplit path holds as ONE
        # block payload anyway.
        # resource: reader.skew_reorder_bytes (parked sub-block payloads)
        # (mid, rid) -> {sub index: (payload, ledger ticket)}
        self._sub_buf: Dict[Any, Dict[int, Any]] = {}
        # in-task fetch retry (faults/): transient transport failures
        # back off and requeue through the normal _pump path instead of
        # converting straight to FetchFailedError.  fetchRetryCount=0
        # keeps the reference posture — the first-failure path is then
        # byte-identical to the pre-retry reader (no health recording,
        # no breaker consultation, same conversion)
        conf = manager.conf
        self._retry = RetryPolicy(
            conf.fetch_retry_count, conf.fetch_retry_wait_ms,
            conf.fetch_retry_max_ms,
        )
        # peers this READER already sent through an open breaker as its
        # one probe — the breaker is node-resident and outlives the
        # task, but a fresh reader (a stage retry on a healed fleet)
        # must never be fast-failed on stale state alone: its first
        # fetch per peer always goes out, and only after THAT fails do
        # the remaining fetches take the fast path
        # (guarded-by: _pending_lock)
        self._breaker_probes: set = set()
        # push-based merged shuffle (shuffle/push.py): merged-first plan
        # state — one phase guard on _awaiting_hosts covers the whole
        # merge-status round, so the consumer cannot observe a false
        # idle between the queries going out and the plan settling
        self._push_state: Optional[Dict[str, Any]] = None  # guarded-by: _pending_lock
        self._push_timer: Optional[threading.Timer] = None
        # every map id this reader owes output for — merged provenance
        # rows outside this set (a speculative attempt the map-output
        # tracker never committed) are neither consumed nor counted as
        # coverage, so delivery stays exactly-once per (map, reduce)
        self._expected_maps: set = set()
        self._m_fetch_latency = histogram("shuffle_remote_fetch_ms")
        self._m_local_read = histogram("shuffle_local_read_ms")
        self._m_rpc_rtt = histogram("rpc_roundtrip_ms", op="fetch_status")
        self._m_merge_fanin = histogram("skew_merge_fanin")
        # distributed tracing (obs/): one root context per reduce task;
        # each issued fetch gets a child span that rides the read
        # request's v2 wire tail so serve-side events join this trace.
        # None when tracing is off or this task was sampled out — every
        # site below is a cheap ``is not None`` / RECORDER.enabled gate.
        self._trace_ctx = TRACING.start()

    # -- fetch machinery ----------------------------------------------------
    def _start_remote_fetches(self) -> Iterator:
        """Kick off async location fetches; returns a LAZY iterator of
        local block payloads (startAsyncRemoteFetches,
        RdmaShuffleFetcherIterator.scala:174-311).  Locals must stream
        one map output at a time: a local-heavy reduce of a GB-scale
        partition would otherwise hold every pread copy resident
        before the consumer sees byte one (observed as whole-partition
        RSS on the 50 GB assembled run), while remote fetches overlap
        the local consumption either way."""
        local_map_ids: List[int] = []
        if self._inflight is not None and not self._pump_registered:
            # brokered window: a credit release anywhere re-pumps this
            # reader's pending queue (unregistered at cleanup)
            self._pump_registered = True
            self._inflight.add_pump(self._pump)
        reduce_ids = range(self.start_partition, self.end_partition)
        if self.manager.conf.push_enabled:
            # push-based merged shuffle: EVERY map output — the local
            # short-circuit included — resolves through the merged-first
            # plan, so coverage accounting stays uniform and each
            # (map, reduce) pair is delivered exactly once.  A merged
            # span freely interleaves local and remote maps' bytes;
            # consuming it remotely while also short-circuiting locals
            # would double-deliver, and skipping spans that contain
            # local bytes would forfeit most of the sequential win.
            # Local data rides transport-to-self, same as a reader that
            # happens to BE its reduce partition's merger.
            self._start_push_phase(reduce_ids)
            return iter(())
        for host, map_ids in self.maps_by_host.items():
            if host == self.manager.local_smid:
                local_map_ids.extend(map_ids)
                continue

            pairs = [(mid, rid) for mid in map_ids for rid in reduce_ids]
            if not pairs:
                continue
            with self._pending_lock:
                self._awaiting_hosts += 1
            self._query_locations(
                host, pairs,
                lambda locs, host=host, pairs=pairs:
                    self._on_primary_locations(host, pairs, locs),
            )

        def _iter_local() -> Iterator:
            # local_blocks/local_bytes count in _iter_block_bytes at
            # CONSUMPTION — NOT here, where the decode-ahead wrapper
            # pulls payloads up to decodeAheadBytes early: an abandoned
            # iteration must report only what was actually read (remote
            # counters behave the same — blocks left in the results
            # queue at cleanup were never yielded)
            for mid in local_map_ids:
                # one batched backing-store read per map output
                # (device segments pay a host round-trip per
                # Segment read; read_many fetches the union span)
                t0 = time.monotonic()
                blocks = self.manager.resolver.get_local_blocks(
                    self.handle.shuffle_id, mid, reduce_ids
                )
                # local payloads used to bypass fetch-wait/latency
                # accounting entirely; the backing-store read is this
                # path's wire time, so the wire-wait/decode-wait split
                # stays honest on loopback-heavy reduces
                dt_ms = (time.monotonic() - t0) * 1000
                self.metrics.fetch_wait_ms += dt_ms
                self._m_local_read.observe(dt_ms)
                for data in blocks:
                    if len(data):  # ndarray views: no bool()
                        yield data

        return _iter_local()

    def _on_metadata_timeout(self, host: ShuffleManagerId) -> None:
        self._fail(
            MetadataFetchFailedError(
                host.host, self.handle.shuffle_id,
                f"no location response within "
                f"{self.manager.conf.partition_location_fetch_timeout_ms}ms",
            )
        )

    def _query_locations(self, host: ShuffleManagerId, pairs, on_ok) -> None:
        """One fetch-status round against the driver for ``pairs`` =
        (map_id, table row) index pairs; ``on_ok`` receives the resolved
        locations.  Shared by the primary round (map × reduce pairs) and
        the skew follow-up round, whose rows are sub-block entries in
        the extended table (skew/) — the driver plane serves both
        identically, which is why splitting needs zero wire change."""
        conf = self.manager.conf
        counter("shuffle_fetch_rpcs_total", mode="location").inc()
        t0 = time.monotonic()
        timer = threading.Timer(
            conf.partition_location_fetch_timeout_ms / 1000.0,
            self._on_metadata_timeout,
            args=(host,),
        )
        timer.daemon = True
        self._timers.append(timer)

        def on_locations(locs, timer=timer, t0=t0):
            timer.cancel()
            rtt_ms = (time.monotonic() - t0) * 1000
            self._m_rpc_rtt.observe(rtt_ms)
            logger.debug(
                "locations for %s resolved in %.1fms",
                host.host, rtt_ms,
            )
            on_ok(locs)

        def on_status_failed(reason, timer=timer):
            # driver answered negatively (executor lost / shuffle
            # unregistered): fail NOW, not at the timeout
            timer.cancel()
            self._fail(MetadataFetchFailedError(
                host.host, self.handle.shuffle_id, reason
            ))

        cb_id = self.manager.register_fetch_callback(
            on_locations, on_status_failed
        )
        self._callback_ids.append(cb_id)
        ctx = self._trace_ctx
        msg = FetchMapStatusMsg(
            self.manager.local_smid, host, self.handle.shuffle_id,
            cb_id, pairs,
            trace_id=ctx.trace_id if ctx is not None else 0,
            span_id=ctx.span_id if ctx is not None else 0,
        )
        timer.start()
        try:
            if FAULTS.enabled:
                FAULTS.check("location_rpc")
            # _send_driver_msg retries once if the cached driver
            # channel was evicted from the bounded cache between
            # lookup and post (reconnects transparently)
            self.manager._send_driver_msg(
                msg,
                on_failure=lambda e: self._fail(
                    MetadataFetchFailedError(
                        host.host, self.handle.shuffle_id,
                        f"status rpc failed: {e}",
                    )
                ),
            )
        except Exception as e:
            self._fail(MetadataFetchFailedError(
                host.host, self.handle.shuffle_id, str(e)))

    def _on_primary_locations(self, host: ShuffleManagerId, pairs,
                              locs) -> None:
        """Primary fetch-status response.  A split partition answers
        with a MARKER entry (skew/) naming its sub-block rows in the
        extended table; resolving those costs ONE more fetch-status
        round against the same plane, after which the sub-blocks join
        this host's fetch plan as ordinary blocks.  ``_awaiting_hosts``
        stays elevated across the second round — ``_enqueue_fetches``
        is the sole decrementer and still runs exactly once per host."""
        markers = [
            (i, loc) for i, loc in enumerate(locs) if is_split_marker(loc)
        ]
        if not markers:
            self._enqueue_fetches(host, locs)
            return
        # aux rows in enumeration order, matching the writer's
        # ascending-pid aux allocation (resolver._put_partition_entry)
        aux_pairs = [
            (pairs[i][0], loc.address + j)
            for i, loc in markers
            for j in range(loc.length)
        ]
        self._query_locations(
            host, aux_pairs,
            lambda aux_locs: self._on_aux_locations(
                host, pairs, locs, markers, aux_locs
            ),
        )

    def _on_aux_locations(self, host: ShuffleManagerId, pairs, locs,
                          markers, aux_locs) -> None:
        """Second-round response: substitute each marker's sub-blocks
        and interleave.  Sub-blocks are dealt depth-wise round-robin
        across per-origin queues so one hot partition's bytes spread
        over fetch groups instead of arriving as one serial lump — the
        balanced-fetch half of the skew story — while the
        ``(map, reduce, sub, of)`` tags let the consumer re-sequence
        them for the bit-exact merge."""
        marker_at = dict(markers)
        cursor = 0
        # one queue per origin block: ordinary entries (empties
        # included — _enqueue_fetches skips them but the wake-up /
        # termination accounting wants the full list) are singletons,
        # split partitions contribute their sub-blocks in sub order
        origins: List[List] = []
        for i, loc in enumerate(locs):
            m = marker_at.get(i)
            if m is None:
                origins.append([(loc, None)])
                continue
            mid, rid = pairs[i]
            subs = aux_locs[cursor:cursor + m.length]
            cursor += m.length
            if len(subs) != m.length or any(
                s.is_empty or is_split_marker(s) for s in subs
            ):
                # a sub row that is empty, missing, or itself a marker
                # means the table we resolved against is torn — treat
                # it as a metadata failure so the stage retries
                self._fail(MetadataFetchFailedError(
                    host.host, self.handle.shuffle_id,
                    f"bad sub-block rows for map {mid} partition {rid}",
                ))
                return
            origins.append([
                (sub, (mid, rid, j, m.length))
                for j, sub in enumerate(subs)
            ])
        out_locs: List[BlockLocation] = []
        out_tags: List[Any] = []
        depth = 0
        while True:
            row = [org[depth] for org in origins if depth < len(org)]
            if not row:
                break
            for loc, tag in row:
                out_locs.append(loc)
                out_tags.append(tag)
            depth += 1
        self._enqueue_fetches(host, out_locs, out_tags)

    # -- push-based merged shuffle (shuffle/push.py) -------------------------
    def _start_push_phase(self, reduce_ids) -> None:
        """Merged-first plan: ask each reduce partition's deterministic
        merger (manager.push_merger_for — the writers pushed there) for
        its merged span, then pull only what the answered provenance
        does not cover.  Best-effort throughout: an unreachable, pre-v3,
        timed-out or fault-drilled merger simply contributes no
        coverage, and those pairs ride the unchanged pull path —
        bit-exact always, the stage never retries over push."""
        mgr = self.manager
        self._expected_maps = {
            mid for ids in self.maps_by_host.values() for mid in ids
        }
        mergers: Dict[ShuffleManagerId, List[int]] = {}
        for rid in reduce_ids:
            m = mgr.push_merger_for(rid)
            if m is not None:
                mergers.setdefault(m, []).append(rid)
        state = {
            "remaining": len(mergers),
            "answered": set(),   # mergers already counted (idempotence)
            "coverage": {},      # rid -> (merger, mkey, length, prov)
            "done": False,
        }
        with self._pending_lock:
            self._push_state = state
            # the phase guard: held until _finish_push_phase has planned
            # every merged fetch and pull re-query
            self._awaiting_hosts += 1
        if not mergers:
            self._finish_push_phase({})
            return
        timer = threading.Timer(
            mgr.conf.push_merge_timeout_ms / 1000.0, self._on_push_timeout,
        )
        timer.daemon = True
        self._timers.append(timer)
        self._push_timer = timer
        timer.start()
        for host, rids in mergers.items():
            self._query_merger(host, rids)

    def _query_merger(self, host: ShuffleManagerId, rids: List[int]) -> None:
        """One merge-status round against one merger.  Every failure
        mode — send failure, merger-side MergeUnavailable, pre-v3 peer —
        lands in ``_merger_answered`` with no coverage."""
        mgr = self.manager
        counter("shuffle_fetch_rpcs_total", mode="merge_status").inc()

        def on_status(result, host=host):
            self._merger_answered(host, [
                (rid, mkey, length, prov)
                for rid, (mkey, length, prov) in result.items()
            ])

        def on_error(reason, host=host):
            counter("push_merge_query_failures_total").inc()
            logger.debug("merger %s gave no coverage: %s",
                         host.host, reason)
            self._merger_answered(host, [])

        if host == mgr.local_smid:
            # the reader's own manager is the merger: seal and answer
            # in-process — no reply channel to self needed.  The merged
            # FETCH still rides the transport (to self), keeping the
            # data path uniform.
            try:
                answers = mgr.push_merger.merge_status(
                    self.handle.shuffle_id, rids)
            except Exception as e:
                on_error(str(e))
                return
            self._merger_answered(host, answers)
            return
        cb_id = mgr.register_merge_callback(on_status, on_error)
        self._callback_ids.append(cb_id)
        msg = FetchMergeStatusMsg(
            mgr.local_smid, self.handle.shuffle_id, cb_id, rids,
        )
        mgr.send_merge_query(host, msg,
                             on_failure=lambda e: on_error(str(e)))

    def _merger_answered(self, host: ShuffleManagerId, answers) -> None:
        """Fold one merger's answers into the plan; the LAST answer (or
        the phase timeout, whichever first) settles it."""
        with self._pending_lock:
            state = self._push_state
            if state["done"] or host in state["answered"]:
                return
            state["answered"].add(host)
            for rid, mkey, length, prov in answers:
                if mkey and length > 0:
                    state["coverage"][rid] = (host, mkey, length,
                                              tuple(prov))
            state["remaining"] -= 1
            if state["remaining"] > 0:
                return
            state["done"] = True
            coverage = dict(state["coverage"])
        if self._push_timer is not None:
            self._push_timer.cancel()
        self._finish_push_phase(coverage)

    def _on_push_timeout(self) -> None:
        """The merge-status round overran pushMergeTimeout: settle the
        plan from whatever answered — unanswered mergers contribute no
        coverage and their partitions pull.  Never a stage failure (the
        metadata-timeout analog deliberately does NOT apply: push is
        advisory, the pull plane still owns every block)."""
        with self._pending_lock:
            state = self._push_state
            if state["done"]:
                return
            state["done"] = True
            coverage = dict(state["coverage"])
        counter("push_merge_timeouts_total").inc()
        logger.warning(
            "merge-status round timed out after %dms; "
            "unanswered mergers fall back to pull",
            self.manager.conf.push_merge_timeout_ms,
        )
        self._finish_push_phase(coverage)

    def _finish_push_phase(self, coverage: Dict) -> None:
        """The merged-first plan is settled: enqueue one sequential
        fetch per merged span, route every uncovered (map, reduce) pair
        through the unchanged pull path, release the phase guard."""
        reduce_ids = range(self.start_partition, self.end_partition)
        expected = self._expected_maps
        covered = set()
        for rid, (_h, _mkey, _length, prov) in coverage.items():
            for mid, _off, _ln in prov:
                if mid in expected:
                    covered.add((mid, rid))
        pull_by_host = []
        for host, map_ids in self.maps_by_host.items():
            pairs = [
                (mid, rid)
                for mid in map_ids for rid in reduce_ids
                if (mid, rid) not in covered
            ]
            if pairs:
                pull_by_host.append((host, pairs))
        with self._pending_lock:
            self._awaiting_hosts += len(pull_by_host)
        for host, pairs in pull_by_host:
            self._query_locations(
                host, pairs,
                lambda locs, host=host, pairs=pairs:
                    self._on_primary_locations(host, pairs, locs),
            )
        for rid in sorted(coverage):
            host, mkey, length, prov = coverage[rid]
            self._enqueue_merged(host, rid, mkey, length, prov)
        with self._pending_lock:
            self._awaiting_hosts -= 1  # release the phase guard
        self._results.put(_Result(blocks=[], host=None))
        self._pump()

    def _enqueue_merged(self, host: ShuffleManagerId, rid: int, mkey: int,
                        length: int, prov) -> None:
        """One merged per-reduce span as ONE pending fetch — a single
        sequential read of the whole span, never re-grouped by
        read_block_size (that cap shapes RANDOM pull batches; splitting
        the sequential run would reintroduce exactly the seeks push
        removes).  Outstanding-block accounting counts the per-map
        blocks the span will deliver, matching the consumer's
        per-result decrement."""
        rows = tuple(r for r in prov if r[0] in self._expected_maps)
        if not rows:
            return  # nothing consumable: the pairs pulled above
        loc = BlockLocation(0, length, mkey)
        pf = _PendingFetch(host, [loc], length, merged=(rid, rows))
        with self._pending_lock:
            self._outstanding_blocks += len(rows)
            self._pending.append(pf)
        if RECORDER.enabled:
            ctx = self._trace_ctx
            fr_event(
                "reader", "merged_enqueue",
                trace_id=ctx.trace_id if ctx is not None else 0,
                host=host.host, reduce_id=rid, blocks=len(rows),
                bytes=length,
            )

    def _slice_merged(self, fetch: _PendingFetch, blocks) -> List:
        """Slice one landed merged span back into its per-map blocks
        (zero-copy views) along the provenance rows the plan consumed —
        from here on they are ordinary remote blocks to the decode and
        merge stages."""
        _rid, rows = fetch.merged
        payload = blocks[0]
        view = (
            memoryview(payload)
            if isinstance(payload, (bytes, bytearray)) else payload
        )
        return [view[off:off + ln] for _mid, off, ln in rows]

    def _repull_merged(self, fetch: _PendingFetch, err) -> None:
        """A merged-span fetch failed (the merger died after planning,
        or its breaker is open): degrade exactly its pairs to the pull
        path — never the stage.  The span's provenance names the
        (map, reduce) pairs this fetch owed; re-query their origin
        hosts like a primary round."""
        rid, rows = fetch.merged
        counter("push_merged_fetch_fallbacks_total").inc()
        logger.warning(
            "merged fetch for reduce %d from %s failed (%s); "
            "pulling its %d blocks", rid, fetch.host.host, err, len(rows),
        )
        if RECORDER.enabled:
            root = self._trace_ctx
            fr_event(
                "reader", "merged_fallback",
                trace_id=root.trace_id if root is not None else 0,
                host=fetch.host.host, reduce_id=rid, blocks=len(rows),
            )
        owner = {
            mid: host
            for host, ids in self.maps_by_host.items() for mid in ids
        }
        by_host: Dict[ShuffleManagerId, List] = {}
        for mid, _off, _ln in rows:
            h = owner.get(mid)
            if h is not None:
                by_host.setdefault(h, []).append((mid, rid))
        with self._pending_lock:
            self._outstanding_blocks -= len(rows)
            self._awaiting_hosts += len(by_host)
        for h, pairs in by_host.items():
            self._query_locations(
                h, pairs,
                lambda locs, host=h, pairs=pairs:
                    self._on_primary_locations(host, pairs, locs),
            )
        self._results.put(_Result(blocks=[], host=fetch.host))
        self._pump()

    def _enqueue_fetches(self, host: ShuffleManagerId,
                         locations: Sequence[BlockLocation],
                         tags: Optional[Sequence[Any]] = None) -> None:
        """Group locations into bounded fetches
        (RdmaShuffleFetcherIterator.scala:214-240).  ``tags`` rides
        along per location (skew sub-block identity or None)."""
        conf = self.manager.conf
        group: List[BlockLocation] = []
        gtags: List[Any] = []
        group_bytes = 0
        new_fetches: List[_PendingFetch] = []
        nonempty = 0

        def close_group():
            new_fetches.append(_PendingFetch(
                host, group, group_bytes,
                tags=gtags if any(t is not None for t in gtags) else None,
            ))

        for idx, loc in enumerate(locations):
            if loc.is_empty:
                continue
            nonempty += 1
            if group and (
                group_bytes + loc.length > conf.shuffle_read_block_size
                or group_bytes + loc.length > conf.max_agg_block
            ):
                close_group()
                group, gtags, group_bytes = [], [], 0
            group.append(loc)
            gtags.append(tags[idx] if tags is not None else None)
            group_bytes += loc.length
        if group:
            close_group()
        with self._pending_lock:
            self._outstanding_blocks += nonempty
            self._pending.extend(new_fetches)
            self._awaiting_hosts -= 1
        if RECORDER.enabled:
            ctx = self._trace_ctx
            for pf in new_fetches:
                fr_event(
                    "reader", "fetch_enqueue",
                    trace_id=ctx.trace_id if ctx is not None else 0,
                    host=pf.host.host, blocks=len(pf.locations),
                    bytes=pf.total_bytes,
                )
        # announce the head of this host's fetch plan before the first
        # read is even issued — the responder's tier warms those blocks
        # off disk while the RPCs are still in flight
        self._send_hint(host)
        # deliver a wake-up marker even if everything was empty so the
        # consumer can re-check its termination condition
        self._results.put(_Result(blocks=[], host=host))
        self._pump()

    def _pump(self) -> None:
        """Issue pending fetches within the in-flight byte window
        (RdmaShuffleFetcherIterator.scala:241-251,357-366).  With QoS
        on, each fetch additionally acquires its bytes from the
        manager's brokered in-flight budget (weighted across tenants,
        per-tenant ``qosTenantMaxInFlight`` cap) — a denied fetch goes
        back to the head of the queue and the broker re-pumps this
        reader when credits release."""
        conf = self.manager.conf
        broker = self._inflight
        while True:
            with self._pending_lock:
                if not self._pending:
                    return
                if (
                    self._bytes_in_flight > 0
                    and self._bytes_in_flight + self._pending[0].total_bytes
                    > conf.max_bytes_in_flight
                ):
                    return
                fetch = self._pending.pop(0)
                self._bytes_in_flight += fetch.total_bytes
            # the window reservation rides the fetch: landed stripes
            # release piecewise, the completion/failure settle closes
            # the remainder
            # owns: reader.inflight_bytes -> on_progress
            # owns: reader.inflight_bytes -> settle
            fetch.win_tkt = ledger_acquire(
                "reader.inflight_bytes", fetch.total_bytes
            )  # acquires: reader.inflight_bytes
            if broker is not None:
                granted = broker.clamp(fetch.total_bytes)
                cls = (
                    INTERACTIVE
                    if self._tenant is not None
                    and self._tenant.interactive else BULK
                )
                seq = broker.release_seq
                if not broker.try_acquire(granted, self._tenant, cls):
                    # over share/quota: requeue at the head — the
                    # broker's release pump retries this reader
                    with self._pending_lock:
                        self._bytes_in_flight -= fetch.total_bytes
                        self._pending.insert(0, fetch)
                    tkt, fetch.win_tkt = fetch.win_tkt, NOOP_TICKET
                    tkt.release()  # releases: reader.inflight_bytes
                    if broker.release_seq != seq:
                        # a release's pump fired INSIDE our deny-and-
                        # requeue window and saw an empty queue — that
                        # wakeup was for us; retry now instead of
                        # waiting for a release that may never come
                        continue
                    return
                fetch.qos_granted = granted
                # owns: reader.qos_inflight_bytes -> on_progress
                # owns: reader.qos_inflight_bytes -> settle
                fetch.qos_tkt = ledger_acquire(
                    "reader.qos_inflight_bytes", granted
                )  # acquires: reader.qos_inflight_bytes
            self._issue(fetch)

    def _send_hint(self, host: ShuffleManagerId) -> None:
        """Announce the next blocks of THIS host's fetch plan so its
        tiered store can warm them off disk before the read RPCs land
        (PrefetchHintMsg — the reader knows its whole plan, the
        responder owns the residency).  Each block is hinted once;
        hints are advisory and never fail the fetch."""
        conf = self.manager.conf
        n = conf.tier_hint_blocks
        if n <= 0 or not conf.tier_prefetch:
            return
        # bounded scan: _pending is plan-ordered and shrinks as fetches
        # issue, so the next unhinted blocks live near its head — give
        # up after examining a few hint-windows' worth rather than
        # sweeping the whole remaining plan under the hot-path lock on
        # every issue (the window advances as the head drains)
        scan_budget = 4 * n
        with self._pending_lock:
            fresh: List[BlockLocation] = []
            for pf in self._pending:
                if pf.host != host:
                    continue
                for loc in pf.locations:
                    scan_budget -= 1
                    key = (host, loc.mkey, loc.address)
                    if key not in self._hinted:
                        self._hinted.add(key)
                        fresh.append(loc)
                    if len(fresh) >= n or scan_budget <= 0:
                        break
                if len(fresh) >= n or scan_budget <= 0:
                    break
        if fresh:
            self.manager.send_prefetch_hint(
                host, self.handle.shuffle_id, fresh
            )

    def _issue(self, fetch: _PendingFetch) -> None:
        # warm the blocks we will ask for NEXT while this fetch is on
        # the wire — the disk reads overlap the transfer instead of
        # serializing behind it
        self._send_hint(fetch.host)
        # the push/pull RPC ledger the bench reads: one increment per
        # read RPC actually put on the wire (retries re-count — they
        # ARE another RPC)
        mode = "push" if fetch.merged is not None else "pull"
        counter("shuffle_fetch_rpcs_total", mode=mode).inc()
        counter("shuffle_fetch_rpc_bytes", mode=mode).inc(fetch.total_bytes)
        t0 = time.monotonic()
        # per-fetch child span: carried on the read request's v2 wire
        # tail so the serving peer's events join this reader's trace
        root = self._trace_ctx
        ctx = root.child() if root is not None else None
        if RECORDER.enabled:
            fr_event(
                "reader", "fetch_issue",
                trace_id=ctx.trace_id if ctx is not None else 0,
                span_id=ctx.span_id if ctx is not None else 0,
                host=fetch.host.host, bytes=fetch.total_bytes,
                attempt=fetch.attempts,
            )
        progressed = [0]
        settled = [False]
        done = [False]
        broker = self._inflight
        qos_left = [fetch.qos_granted]
        peer = (fetch.host.host, fetch.host.port)
        # per-peer recovery state, consulted only with retry on (the
        # fetchRetryCount=0 path must stay byte-identical)
        health = (
            self.manager.node.peer_health(peer)
            if self._retry.enabled else None
        )

        def on_progress(n):
            if RECORDER.enabled:
                fr_event(
                    "transport", "stripe_land",
                    trace_id=ctx.trace_id if ctx is not None else 0,
                    span_id=ctx.span_id if ctx is not None else 0,
                    bytes=n,
                )
            # stripe-granular window accounting: each landed stripe (or
            # small block) frees its bytes from the in-flight window
            # IMMEDIATELY, so the next pending fetch can issue while
            # the rest of a big striped block is still crossing the
            # wire — the window throttles bytes, not whole blocks
            with self._pending_lock:
                if settled[0]:
                    # a lane's progress racing the group's completion/
                    # failure must not release bytes settle() already
                    # reclaimed (the window would over-admit)
                    return
                progressed[0] += n
                self._bytes_in_flight -= n
                rel = min(n, qos_left[0])
                qos_left[0] -= rel
            fetch.win_tkt.release(n)  # releases: reader.inflight_bytes
            if rel and broker is not None:
                # brokered credits free per stripe too (outside the
                # pending lock: the release's grant scan runs pumps)
                broker.release(rel, self._tenant)
                fetch.qos_tkt.release(rel)  # releases: reader.qos_inflight_bytes
            self._pump()

        def settle():
            # idempotent: release whatever progress callbacks didn't.
            # EXPLICIT remainders, never the no-arg close: a progress
            # callback claims its n under the lock but releases the
            # ticket after dropping it, so a no-arg settle racing that
            # window closes the ticket first and turns the late
            # release(n) into a double release (the schedule shaker
            # caught exactly this interleaving in the tcp-async soak).
            # With amounts pinned to the under-lock claims the releases
            # sum to the acquisition exactly, in any order.
            with self._pending_lock:
                if settled[0]:
                    return
                settled[0] = True
                left = fetch.total_bytes - progressed[0]
                if left > 0:
                    self._bytes_in_flight -= left
                rel = qos_left[0]
                qos_left[0] = 0
            fetch.win_tkt.release(left)  # releases: reader.inflight_bytes  # one-shot
            if rel and broker is not None:
                broker.release(rel, self._tenant)
            fetch.qos_tkt.release(rel)  # releases: reader.qos_inflight_bytes # one-shot

        def finish_once() -> bool:
            # the group's FIRST outcome wins: a channel torn down while
            # its completion is in flight can fail a listener from both
            # the reads drain and the outstanding drain (or fail after
            # a success already landed) — a second outcome must neither
            # deliver blocks twice nor schedule a second retry timer
            # for the same fetch
            with self._pending_lock:
                if done[0]:
                    return False
                done[0] = True
                return True

        def on_success(blocks):
            if not finish_once():
                return
            latency = (time.monotonic() - t0) * 1000
            settle()
            if health is not None:
                health.breaker.record_success()
            if self.manager.stats is not None:
                self.manager.stats.update(fetch.host.host, latency)
            self._m_fetch_latency.observe(latency)
            get_tracer().instant(
                "shuffle.fetch.complete", host=fetch.host.host,
                bytes=fetch.total_bytes, latency_ms=round(latency, 2),
            )
            if RECORDER.enabled:
                fr_event(
                    "reader", "fetch_land",
                    trace_id=ctx.trace_id if ctx is not None else 0,
                    span_id=ctx.span_id if ctx is not None else 0,
                    host=fetch.host.host, bytes=fetch.total_bytes,
                    us=int(latency * 1000),
                )
            if fetch.merged is not None:
                # merged span: slice back into per-map blocks before
                # the decode stage — downstream sees ordinary blocks
                blocks = self._slice_merged(fetch, blocks)
            stream = self._decode_stream
            if stream is not None:
                # decode-ahead: landed payloads go to the pool NOW,
                # while the task thread may still be consuming earlier
                # results — the consumer receives tickets (len() keeps
                # the byte accounting identical) in the same order
                blocks = [stream.submit_block(b) for b in blocks]
            self._results.put(
                _Result(blocks=blocks, host=fetch.host, latency_ms=latency,
                        tags=fetch.tags)
            )
            self._pump()

        def on_failure(err):
            if not finish_once():
                return
            settle()
            # the peer's striped group just failed a read: drop its
            # cached read group so the retried stage (or the next
            # reader) rebuilds lanes from scratch instead of riding a
            # group whose peer may be gone
            self.manager.node.invalidate_read_group(
                (fetch.host.host, fetch.host.port)
            )
            if fetch.merged is not None:
                # best-effort posture: a dead merger costs pull
                # traffic, never the stage (and never a retry timer —
                # the pull plane re-resolves from live peers directly)
                if health is not None:
                    health.breaker.record_failure()
                self._repull_merged(fetch, err)
                return
            if health is None:
                self._fail(
                    FetchFailedError(
                        fetch.host.host, self.handle.shuffle_id, str(err)
                    )
                )
                return
            health.breaker.record_failure()
            now = time.monotonic()
            if fetch.attempts == 0:
                # the retry deadline is budgeted from the FIRST failure,
                # not per attempt — a peer limping along cannot stretch
                # the task past fetchRetryMaxMs by failing slowly
                fetch.first_failure_at = now
            fetch.attempts += 1
            elapsed_ms = (now - fetch.first_failure_at) * 1000.0
            delay_ms = self._retry.next_delay_ms(fetch.attempts, elapsed_ms)
            if (
                is_transient(err)
                and delay_ms is not None
                and self._failed is None
                and health.breaker.allow()
            ):
                counter("shuffle_fetch_retries_total").inc()
                counter("shuffle_fetch_retry_ms_total").inc(int(delay_ms))
                get_tracer().instant(
                    "shuffle.fetch.retry", host=fetch.host.host,
                    attempt=fetch.attempts, delay_ms=round(delay_ms, 1),
                )
                if RECORDER.enabled:
                    fr_event(
                        "reader", "fetch_retry",
                        trace_id=ctx.trace_id if ctx is not None else 0,
                        host=fetch.host.host, attempt=fetch.attempts,
                        delay_ms=int(delay_ms),
                    )
                tm = threading.Timer(
                    delay_ms / 1000.0, self._requeue, args=(fetch,)
                )
                tm.daemon = True
                with self._pending_lock:
                    self._timers.append(tm)
                tm.start()
                return
            counter("shuffle_fetch_failures_total").inc()
            self._fail(
                FetchFailedError(
                    fetch.host.host, self.handle.shuffle_id, str(err)
                )
            )

        if health is not None and not health.breaker.allow():
            # breaker open: this peer burned its failure budget — fail
            # the remaining fetches fast instead of paying another full
            # connect+backoff cycle against a peer known bad.  But the
            # breaker outlives the task (node-resident by design), and
            # a stage retry's fresh reader must not inherit a fast-fail
            # for a peer that may have healed: each reader's FIRST
            # fetch per open peer goes out as the probe — success
            # closes the breaker, failure arms the fast path for the
            # fetches behind it.
            with self._pending_lock:
                probed = peer in self._breaker_probes
                self._breaker_probes.add(peer)
            if probed:
                settle()
                if fetch.merged is not None:
                    # an open merger breaker is just "no merger":
                    # degrade this span's pairs to pull
                    self._repull_merged(
                        fetch, "circuit breaker open for %s:%d" % peer
                    )
                    return
                counter("shuffle_fetch_failures_total").inc()
                self._fail(
                    FetchFailedError(
                        fetch.host.host, self.handle.shuffle_id,
                        "circuit breaker open for %s:%d" % peer,
                    )
                )
                return
        try:
            group = self.manager.node.get_read_group(
                (fetch.host.host, fetch.host.port),
                self.manager.network.connect,
            )
            group.read_blocks(
                fetch.locations,
                FnCompletionListener(on_success, on_failure),
                on_progress=on_progress,
                tenant=self._tenant,
                ctx=ctx,
            )
        except Exception as e:
            on_failure(e)

    def _fail(self, err: FetchFailedError) -> None:
        self._failed = err
        if RECORDER.enabled:
            root = self._trace_ctx
            fr_event(
                "reader", "fetch_fail",
                trace_id=root.trace_id if root is not None else 0,
                host=err.host, shuffle_id=err.shuffle_id,
                reason=str(err)[:200],
            )
            # the first FetchFailed is exactly the moment the rings
            # still hold the lead-up — dump before the stage unwinds
            RECORDER.auto_dump("fetch_failed")
        self._results.put(_Result(error=err))

    def _requeue(self, fetch: _PendingFetch) -> None:
        # timer callback: the backoff elapsed, put the fetch back at
        # the HEAD of the pending queue (it already waited its turn)
        # and let the normal pump re-acquire window + QoS tickets for
        # the new attempt.  _outstanding_blocks never dropped, so the
        # consumer keeps blocking through the backoff window.
        with self._pending_lock:
            if self._failed is not None:
                return
            self._pending.insert(0, fetch)
        self._pump()

    # -- consumption --------------------------------------------------------
    def _iter_block_bytes(self) -> Iterator:
        """Blocking consume of raw block payloads: local first, then
        remote completions (hasNext/next,
        RdmaShuffleFetcherIterator.scala:332-374).  Payloads are
        bytes-LIKE, not necessarily ``bytes``: local short-circuits and
        pooled receives hand back zero-copy views (ndarray/memoryview),
        exactly like the windowed plane's destination-row slices — the
        deserializers (utils/serde.py) take any of them without
        copying."""
        try:
            local_payloads = self._start_remote_fetches()
            if self._decode_stream is not None:
                # local payloads decode ahead too: the task thread
                # submits up to decodeAheadBytes of blocks before
                # consuming the first ticket, so local decode overlaps
                # the remote fetches already in flight
                from sparkrdma_tpu.shuffle.decode import iter_decoded_ahead

                local_payloads = iter_decoded_ahead(
                    self._decode_stream, local_payloads,
                    self.manager.conf.decode_ahead_bytes,
                )
            for item in local_payloads:
                # consumption-time accounting (tickets report the raw
                # payload size via len()), mirroring the remote side
                self.metrics.local_blocks += 1
                self.metrics.local_bytes += len(item)
                yield item
            while True:
                with self._pending_lock:
                    if (
                        self._awaiting_hosts == 0
                        and self._outstanding_blocks == 0
                        and not self._pending
                    ):
                        break
                t0 = time.monotonic()
                res = self._results.get()
                waited = (time.monotonic() - t0) * 1000
                self.metrics.fetch_wait_ms += waited
                if RECORDER.enabled:
                    root = self._trace_ctx
                    fr_event(
                        "reader", "consume_wait",
                        trace_id=root.trace_id if root is not None else 0,
                        us=int(waited * 1000),
                    )
                if res.error is not None:
                    raise res.error
                if not res.blocks:
                    continue  # wake-up marker
                with self._pending_lock:
                    self._outstanding_blocks -= len(res.blocks)
                for i, data in enumerate(res.blocks):
                    tag = res.tags[i] if res.tags is not None else None
                    if tag is None:
                        self.metrics.remote_blocks += 1
                        self.metrics.remote_bytes += len(data)
                        yield data
                    else:
                        # skew sub-block: deliver in sub-index order so
                        # the merge sees each split partition as the
                        # exact unsplit payload cut at frame boundaries
                        yield from self._sequence_sub_block(tag, data)
        finally:
            # runs on normal exhaustion, fetch failure, AND abandoned
            # iteration (GeneratorExit) — timers and callbacks never leak
            self._cleanup()

    def _sequence_sub_block(self, tag, item) -> Iterator:
        """Park one landed sub-block of a split partition (skew/) and,
        once ALL its siblings have landed, emit the whole partition
        contiguously in sub-index order.  Contiguity — not just sub
        order — is what keeps the merge bit-exact with the unsplit
        path: each sub-run is a stable slice of the map task's sorted
        partition payload, so emitting them back-to-back reconstructs
        the exact record stream of the original block at ONE stream
        position, just as an unsplit fetch would have delivered it;
        draining subs early would interleave the partition's records
        with other blocks and flip equal-key order under the stable
        merge.  Items are raw payloads or decode tickets; both report
        their payload size via ``len()``, and peak parked residency is
        bounded by what the unsplit path holds as one block payload."""
        mid, rid, sub_idx, num_subs = tag
        key = (mid, rid)
        # owns: reader.skew_reorder_bytes -> _sequence_sub_block
        # owns: reader.skew_reorder_bytes -> _cleanup
        tkt = ledger_acquire(
            "reader.skew_reorder_bytes", len(item)
        )  # acquires: reader.skew_reorder_bytes
        buf = self._sub_buf.setdefault(key, {})
        buf[sub_idx] = (item, tkt)
        if len(buf) < num_subs:
            return
        # complete: release every ticket and clear state BEFORE the
        # first yield, so an abandoned iteration (GeneratorExit
        # mid-yield) can't double-release a parked ticket
        del self._sub_buf[key]
        self._m_merge_fanin.observe(num_subs)
        ready = []
        for j in range(num_subs):
            parked, t = buf.pop(j)
            t.release()  # releases: reader.skew_reorder_bytes  # one-shot
            ready.append(parked)
        for it in ready:
            self.metrics.remote_blocks += 1
            self.metrics.remote_bytes += len(it)
            yield it

    def _iter_raw(self) -> Iterator[Record]:
        """Serial decode on the task thread (decodeThreads=0): blocks
        materialize one at a time so the decode half of the wire-wait/
        decode-wait split is measured (block-granular — payloads are
        bounded by maxAggBlock)."""
        deser = self.manager.serializer.deserialize
        for data in self._iter_block_bytes():
            t0 = time.monotonic()
            recs = list(deser(data))
            self.metrics.decode_wait_ms += (time.monotonic() - t0) * 1000
            self.metrics.records_read += len(recs)
            yield from recs

    def _resolve_decoded(self, item):
        """One pipelined block: wait for (or steal) its decode ticket;
        returns the decoded item list.  Ticket wait time is the
        decode-wait half of the fetch-wait split."""
        t0 = time.monotonic()
        items, n = item.get()
        waited = (time.monotonic() - t0) * 1000
        self.metrics.decode_wait_ms += waited
        self.metrics.records_read += n
        if RECORDER.enabled:
            root = self._trace_ctx
            fr_event(
                "reader", "decode_wait",
                trace_id=root.trace_id if root is not None else 0,
                us=int(waited * 1000), records=n,
            )
        return items

    def _iter_record_runs(self) -> Iterator[List[Record]]:
        """Pipelined tuple plane: yields one decoded (and, under
        key_ordering, worker-sorted) record list per block."""
        for item in self._iter_block_bytes():
            yield self._resolve_decoded(item)

    def _cleanup(self) -> None:
        for t in self._timers:
            t.cancel()
        # parked sub-blocks an abandoned or failed iteration never
        # drained still hold reorder-buffer tickets
        for buf in self._sub_buf.values():
            for _item, tkt in buf.values():
                tkt.release()  # releases: reader.skew_reorder_bytes  # one-shot
        self._sub_buf.clear()
        for cb_id in self._callback_ids:
            self.manager.unregister_fetch_callback(cb_id)
        if self._pump_registered:
            self._pump_registered = False
            self._inflight.remove_pump(self._pump)
        if self._decode_stream is not None:
            # poison in-flight decodes: queued tickets cancel, credits
            # release — runs on normal exhaustion, FetchFailedError AND
            # abandoned iteration, so no worker ever hangs on a dead
            # reader
            self._decode_stream.close()
        flush_read_metrics(self.manager, self.handle.shuffle_id,
                           self.metrics, self)

    def _read_columnar(self) -> Iterator[Record]:
        """Columnar read: blocks deserialize to column batches and the
        aggregate/sort stage runs as numpy kernels — the read-side half
        of the unsafe-row analog.  Yields (key, value) pairs where
        group_by_key values are numpy arrays (the columnar stand-in for
        the tuple plane's lists)."""
        deser = self.manager.serializer.deserialize_columns
        batches = []
        if self._decode_stream is not None:
            for item in self._iter_block_bytes():
                batches.extend(self._resolve_decoded(item))
        else:
            for data in self._iter_block_bytes():
                t0 = time.monotonic()
                got = list(deser(data))
                self.metrics.decode_wait_ms += (
                    time.monotonic() - t0
                ) * 1000
                for b in got:
                    self.metrics.records_read += len(b)
                batches.extend(got)
        return postprocess_column_batches(batches, self.handle)

    def read(self) -> Iterator[Record]:
        """Full read path: fetch → (decode-ahead) deserialize →
        aggregate → sort/merge (RdmaShuffleReader.scala:43-113)."""
        from sparkrdma_tpu.shuffle.decode import open_decode_stream
        from sparkrdma_tpu.shuffle.manager import ColumnarAggregator

        agg = self.handle.aggregator
        columnar = getattr(
            self.manager.serializer, "supports_columns", False
        ) and (agg is None or isinstance(agg, ColumnarAggregator))
        self._decode_stream = open_decode_stream(
            self.manager, self.handle, columnar
        )
        if columnar:
            return self._read_columnar()
        if self._decode_stream is not None:
            return postprocess_record_runs(
                self._iter_record_runs(), self.handle,
                presorted=True,  # workers sort per block (decode_fn)
            )
        return postprocess_records(self._iter_raw(), self.handle)


def postprocess_column_batches(batches, handle) -> Iterator[Record]:
    """The columnar aggregate/sort stage on deserialized ColumnBatch
    lists — shared by the pull reader and the bulk-exchange plane."""
    from sparkrdma_tpu.utils.columns import (
        combine_columns,
        concat_batches,
        group_columns,
        sorted_runs_order,
    )

    total = sum(len(b) for b in batches)
    if total == 0:
        return iter(())
    agg = handle.aggregator
    if agg is not None and agg.kind != "group":
        # reduce each block first (key-sorted blocks reduce with no
        # sort), then combine the shrunken remainders
        reduced = [combine_columns(b, agg.kind) for b in batches]
        batch = combine_columns(concat_batches(reduced), agg.kind)
        # combine output is key-sorted, so key_ordering holds too
        return iter(zip(batch.keys.tolist(), batch.vals.tolist()))
    if agg is not None:
        if all(b.key_sorted for b in batches):
            nonempty = [b for b in batches if len(b)]
            # fused native merge: ONE streaming pass copies each
            # key's contiguous run slices into the grouped output
            # (per-key values are then views) — beats both the
            # per-key Python merge and the concat+gather route.
            # A single run needs no merge at all: group_columns /
            # merge_sorted_groups below serve it with zero-copy views
            from sparkrdma_tpu.memory.staging import (
                native_merge_runs_groups,
            )

            res = None
            if len(nonempty) >= 2:
                res = native_merge_runs_groups(
                    [b.keys for b in nonempty],
                    [b.vals for b in nonempty],
                )
            if res is not None:
                uk, merged_vals, offs = res

                def _native_groups():
                    for i, k in enumerate(uk.tolist()):
                        yield k, merged_vals[offs[i]:offs[i + 1]]

                return _native_groups()
            from sparkrdma_tpu.utils.columns import merge_sorted_groups

            per = [group_columns(b) for b in nonempty]
            entries = sum(len(uk) for uk, _ in per)
            # per-key merge beats concat+gather only while the
            # Python loop stays small next to the moved bytes
            if entries <= max(1 << 15, total // 8):
                return merge_sorted_groups(per)
        cat = concat_batches(batches)
        uk, groups = group_columns(
            cat,
            # an already-key_sorted concat takes group_columns' own
            # fast path; computing the (identity) order would only
            # allocate
            order=None if cat.key_sorted
            else sorted_runs_order(batches, cat),
        )
        return iter(zip(uk.tolist(), groups))
    if handle.key_ordering:
        # streaming k-way merge over per-block sorted runs (unsorted
        # stragglers sort once per block inside) — replaces the
        # concat → global sort → whole-partition gather+tolist
        from sparkrdma_tpu.utils.columns import iter_merged_sorted_batches

        return iter_merged_sorted_batches(batches)
    return iter(concat_batches(batches))


def postprocess_record_runs(runs, handle,
                            presorted: bool = False) -> Iterator[Record]:
    """The read-side aggregate → order stage over PER-BLOCK record
    runs — the streaming replacement for materialize-then-sort
    (Spark's ``ExternalSorter`` merge phase, reduce side): with
    ``key_ordering`` and no aggregator the runs (each sorted — by the
    decode workers on the pipelined path, map-side or here otherwise)
    k-way heap-merge lazily, so peak residency is the per-block lists
    plus the heap instead of a second whole-partition sorted copy.
    Stable per-run sort + run-order-stable merge emits the exact
    sequence a stable global sort of the concatenated runs would.
    Aggregation keeps the streaming dict combine (arrival order —
    identical to the serial path's)."""
    import heapq

    agg = handle.aggregator
    if agg is not None:
        combined: Dict[Any, Any] = {}
        if handle.map_side_combine:
            # records are (key, combiner) pairs already
            for run in runs:
                for k, c in run:
                    combined[k] = (
                        agg.merge_combiners(combined[k], c)
                        if k in combined else c
                    )
        else:
            for run in runs:
                for k, v in run:
                    combined[k] = (
                        agg.merge_value(combined[k], v)
                        if k in combined else agg.create_combiner(v)
                    )
        records: Iterator[Record] = iter(combined.items())
        if handle.key_ordering:
            records = iter(sorted(records, key=lambda kv: kv[0]))
        return records
    if not handle.key_ordering:
        return (rec for run in runs for rec in run)
    run_lists: List[List[Record]] = []
    for run in runs:
        if not isinstance(run, list):
            run = list(run)
        elif not presorted:
            run = list(run)  # never mutate a caller's list in place
        if not presorted:
            run.sort(key=lambda kv: kv[0])
        if run:
            run_lists.append(run)
    if not run_lists:
        return iter(())
    if len(run_lists) == 1:
        return iter(run_lists[0])
    return heapq.merge(*run_lists, key=lambda kv: kv[0])


def postprocess_records(records: Iterator[Record], handle) -> Iterator[Record]:
    """The read-side aggregate → sort stage on one flat record iterator
    (RdmaShuffleReader.scala:82-113) — the single-run adapter over
    :func:`postprocess_record_runs`, shared by the serial pull path and
    the bulk-exchange readers."""
    return postprocess_record_runs([records], handle)
