"""Map-side shuffle writer: partition → (combine) → serialize → commit → publish.

Analog of RdmaWrapperShuffleWriter (RdmaWrapperShuffleWriter.scala:76-153).
Where the reference wraps Spark's UnsafeShuffleWriter/SortShuffleWriter
and intercepts the commit to mmap+register the produced file, this
writer owns the whole path: bucket records by partitioner, optionally
map-side combine, serialize per partition, commit into a registered HBM
segment via the resolver, and publish the location table to the driver
(the ``stop(success=true)`` publish at
RdmaWrapperShuffleWriter.scala:115-149).
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Tuple

from sparkrdma_tpu.rpc.messages import PublishMapTaskOutputMsg
from sparkrdma_tpu.shuffle.map_output import MapTaskOutput
from sparkrdma_tpu.utils.serde import Record
from sparkrdma_tpu.utils.trace import get_tracer


class WriteMetrics:
    def __init__(self):
        self.records_written = 0
        self.bytes_written = 0
        self.write_time_ms = 0.0
        self.spills = 0
        self.bytes_spilled = 0


class ShuffleWriter:
    """One writer per (shuffle, map task)."""

    def __init__(self, manager, handle, map_id: int):
        self.manager = manager
        self.handle = handle
        self.map_id = map_id
        self.metrics = WriteMetrics()
        self._buckets: List[List[Record]] = [
            [] for _ in range(handle.partitioner.num_partitions)
        ]
        self._combined: Optional[List[dict]] = (
            [dict() for _ in range(handle.partitioner.num_partitions)]
            if (handle.aggregator is not None and handle.map_side_combine)
            else None
        )
        self._stopped = False
        # spill state (Spark sort-shuffle spill role; 0 = disabled)
        self._spill_threshold = manager.conf.shuffle_spill_record_threshold
        self._records_in_memory = 0
        self._spill_file = None
        # per partition: [(offset, length)] chunks in the spill file
        self._spilled: List[List[Tuple[int, int]]] = [
            [] for _ in range(handle.partitioner.num_partitions)
        ]

    # -- write --------------------------------------------------------------
    def write(self, records: Iterable[Record]) -> None:
        t0 = time.monotonic()
        part = self.handle.partitioner.partition
        if self._combined is not None:
            agg = self.handle.aggregator
            for k, v in records:
                d = self._combined[part(k)]
                if k in d:
                    d[k] = agg.merge_value(d[k], v)
                else:
                    d[k] = agg.create_combiner(v)
                    self._records_in_memory += 1
                self.metrics.records_written += 1
                if (self._spill_threshold
                        and self._records_in_memory >= self._spill_threshold):
                    self.spill()
        else:
            for rec in records:
                self._buckets[part(rec[0])].append(rec)
                self._records_in_memory += 1
                self.metrics.records_written += 1
                if (self._spill_threshold
                        and self._records_in_memory >= self._spill_threshold):
                    self.spill()
        self.metrics.write_time_ms += (time.monotonic() - t0) * 1000

    # -- spill --------------------------------------------------------------
    def spill(self) -> None:
        """Serialize buffered buckets to the spill file and release the
        memory.  The serializer's framing is concatenation-safe, so the
        commit merges spilled chunks with the final in-memory remainder
        by plain byte concatenation; with map-side combine the reader's
        merge_combiners folds duplicate keys across spilled chunks."""
        if self._records_in_memory == 0:
            return
        serializer = self.manager.serializer
        if self._spill_file is None:
            spill_dir = self.manager.conf.spill_dir
            os.makedirs(spill_dir, exist_ok=True)
            fd, path = tempfile.mkstemp(
                prefix=f"sparkrdma_tpu_spill_{self.handle.shuffle_id}_"
                       f"{self.map_id}_",
                dir=spill_dir,
            )
            self._spill_file = os.fdopen(fd, "w+b")
            self._spill_path = path
        f = self._spill_file
        f.seek(0, os.SEEK_END)
        sources = (
            [d.items() if d else None for d in self._combined]
            if self._combined is not None
            else [b if b else None for b in self._buckets]
        )
        for pid, src in enumerate(sources):
            if src is None:
                continue
            raw = serializer.serialize(src)
            off = f.tell()
            f.write(raw)
            self._spilled[pid].append((off, len(raw)))
            self.metrics.bytes_spilled += len(raw)
        if self._combined is not None:
            self._combined = [dict() for _ in self._combined]
        else:
            self._buckets = [[] for _ in self._buckets]
        self._records_in_memory = 0
        self.metrics.spills += 1

    def _iter_partition_chunks(self, pid: int, final: bytes):
        """Yield a partition's spilled chunks (read back one at a time)
        followed by the final in-memory remainder — at most one spill
        chunk is ever resident during the commit copy."""
        for off, n in self._spilled[pid]:
            self._spill_file.seek(off)
            yield self._spill_file.read(n)
        if final:
            yield final

    def _close_spill(self) -> None:
        if self._spill_file is not None:
            f, self._spill_file = self._spill_file, None
            try:
                f.close()
            finally:
                try:
                    os.unlink(self._spill_path)
                except OSError:
                    pass

    # -- commit + publish ---------------------------------------------------
    def stop(self, success: bool = True) -> Optional[MapTaskOutput]:
        if self._stopped:
            return None
        self._stopped = True
        if not success:
            self._close_spill()
            return None
        tracer = get_tracer()
        try:
            with tracer.span(
                "shuffle.write.commit",
                shuffle=self.handle.shuffle_id, map=self.map_id,
            ):
                return self._commit()
        finally:
            self._close_spill()

    def _commit(self) -> MapTaskOutput:
        t0 = time.monotonic()
        serializer = self.manager.serializer
        if self._combined is not None:
            finals = [
                serializer.serialize(d.items()) if d else b""
                for d in self._combined
            ]
        else:
            finals = [
                serializer.serialize(b) if b else b"" for b in self._buckets
            ]
        if self._spill_file is not None:
            # merge = chunk concatenation (both serializers frame
            # concatenation-safely), STREAMED through ChunkedPayload so
            # the spilled output is never fully resident at commit
            from sparkrdma_tpu.shuffle.resolver import ChunkedPayload

            partition_bytes = []
            for pid, final in enumerate(finals):
                spilled_len = sum(n for _, n in self._spilled[pid])
                total_len = spilled_len + len(final)
                if total_len == 0:
                    partition_bytes.append(b"")
                else:
                    partition_bytes.append(ChunkedPayload(
                        total_len,
                        lambda pid=pid, final=final:
                            self._iter_partition_chunks(pid, final),
                    ))
        else:
            partition_bytes = finals
        from sparkrdma_tpu.shuffle.resolver import _payload_len

        self.metrics.bytes_written = sum(
            _payload_len(b) for b in partition_bytes
        )
        mto = self.manager.resolver.commit_map_output(
            self.handle.shuffle_id, self.map_id, partition_bytes,
            # spilled output is already on disk: commit via the mmap
            # path so peak memory stays bounded by the spill threshold
            prefer_file_backed=self._spill_file is not None,
        )
        self.manager.publish_map_output(self.handle.shuffle_id, self.map_id, mto)
        self.metrics.write_time_ms += (time.monotonic() - t0) * 1000
        return mto
