"""Map-side shuffle writer: partition → (combine) → serialize → commit → publish.

Analog of RdmaWrapperShuffleWriter (RdmaWrapperShuffleWriter.scala:76-153).
Where the reference wraps Spark's UnsafeShuffleWriter/SortShuffleWriter
and intercepts the commit to mmap+register the produced file, this
writer owns the whole path: bucket records by partitioner, optionally
map-side combine, serialize per partition, commit into a registered HBM
segment via the resolver, and publish the location table to the driver
(the ``stop(success=true)`` publish at
RdmaWrapperShuffleWriter.scala:115-149).
"""

from __future__ import annotations

import logging
import os
import tempfile
import time
from typing import Any, Iterable, List, Optional, Tuple

import numpy as np

from sparkrdma_tpu.memory.staging import native_hash_partition_order
from sparkrdma_tpu.metrics import counter
from sparkrdma_tpu.shuffle.map_output import MapTaskOutput
from sparkrdma_tpu.skew import (
    HeavyHitterSketch,
    PartitionSketch,
    get_skew,
    plan_commit_splits,
    sub_spans,
)
from sparkrdma_tpu.shuffle.partitioner import (
    HashPartitioner,
    RangePartitioner,
)
from sparkrdma_tpu.utils.columns import (
    ColumnBatch,
    combine_columns,
    concat_batches,
    stable_key_order,
    take_rows,
)
from sparkrdma_tpu.utils.serde import Record
from sparkrdma_tpu.utils.trace import get_tracer

logger = logging.getLogger(__name__)


def _chunked_payload(length: int, chunks_fn):
    from sparkrdma_tpu.shuffle.resolver import ChunkedPayload

    return ChunkedPayload(length, chunks_fn)


class WriteMetrics:
    def __init__(self):
        self.records_written = 0
        self.bytes_written = 0
        self.write_time_ms = 0.0
        self.spills = 0
        self.bytes_spilled = 0


class ShuffleWriter:
    """One writer per (shuffle, map task)."""

    def __init__(self, manager, handle, map_id: int):
        self.manager = manager
        self.handle = handle
        self.map_id = map_id
        self.metrics = WriteMetrics()
        self._buckets: List[List[Record]] = [
            [] for _ in range(handle.partitioner.num_partitions)
        ]
        self._combined: Optional[List[dict]] = (
            [dict() for _ in range(handle.partitioner.num_partitions)]
            if (handle.aggregator is not None and handle.map_side_combine)
            else None
        )
        self._stopped = False
        # columnar plane: per-partition ColumnBatch runs (created on the
        # first columnar write; a writer is tuple- OR column-mode)
        self._col_buckets: Optional[List[List[ColumnBatch]]] = None
        # pending columnar writes: (batch, order, counts) with the
        # gather DEFERRED — the no-spill commit gathers records straight
        # into the final segment buffer (one copy total); spills
        # materialize first
        self._col_pending: Optional[List[Tuple[ColumnBatch, Optional[Any], Any]]] = None
        # spill state (Spark sort-shuffle spill role; 0 = disabled)
        self._spill_threshold = manager.conf.shuffle_spill_record_threshold
        self._records_in_memory = 0
        self._spill_file = None
        # per partition: [(offset, length)] chunks in the spill file
        self._spilled: List[List[Tuple[int, int]]] = [
            [] for _ in range(handle.partitioner.num_partitions)
        ]
        # per-partition spill layout (conf spillPartitionFiles): one
        # O_DIRECT appender per partition, promoted at commit into the
        # shuffle files themselves (no consolidation rewrite)
        self._spill_appenders = None
        self._spill_io = None  # shared 1-thread flush executor
        self._spill_direct = False
        # skew detection (skew/): streaming per-partition record
        # sketch, plus a Misra-Gries hot-key sketch on aggregating
        # shuffles (hot-KEY attribution in telemetry).  Both None
        # unless skewEnabled, so the default record path pays one
        # predictable None check per record and nothing else
        self._psketch = self._hot_keys = None
        self._skew_stride = 1
        self._skew_seen = 0
        if manager.skew is not None and manager.skew.enabled:
            self._psketch = PartitionSketch(
                handle.partitioner.num_partitions
            )
            self._skew_stride = manager.conf.skew_sample_stride
            if handle.aggregator is not None:
                self._hot_keys = HeavyHitterSketch()

    # -- write --------------------------------------------------------------
    def write(self, records) -> None:
        if isinstance(records, ColumnBatch):
            # the columnar path needs BOTH a column-capable serializer
            # (else ColumnBatch objects would be pickled whole and the
            # reader's tuple unpack breaks) AND either no map-side
            # combine or a vectorizable one; otherwise iterate the
            # batch through the tuple plane (correct, just slow)
            if getattr(self.manager.serializer, "supports_columns", False) \
                    and (self._combined is None or getattr(
                        self.handle.aggregator, "kind", None) is not None):
                return self.write_columns(records)
        self._write_records(records)

    def write_columns(self, batch: ColumnBatch) -> None:
        """Columnar fast path: one vectorized partition pass per batch —
        the unsafe-row analog of keeping the reference's map-side hot
        loop inside Spark's serialized-row writers
        (RdmaWrapperShuffleWriter.scala:85-101).

        Only the (pid, key) PERMUTATION is computed here; the expensive
        record gather is deferred so the commit can gather straight into
        the final segment buffer.  Sorting by key within each bucket
        costs two cheap index sorts (stable_key_order rides the uint16
        radix path for pids, and for modest-range keys) and lets readers
        merge blocks as views instead of re-sorting."""
        t0 = time.monotonic()
        if any(self._buckets) or any(self._combined or []):
            raise TypeError(
                "writer already holds tuple records; one map task must "
                "stay on a single record plane"
            )
        P = self.handle.partitioner.num_partitions
        if self._col_pending is None:
            self._col_pending = []
        n = len(batch)
        if n == 0:
            return
        if P == 1:
            counts = np.array([n], np.int64)
            self._col_pending.append((batch, None, counts))
        else:
            order = counts = None
            is_hash = type(self.handle.partitioner) is HashPartitioner
            if is_hash and np.issubdtype(batch.keys.dtype, np.integer):
                kmin = int(batch.keys.min())
                kmax = int(batch.keys.max())
                krange = kmax - kmin + 1
                # uint64 keys past int64.max cannot ride the int64 fast
                # path (ctypes arg + astype both break); generic
                # partition_array handles them
                if kmax <= np.iinfo(np.int64).max and (
                    krange * P <= (1 << 16)
                ):
                    # modest-cardinality int keys: ONE fused native
                    # pass (splitmix64 + composite counting sort)
                    # replaces hash + two radix argsorts + two index
                    # gathers + bincount — or, without the native lib,
                    # one composite uint16 radix argsort does
                    got = native_hash_partition_order(
                        np.ascontiguousarray(batch.keys, np.int64),
                        P, kmin, krange,
                    )
                    if got is not None:
                        order, counts = got
                    else:
                        pids = self.handle.partitioner.partition_array(
                            batch.keys
                        )
                        # widen BEFORE subtracting: narrow key dtypes
                        # (int8 span 256) overflow on (keys - kmin)
                        comp = (
                            pids.astype(np.uint32) * np.uint32(krange)
                            + (batch.keys.astype(np.int64) - kmin)
                            .astype(np.uint32)
                        ).astype(np.uint16)
                        order = np.argsort(comp, kind="stable")
                        counts = np.bincount(
                            pids, minlength=P
                        ).astype(np.int64)
            if order is None and type(
                self.handle.partitioner
            ) is RangePartitioner:
                # range partitioning: key order IS pid-major order, so
                # ONE key sort suffices and counts fall out of P-1
                # binary searches (no pid column, no second sort)
                spl = self.handle.partitioner.splitters
                try:
                    spl_arr = np.asarray(spl)
                except (TypeError, ValueError):
                    spl_arr = None
                if spl_arr is not None and (
                    spl_arr.dtype != batch.keys.dtype
                    or spl_arr.dtype.hasobject
                ):
                    # dtype mismatch could change comparison semantics
                    # vs the scalar bisect path — stay generic
                    spl_arr = None
                if spl_arr is not None and len(spl_arr) == P - 1:
                    order = stable_key_order(batch.keys)
                    sk = take_rows(batch.keys, order)
                    bounds = np.searchsorted(sk, spl_arr, side="left")
                    counts = np.diff(
                        np.concatenate(([0], bounds, [n]))
                    ).astype(np.int64)
            if (order is None and is_hash
                    and batch.keys.dtype == np.int64
                    and n >= (1 << 14)):
                # wide-RANGE but low-CARDINALITY keys: compress to
                # dense sorted uint16 ranks (size gate matches
                # stable_key_order's — the kernel's 2MB table isn't
                # worth filling for small batches), then ONE composite
                # uint16 radix argsort replaces the two-sort-two-
                # gather chain (pid-major, key-ascending, stable —
                # same order).  uint16 only: numpy's STABLE sort is
                # radix for <=16-bit ints but timsort at 32 bits
                # (measured 5ms vs 80ms per M); past 65536 composites
                # the ranks still replace the key sort in the two-sort
                # chain below
                from sparkrdma_tpu.memory.staging import (
                    native_rank_compress,
                )

                res = native_rank_compress(batch.keys)
                if res is not None:
                    ranks, nr = res
                    pids = self.handle.partitioner.partition_array(
                        batch.keys
                    )
                    # nr < 2**16 is defensive: unreachable today (P==1
                    # short-circuits above, so P>=2 bounds nr<=32768)
                    # but np.uint16(nr) needs it if that ever changes
                    if nr < (1 << 16) and int(P) * nr <= (1 << 16):
                        comp = (
                            pids.astype(np.uint16) * np.uint16(nr)
                            + ranks
                        )
                        order = np.argsort(comp, kind="stable")
                    else:
                        korder = np.argsort(ranks, kind="stable")
                        porder = stable_key_order(pids[korder])
                        order = korder[porder]
                    counts = np.bincount(
                        pids, minlength=P
                    ).astype(np.int64)
            if order is None:
                pids = self.handle.partitioner.partition_array(batch.keys)
                korder = stable_key_order(batch.keys)
                porder = stable_key_order(pids[korder])
                order = korder[porder]  # pid-major, key-sorted within
                counts = np.bincount(pids, minlength=P).astype(np.int64)
            self._col_pending.append((batch, order, counts))
        if self._psketch is not None:
            counts = self._col_pending[-1][2]
            for pid, cnt in enumerate(counts):
                if cnt:
                    self._psketch.add(pid, int(cnt))
            if self._hot_keys is not None:
                # strided key sample (vectorized slice, scalar adds)
                for k in batch.keys[:: self._skew_stride]:
                    self._hot_keys.add(k.item() if hasattr(k, "item") else k)
        self.metrics.records_written += n
        self._records_in_memory += n
        if (self._spill_threshold
                and self._records_in_memory >= self._spill_threshold):
            self.spill()
        self.metrics.write_time_ms += (time.monotonic() - t0) * 1000

    def _materialize_pending(self) -> None:
        """Gather pending columnar writes into per-partition batches
        (the spill / combine / compressed-serializer path)."""
        P = self.handle.partitioner.num_partitions
        if self._col_buckets is None:
            self._col_buckets = [[] for _ in range(P)]
        if not self._col_pending:
            self._col_pending = []
            return
        for batch, order, counts in self._col_pending:
            if order is None:  # P == 1: whole batch, original order
                self._col_buckets[0].append(batch)
                continue
            sk = take_rows(batch.keys, order)
            sv = take_rows(batch.vals, order)
            bounds = np.cumsum(counts)[:-1]
            ksp = np.split(sk, bounds)
            vsp = np.split(sv, bounds)
            for pid in range(P):
                if len(ksp[pid]):
                    self._col_buckets[pid].append(
                        ColumnBatch(ksp[pid], vsp[pid], key_sorted=True)
                    )
        self._col_pending = []

    def _write_records(self, records: Iterable[Record]) -> None:
        t0 = time.monotonic()
        if self._col_buckets is not None or self._col_pending is not None:
            raise TypeError(
                "writer already holds columnar records; one map task "
                "must stay on a single record plane"
            )
        part = self.handle.partitioner.partition
        psk = self._psketch
        if self._combined is not None:
            agg = self.handle.aggregator
            for k, v in records:
                pid = part(k)
                d = self._combined[pid]
                if k in d:
                    d[k] = agg.merge_value(d[k], v)
                else:
                    d[k] = agg.create_combiner(v)
                    self._records_in_memory += 1
                self.metrics.records_written += 1
                if psk is not None:
                    psk.add(pid)
                    self._skew_seen += 1
                    if self._skew_seen % self._skew_stride == 0:
                        self._hot_keys.add(k)
                if (self._spill_threshold
                        and self._records_in_memory >= self._spill_threshold):
                    self.spill()
        else:
            for rec in records:
                pid = part(rec[0])
                self._buckets[pid].append(rec)
                self._records_in_memory += 1
                self.metrics.records_written += 1
                if psk is not None:
                    psk.add(pid)
                if (self._spill_threshold
                        and self._records_in_memory >= self._spill_threshold):
                    self.spill()
        self.metrics.write_time_ms += (time.monotonic() - t0) * 1000

    def _ordered_bucket(self, bucket: List[Record]) -> List[Record]:
        """Tuple-plane map-side ordering: with ``key_ordering`` (and no
        aggregation) each committed/spilled bucket serializes key-
        sorted, so reduce-side blocks are PRE-SORTED RUNS — the decode
        pipeline's streaming k-way merge (and the serial path's
        timsort, which gallops over runs) then merge instead of
        re-sorting, the Spark ``ExternalSorter`` map-side-sort shape.
        Stable, so the merged reduce output is bit-identical to sorting
        unsorted blocks reduce-side (the columnar plane already ships
        ``key_sorted`` batches)."""
        if self.handle.key_ordering and self.handle.aggregator is None:
            return sorted(bucket, key=lambda kv: kv[0])
        return bucket

    # -- spill --------------------------------------------------------------
    def spill(self) -> None:
        """Serialize buffered buckets to the spill file and release the
        memory.  The serializer's framing is concatenation-safe, so the
        commit merges spilled chunks with the final in-memory remainder
        by plain byte concatenation; with map-side combine the reader's
        merge_combiners folds duplicate keys across spilled chunks."""
        if self._records_in_memory == 0:
            return
        if self._col_pending:
            self._materialize_pending()
        serializer = self.manager.serializer
        P = self.handle.partitioner.num_partitions
        pid_layout = (
            0 < P <= self.manager.conf.spill_partition_files
        )
        if self._spill_file is None and self._spill_appenders is None:
            spill_dir = self.manager.conf.spill_dir
            os.makedirs(spill_dir, exist_ok=True)
            if pid_layout:
                from sparkrdma_tpu.memory.direct_io import direct_supported

                mode = self.manager.conf.direct_io
                self._spill_direct = mode == "on" or (
                    mode == "auto" and direct_supported(spill_dir)
                )
                self._spill_appenders = [None] * P
            else:
                fd, path = tempfile.mkstemp(
                    prefix=f"sparkrdma_tpu_spill_"
                           f"{self.handle.shuffle_id}_{self.map_id}_",
                    dir=spill_dir,
                )
                self._spill_file = os.fdopen(fd, "w+b")
                self._spill_path = path
        if self._col_buckets is not None:
            sources = self._columnar_sources()
        elif self._combined is not None:
            sources = [d.items() if d else None for d in self._combined]
        else:
            sources = [
                self._ordered_bucket(b) if b else None
                for b in self._buckets
            ]
        if self._spill_appenders is not None:
            # stream header + column VIEWS straight into the appender's
            # aligned buffers — no per-partition bytes join (each byte
            # is copied once between the batch and the bounce buffer)
            chunked = getattr(serializer, "serialize_chunks", None)
            for pid, src in enumerate(sources):
                if src is None:
                    continue
                app = self._appender(pid)
                if chunked is not None:
                    total_n, chunks = chunked(src)
                    off = app.size
                    for c in chunks():
                        app.append(c)
                    n = total_n
                else:
                    off, n = app.append(serializer.serialize(src))
                self._spilled[pid].append((off, n))
                self.metrics.bytes_spilled += n
        else:
            f = self._spill_file
            f.seek(0, os.SEEK_END)
            for pid, src in enumerate(sources):
                if src is None:
                    continue
                raw = serializer.serialize(src)
                off = f.tell()
                f.write(raw)
                self._spilled[pid].append((off, len(raw)))
                self.metrics.bytes_spilled += len(raw)
        if self._col_buckets is not None:
            self._col_buckets = [[] for _ in self._col_buckets]
        elif self._combined is not None:
            self._combined = [dict() for _ in self._combined]
        else:
            self._buckets = [[] for _ in self._buckets]
        self._records_in_memory = 0
        self.metrics.spills += 1

    def _columnar_sources(self) -> List[Optional[object]]:
        """Per-partition serialize sources: a list of key-sorted batches
        (one frame each — concatenation would cost a copy AND lose the
        sortedness flag readers exploit), or one combined batch for
        reducing aggregators."""
        kind = (
            getattr(self.handle.aggregator, "kind", None)
            if self.handle.map_side_combine else None
        )
        out: List[Optional[object]] = []
        for batches in self._col_buckets:
            if not batches:
                out.append(None)
            elif kind is None or kind == "group":
                out.append(batches)
            else:
                # per-batch combine first: key-sorted batches reduce
                # without a sort, and the re-combine input is tiny
                reduced = [combine_columns(b, kind) for b in batches]
                b = (
                    reduced[0] if len(reduced) == 1
                    else combine_columns(concat_batches(reduced), kind)
                )
                out.append(b if len(b) else None)
        return out

    def _appender(self, pid: int):
        """Lazily create partition ``pid``'s spill appender."""
        app = self._spill_appenders[pid]
        if app is None:
            from concurrent.futures import ThreadPoolExecutor

            from sparkrdma_tpu.memory.direct_io import DirectAppender

            if self._spill_io is None:
                self._spill_io = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="spill-io"
                )
            spill_dir = self.manager.conf.spill_dir
            fd, path = tempfile.mkstemp(
                prefix=f"sparkrdma_tpu_shuffle_"
                       f"{self.handle.shuffle_id}_{self.map_id}_"
                       f"p{pid}_",
                dir=spill_dir,
            )
            os.close(fd)  # DirectAppender reopens with its own flags
            P = self.handle.partitioner.num_partitions
            app = DirectAppender(
                path, use_direct=self._spill_direct,
                buf_bytes=(1 << 20) if P <= 32 else (256 << 10),
                executor=self._spill_io,
                # round-robin appends across P files fragment extents
                # at bounce-buffer size; 32 MiB preallocation steps
                # keep each shuffle file's later sequential read fast
                prealloc_bytes=32 << 20,
            )
            self._spill_appenders[pid] = app
        return app

    def _iter_partition_chunks(self, pid: int, final: bytes):
        """Yield a partition's spilled chunks (read back one at a time)
        followed by the final in-memory remainder — at most one spill
        chunk is ever resident during the commit copy."""
        for off, n in self._spilled[pid]:
            self._spill_file.seek(off)
            yield self._spill_file.read(n)
        if final:
            yield final

    def _close_spill(self) -> None:
        if self._spill_file is not None:
            f, self._spill_file = self._spill_file, None
            try:
                f.close()
            finally:
                try:
                    os.unlink(self._spill_path)
                except OSError:
                    pass
        if self._spill_appenders is not None:
            # still owned here = the commit never promoted them
            # (failure / unsuccessful stop): discard
            apps, self._spill_appenders = self._spill_appenders, None
            for app in apps:
                if app is not None:
                    app.abandon()
        if self._spill_io is not None:
            io, self._spill_io = self._spill_io, None
            io.shutdown(wait=True)

    # -- commit + publish ---------------------------------------------------
    def stop(self, success: bool = True) -> Optional[MapTaskOutput]:
        if self._stopped:
            return None
        self._stopped = True
        if not success:
            self._close_spill()
            return None
        tracer = get_tracer()
        try:
            with tracer.span(
                "shuffle.write.commit",
                shuffle=self.handle.shuffle_id, map=self.map_id,
            ):
                mto = self._commit()
            # QoS admission (qos/registry.py): account the committed
            # bytes under the tenant's registered-byte quota — an
            # over-quota tenant queues briefly for earlier shuffles to
            # release, then proceeds DEGRADED (narrower stripes,
            # cold-tier serves) instead of OOMing the node
            self.manager.qos_admit(self.handle, self.metrics.bytes_written)
            self._record_metrics()
            return mto
        finally:
            self._close_spill()

    def _record_metrics(self) -> None:
        """Flush this map task's write metrics into the registry and
        the manager's per-shuffle telemetry (aggregated at the driver
        alongside the map-output locations)."""
        m = self.metrics
        counter("shuffle_map_tasks_total").inc()
        counter("shuffle_write_bytes_total").inc(m.bytes_written)
        counter("shuffle_write_records_total").inc(m.records_written)
        if m.spills:
            counter("shuffle_spills_total").inc(m.spills)
            counter("shuffle_spill_bytes_total").inc(m.bytes_spilled)
        self.manager.record_shuffle_write(self.handle.shuffle_id, m)

    # -- skew detection + split planning (skew/) -----------------------------
    def _split_plan(self, payloads, sizes):
        """Commit-hook into the skew subsystem: classify hot partitions
        from the EXACT committed sizes and frame-walk their contiguous
        payloads into sub-block spans.  None (no entry changes) unless
        skewEnabled — and only on the pull read plane: the collective
        planes iterate primary table rows and move whole partitions by
        construction, so markers must never reach them."""
        mgr = self.manager
        if mgr.skew is None or not mgr.skew.enabled:
            return None
        if mgr.conf.read_plane != "host":
            return None
        return plan_commit_splits(
            mgr.serializer, payloads, sizes, mgr.conf
        ) or None

    def _record_skew_commit(self, sizes, split_plan) -> None:
        """Fold this task's partition-balance snapshot into the skew
        registry and the per-shuffle telemetry plane — published even
        when splitting is off (or found nothing), so the driver's
        report can show partition balance either way."""
        mgr = self.manager
        if mgr.skew is None and not mgr.conf.metrics_enabled:
            return
        snap = get_skew().record_commit(
            self.handle.shuffle_id, sizes, split_plan,
            hot_key_share=(
                self._hot_keys.top_share() if self._hot_keys else 0.0
            ),
            records=self._psketch.records() if self._psketch else None,
        )
        mgr.record_shuffle_skew(self.handle.shuffle_id, snap)

    # -- push-based merged shuffle (shuffle/push.py) --------------------------
    def _maybe_push(self, payloads) -> None:
        """Push-mode commit hook: AFTER the local commit + publish, cut
        each non-empty contiguous partition payload at serializer frame
        boundaries (the skew splitter's span packer) and push the
        sub-blocks to the partition's deterministic merger.  Strictly
        best-effort — any failure here costs pull traffic, never the
        commit — and strictly additive: the local segments stay
        registered and published, so the pull path can always serve
        every block bit-exactly."""
        mgr = self.manager
        if not mgr.conf.push_enabled:
            return
        try:
            self._push_payloads(payloads)
        except Exception:
            logger.warning(
                "push after commit failed (shuffle=%d map=%d); blocks "
                "will be pulled", self.handle.shuffle_id, self.map_id,
                exc_info=True,
            )

    def _push_payloads(self, payloads) -> None:
        mgr = self.manager
        sid = self.handle.shuffle_id
        target = mgr.conf.push_block_target
        max_subs = mgr.conf.skew_max_sub_blocks
        for pid, payload in payloads.items():
            n = len(payload)
            if not n:
                continue
            host = mgr.push_merger_for(pid)
            if host is None:
                continue
            try:
                spans = sub_spans(
                    mgr.serializer.frame_spans(payload), target, max_subs
                )
            except (ValueError, IndexError):
                spans = None  # unparseable payload: push it whole
            from sparkrdma_tpu.rpc.messages import PushSubBlockMsg

            msgs = [
                PushSubBlockMsg(
                    mgr.local_smid, sid, self.map_id, pid, n, off,
                    bytes(memoryview(payload[off : off + ln])),
                )
                for off, ln in (spans or [(0, n)])
            ]
            counter("push_sub_blocks_sent_total").inc(len(msgs))
            counter("push_bytes_sent_total").inc(n)
            mgr.push_partition(host, msgs)

    def _commit(self) -> MapTaskOutput:
        t0 = time.monotonic()
        serializer = self.manager.serializer
        if self._col_pending is not None or self._col_buckets is not None:
            kind = (
                getattr(self.handle.aggregator, "kind", None)
                if self.handle.map_side_combine else None
            )
            if (
                self._spill_file is None
                and self._spill_appenders is None
                and (self._col_buckets is None
                     or not any(self._col_buckets))
                and (kind is None or kind == "group")
                and getattr(serializer, "frame_header", None) is not None
            ):
                return self._commit_direct(t0)
            self._materialize_pending()
        if self._col_buckets is not None:
            chunked = getattr(serializer, "serialize_chunks", None)
            if chunked is not None and self._spill_file is None \
                    and self._spill_appenders is None:
                # zero-copy commit: headers + uint8 column views stream
                # straight into the resolver's staging buffer
                return self._commit_payloads([
                    _chunked_payload(*chunked(src)) if src is not None
                    else b""
                    for src in self._columnar_sources()
                ], t0)
            finals = [
                serializer.serialize(src) if src is not None else b""
                for src in self._columnar_sources()
            ]
        elif self._combined is not None:
            finals = [
                serializer.serialize(d.items()) if d else b""
                for d in self._combined
            ]
        else:
            finals = [
                serializer.serialize(self._ordered_bucket(b)) if b else b""
                for b in self._buckets
            ]
        if self._spill_appenders is not None:
            return self._commit_spilled_files(finals, t0)
        if self._spill_file is not None:
            # merge = chunk concatenation (both serializers frame
            # concatenation-safely), STREAMED through ChunkedPayload so
            # the spilled output is never fully resident at commit
            from sparkrdma_tpu.shuffle.resolver import ChunkedPayload

            partition_bytes = []
            for pid, final in enumerate(finals):
                spilled_len = sum(n for _, n in self._spilled[pid])
                total_len = spilled_len + len(final)
                if total_len == 0:
                    partition_bytes.append(b"")
                else:
                    partition_bytes.append(ChunkedPayload(
                        total_len,
                        lambda pid=pid, final=final:
                            self._iter_partition_chunks(pid, final),
                    ))
        else:
            partition_bytes = finals
        return self._commit_payloads(partition_bytes, t0)

    def _commit_direct(self, t0: float) -> MapTaskOutput:
        """Zero-intermediate-copy columnar commit: lay all frames out in
        ONE buffer and gather each column straight into place with the
        deferred (pid, key) permutation — records touch host memory once
        between the user's arrays and the registered segment."""
        ser = self.manager.serializer
        P = self.handle.partitioner.num_partitions
        frames = []  # (pid, batch, order, lo, cnt, header)
        pid_sizes = np.zeros(P + 1, np.int64)
        for batch, order, counts in (self._col_pending or []):
            kitem = batch.keys.dtype.itemsize
            vitem = batch.vals.dtype.itemsize
            lo = 0
            for pid in range(P):
                cnt = int(counts[pid]) if pid < len(counts) else 0
                if cnt:
                    header = ser.frame_header(
                        batch.keys.dtype, batch.vals.dtype, cnt,
                        key_sorted=order is not None,
                    )
                    frames.append((pid, batch, order, lo, cnt, header))
                    pid_sizes[pid + 1] += len(header) + cnt * (kitem + vitem)
                lo += cnt
        # partition starts honor the resolver's commit alignment (the
        # collective plane row-gathers arena blocks at ROW_BYTES
        # granularity); sizes stay exact, the gaps are never served
        align = self.manager.resolver.commit_align
        sizes = pid_sizes[1:]
        starts = np.zeros(P + 1, np.int64)
        for p in range(P):
            starts[p + 1] = (
                (starts[p] + sizes[p] + align - 1) // align * align
            )
        total = int(starts[P - 1] + sizes[P - 1]) if P else 0
        # assemble in a POOLED buffer: repeated shuffles reuse warm
        # pages (a fresh np.empty of tens of MB pays ~0.4ms/MB in
        # first-touch page faults — measured 25ms per 72MB commit);
        # the GC-tied release returns it to the pool when the shuffle's
        # segment dies
        try:
            buf = self.manager.staging_pool.alloc_gc(max(total, 1))
        except MemoryError:
            buf = np.empty(max(total, 1), np.uint8)
        # zero the alignment gaps so committed segments are
        # deterministic (gap bytes are staged but never served)
        for p in range(P - 1):
            buf[starts[p] + sizes[p] : starts[p + 1]] = 0
        cursors = starts[:P].copy()
        for pid, batch, order, lo, cnt, header in frames:
            c = int(cursors[pid])
            hl = len(header)
            buf[c : c + hl] = np.frombuffer(header, np.uint8)
            c += hl
            for col in (batch.keys, batch.vals):
                nb = cnt * col.dtype.itemsize
                out = buf[c : c + nb].view(col.dtype)
                if order is None:  # P == 1: original order, no gather
                    np.copyto(out, col)
                else:
                    take_rows(col, order[lo : lo + cnt], out=out)
                c += nb
            cursors[pid] = c
        ranges = [(int(starts[p]), int(sizes[p])) for p in range(P)]
        self.metrics.bytes_written = int(sizes.sum())  # payload, not gaps
        psizes = [n for _o, n in ranges]
        split_plan = self._split_plan(
            {
                p: buf[o : o + n]
                for p, (o, n) in enumerate(ranges) if n
            },
            psizes,
        )
        self._record_skew_commit(psizes, split_plan)
        mto = self.manager.resolver.commit_assembled(
            self.handle.shuffle_id, self.map_id, buf[:total], ranges,
            split_spans=split_plan,
        )
        self.manager.publish_map_output(
            self.handle.shuffle_id, self.map_id, mto
        )
        self._maybe_push({
            p: buf[o : o + n] for p, (o, n) in enumerate(ranges) if n
        })
        self.metrics.write_time_ms += (time.monotonic() - t0) * 1000
        return mto

    def _commit_spilled_files(self, finals, t0: float) -> MapTaskOutput:
        """Promote the per-partition spill files into the shuffle files
        (resolver.commit_spilled_files): append each partition's final
        in-memory remainder to its spill file, seal, and register the
        files as the map output's segments — the spilled bytes are
        written to disk exactly ONCE."""
        entries = []
        total = 0
        for pid, final in enumerate(finals):
            if self._spill_appenders[pid] is None and not final:
                entries.append(None)
                continue
            app = self._appender(pid)
            if final:
                app.append(final)
            n = app.finish()
            entries.append((app.path, n))
            total += n
        appenders, self._spill_appenders = self._spill_appenders, None
        # spill-file commits never split (their payloads are on disk,
        # not walkable views) — counted as unsplit in the balance stats
        self._record_skew_commit(
            [0 if e is None else e[1] for e in entries], None
        )
        try:
            mto = self.manager.resolver.commit_spilled_files(
                self.handle.shuffle_id, self.map_id, entries
            )
        except BaseException:
            # resolver cleans up what it registered; unlink the rest
            for app in appenders:
                if app is not None:
                    app.abandon()
            raise
        self.metrics.bytes_written = total
        self.manager.publish_map_output(
            self.handle.shuffle_id, self.map_id, mto
        )
        self.metrics.write_time_ms += (time.monotonic() - t0) * 1000
        return mto

    def _commit_payloads(self, partition_bytes, t0: float) -> MapTaskOutput:
        from sparkrdma_tpu.shuffle.resolver import ChunkedPayload, _payload_len

        sizes = [_payload_len(b) for b in partition_bytes]
        self.metrics.bytes_written = sum(sizes)
        # only contiguous finals are frame-walkable; chunked payloads
        # (spill merges, streamed columnar) commit unsplit
        split_plan = self._split_plan(
            {
                pid: b for pid, b in enumerate(partition_bytes)
                if not isinstance(b, ChunkedPayload) and len(b)
            },
            sizes,
        )
        self._record_skew_commit(sizes, split_plan)
        mto = self.manager.resolver.commit_map_output(
            self.handle.shuffle_id, self.map_id, partition_bytes,
            # spilled output is already on disk: commit via the mmap
            # path so peak memory stays bounded by the spill threshold
            prefer_file_backed=self._spill_file is not None,
            split_spans=split_plan,
        )
        self.manager.publish_map_output(self.handle.shuffle_id, self.map_id, mto)
        # chunked payloads (spill merges, streamed columnar) are not
        # frame-walkable views — their blocks stay pull-served
        self._maybe_push({
            pid: b for pid, b in enumerate(partition_bytes)
            if not isinstance(b, ChunkedPayload) and len(b)
        })
        self.metrics.write_time_ms += (time.monotonic() - t0) * 1000
        return mto
