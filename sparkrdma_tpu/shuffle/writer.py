"""Map-side shuffle writer: partition → (combine) → serialize → commit → publish.

Analog of RdmaWrapperShuffleWriter (RdmaWrapperShuffleWriter.scala:76-153).
Where the reference wraps Spark's UnsafeShuffleWriter/SortShuffleWriter
and intercepts the commit to mmap+register the produced file, this
writer owns the whole path: bucket records by partitioner, optionally
map-side combine, serialize per partition, commit into a registered HBM
segment via the resolver, and publish the location table to the driver
(the ``stop(success=true)`` publish at
RdmaWrapperShuffleWriter.scala:115-149).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterable, List, Optional

from sparkrdma_tpu.rpc.messages import PublishMapTaskOutputMsg
from sparkrdma_tpu.shuffle.map_output import MapTaskOutput
from sparkrdma_tpu.utils.serde import Record
from sparkrdma_tpu.utils.trace import get_tracer


class WriteMetrics:
    def __init__(self):
        self.records_written = 0
        self.bytes_written = 0
        self.write_time_ms = 0.0


class ShuffleWriter:
    """One writer per (shuffle, map task)."""

    def __init__(self, manager, handle, map_id: int):
        self.manager = manager
        self.handle = handle
        self.map_id = map_id
        self.metrics = WriteMetrics()
        self._buckets: List[List[Record]] = [
            [] for _ in range(handle.partitioner.num_partitions)
        ]
        self._combined: Optional[List[dict]] = (
            [dict() for _ in range(handle.partitioner.num_partitions)]
            if (handle.aggregator is not None and handle.map_side_combine)
            else None
        )
        self._stopped = False

    # -- write --------------------------------------------------------------
    def write(self, records: Iterable[Record]) -> None:
        t0 = time.monotonic()
        part = self.handle.partitioner.partition
        if self._combined is not None:
            agg = self.handle.aggregator
            for k, v in records:
                d = self._combined[part(k)]
                if k in d:
                    d[k] = agg.merge_value(d[k], v)
                else:
                    d[k] = agg.create_combiner(v)
                self.metrics.records_written += 1
        else:
            for rec in records:
                self._buckets[part(rec[0])].append(rec)
                self.metrics.records_written += 1
        self.metrics.write_time_ms += (time.monotonic() - t0) * 1000

    # -- commit + publish ---------------------------------------------------
    def stop(self, success: bool = True) -> Optional[MapTaskOutput]:
        if self._stopped:
            return None
        self._stopped = True
        if not success:
            return None
        tracer = get_tracer()
        with tracer.span(
            "shuffle.write.commit",
            shuffle=self.handle.shuffle_id, map=self.map_id,
        ):
            return self._commit()

    def _commit(self) -> MapTaskOutput:
        t0 = time.monotonic()
        serializer = self.manager.serializer
        if self._combined is not None:
            partition_bytes = [
                serializer.serialize(d.items()) if d else b""
                for d in self._combined
            ]
        else:
            partition_bytes = [
                serializer.serialize(b) if b else b"" for b in self._buckets
            ]
        self.metrics.bytes_written = sum(len(b) for b in partition_bytes)
        mto = self.manager.resolver.commit_map_output(
            self.handle.shuffle_id, self.map_id, partition_bytes
        )
        self.manager.publish_map_output(self.handle.shuffle_id, self.map_id, mto)
        self.metrics.write_time_ms += (time.monotonic() - t0) * 1000
        return mto
