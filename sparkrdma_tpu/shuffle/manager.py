"""TpuShuffleManager: the plugin-root API + driver control plane.

Analog of RdmaShuffleManager (RdmaShuffleManager.scala:38-388), the L1
surface of SURVEY.md §1: ``register_shuffle`` / ``get_writer`` /
``get_reader`` / ``unregister_shuffle`` / ``stop``, plus the
driver-mediated control plane:

- executors **hello** the driver on lazy start
  (startRdmaNodeIfMissing, :277-318),
- the driver **announces** full membership so executors pre-connect the
  peer mesh hot (:70-118),
- map tasks **publish** their location tables (:120-141),
- reducers **fetch-status** and the driver answers once the relevant
  tables' fill-futures resolve (:143-216),
- executor loss **prunes** driver maps (onBlockManagerRemoved,
  :253-263).

One manager per process; driver and executors are distinguished by
``is_driver`` exactly like the reference.
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.faults.injector import FAULTS, FaultInjectedError
from sparkrdma_tpu.memory.arena import ArenaManager
from sparkrdma_tpu.memory.staging import StagingPool
from sparkrdma_tpu.metrics import (
    counter,
    get_registry,
    write_json_snapshot,
    write_prometheus,
)
from sparkrdma_tpu.obs import RECORDER, TRACING
from sparkrdma_tpu.qos import WeightedCreditBroker, get_qos
from sparkrdma_tpu.skew import get_skew
from sparkrdma_tpu.utils.dbglock import dbg_lock, dbg_rlock
from sparkrdma_tpu.utils.statemachine import StateMachine
from sparkrdma_tpu.utils.trace import get_tracer
from sparkrdma_tpu.rpc.messages import (
    AnnounceShuffleManagersMsg,
    CleanShuffleMsg,
    ExchangePlanMsg,
    FetchExchangePlanMsg,
    FetchMapStatusFailedMsg,
    FetchMapStatusMsg,
    FetchMapStatusResponseMsg,
    FetchMergeStatusMsg,
    HeartbeatMsg,
    HelloMsg,
    MergeStatusResponseMsg,
    PrefetchHintMsg,
    PublishMapTaskOutputMsg,
    PublishShuffleMetricsMsg,
    PushSubBlockMsg,
    PUSH_MIN_WIRE_VERSION,
    RpcMsg,
    WireFormatError,
    decode_msg,
    hex_context,
)
from sparkrdma_tpu.shuffle.map_output import MapTaskOutput
from sparkrdma_tpu.shuffle.partitioner import Partitioner
from sparkrdma_tpu.shuffle.push import PushMerger
from sparkrdma_tpu.shuffle.resolver import ShuffleBlockResolver
from sparkrdma_tpu.shuffle.writer import ShuffleWriter
from sparkrdma_tpu.stats import ShuffleReaderStats
from sparkrdma_tpu.transport.channel import (
    Channel,
    ChannelType,
    FnCompletionListener,
    TransportError,
)
from sparkrdma_tpu.transport.node import Node
from sparkrdma_tpu.utils.serde import (
    CompressedSerializer,
    PickleSerializer,
    Serializer,
)
from sparkrdma_tpu.utils.types import (
    BlockLocation,
    BlockManagerId,
    ShuffleManagerId,
    get_cached_shuffle_manager_id,
)

logger = logging.getLogger(__name__)

# sentinel: the exchange-plan barrier is not failed, just not ready
# (e.g. a publisher's hello has not landed yet) — keep waiters queued
_PLAN_WAIT = object()

# driver keeps per-shuffle telemetry for this many recent shuffles
_TELEMETRY_KEEP = 64


def _fold_telemetry(acc, key: str, v):
    """Telemetry merge rule, applied identically at every aggregation
    layer (task→executor, executor→driver per-host, per-host→total):
    ``max_``-prefixed keys are maxima (summing a max across tasks or
    hosts corrupts it — the skew partition-balance stats ride this),
    everything else sums."""
    return max(acc, v) if key.startswith("max_") else acc + v


@dataclass
class Aggregator:
    """Combiner triple (Spark Aggregator analog)."""

    create_combiner: Callable[[Any], Any]
    merge_value: Callable[[Any, Any], Any]
    merge_combiners: Callable[[Any, Any], Any]


@dataclass
class ColumnarAggregator(Aggregator):
    """Aggregator the columnar plane can vectorize.

    ``kind`` names the combine: ``"group"`` (group_by_key — values
    collected per key) or a reduction ``"sum"``/``"min"``/``"max"``.
    The inherited scalar callables keep tuple-plane interop working, so
    a ColumnarAggregator is always safe to hand to the generic path."""

    kind: str = "group"

    _REDUCERS = {
        "sum": (lambda a, b: a + b),
        "min": min,
        "max": max,
    }

    @classmethod
    def group(cls) -> "ColumnarAggregator":
        return cls(
            create_combiner=lambda v: [v],
            merge_value=lambda c, v: c + [v],
            merge_combiners=lambda a, b: a + b,
            kind="group",
        )

    @classmethod
    def reduce(cls, kind: str) -> "ColumnarAggregator":
        if kind not in cls._REDUCERS:
            raise ValueError(
                f"unknown columnar reduction {kind!r} "
                f"(have {sorted(cls._REDUCERS)})"
            )
        f = cls._REDUCERS[kind]
        return cls(
            create_combiner=lambda v: v,
            merge_value=f,
            merge_combiners=f,
            kind=kind,
        )


@dataclass
class ShuffleHandle:
    """Returned by register_shuffle; carried to writers and readers
    (reference: Serialized/BaseShuffleHandle selection,
    RdmaShuffleManager.scala:267-274 — serialization strategy here is a
    Serializer instance rather than a handle subclass)."""

    shuffle_id: int
    num_maps: int
    partitioner: Partitioner
    aggregator: Optional[Aggregator] = None
    map_side_combine: bool = False
    key_ordering: bool = False
    # QoS tenant id this shuffle registered under (qos/registry.py);
    # empty until stamped by register_shuffle with qosEnabled
    tenant: str = ""

    def __post_init__(self):
        if self.map_side_combine and self.aggregator is None:
            raise ValueError("map_side_combine requires an aggregator")


class _FetchCallback:
    """Reassembles segmented fetch-status responses by (index, total)
    and fires once complete (registry analog of
    RdmaShuffleManager.scala:378-387); ``on_error`` fires instead when
    the driver answers with FetchMapStatusFailedMsg."""

    def __init__(self, on_locations: Callable[[List[BlockLocation]], None],
                 on_error: Optional[Callable[[str], None]] = None):
        self.on_locations = on_locations
        self.on_error = on_error
        self._parts: Dict[int, Tuple[BlockLocation, ...]] = {}  # guarded-by: _lock
        self._got = 0  # guarded-by: _lock
        self._lock = dbg_lock("manager.fetch_callback", 22)

    def on_response(self, msg: FetchMapStatusResponseMsg) -> None:
        with self._lock:
            if msg.index in self._parts:
                return  # duplicate segment
            self._parts[msg.index] = msg.locations
            self._got += len(msg.locations)
            done = self._got >= msg.total
            # snapshot under the lock; the callback runs outside it
            # (it issues fetches) and a straggling duplicate segment
            # must not mutate what we iterate
            parts = dict(self._parts) if done else None
        if done:
            locs: List[BlockLocation] = []
            for idx in sorted(parts):
                locs.extend(parts[idx])
            self.on_locations(locs)

    def on_failed(self, reason: str) -> None:
        if self.on_error is not None:
            self.on_error(reason)


class _PlanCallback:
    """Registry entry for a pending bulk-exchange plan request
    (shuffle/bulk.py); shares the callback id space and the negative
    FetchMapStatusFailed path with _FetchCallback."""

    def __init__(self, on_plan: Callable, on_error: Callable[[str], None]):
        self.on_plan = on_plan
        self.on_error = on_error

    def on_failed(self, reason: str) -> None:
        self.on_error(reason)


class _MergeCallback:
    """Registry entry for a pending merge-status query (push-based
    merged shuffle): accumulates one answer per reduce id — a wide
    answer's provenance may split across segments, each repeating
    ``rows_total`` — and fires ``on_status`` once every queried id has
    a full answer.  Shares the callback id space and the negative
    FetchMapStatusFailed path with _FetchCallback."""

    def __init__(self, on_status: Callable[[Dict], None],
                 on_error: Callable[[str], None]):
        self.on_status = on_status
        self.on_error = on_error
        # reduce_id -> (mkey, length, rows_total)
        self._meta: Dict[int, Tuple[int, int, int]] = {}  # guarded-by: _lock
        # reduce_id -> {rel_off: (map_id, rel_off, rel_len)}
        self._rows: Dict[int, Dict] = {}  # guarded-by: _lock
        self._done: set = set()  # guarded-by: _lock
        self._fired = False  # guarded-by: _lock
        self._lock = dbg_lock("manager.merge_callback", 23)

    def on_response(self, msg: MergeStatusResponseMsg) -> None:
        with self._lock:
            if self._fired or msg.reduce_id in self._done:
                return
            meta = self._meta.setdefault(
                msg.reduce_id, (msg.mkey, msg.length, msg.rows_total)
            )
            rows = self._rows.setdefault(msg.reduce_id, {})
            for row in msg.provenance:
                rows[row[1]] = row  # rel_off-keyed: dedups resent rows
            if len(rows) < meta[2]:
                return  # more provenance segments in flight
            self._done.add(msg.reduce_id)
            if len(self._done) < msg.total:
                return
            self._fired = True
            result = {
                rid: (
                    self._meta[rid][0], self._meta[rid][1],
                    tuple(sorted(self._rows[rid].values(),
                                 key=lambda r: r[1])),
                )
                for rid in self._done
            }
        # fires outside the lock — the reader enqueues fetches from it
        self.on_status(result)

    def on_failed(self, reason: str) -> None:
        self.on_error(reason)


class TpuShuffleManager(StateMachine):
    """One per process.  ``network`` supplies the transport connector
    (LoopbackNetwork in-process; a real fabric connector on a pod)."""

    MACHINE = "manager.lifecycle"
    STATES = ("running", "stopping", "stopped")
    INITIAL = "running"
    TERMINAL = ("stopped",)
    TRANSITIONS = {
        "running": ("stopping",),
        "stopping": ("stopped",),
    }

    def __init__(
        self,
        conf: TpuShuffleConf,
        is_driver: bool,
        network,
        host: str = "127.0.0.1",
        port: int = 0,
        executor_id: str = "driver",
        serializer: Optional[Serializer] = None,
        stage_to_device: Optional[bool] = None,
    ):
        if stage_to_device is None:
            # plane-aware default: windowed/bulk exchanges source their
            # streams from HOST block reads (the collective stages the
            # bytes itself), so committing map outputs into HBM first
            # would only add a per-block device round-trip —
            # milliseconds each on the tunneled chip.  The host plane
            # and the collective fixture (whose conf keeps
            # readPlane=collective) resolve to HBM staging.
            stage_to_device = conf.read_plane not in ("bulk", "windowed")
        self.conf = conf
        self.is_driver = is_driver
        self.network = network
        self.executor_id = executor_id
        if conf.metrics_enabled:
            # flip the process-wide registry on BEFORE any instrumented
            # object (node, arena, pool, writer) fetches its handles
            get_registry().enabled = True
        if conf.lock_debug:
            # same flow for the lock sanitizer: locks created from here
            # on are rank-checked DebugLock wrappers (utils/dbglock.py)
            from sparkrdma_tpu.utils.dbglock import get_lock_factory

            get_lock_factory().enabled = True
        if conf.resource_debug:
            # and the resource-lifecycle ledger (utils/ledger.py):
            # every annotated acquire from here on hands out a live
            # ticket; stop() renders the leak report
            from sparkrdma_tpu.utils.ledger import get_resource_ledger

            get_resource_ledger().enabled = True
            # register as an owner: in a multi-manager process only
            # the LAST manager's stop flushes the leak report (the
            # others' live channels are not leaks)
            get_resource_ledger().retain()
        if conf.wire_debug:
            # and the wire-frame validator (utils/wiredbg.py): every
            # frame both engines and the loopback plane receive from
            # here on is header- and schema-checked before dispatch
            from sparkrdma_tpu.utils.wiredbg import set_wire_debug

            set_wire_debug(True)
        if conf.state_debug:
            # and the lifecycle state-machine validator
            # (utils/statemachine.py): every _transition() from here on
            # is checked against its declared table; a non-zero
            # schedShake seed additionally perturbs the schedule at
            # each validated transition
            from sparkrdma_tpu.utils.statemachine import get_state_debug

            get_state_debug().enabled = True
            get_state_debug().shake_seed = conf.sched_shake
        # deterministic fault plane (faults/): arm the process-global
        # injector from the seeded spec BEFORE building the node, so
        # every fault point the transport/memory/control planes pass
        # through sees the schedule from the first call.  Empty spec
        # (the default) leaves FAULTS.enabled False — each woven point
        # costs one attribute check and nothing else.
        self._faults_armed = False
        if conf.fault_inject:
            FAULTS.arm(conf.fault_inject)
            self._faults_armed = True
        # multi-tenant QoS (qos/): flip the process-global tenant
        # registry on BEFORE building the node, exactly like the
        # metrics registry — the node's pools classify/broker through
        # it from their first task.  None keeps every edge plain FIFO.
        self.qos = None
        if conf.qos_enabled:
            self.qos = get_qos()
            self.qos.enabled = True
        # skew-adaptive partitioning (skew/): same process-global
        # registry flip — writers consult it at commit to split hot
        # partitions into sub-blocks, readers to resolve the markers.
        # None (the default) keeps every commit and fetch bit-identical
        # to the pre-skew path.
        self.skew = None
        if conf.skew_enabled:
            self.skew = get_skew()
            self.skew.enabled = True
        # live scrape endpoint (qos/http.py): serves /metrics,
        # /metrics.json and /tenants for the manager's lifetime
        self.metrics_http = None
        if conf.metrics_http_port >= 0:
            from sparkrdma_tpu.qos.http import MetricsHttpServer

            try:
                self.metrics_http = MetricsHttpServer(
                    conf.metrics_http_port,
                    host=conf.metrics_http_host,
                )
            except OSError:
                logger.exception(
                    "metrics scrape endpoint on port %d failed to bind "
                    "— continuing without it", conf.metrics_http_port,
                )
        if serializer is not None:
            self.serializer = serializer
        else:
            name = conf.serializer_name
            if name == "columnar":
                from sparkrdma_tpu.utils.serde import ColumnarSerializer

                inner: Serializer = ColumnarSerializer()
            elif name in ("", "pickle"):
                inner = PickleSerializer()
            else:
                raise ValueError(
                    f"unknown serializer {name!r} (want columnar|pickle)"
                )
            self.serializer = (
                CompressedSerializer(
                    inner, codec=conf.compress_codec,
                    frame_records=conf.compress_frame_records,
                )
                if conf.compress else inner
            )
        self.stats = (
            ShuffleReaderStats(conf)
            if conf.collect_shuffle_reader_stats else None
        )

        if is_driver:
            port = port or conf.driver_port or 37000
        else:
            # reference: spark.shuffle.rdma.executorPort (+ retries)
            port = port or conf.executor_port
        self.node = self._bind_node(host, port)
        self.node.set_receive_listener(self._receive)
        if is_driver:
            conf.set_driver_port(self.node.address[1])
            conf.set("driverHost", host)
        self.local_smid = get_cached_shuffle_manager_id(
            ShuffleManagerId(
                self.node.address[0],
                self.node.address[1],
                BlockManagerId(executor_id, host, self.node.address[1]),
            )
        )

        if conf.trace:
            get_tracer().enabled = True
        # observability plane (obs/): the flight recorder's per-plane
        # event rings and the distributed-trace context generator.
        # Owner-counted like the fault injector — in-process clusters
        # retain per manager, and only the LAST stop() turns them off.
        self._obs_retained = False
        self._tracing_retained = False
        if conf.flight_recorder:
            RECORDER.retain(
                ring_size=conf.flight_recorder_ring_size,
                dump_dir=conf.flight_recorder_dump_path,
            )
            self._obs_retained = True
        if conf.trace_enabled:
            TRACING.retain(conf.trace_sample_rate)
            self._tracing_retained = True
        # persistent per-device HBM arena — set when a CollectiveNetwork
        # attaches this executor to a mesh device
        self.device_arena = None
        self.arena = ArenaManager(conf.max_buffer_allocation_size)
        self.staging_pool = StagingPool(conf.max_buffer_allocation_size)
        # bulk TCP receives land in pooled buffers served as zero-copy
        # slices (release tied to slice GC, the
        # BufferReleasingInputStream analog)
        self.node.staging_pool = self.staging_pool
        if not is_driver and conf.max_agg_prealloc > 0:
            # warm the pool off the critical path (reference: async
            # preallocation, RdmaBufferManager.java:112-120)
            threading.Thread(
                target=self.staging_pool.prealloc,
                args=(conf.max_agg_prealloc, conf.max_agg_block),
                daemon=True,
            ).start()
        # tiered residency for file-backed commits (memory/tier.py):
        # hot blocks in budgeted pooled rows, cold blocks on disk with
        # prefetch promotion riding the node's serve-pool credits
        from sparkrdma_tpu.memory.tier import TieredBlockStore
        from sparkrdma_tpu.qos import BULK as _QOS_BULK

        self.tier_store = TieredBlockStore(
            staging_pool=self.staging_pool,
            hot_bytes=conf.tier_hot_bytes,
            prefetch_blocks=(
                conf.tier_prefetch_blocks if conf.tier_prefetch else 0
            ),
            # readahead warms ride the serve pool at BULK class — a
            # prefetch storm never outranks demand serves
            submitter=lambda fn, args, cost: self.node.submit_serve(
                fn, args, cost, cls=_QOS_BULK
            ),
            qos=self.qos,
        )
        self.node.tier_store = self.tier_store
        self.resolver = ShuffleBlockResolver(
            self.arena, self.node,
            stage_to_device=stage_to_device and not conf.lazy_staging,
            staging_pool=self.staging_pool,
            file_backed_threshold=conf.file_backed_commit_bytes,
            spill_dir=conf.spill_dir,
            lazy_staging=conf.lazy_staging,
            write_block_size=conf.shuffle_write_block_size,
            direct_io=conf.direct_io,
            tier_store=self.tier_store,
        )
        # push-based merged shuffle (shuffle/push.py): every manager
        # runs a merger endpoint — receiving is cheap and peers' conf
        # may differ — but nothing arrives unless a writer with
        # pushEnabled selects this node for a reduce partition
        self.push_merger = PushMerger(
            conf, self.arena, tier_store=self.tier_store,
            node=self.node, spill_dir=conf.spill_dir,
            direct_io=conf.direct_io,
        )

        # driver-side metadata (RdmaShuffleManager.scala:46-57)
        # join order  # (see README "Concurrency discipline" rank table)
        self._executors: List[ShuffleManagerId] = []  # guarded-by: _executors_lock
        # tombstones for pruned executors
        self._removed: set = set()  # guarded-by: _executors_lock
        self._executors_lock = dbg_lock("manager.executors", 16)
        self._shuffle_partitions: Dict[int, int] = {}
        self._shuffle_num_maps: Dict[int, int] = {}
        # shuffle -> host smid -> map_id -> table
        self._outputs: Dict[
            int, Dict[ShuffleManagerId, Dict[int, MapTaskOutput]]
        ] = {}  # guarded-by: _outputs_lock
        self._outputs_lock = dbg_lock("manager.outputs", 14)
        # pending bulk-exchange plan requests (driver): shuffle_id →
        # [(msg, reply channel)], answered once every map published
        self._plan_waiters: Dict[int, List] = {}  # guarded-by: _plan_lock
        self._plan_cache: Dict[int, tuple] = {}  # guarded-by: _plan_lock
        # bulk plans are only valid for the membership they were
        # registered under: every executor REMOVAL bumps the epoch and
        # dooms shuffles registered before it (additions are safe — the
        # cached snapshot keeps all requesters consistent)
        self._membership_epoch = 0  # guarded-by: _plan_lock
        self._shuffle_epoch: Dict[int, int] = {}  # guarded-by: _plan_lock
        self._plan_lock = dbg_lock("manager.plan", 12)
        # bumped (under _plan_lock) on every hello: lets the barrier
        # detect a hello that raced its pop/requeue of plan waiters
        self._hello_gen = 0
        # incremental (windowed) bulk plans: per-shuffle window state —
        # built in order under _window_lock (see _maybe_answer_windows)
        self._window_state: Dict[int, dict] = {}  # guarded-by: _window_lock
        # the OUTERMOST rank: window planning calls into the plan/
        # outputs/executors locks below it; reentrant because
        # _pin_window_hosts re-enters from _try_build_window
        self._window_lock = dbg_rlock("manager.window", 10)
        # shuffle → first-seen plan mode (True = windowed); mixed modes
        # across hosts (conf skew) are rejected at request time
        self._plan_mode: Dict[int, bool] = {}
        # shuffle → hosts that requested windowed plans (participation
        # evidence for host-set pinning ahead of a racing hello)
        self._window_requesters: Dict[int, set] = {}
        self._fetch_pool = (
            ThreadPoolExecutor(max_workers=8, thread_name_prefix="drv-fetch")
            if is_driver
            else None
        )

        # executor-side state
        self._peers: List[ShuffleManagerId] = []
        self._callbacks: Dict[int, _FetchCallback] = {}  # guarded-by: _callbacks_lock
        self._callbacks_lock = dbg_lock("manager.callbacks", 18)
        self._next_callback_id = 1
        self._hello_sent = False
        # manager lifecycle: check-and-flip UNDER _life_lock — two
        # concurrent stop() calls (SparkContext teardown racing an
        # atexit hook or a test fixture) must not both run the
        # teardown body, which releases owner-counted globals
        # (RECORDER/TRACING/ledger) and would double-release them
        self._life_lock = dbg_lock("manager.lifecycle", 16)
        self._state = "running"  # state: manager.lifecycle guarded-by: _life_lock
        # per-shuffle telemetry: local accumulators (writers/readers
        # record in), published to the driver at unregister time the
        # same way map-output locations flow; the driver keeps the last
        # _TELEMETRY_KEEP shuffles' per-host snapshots
        self._telemetry: Dict[int, Dict[str, float]] = {}  # guarded-by: _telemetry_lock
        self._telemetry_lock = dbg_lock("manager.telemetry", 20)
        self._shuffle_telemetry: Dict[
            int, Dict[str, Dict[str, float]]
        ] = {}  # guarded-by: _telemetry_lock
        # unified reactive device plane (readPlane=windowed): attached
        # by the job layer (shared in-process session) or lazily built
        # by get_reader (one exchange per process on a multi-host mesh)
        self.windowed_plane = None
        # reduce-side decode pool (shuffle/decode.py): lazily built on
        # the first pipelined read when conf decodeThreads > 0; shared
        # by every reader of this manager like the node's serve pool
        # (same double-checked create: benign unlocked fast-path read)
        self._decode_pool = None
        self._decode_lock = dbg_lock("manager.decode_pool", 21)
        # brokered in-flight fetch window (qos/): every reader of this
        # manager shares ONE weighted maxBytesInFlight budget across
        # tenants (per-tenant qosTenantMaxInFlight caps ride on it);
        # None (QoS off) keeps each reader's private window alone
        self._qos_inflight = None
        if self.qos is not None:
            from sparkrdma_tpu.utils.dbglock import dbg_condition

            self._qos_inflight_cv = dbg_condition(
                "manager.qos_inflight", 31
            )
            self._qos_inflight = WeightedCreditBroker(
                "inflight", conf.max_bytes_in_flight,
                self._qos_inflight_cv,
                qos=self.qos, classed=True,
                aging_ms=conf.qos_aging_ms, quota_inflight=True,
                wait_counter=counter(
                    "shuffle_inflight_credit_waits_total"
                ),
            )

        # heartbeat plane (driver side): last ack time per executor +
        # monitor thread — the CM DISCONNECTED/onBlockManagerRemoved
        # analog (RdmaNode.java:176-189, RdmaShuffleManager.scala:253-263)
        self._last_ack: Dict[ShuffleManagerId, float] = {}
        self._hb_seq = 0
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if is_driver and conf.heartbeat_interval_ms > 0:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name="drv-heartbeat",
            )
            self._hb_thread.start()

        if not is_driver:
            self._say_hello()

    # -- node binding with port retries (RdmaNode.java:73-87) ---------------
    def _bind_node(self, host: str, port: int) -> Node:
        last_err = None
        base = port or 38000
        for attempt in range(self.conf.port_max_retries):
            node = Node((host, base + attempt), self.conf,
                        is_executor=not self.is_driver)
            try:
                self.network.register(node)
                return node
            except Exception as e:
                node.stop()  # release the failed node's dispatcher threads
                last_err = e
                # a silent move is a debugging nightmare: every peer
                # that dials the CONFIGURED port sees dead refusals,
                # so the move must at least be visible in the log
                logger.warning(
                    "bind at %s:%d failed (%s) — retrying at %d",
                    host, base + attempt, e, base + attempt + 1,
                )
        raise RuntimeError(f"could not bind node near {host}:{base}") from last_err

    # -- control-plane send helpers -----------------------------------------
    def _driver_channel(self) -> Channel:
        addr = (self.conf.driver_host, self.conf.driver_port)
        return self.node.get_channel(
            addr, ChannelType.RPC_REQUESTOR, self.network.connect
        )

    def _send_msg(self, channel: Channel, msg: RpcMsg,
                  on_failure: Optional[Callable] = None
                  ) -> None:
        # pin the frames to the channel's negotiated wire generation so
        # v2-only tail fields stay off frames bound for v1 peers
        # (wire_version 0 = unversioned/in-process = current)
        frames = msg.encode_segments(
            self.conf.recv_wr_size,
            wire_version=channel.wire_version or None,
        )
        channel.send_rpc(
            frames,
            FnCompletionListener(on_failure=on_failure or (
                lambda e: logger.warning("rpc send failed: %s", e)
            )),
        )

    def _send_via(self, addr: Tuple[str, int], channel_type: ChannelType,
                  msg: RpcMsg, on_failure: Optional[Callable] = None,
                  must_retry: bool = True) -> None:
        """get_channel + send with ONE eviction-race retry: the node's
        bounded channel cache may evict an RPC channel between the
        cache lookup and the post (synchronous TransportError, listener
        untouched) — the retried get_channel reconnects the evicted
        key.  A genuinely dead peer still fails: the reconnect itself
        raises, or the retried post's failure propagates."""
        for attempt in (0, 1):
            ch = self.node.get_channel(
                addr, channel_type, self.network.connect,
                must_retry=must_retry,
            )
            try:
                self._send_msg(ch, msg, on_failure)
                return
            except TransportError:
                if attempt:
                    raise
                counter("transport_channel_evict_races_total").inc()

    def _send_driver_msg(self, msg: RpcMsg,
                         on_failure: Optional[Callable] = None) -> None:
        self._send_via(
            (self.conf.driver_host, self.conf.driver_port),
            ChannelType.RPC_REQUESTOR, msg, on_failure,
        )

    def _say_hello(self) -> None:
        if self._hello_sent:
            return
        self._hello_sent = True
        msg = HelloMsg(self.local_smid, self.node.address[1])
        self._send_driver_msg(msg)

    # -- receive dispatch ----------------------------------------------------
    def _receive(self, channel: Channel, frame: bytes) -> None:
        try:
            msg = decode_msg(frame)
        except WireFormatError as e:
            # one-frame blast radius: the channel stays up, the frame
            # is counted and dropped with structured context — an
            # unknown MSG_TYPE (future peer?) is tallied apart from a
            # frame whose declared type fails its own schema
            kind = "msg_type" if e.unknown_type else "malformed"
            counter(
                "wire_unknown_frames_total", engine="control", kind=kind
            ).inc()
            logger.warning(
                "dropping control frame (%s): %s (frame %s)",
                kind, e, hex_context(bytes(frame)),
            )
            return
        except ValueError:
            logger.exception("dropping malformed control frame")
            return
        if isinstance(msg, HelloMsg):
            self._handle_hello(msg)
        elif isinstance(msg, AnnounceShuffleManagersMsg):
            self._handle_announce(msg)
        elif isinstance(msg, PublishMapTaskOutputMsg):
            self._handle_publish(msg)
        elif isinstance(msg, FetchMapStatusMsg):
            self._handle_fetch_status(msg, channel)
        elif isinstance(msg, FetchMapStatusResponseMsg):
            self._handle_fetch_response(msg)
        elif isinstance(msg, FetchMapStatusFailedMsg):
            self._handle_fetch_failed(msg)
        elif isinstance(msg, HeartbeatMsg):
            self._handle_heartbeat(msg, channel)
        elif isinstance(msg, FetchExchangePlanMsg):
            self._handle_fetch_plan(msg, channel)
        elif isinstance(msg, ExchangePlanMsg):
            self._handle_exchange_plan(msg)
        elif isinstance(msg, PublishShuffleMetricsMsg):
            self._handle_shuffle_metrics(msg)
        elif isinstance(msg, PrefetchHintMsg):
            self._handle_prefetch_hint(msg)
        elif isinstance(msg, CleanShuffleMsg):
            self._handle_clean_shuffle(msg)
        elif isinstance(msg, PushSubBlockMsg):
            self._handle_push_sub_block(msg)
        elif isinstance(msg, FetchMergeStatusMsg):
            self._handle_fetch_merge_status(msg, channel)
        elif isinstance(msg, MergeStatusResponseMsg):
            self._handle_merge_response(msg)

    # -- heartbeat / failure detection ---------------------------------------
    def _heartbeat_loop(self) -> None:
        """Driver liveness monitor: ping every executor each interval;
        prune executors whose acks stop (or whose ping can't even be
        posted — the loopback-partition / dead-TCP-peer fast path)."""
        import time as _time

        interval = self.conf.heartbeat_interval_ms / 1000.0
        timeout = self.conf.heartbeat_timeout_ms / 1000.0
        while not self._hb_stop.wait(interval):
            self._hb_seq += 1
            now = _time.monotonic()
            for smid in self.executors:
                if self._hb_stop.is_set():
                    break  # quiesced mid-sweep: stop probing/pruning
                # the monitor must survive anything one executor's
                # bookkeeping throws — a dead monitor silently disables
                # failure detection for the rest of the job
                try:
                    last = self._last_ack.get(smid, now)
                    if now - last > timeout:
                        logger.warning(
                            "driver: executor %s missed heartbeats for "
                            "%.1fs — pruning",
                            smid.block_manager_id.executor_id, now - last,
                        )
                        self.remove_executor(smid)
                        continue
                    if FAULTS.enabled and FAULTS.fires("heartbeat"):
                        # dropped probe, NOT a raised error: a raised
                        # send failure would prune the executor, but
                        # this point models a lost packet — the peer
                        # stays alive and the next sweep probes again
                        continue
                    try:
                        # _send_via retries once on the eviction race:
                        # a cache-evicted (healthy) channel must not
                        # read as a dead executor and trigger a prune
                        self._send_via(
                            (smid.host, smid.port),
                            ChannelType.RPC_REQUESTOR,
                            HeartbeatMsg(self.local_smid, self._hb_seq,
                                         False),
                            on_failure=lambda e, smid=smid:
                                self._on_executor_send_failure(smid, e),
                            must_retry=False,
                        )
                    except Exception as e:
                        self._on_executor_send_failure(smid, e)
                except Exception:
                    logger.exception(
                        "heartbeat monitor: probe of %s failed", smid.host
                    )

    def _on_executor_send_failure(self, smid: ShuffleManagerId,
                                  err: BaseException) -> None:
        """A control-plane send to an executor failed outright: its
        channel is dead (partition / closed peer).  Prune immediately —
        the reference gets this signal from CM DISCONNECTED events."""
        # racy shutdown hint only — stop() re-checks under _life_lock
        if self._state != "running" or self._hb_stop.is_set():  # noqa: SC03 hint
            return
        import sys as _sys

        # racy quiescence probe, not a decision point
        if (self._state != "running" or self.node._stopped.is_set()  # noqa: SC03
                or _sys.is_finalizing()):
            # OUR node (or the interpreter) is shutting down — that is
            # quiescence, not an executor failure; stop probing instead
            # of spamming prunes.  Classified by explicit state ONLY:
            # manager.stop() and node.stop() both set their flag before
            # shutting any pool, and sys.is_finalizing() covers the
            # interpreter-shutdown RuntimeError — so a foreign
            # RuntimeError whose message merely LOOKS like a pool
            # shutdown ("cannot schedule new futures ...") still falls
            # through and prunes the dead peer (round-4 verdict: the
            # old substring heuristic silently reverted to the round-3
            # bug class whenever CPython reworded the message).
            logger.info("heartbeat monitor quiescing (%s)", err)
            self._hb_stop.set()
            return
        with self._executors_lock:
            known = smid in self._executors
        if known:
            logger.warning(
                "driver: channel to executor %s dead (%s) — pruning",
                smid.block_manager_id.executor_id, err,
            )
            self.remove_executor(smid)

    def _handle_heartbeat(self, msg: HeartbeatMsg, channel: Channel) -> None:
        if msg.is_ack:
            import time as _time

            self._last_ack[msg.shuffle_manager_id] = _time.monotonic()
            return
        # executor side: echo on the receiving channel's reply path
        try:
            self._send_msg(
                channel.reply_channel(),
                HeartbeatMsg(self.local_smid, msg.seq, True),
            )
        except Exception:
            logger.warning("heartbeat ack failed", exc_info=True)

    # -- driver handlers -----------------------------------------------------
    def _handle_hello(self, msg: HelloMsg) -> None:
        assert self.is_driver, "hello must only reach the driver"
        import time as _time

        smid = msg.shuffle_manager_id
        with self._executors_lock:
            self._removed.discard(smid)  # re-join after a prune is legal
            if smid not in self._executors:
                self._executors.append(smid)
            members = list(self._executors)
            # a hello is liveness proof: REFRESH the ack clock
            # (setdefault would keep a pre-partition timestamp, and the
            # monitor's next sweep would re-prune a healed executor
            # that re-helloed before its first fresh ack landed — found
            # by the seeded chaos sweep).  Inside the membership lock
            # so a concurrent sweep can't interleave its stale read
            # between this handler's membership write and clock write
            # (remove_executor prunes under the same lock).
            self._last_ack[smid] = _time.monotonic()
        logger.info("driver: hello from %s (now %d executors)",
                    smid.block_manager_id.executor_id, len(members))
        announce = AnnounceShuffleManagersMsg(members)
        for peer in members:
            try:
                self._send_via(
                    (peer.host, peer.port), ChannelType.RPC_REQUESTOR,
                    announce,
                )
            except Exception:
                logger.exception("driver: announce to %s failed", peer.host)
        # a bulk-plan barrier may be waiting on exactly this hello (a
        # publish can land before its publisher's hello — separate
        # channels): re-trigger pending barriers
        with self._plan_lock:
            self._hello_gen += 1
            pending = list(self._plan_waiters.keys())
        for sid in pending:
            self._maybe_answer_plans(sid)

    def _handle_announce(self, msg: AnnounceShuffleManagersMsg) -> None:
        with self._executors_lock:
            for smid in msg.shuffle_manager_ids:
                if smid not in self._peers:
                    self._peers.append(smid)
            peers = [p for p in self._peers if p != self.local_smid]
        # pre-connect the peer mesh in the background so the first fetch
        # is hot (reference: RdmaShuffleManager.scala:111-118) — but
        # only up to the bounded cache's free room: warming past the
        # cap would be pure connect/evict churn that also evicts
        # genuinely hot channels (at 256-peer fan-out the mesh cannot
        # be all-hot by definition; fetches connect lazily instead)
        def warm():
            cap = self.node._max_cached
            for peer in peers:
                if cap > 0:
                    with self.node._active_lock:
                        room = cap - len(self.node._active)
                    if room <= 0:
                        logger.info(
                            "mesh pre-connect stopped at the channel-"
                            "cache cap (%d): remaining peers connect "
                            "lazily on first fetch", cap,
                        )
                        return
                try:
                    self.node.get_channel(
                        (peer.host, peer.port), ChannelType.READ_REQUESTOR,
                        self.network.connect,
                    )
                except Exception:
                    logger.warning("pre-connect to %s:%d failed",
                                   peer.host, peer.port)
        threading.Thread(target=warm, daemon=True).start()

    def _get_or_create_mto(
        self, shuffle_id: int, host: ShuffleManagerId, map_id: int,
        num_partitions: Optional[int] = None,
    ) -> MapTaskOutput:
        with self._outputs_lock:
            by_host = self._outputs.setdefault(shuffle_id, {})
            by_map = by_host.setdefault(host, {})
            mto = by_map.get(map_id)
            if mto is None:
                n = num_partitions or self._shuffle_partitions.get(shuffle_id)
                if n is None:
                    raise KeyError(
                        f"shuffle {shuffle_id} not registered on driver"
                    )
                mto = by_map.setdefault(map_id, MapTaskOutput(n))
            return mto

    def _handle_publish(self, msg: PublishMapTaskOutputMsg) -> None:
        assert self.is_driver, "publish must only reach the driver"
        with self._executors_lock:
            tombstoned = msg.shuffle_manager_id in self._removed
        if tombstoned:
            # an in-flight publish racing the executor's prune must not
            # resurrect its outputs (they are unreachable: fetch-status
            # fails fast for tombstoned hosts, and a later duplicate
            # prune no longer re-clears state)
            logger.warning(
                "dropping publish from removed executor %s (shuffle=%d "
                "map=%d)", msg.shuffle_manager_id, msg.shuffle_id,
                msg.map_id,
            )
            return
        mto = self._get_or_create_mto(
            msg.shuffle_id, msg.shuffle_manager_id, msg.map_id,
            msg.total_num_partitions,
        )
        # skew-split outputs publish EXTRA sub-block rows past the
        # logical partition count, but an early fetch-status query may
        # have pre-created this table at the logical size — widen to
        # the sender's row count BEFORE any segment lands, so the fill
        # future can only complete at the extended threshold
        mto.ensure_capacity(msg.total_num_partitions)
        mto.put_range(
            msg.first_reduce_id, msg.last_reduce_id, msg.entries,
            epoch=msg.epoch,
        )
        self._maybe_answer_plans(msg.shuffle_id)

    def _handle_fetch_status(self, msg: FetchMapStatusMsg, channel: Channel) -> None:
        assert self.is_driver, "fetch-status must only reach the driver"

        def reply_failed(reason: str) -> None:
            # immediate negative answer → requester converts to a
            # metadata fetch failure and the stage retries NOW instead
            # of riding out the full location timeout
            logger.warning("fetch-status failed (shuffle=%d): %s",
                           msg.shuffle_id, reason)
            try:
                self._send_msg(
                    channel.reply_channel(),
                    FetchMapStatusFailedMsg(msg.callback_id, reason),
                )
            except Exception:
                logger.exception("fetch-status failure reply failed")

        with self._executors_lock:
            tombstoned = msg.host in self._removed
        if tombstoned:
            reply_failed(
                f"executor {msg.host.host}:{msg.host.port} was removed"
            )
            return
        try:
            mtos = {
                mid: self._get_or_create_mto(msg.shuffle_id, msg.host, mid)
                for mid in {m for m, _ in msg.block_ids}
            }
        except KeyError:
            reply_failed(f"shuffle {msg.shuffle_id} not registered on driver")
            return

        def answer():
            # all futures are complete (or failed) by the time this runs
            try:
                failed = [
                    m for m, t in mtos.items()
                    if t.fill_future.exception() is not None
                ]
                if failed:
                    # executor lost mid-publish
                    reply_failed(
                        f"maps {sorted(failed)} lost before publish "
                        f"completed (executor removed)"
                    )
                    return
                locs = [mtos[m].get_location(r) for m, r in msg.block_ids]
                resp = FetchMapStatusResponseMsg(
                    msg.callback_id, msg.total, msg.index, locs
                )
                self._send_msg(channel.reply_channel(), resp)
            except Exception:
                logger.exception(
                    "fetch-status reply failed (shuffle=%d host=%s)",
                    msg.shuffle_id, msg.host.host,
                )

        # chain on the fill futures instead of blocking a pool thread, so
        # a straggler map can never starve answerable requests
        self._when_all_filled(mtos.values(), answer)

    def _when_all_filled(self, mtos, fn) -> None:
        """Run ``fn`` on the fetch pool once every table's fill future
        is done (completed OR failed) — chained, never blocking a pool
        thread.  Shared by the pull path (fetch-status) and the bulk
        plan barrier."""
        remaining = [t for t in mtos if not t.fill_future.done()]
        if not remaining:
            self._fetch_pool.submit(fn)
            return
        countdown = {"n": len(remaining)}
        lock = threading.Lock()

        def on_done(_fut):
            with lock:
                countdown["n"] -= 1
                last = countdown["n"] == 0
            if last:
                self._fetch_pool.submit(fn)

        for t in remaining:
            t.fill_future.add_done_callback(on_done)

    # -- bulk-exchange plan (shuffle/bulk.py) --------------------------------
    def _handle_fetch_plan(self, msg: FetchExchangePlanMsg,
                           channel: Channel) -> None:
        assert self.is_driver, "fetch-plan must only reach the driver"

        def reply_failed(reason: str) -> None:
            self._reply_plan_failed(channel, msg.callback_id, reason)

        if msg.shuffle_id not in self._shuffle_num_maps:
            reply_failed(
                f"shuffle {msg.shuffle_id} not registered on driver"
            )
            return
        # one plan mode per shuffle: a windowed host and a full-barrier
        # host would run DIFFERENT collective sequences against the
        # same exchange (conf skew) — reject the latecomer's mode
        # loudly instead of letting the barrier hang to timeout
        windowed = msg.window >= 0
        with self._window_lock:
            prev = self._plan_mode.setdefault(msg.shuffle_id, windowed)
        if prev != windowed:
            mine = "windowed" if windowed else "full-barrier"
            served = "windowed" if prev else "full-barrier"
            reply_failed(
                f"shuffle {msg.shuffle_id} plan mode mismatch: this "
                f"host requested {mine} plans but the shuffle is being "
                f"served {served} — align "
                f"spark.shuffle.tpu.bulkWindowMaps across hosts"
            )
            return
        if windowed:
            # a fetch-plan request proves the requester participates:
            # remember it so the window host set pinned below includes
            # hosts whose hello is still in flight
            with self._window_lock:
                self._window_requesters.setdefault(
                    msg.shuffle_id, set()
                ).add(msg.requester)
        with self._plan_lock:
            stale = (
                self._shuffle_epoch.get(msg.shuffle_id)
                != self._membership_epoch
            )
            if not stale:
                self._plan_waiters.setdefault(msg.shuffle_id, []).append(
                    (msg, channel)
                )
        if stale:
            # membership changed since registration: the barrier may
            # never pass and any earlier plan is invalid — fail fast
            # (the job layer re-registers and retries the stage)
            reply_failed(
                f"membership changed since shuffle {msg.shuffle_id} was "
                f"registered (executor lost) — retry the stage"
            )
            return
        self._maybe_answer_plans(msg.shuffle_id)

    def _maybe_answer_plans(self, shuffle_id: int) -> None:
        """Answer pending plan requests: full-barrier waiters
        (``window == -1``) once EVERY registered map has published and
        filled; windowed waiters (``window >= 0``) as soon as their
        window's map quota is met (_maybe_answer_windows)."""
        if not self.is_driver:
            return
        num_maps = self._shuffle_num_maps.get(shuffle_id)
        if num_maps is None:
            return
        with self._plan_lock:
            waiters_now = self._plan_waiters.get(shuffle_id, [])
            any_windowed = any(m.window >= 0 for m, _ in waiters_now)
            any_legacy = any(m.window < 0 for m, _ in waiters_now)
        if any_windowed:
            self._maybe_answer_windows(shuffle_id, num_maps)
        if not any_legacy:
            return
        with self._outputs_lock:
            mtos = [
                m for bm in self._outputs.get(shuffle_id, {}).values()
                for m in bm.values()
            ]
        if len(mtos) < num_maps:
            return  # more publishes coming; re-checked on each publish

        def answer_all():
            while True:
                with self._plan_lock:
                    gen = self._hello_gen
                waiters = self._take_plan_waiters(
                    shuffle_id, lambda m: m.window < 0
                )
                if not waiters:
                    return
                plan = self._get_or_build_plan(shuffle_id, num_maps)
                if plan is not _PLAN_WAIT:
                    break
                # a publisher's hello hasn't landed yet (publish and
                # hello race on separate channels): keep the waiters —
                # _handle_hello re-triggers this barrier.  A hello that
                # arrived between our pop and this requeue saw an empty
                # waiter list and will never re-trigger — detect it via
                # the generation counter and re-check ourselves.
                with self._plan_lock:
                    self._plan_waiters.setdefault(
                        shuffle_id, []
                    ).extend(waiters)
                    raced = self._hello_gen != gen
                if not raced:
                    return
            for msg, channel in waiters:
                if isinstance(plan, str):
                    reply: RpcMsg = FetchMapStatusFailedMsg(
                        msg.callback_id, plan
                    )
                else:
                    hosts, flat, full_manifest, idx = plan
                    me = idx.get(msg.requester)
                    if me is None:
                        reply = FetchMapStatusFailedMsg(
                            msg.callback_id,
                            f"requester {msg.requester.host}:"
                            f"{msg.requester.port} is not in the plan's "
                            f"host set",
                        )
                    else:
                        reply = ExchangePlanMsg(
                            msg.callback_id, hosts, flat,
                            [row[me] for row in full_manifest],
                        )
                try:
                    self._send_msg(channel.reply_channel(), reply)
                except Exception:
                    logger.exception("plan reply failed")

        self._when_all_filled(mtos, answer_all)

    def _get_or_build_plan(self, shuffle_id: int, num_maps: int):
        """Build (once) and cache the shuffle's exchange plan so every
        requester sees ONE membership snapshot — divergent host sets
        would compile different collectives and deadlock (SPMD).
        Returns (hosts, flat_lengths, manifest[s][d], idx) or an error
        string.  Re-validates the barrier: fills may have FAILED or
        maps been pruned (executor loss) since the publish count
        passed."""
        with self._plan_lock:
            if (self._shuffle_epoch.get(shuffle_id)
                    != self._membership_epoch):
                return (
                    "membership changed since shuffle registration "
                    "(executor lost) — retry the stage"
                )
            cached = self._plan_cache.get(shuffle_id)
        if cached is not None:
            return cached
        with self._outputs_lock:
            snapshot = {
                h: dict(bm)
                for h, bm in self._outputs.get(shuffle_id, {}).items()
            }
        mtos = [m for bm in snapshot.values() for m in bm.values()]
        if len(mtos) < num_maps:
            return (
                f"maps lost before the plan was built "
                f"({len(mtos)}/{num_maps} remain — executor removed?)"
            )
        failed = [
            m for m in mtos
            if m.fill_future.done() and m.fill_future.exception() is not None
        ]
        if failed:
            return (
                f"{len(failed)} map table(s) failed before publish "
                f"completed (executor removed)"
            )
        hosts = sorted(self.executors, key=lambda s: (s.host, s.port))
        E = len(hosts)
        idx = {h: i for i, h in enumerate(hosts)}
        num_parts = self._shuffle_partitions[shuffle_id]
        lengths = [[0] * E for _ in range(E)]
        # manifest[s][d]: (map, reduce, length) blocks of src s → dst d
        manifest = [[[] for _ in range(E)] for _ in range(E)]
        for host, by_map in snapshot.items():
            s = idx.get(host)
            if s is None:
                with self._executors_lock:
                    tombstoned = host in self._removed
                if not tombstoned:
                    # published before its hello landed (separate
                    # channels): not an error — wait for the hello
                    return _PLAN_WAIT
                return (
                    f"publisher {host.host}:{host.port} is not a "
                    f"registered executor (bulk mode needs stable "
                    f"membership)"
                )
            for map_id in sorted(by_map):
                mto = by_map[map_id]
                for r in range(num_parts):
                    loc = mto.get_location(r)
                    if loc.is_empty or loc.length == 0:
                        continue
                    d = r % E
                    lengths[s][d] += loc.length
                    manifest[s][d].append((map_id, r, loc.length))
        flat = [lengths[s][d] for s in range(E) for d in range(E)]
        plan = (tuple(hosts), flat, manifest, idx)
        with self._plan_lock:
            if (self._shuffle_epoch.get(shuffle_id)
                    != self._membership_epoch):
                # an executor was removed while we built: this plan's
                # host set is already invalid — do NOT reinstate it
                return (
                    "membership changed while the exchange plan was "
                    "being built (executor lost) — retry the stage"
                )
            self._plan_cache.setdefault(shuffle_id, plan)
            return self._plan_cache[shuffle_id]

    # -- incremental (windowed) bulk plans -----------------------------------
    # The overlap the reference gets from partial-fill futures + a
    # bounded in-flight window (RdmaMapTaskOutput.scala:41-44,
    # RdmaShuffleFetcherIterator.scala:241-251), re-architected for
    # symmetric collectives: instead of one all-maps barrier the driver
    # cuts plan windows of `bulkWindowMaps` maps as they publish+fill;
    # every host runs one collective per window, so early bytes move
    # while straggler maps still write.

    def _maybe_answer_windows(self, shuffle_id: int,
                              num_maps: int) -> None:
        with self._window_lock:
            st = self._window_state.setdefault(shuffle_id, {
                "hosts": None,      # pinned at first window build
                "idx": None,
                "assigned": {},     # host → set(map_id)
                "total_assigned": 0,
                "next": 0,          # next window number to build
                "plans": {},        # window → (flat, manifest, final,
                                    #           my_maps_by_host)
                "failure": None,    # sticky error string
                "hooked": set(),    # id(mto) with fill retriggers
            })
            progress = True
            while progress:
                progress = False
                with self._plan_lock:
                    win = [
                        w for w in self._plan_waiters.get(shuffle_id, [])
                        if w[0].window >= 0
                    ]
                    stale = (
                        self._shuffle_epoch.get(shuffle_id)
                        != self._membership_epoch
                    )
                if not win:
                    return
                fail = st["failure"]
                if fail is None and stale:
                    fail = st["failure"] = (
                        "membership changed since shuffle "
                        "registration (executor lost) — retry "
                        "the stage"
                    )
                if fail is not None:
                    self._fail_window_waiters(shuffle_id, fail)
                    return
                if any(m.window == st["next"] for m, _ in win):
                    if self._try_build_window(shuffle_id, num_maps, st):
                        progress = True
                        if st["failure"] is not None:
                            continue  # dispatch the failure above
                # answer every waiter whose window is already built
                done_all = st["total_assigned"] >= num_maps
                taken = self._take_plan_waiters(
                    shuffle_id,
                    lambda m: 0 <= m.window < st["next"]
                    or (done_all and m.window >= st["next"]),
                )
                ready = [w for w in taken if w[0].window < st["next"]]
                beyond = [w for w in taken if w[0].window >= st["next"]]
                for m, ch in ready:
                    self._send_window_plan(m, ch, st)
                    progress = True
                for m, ch in beyond:
                    self._reply_plan_failed(
                        ch, m.callback_id,
                        f"window {m.window} is beyond the final window "
                        f"({st['next'] - 1})",
                    )

    def _try_build_window(self, shuffle_id: int, num_maps: int,
                          st: dict) -> bool:
        """Build window ``st['next']`` if its quota of published+filled
        maps is available.  Returns True when state advanced (a window
        was built OR a sticky failure was recorded)."""
        remaining = num_maps - st["total_assigned"]
        if remaining <= 0:
            if num_maps == 0 and st["next"] == 0:
                # zero-map shuffle (empty upstream stage): cut one
                # empty FINAL window so readers complete with no
                # records, exactly like the legacy full-barrier path
                self._pin_window_hosts(st, shuffle_id, ())
                E = len(st["hosts"])
                st["plans"][0] = (
                    [0] * (E * E),
                    [[[] for _ in range(E)] for _ in range(E)],
                    True, {},
                )
                st["next"] = 1
                return True
            return False
        with self._outputs_lock:
            snapshot = {
                h: dict(bm)
                for h, bm in self._outputs.get(shuffle_id, {}).items()
            }
        eligible: List = []
        pending: List = []
        for host, by_map in snapshot.items():
            assigned = st["assigned"].get(host, set())
            for map_id, mto in by_map.items():
                if map_id in assigned:
                    continue
                f = mto.fill_future
                if not f.done():
                    pending.append(mto)
                elif f.exception() is not None:
                    st["failure"] = (
                        f"map {map_id} of {host.host}:{host.port} "
                        f"failed before publish completed "
                        f"(executor removed)"
                    )
                    return True
                else:
                    eligible.append((host, map_id, mto))
        window_maps = self.conf.bulk_window_maps
        need = min(window_maps, remaining) if window_maps > 0 else remaining
        if len(eligible) < need:
            # not enough filled maps yet: retrigger when fills land
            for mto in pending:
                key = id(mto)
                if key not in st["hooked"]:
                    st["hooked"].add(key)
                    mto.fill_future.add_done_callback(
                        lambda _f, sid=shuffle_id:
                            self._maybe_answer_plans(sid)
                    )
            return False
        if st["hosts"] is None:
            self._pin_window_hosts(st, shuffle_id, snapshot.keys())
        idx = st["idx"]
        unknown = [h for (h, _m, _t) in eligible if h not in idx]
        if unknown:
            h = unknown[0]
            st["failure"] = (
                f"publisher {h.host}:{h.port} is not in the pinned "
                f"window host set (joined after window 0 — windowed "
                f"bulk needs stable membership)"
            )
            return True
        eligible.sort(key=lambda e: (e[0].host, e[0].port, e[1]))
        selected = eligible[:need]
        E = len(st["hosts"])
        num_parts = self._shuffle_partitions[shuffle_id]
        lengths = [[0] * E for _ in range(E)]
        manifest = [[[] for _ in range(E)] for _ in range(E)]
        my_maps_by_host: Dict[ShuffleManagerId, List[int]] = {}
        for host, map_id, mto in selected:
            s = idx[host]
            my_maps_by_host.setdefault(host, []).append(map_id)
            for r in range(num_parts):
                loc = mto.get_location(r)
                if loc.is_empty or loc.length == 0:
                    continue
                d = r % E
                lengths[s][d] += loc.length
                manifest[s][d].append((map_id, r, loc.length))
        flat = [lengths[s][d] for s in range(E) for d in range(E)]
        final = st["total_assigned"] + len(selected) >= num_maps
        st["plans"][st["next"]] = (flat, manifest, final, my_maps_by_host)
        for host, map_id, _mto in selected:
            st["assigned"].setdefault(host, set()).add(map_id)
        st["total_assigned"] += len(selected)
        logger.info(
            "shuffle %d: window %d planned (%d map(s), final=%s, "
            "%d assigned / %d total)",
            shuffle_id, st["next"], len(selected), final,
            st["total_assigned"], num_maps,
        )
        st["next"] += 1
        return True

    def _pin_window_hosts(self, st: dict, shuffle_id: int,
                          publishers) -> None:
        """Pin ONE membership snapshot for every window of a shuffle
        (divergent host sets across windows would shift partition
        ownership r % E and compile different collectives).  Publishers
        and plan REQUESTERS whose hello hasn't landed yet are still
        included — a publish or a plan request proves the executor
        participates, and the legacy path's wait-for-hello (_PLAN_WAIT)
        would stall the whole window on a control-plane race the data
        plane has already won."""
        with self._executors_lock:
            members = set(self._executors)
            removed = set(self._removed)
        with self._window_lock:
            requesters = set(
                self._window_requesters.get(shuffle_id, ())
            )
        members.update(
            h for h in list(publishers) + sorted(
                requesters, key=lambda s: (s.host, s.port)
            )
            if h not in removed
        )
        hosts = sorted(members, key=lambda s: (s.host, s.port))
        st["hosts"] = tuple(hosts)
        st["idx"] = {h: i for i, h in enumerate(hosts)}

    def _send_window_plan(self, msg: FetchExchangePlanMsg,
                          channel: Channel, st: dict) -> None:
        flat, manifest, final, my_maps_by_host = st["plans"][msg.window]
        me = st["idx"].get(msg.requester)
        if me is None:
            self._reply_plan_failed(
                channel, msg.callback_id,
                f"requester {msg.requester.host}:{msg.requester.port} "
                f"is not in the plan's host set",
            )
            return
        reply = ExchangePlanMsg(
            msg.callback_id, st["hosts"], flat,
            [row[me] for row in manifest],
            window=msg.window, final=final,
            my_maps=sorted(my_maps_by_host.get(msg.requester, [])),
        )
        try:
            self._send_msg(channel.reply_channel(), reply)
        except Exception:
            logger.exception("window plan reply failed")

    def _take_plan_waiters(self, shuffle_id: int, pred) -> List:
        """Pop (under _plan_lock) the plan waiters whose request
        matches ``pred``; the rest stay queued."""
        with self._plan_lock:
            cur = self._plan_waiters.get(shuffle_id, [])
            taken = [w for w in cur if pred(w[0])]
            rest = [w for w in cur if not pred(w[0])]
            if rest:
                self._plan_waiters[shuffle_id] = rest
            else:
                self._plan_waiters.pop(shuffle_id, None)
        return taken

    def _fail_window_waiters(self, shuffle_id: int, reason: str) -> None:
        taken = self._take_plan_waiters(
            shuffle_id, lambda m: m.window >= 0
        )
        for m, ch in taken:
            self._reply_plan_failed(ch, m.callback_id, reason)

    def _reply_plan_failed(self, channel: Channel, callback_id: int,
                           reason: str) -> None:
        try:
            self._send_msg(
                channel.reply_channel(),
                FetchMapStatusFailedMsg(callback_id, reason),
            )
        except Exception:
            logger.exception("plan failure reply failed")

    # -- prefetch hints (memory/tier.py) -------------------------------------
    def _handle_prefetch_hint(self, msg: PrefetchHintMsg) -> None:
        """A reader announced the blocks it is about to request: warm
        them through the serve pool so the disk reads finish before
        the read RPCs arrive.  Advisory — any failure is swallowed."""
        try:
            n = self.node.warm_blocks(msg.locations)
        except Exception:
            logger.warning("prefetch hint handling failed", exc_info=True)
            return
        if n:
            counter("tier_hint_blocks_total").inc(n)

    def send_prefetch_hint(self, host: ShuffleManagerId, shuffle_id: int,
                           locations) -> None:
        """Reader-side: ship the next-N fetch-plan locations to the
        peer that will serve them (local hints short-circuit to our
        own node).  Best-effort — a hint must never fail a fetch."""
        msg = PrefetchHintMsg(shuffle_id, locations)
        counter("tier_hint_msgs_total").inc()
        if host == self.local_smid:
            self._handle_prefetch_hint(msg)
            return
        try:
            self._send_via(
                (host.host, host.port), ChannelType.RPC_REQUESTOR, msg,
                must_retry=False,
            )
        except Exception:
            logger.debug("prefetch hint to %s dropped", host.host,
                         exc_info=True)

    # -- push-based merged shuffle (shuffle/push.py) --------------------------
    def push_merger_for(self, reduce_id: int):
        """Deterministic merger for one reduce partition: every member
        of the fleet maps ``reduce_id`` onto the same executor from the
        announced membership, sorted canonically — no coordination RPC.
        A membership mismatch (joiner mid-stage) only means a writer
        pushes where no reader will look: the blocks pull instead, and
        the driver's clean-shuffle broadcast sweeps the orphan merge
        state.  Falls back to SELF when no membership was announced
        (single-manager/in-process runs merge locally)."""
        with self._executors_lock:
            peers = list(self._executors if self.is_driver else self._peers)
        if not peers:
            return self.local_smid
        peers.sort(key=lambda s: (s.host, s.port))
        return peers[reduce_id % len(peers)]

    def push_partition(self, host, msgs) -> None:
        """Writer-side: best-effort push of ONE partition's sub-block
        messages to its merger (prefetch-hint posture: a failed or
        skipped push costs pull traffic, never the commit).  Local
        mergers short-circuit; remote sends are gated on the channel's
        negotiated wire generation so pre-v3 peers never see type-13
        frames."""
        if host == self.local_smid:
            for m in msgs:
                self._handle_push_sub_block(m)
            counter("push_pushes_total", target="local").inc()
            return
        try:
            ch = self.node.get_channel(
                (host.host, host.port), ChannelType.RPC_REQUESTOR,
                self.network.connect, must_retry=False,
            )
            if ch.wire_version and ch.wire_version < PUSH_MIN_WIRE_VERSION:
                counter("push_version_skips_total").inc()
                return
            def on_fail(e):
                counter("push_send_failures_total").inc()
                logger.debug("push send to %s failed: %s", host.host, e)
            for m in msgs:
                self._send_msg(ch, m, on_failure=on_fail)
            counter("push_pushes_total", target="remote").inc()
        except Exception:
            counter("push_send_failures_total").inc()
            logger.debug("push to %s dropped", host.host, exc_info=True)

    def send_merge_query(self, host, msg: FetchMergeStatusMsg,
                         on_failure: Callable) -> None:
        """Reader-side: post one merge-status query to a merger.  Any
        inability to send — a pre-v3 peer that has no merge plane, a
        connect failure — reports through ``on_failure``, which the
        reader treats as no coverage (pull everything)."""
        try:
            ch = self.node.get_channel(
                (host.host, host.port), ChannelType.RPC_REQUESTOR,
                self.network.connect, must_retry=False,
            )
            if ch.wire_version and ch.wire_version < PUSH_MIN_WIRE_VERSION:
                counter("push_version_skips_total").inc()
                on_failure(TransportError(
                    f"peer {host.host} negotiated wire v{ch.wire_version} "
                    f"< v{PUSH_MIN_WIRE_VERSION}: no merge plane"
                ))
                return
            self._send_msg(ch, msg, on_failure=on_failure)
        except Exception as e:
            on_failure(e)

    def _handle_push_sub_block(self, msg: PushSubBlockMsg) -> None:
        self.push_merger.on_sub_block(
            msg.shuffle_id, msg.map_id, msg.reduce_id,
            msg.total_len, msg.offset, msg.data,
        )

    def _handle_fetch_merge_status(self, msg: FetchMergeStatusMsg,
                                   channel: Channel) -> None:
        """Merger side of the reader's merged-location query: seal the
        queried reduce partitions and answer one response per id (the
        fetch-status response convention).  Any failure — including the
        dead-merger fault drill — replies failed, which the reader
        treats as no coverage → pull."""
        try:
            answers = self.push_merger.merge_status(
                msg.shuffle_id, msg.reduce_ids
            )
        except Exception as e:
            try:
                self._send_msg(
                    channel.reply_channel(),
                    FetchMapStatusFailedMsg(
                        msg.callback_id, f"merger unavailable: {e}"
                    ),
                )
            except Exception:
                logger.debug("merge-status failure reply failed",
                             exc_info=True)
            return
        total = len(answers)
        for idx, (rid, mkey, length, prov) in enumerate(answers):
            try:
                self._send_msg(
                    channel.reply_channel(),
                    MergeStatusResponseMsg(
                        msg.callback_id, total, idx, rid, mkey,
                        length, prov,
                    ),
                )
            except Exception:
                logger.warning("merge-status reply failed", exc_info=True)
                return

    def _handle_merge_response(self, msg: MergeStatusResponseMsg) -> None:
        with self._callbacks_lock:
            cb = self._callbacks.get(msg.callback_id)
        if cb is None or not isinstance(cb, _MergeCallback):
            logger.warning("merge response for unknown callback %d",
                           msg.callback_id)
            return
        cb.on_response(msg)

    def register_merge_callback(self, on_status: Callable,
                                on_error: Callable[[str], None]) -> int:
        with self._callbacks_lock:
            cb_id = self._next_callback_id
            self._next_callback_id += 1
            self._callbacks[cb_id] = _MergeCallback(on_status, on_error)
        return cb_id

    # -- executor handlers ---------------------------------------------------
    def _handle_fetch_response(self, msg: FetchMapStatusResponseMsg) -> None:
        with self._callbacks_lock:
            cb = self._callbacks.get(msg.callback_id)
        if cb is None:
            logger.warning("fetch response for unknown callback %d",
                           msg.callback_id)
            return
        cb.on_response(msg)

    def _handle_fetch_failed(self, msg: FetchMapStatusFailedMsg) -> None:
        with self._callbacks_lock:
            cb = self._callbacks.get(msg.callback_id)
        if cb is None:
            return  # reader already gone (timeout fired / task ended)
        cb.on_failed(msg.reason)

    def _handle_exchange_plan(self, msg: ExchangePlanMsg) -> None:
        with self._callbacks_lock:
            cb = self._callbacks.get(msg.callback_id)
        if cb is None or not isinstance(cb, _PlanCallback):
            logger.warning("plan response for unknown callback %d",
                           msg.callback_id)
            return
        cb.on_plan(msg)

    def register_plan_callback(self, on_plan: Callable,
                               on_error: Callable[[str], None]) -> int:
        with self._callbacks_lock:
            cb_id = self._next_callback_id
            self._next_callback_id += 1
            self._callbacks[cb_id] = _PlanCallback(on_plan, on_error)
        return cb_id

    def unregister_plan_callback(self, cb_id: int) -> None:
        with self._callbacks_lock:
            self._callbacks.pop(cb_id, None)

    def register_fetch_callback(
        self, on_locations: Callable[[List[BlockLocation]], None],
        on_error: Optional[Callable[[str], None]] = None,
    ) -> int:
        with self._callbacks_lock:
            cb_id = self._next_callback_id
            self._next_callback_id += 1
            self._callbacks[cb_id] = _FetchCallback(on_locations, on_error)
        return cb_id

    def unregister_fetch_callback(self, cb_id: int) -> None:
        with self._callbacks_lock:
            self._callbacks.pop(cb_id, None)

    # -- multi-tenant QoS helpers (qos/) -------------------------------------
    def qos_tenant_for(self, handle) -> Optional[object]:
        """Resolve (get-or-create) the tenant a shuffle runs under:
        the handle's stamped tenant id, else this manager's conf
        ``tenant``, else one tenant per shuffle (``shuffle-<id>``).
        Conf weight/priority/quotas apply on every resolution (last
        writer wins — that is how policy changes land).  None with
        QoS off."""
        qos = self.qos
        if qos is None:
            return None
        name = (
            getattr(handle, "tenant", "")
            or self.conf.tenant
            or f"shuffle-{handle.shuffle_id}"
        )
        return qos.tenant(
            name,
            weight=self.conf.qos_tenant_weight,
            priority=self.conf.qos_tenant_priority,
            max_bytes=self.conf.qos_tenant_max_bytes,
            max_inflight=self.conf.qos_tenant_max_inflight,
        )

    def qos_inflight_broker(self):
        return self._qos_inflight

    def _qos_bind(self, handle) -> None:
        """Bind shuffle → tenant in the process-global registry so the
        SERVING side (``Node.tenant_of_mkey``) can classify incoming
        reads — called wherever a shuffle becomes live in this
        process (registration, writers, readers)."""
        if self.qos is not None:
            self.qos.bind_shuffle(
                handle.shuffle_id, self.qos_tenant_for(handle)
            )

    def qos_admit(self, handle, nbytes: int) -> bool:
        """Admission control on registration: account ``nbytes`` of
        committed map output under the tenant's registered-byte quota
        (writers call this at commit).  Over quota the commit queues
        up to ``qosAdmissionWait`` then the tenant DEGRADES rather
        than OOM the node.  True = within quota (or QoS off)."""
        if self.qos is None or nbytes <= 0:
            return True
        return self.qos.admit(
            handle.shuffle_id, self.qos_tenant_for(handle), nbytes,
            wait_s=self.conf.qos_admission_wait_ms / 1000.0,
        )

    # -- public API (the ShuffleManager SPI) ---------------------------------
    def register_shuffle(
        self,
        shuffle_id: int,
        num_maps: int,
        partitioner: Partitioner,
        aggregator: Optional[Aggregator] = None,
        map_side_combine: bool = False,
        key_ordering: bool = False,
    ) -> ShuffleHandle:
        """Driver-side registration (reference:
        RdmaShuffleManager.scala:242-274)."""
        handle = ShuffleHandle(
            shuffle_id, num_maps, partitioner, aggregator,
            map_side_combine, key_ordering,
        )
        if self.qos is not None:
            # stamp the tenant id so executors sharing the handle
            # resolve the same tenant, and bind it for the serve path
            handle.tenant = self.qos_tenant_for(handle).name
            self._qos_bind(handle)
        self._shuffle_partitions[shuffle_id] = partitioner.num_partitions
        self._shuffle_num_maps[shuffle_id] = num_maps
        with self._plan_lock:
            self._shuffle_epoch[shuffle_id] = self._membership_epoch
        return handle

    def get_writer(self, handle: ShuffleHandle, map_id: int) -> ShuffleWriter:
        # executor-side binding: the writer's process serves the blocks
        self._qos_bind(handle)
        return ShuffleWriter(self, handle, map_id)

    def get_reader(
        self,
        handle: ShuffleHandle,
        start_partition: int,
        end_partition: int,
        maps_by_host: Dict[ShuffleManagerId, List[int]],
    ):
        """maps_by_host plays the MapOutputTracker's
        getMapSizesByExecutorId role (RdmaShuffleReader.scala:44-49):
        which host ran which map tasks — known to the job scheduler.

        With ``readPlane=windowed`` the reader instead rides the
        unified device plane: blocks arrive via driver-planned window
        collectives (maps_by_host is unused — the plan carries the
        manifest)."""
        self._qos_bind(handle)
        if self.conf.read_plane == "windowed":
            from sparkrdma_tpu.shuffle.bulk import WindowedReadPlane

            if self.windowed_plane is None:
                self.windowed_plane = WindowedReadPlane(self)
            return self.windowed_plane.reader(
                handle, start_partition, end_partition
            )
        from sparkrdma_tpu.shuffle.reader import ShuffleReader

        return ShuffleReader(
            self, handle, start_partition, end_partition, maps_by_host
        )

    def get_decode_pool(self):
        """Get-or-create the manager's shared decode pool — ``None``
        when ``decodeThreads`` is 0 (serial fallback) or the manager
        stopped.  Workers pin to ``dispatcherCpuList`` exactly like the
        transport dispatcher and serve-pool threads."""
        n = self.conf.decode_threads
        if n <= 0 or self._state != "running":  # noqa: SC03 re-checked below
            return None
        pool = self._decode_pool
        if pool is None:
            from sparkrdma_tpu.shuffle.decode import DecodePool

            with self._decode_lock:
                # _decode_lock (not _life_lock) orders this against
                # _stop_decode_pool
                if self._state != "running":  # noqa: SC03 ordered by _decode_lock
                    # re-checked under the lock: a create racing
                    # manager.stop() must not resurrect a pool whose
                    # stop already ran (leaked pinned workers)
                    return None
                if self._decode_pool is None:
                    self._decode_pool = DecodePool(
                        self.executor_id, n,
                        self.conf.decode_ahead_bytes,
                        init_fn=self.node._pin_worker_thread,
                        qos=self.qos,
                    )
                pool = self._decode_pool
        return pool

    def publish_map_output(
        self, shuffle_id: int, map_id: int, mto: MapTaskOutput
    ) -> Tuple[int, int, int]:
        """Executor → driver publish (RdmaWrapperShuffleWriter.scala:115-149).

        DELTA-SYNCED: only the entries changed since the table's last
        publish ship, as epoch-tagged contiguous runs (the first
        publish after commit is the whole table — everything is dirty).
        A republish after relocating a few blocks therefore costs
        O(changed) wire bytes, not O(partitions); the driver's
        per-entry epoch guard makes out-of-order segment application
        safe.  Returns (segments, entries, entry_bytes) published."""
        n = mto.num_partitions
        epoch, runs = mto.take_delta()
        entries = 0
        nbytes = 0
        for first, last, raw in runs:
            msg = PublishMapTaskOutputMsg(
                self.local_smid, shuffle_id, map_id, n, first, last,
                raw, epoch,
            )
            if self.is_driver:
                # driver-local writer (local[*] mode): install directly
                self._handle_publish(msg)
            else:
                def requeue(e, first=first, last=last):
                    # the dirty bits were consumed by take_delta: a
                    # send lost AFTER the synchronous-retry window
                    # must re-dirty its run or no later publish would
                    # ever re-ship it (the pre-delta full publish
                    # self-healed by always resending everything)
                    logger.warning(
                        "publish of shuffle %d map %d [%d,%d] failed "
                        "(%s) — re-marked dirty for the next publish",
                        shuffle_id, map_id, first, last, e,
                    )
                    mto.mark_dirty(first, last)

                if FAULTS.enabled and FAULTS.fires("publish"):
                    # a LOST publish, not a raised one: the run
                    # re-dirties (delta plane's self-heal) and ships
                    # with the next publish instead of failing the
                    # commit — this point exercises exactly that path
                    requeue(FaultInjectedError("publish"))
                    continue
                try:
                    self._send_driver_msg(msg, on_failure=requeue)
                except BaseException:
                    mto.mark_dirty(first, last)
                    raise
            entries += last - first + 1
            nbytes += len(raw)
        if runs:
            counter("shuffle_publish_segments_total").inc(len(runs))
            counter("shuffle_publish_entries_total").inc(entries)
            counter("shuffle_publish_entry_bytes_total").inc(nbytes)
        return len(runs), entries, nbytes

    # -- per-shuffle telemetry (metrics/ tentpole) ---------------------------
    def record_shuffle_write(self, shuffle_id: int, wm) -> None:
        """Writer commit hook: fold one map task's WriteMetrics into
        the shuffle's telemetry accumulator (no-op unless conf
        ``metrics`` is on — the default path stays untouched)."""
        if not self.conf.metrics_enabled:
            return
        self._telemetry_add(
            shuffle_id,
            map_tasks=1,
            write_bytes=wm.bytes_written,
            write_records=wm.records_written,
            spills=wm.spills,
            spill_bytes=wm.bytes_spilled,
            write_time_ms=wm.write_time_ms,
        )

    def record_shuffle_skew(self, shuffle_id: int, snap: Dict) -> None:
        """Writer commit hook: fold one map task's partition-balance /
        split snapshot (skew/registry.py's ``record_commit`` return)
        into the shuffle's telemetry, ``skew_``-prefixed so the report
        can find them.  Rides the PR 1 telemetry plane — published even
        when splitting is off, so ``metrics_report.py`` shows a
        partition-balance view either way."""
        if not self.conf.metrics_enabled or not snap:
            return
        self._telemetry_add(
            shuffle_id,
            **{
                (k if k.startswith("max_") else f"skew_{k}"): v
                for k, v in snap.items()
            },
        )

    def record_shuffle_read(self, shuffle_id: int, rm) -> None:
        """Reader completion hook: fold one reduce task's ReadMetrics
        into the shuffle's telemetry accumulator."""
        if not self.conf.metrics_enabled:
            return
        self._telemetry_add(
            shuffle_id,
            reduce_tasks=1,
            local_blocks=rm.local_blocks,
            remote_blocks=rm.remote_blocks,
            local_bytes=rm.local_bytes,
            remote_bytes=rm.remote_bytes,
            records_read=rm.records_read,
            fetch_wait_ms=rm.fetch_wait_ms,
            decode_wait_ms=getattr(rm, "decode_wait_ms", 0.0),
        )

    def _telemetry_add(self, shuffle_id: int, **kv) -> None:
        with self._telemetry_lock:
            d = self._telemetry.setdefault(shuffle_id, {})
            for k, v in kv.items():
                d[k] = _fold_telemetry(d.get(k, 0), k, v)

    def _publish_shuffle_telemetry(self, shuffle_id: int) -> None:
        """Ship this manager's accumulated per-shuffle telemetry to the
        driver over the control plane — the same executor → driver flow
        the map-output location publishes ride."""
        with self._telemetry_lock:
            snap = self._telemetry.pop(shuffle_id, None)
        if not snap:
            return
        import json as _json

        msg = PublishShuffleMetricsMsg(
            self.local_smid, shuffle_id,
            _json.dumps(snap).encode("utf-8"),
        )
        if self.is_driver:
            self._handle_shuffle_metrics(msg)
        else:
            try:
                self._send_driver_msg(msg)
            except Exception:
                logger.warning(
                    "shuffle %d telemetry publish failed", shuffle_id,
                    exc_info=True,
                )

    def _handle_shuffle_metrics(self, msg: PublishShuffleMetricsMsg) -> None:
        import json as _json

        try:
            snap = _json.loads(bytes(msg.payload).decode("utf-8"))
        except ValueError:
            logger.warning("dropping malformed shuffle telemetry")
            return
        exec_id = msg.shuffle_manager_id.block_manager_id.executor_id
        with self._telemetry_lock:
            per_host = self._shuffle_telemetry.setdefault(
                msg.shuffle_id, {}
            )
            mine = per_host.setdefault(exec_id, {})
            for k, v in snap.items():
                mine[k] = _fold_telemetry(mine.get(k, 0), k, v)
            while len(self._shuffle_telemetry) > _TELEMETRY_KEEP:
                oldest = min(self._shuffle_telemetry)
                del self._shuffle_telemetry[oldest]

    def shuffle_telemetry(self, shuffle_id: int) -> Dict:
        """Driver-side aggregated view of one shuffle's telemetry:
        ``{"per_host": {executor_id: {...}}, "total": {...}}`` — the
        per-shuffle snapshot the issue's observability layer exposes
        next to the registry dump."""
        with self._telemetry_lock:
            per_host = {
                h: dict(m)
                for h, m in self._shuffle_telemetry.get(
                    shuffle_id, {}
                ).items()
            }
        total: Dict[str, float] = {}
        for m in per_host.values():
            for k, v in m.items():
                total[k] = _fold_telemetry(total.get(k, 0), k, v)
        return {"per_host": per_host, "total": total}

    def unregister_shuffle(self, shuffle_id: int) -> None:
        if self.windowed_plane is not None and self.conf.metrics_enabled:
            # fold the zero-copy plane's window landings into the
            # shuffle's telemetry before it ships to the driver
            evs = self.windowed_plane.window_events(shuffle_id)
            if evs:
                self._telemetry_add(
                    shuffle_id,
                    exchange_windows=len(evs),
                    exchange_window_payload_bytes=sum(
                        b for _w, _t, b in evs
                    ),
                )
        self._publish_shuffle_telemetry(shuffle_id)
        if (self.conf.metrics_enabled and self.conf.trace
                and self.conf.metrics_trace_bridge):
            # sample registry counters onto the Perfetto timeline at
            # every shuffle boundary (counter tracks)
            get_registry().publish_to_tracer(get_tracer())
        # merger first: its segments release by mkey, and the
        # resolver's arena.release_shuffle sweep must not find them
        self.push_merger.remove_shuffle(shuffle_id)
        self.resolver.remove_shuffle(shuffle_id)
        if self.windowed_plane is not None:
            self.windowed_plane.forget(shuffle_id)
        with self._plan_lock:
            self._plan_cache.pop(shuffle_id, None)
            self._shuffle_epoch.pop(shuffle_id, None)
        with self._window_lock:
            self._window_state.pop(shuffle_id, None)
            self._plan_mode.pop(shuffle_id, None)
            self._window_requesters.pop(shuffle_id, None)
        with self._outputs_lock:
            self._outputs.pop(shuffle_id, None)
        self._shuffle_partitions.pop(shuffle_id, None)
        self._shuffle_num_maps.pop(shuffle_id, None)
        if self.qos is not None:
            # return the shuffle's admitted registered bytes: a tenant
            # back under quota leaves degraded mode, queued admissions
            # re-check
            self.qos.release_shuffle(shuffle_id)
        # drop the shuffle's skew accounting (written even with
        # splitting off when telemetry is on)
        get_skew().release_shuffle(shuffle_id)
        if self.is_driver:
            # broadcast so every executor releases its OWN side of the
            # shuffle (registered segments, block-store mkeys, QoS
            # quota): without this, executor resources for a finished
            # shuffle survive until manager stop — the resource ledger
            # (conf resourceDebug) flagged exactly that leak.  Best
            # effort, like the membership announce: a lost clean only
            # delays the release to the executor's stop sweep.
            clean = CleanShuffleMsg(shuffle_id)
            for peer in self.executors:
                try:
                    # no connect retries (the heartbeat posture): an
                    # unregister racing executor teardown must not
                    # stall the caller through the full reconnect
                    # budget of a peer that is already gone
                    self._send_via(
                        (peer.host, peer.port), ChannelType.RPC_REQUESTOR,
                        clean, on_failure=lambda e: None,
                        must_retry=False,
                    )
                except Exception:
                    logger.info(
                        "driver: clean-shuffle %d to %s failed",
                        shuffle_id, peer.host,
                    )

    def _handle_clean_shuffle(self, msg: CleanShuffleMsg) -> None:
        """Executor side of the driver's unregister broadcast: run the
        local unregister sweep (idempotent — every pop tolerates an
        already-unknown shuffle, so a duplicate clean is a no-op)."""
        if self.is_driver:
            return  # drivers originate cleans, they don't follow them
        self.unregister_shuffle(msg.shuffle_id)

    def remove_executor(self, smid: ShuffleManagerId) -> None:
        """Elastic membership pruning (reference onBlockManagerRemoved,
        RdmaShuffleManager.scala:253-263).  Unfilled tables from the lost
        executor get their futures failed so driver-side fetch-status
        waits unblock immediately instead of timing out."""
        with self._executors_lock:
            was_member = smid in self._executors
            if was_member:
                self._executors.remove(smid)
            self._removed.add(smid)
        self._last_ack.pop(smid, None)
        if not was_member:
            # duplicate prune (heartbeat timeout racing a send-failure
            # callback): membership did not change again, so do NOT
            # bump the epoch — that would doom shuffles registered
            # after the first prune and clear valid waiters/plans
            return
        # bulk-mode plan waiters can never be satisfied once a member is
        # lost (stable membership is the mode's contract): answer them
        # negatively NOW so readers fail fast instead of timing out
        with self._plan_lock:
            self._membership_epoch += 1
            doomed_waiters = [
                (sid, w) for sid, ws in self._plan_waiters.items()
                for w in ws
            ]
            self._plan_waiters.clear()
            self._plan_cache.clear()
        with self._window_lock:
            self._window_state.clear()
            self._plan_mode.clear()
            self._window_requesters.clear()
        for sid, (msg, channel) in doomed_waiters:
            try:
                self._send_msg(
                    channel.reply_channel(),
                    FetchMapStatusFailedMsg(
                        msg.callback_id,
                        f"executor {smid.host}:{smid.port} lost while "
                        f"awaiting the exchange plan of shuffle {sid}",
                    ),
                )
            except Exception:
                logger.exception("plan-failure reply failed")
        with self._outputs_lock:
            doomed: List[MapTaskOutput] = []
            for by_host in self._outputs.values():
                by_map = by_host.pop(smid, None)
                if by_map:
                    doomed.extend(by_map.values())
        for mto in doomed:
            # check-then-set races a concurrently completing publish;
            # losing that race is fine (the table filled — readers can
            # use it), it must just not kill the caller
            try:
                if not mto.fill_future.done():
                    mto.fill_future.set_exception(
                        RuntimeError(
                            f"executor lost: {smid.host}:{smid.port}"
                        )
                    )
            except Exception:
                pass

    # -- in-process helpers for the job layer --------------------------------
    def maps_by_host(self, shuffle_id: int) -> Dict[ShuffleManagerId, List[int]]:
        """Driver-side view of which host published which maps."""
        with self._outputs_lock:
            by_host = self._outputs.get(shuffle_id, {})
            return {h: sorted(m.keys()) for h, m in by_host.items()}

    @property
    def executors(self) -> List[ShuffleManagerId]:
        with self._executors_lock:
            return list(self._executors)

    def quiesce(self) -> None:
        """Stop the background liveness plane (heartbeat monitor)
        WITHOUT tearing the manager down.  Call on the driver before
        stopping executors: a deliberate shutdown must not race the
        monitor into reporting healthy executors as dead ("channel to
        executor N dead — pruning" noise at exit)."""
        self._hb_stop.set()
        t = self._hb_thread
        if t is not None:
            t.join(timeout=2.0)
            if not t.is_alive():
                self._hb_thread = None

    def _dump_metrics(self) -> None:
        """Stop-time registry exports: JSON snapshot and/or Prometheus
        text when the conf paths are set (executors suffix their id so
        multi-process runs don't clobber the driver's file), plus a
        final bridge of counters into the trace stream."""
        suffix = "" if self.is_driver else f".{self.executor_id}"
        if self.conf.trace and self.conf.metrics_trace_bridge:
            get_registry().publish_to_tracer(get_tracer())
        path = self.conf.metrics_json_path
        if path:
            try:
                write_json_snapshot(path + suffix)
            except OSError:
                logger.exception("metrics JSON dump to %s failed", path)
        path = self.conf.metrics_prom_path
        if path:
            try:
                write_prometheus(path + suffix)
            except OSError:
                logger.exception("metrics prom dump to %s failed", path)

    def stop(self) -> None:
        """Teardown (reference: RdmaShuffleManager.scala:348-357)."""
        with self._life_lock:
            if self._state != "running":
                # a second stop() — concurrent or repeated — must
                # observe the flip atomically with the check: the old
                # unguarded check-then-set let two racing callers both
                # enter the teardown body and double-release the
                # owner-counted RECORDER/TRACING/ledger globals
                return
            self._transition("stopping", frm="running")
        self.quiesce()
        if self.stats is not None:
            self.stats.print_stats()
        if self.conf.metrics_enabled:
            self._dump_metrics()
        if self.conf.trace:
            tracer = get_tracer()
            # only the FIRST manager to stop dumps and clears: the
            # tracer is process-global, so in-process clusters (driver
            # + executors sharing one conf) would otherwise overwrite
            # the dump with the cleared tracer's empty event list,
            # losing every span and bridged counter
            if tracer.enabled:
                try:
                    tracer.dump(self.conf.trace_path)
                except OSError:
                    logger.exception(
                        "trace dump to %s failed", self.conf.trace_path
                    )
                tracer.enabled = False
                tracer.clear()
        if self._obs_retained:
            self._obs_retained = False
            if self.conf.flight_recorder_dump_path:
                # final black-box snapshot before the rings go away —
                # this is how each fleet process leaves its dump for
                # the cross-process merge (obs/collect.py)
                RECORDER.dump("manager_stop")
            RECORDER.release()
        if self._tracing_retained:
            self._tracing_retained = False
            TRACING.release()
        logger.info("staging pool at stop: %s", self.staging_pool.stats())
        logger.info("tier store at stop: %s", self.tier_store.stats())
        if self.metrics_http is not None:
            # the scrape endpoint dies with the manager: synchronous
            # shutdown so the census sees no leaked serving thread
            self.metrics_http.stop()
        if self._qos_inflight is not None:
            self._qos_inflight.stop()
        with self._decode_lock:
            decode_pool, self._decode_pool = self._decode_pool, None
        if decode_pool is not None:
            decode_pool.stop()
        if self._fetch_pool is not None:
            self._fetch_pool.shutdown(wait=False)
        self.push_merger.stop()
        self.resolver.stop()
        self.node.stop()
        self.network.unregister(self.node)
        self.arena.stop()
        # entries normally drain via segment release above; sweep any
        # stragglers (adoption racing teardown) before the pool closes
        self.tier_store.stop()
        self.staging_pool.close()
        if self.conf.resource_debug:
            # leak report LAST, after every pool above returned its
            # resources.  Non-raising here: GC-tied tier views may
            # legitimately outlive the manager and settle their pins
            # from finalizers (the ledger epoch-bumps so those late
            # releases become silent no-ops); the raising form is for
            # tests that fully drain first.
            from sparkrdma_tpu.utils.ledger import get_resource_ledger

            get_resource_ledger().stop(raise_on_leak=False)
        if self._faults_armed:
            # owner-counted like the ledger: only the LAST armed
            # manager in the process disarms the injector, so an
            # in-process cluster keeps one deterministic stream alive
            # until every member has stopped
            FAULTS.stop()
            self._faults_armed = False
        with self._life_lock:
            self._transition("stopped", frm="stopping")
