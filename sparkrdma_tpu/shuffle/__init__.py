"""Shuffle data plane: manager, writer, reader, resolver, map-output index."""
