"""Reduce-side decode-ahead pipeline: parallel deserialize/decompress
overlapped with fetch.

PR 3 striped the wire so blocks *land* fast; everything after landing
(deserialize, ``CompressedSerializer`` inflate, per-block sort) ran
serially on the reduce-task thread — the "CPU copy/decode dominates
once the wire is fast" effect RDMAbox (arXiv:2104.12197) and the DMA
Streaming Framework (arXiv:2603.10030) report for post-transport data
paths.  This module adds the consume-side pipeline:

- :class:`DecodePool` — one bounded pool per manager (the
  ``_ServePool`` shape from transport/node.py): ``decodeThreads``
  workers pinned via ``dispatcherCpuList`` drain a FIFO of decode
  tasks under a ``decodeAheadBytes`` byte-credit budget.  A task's
  cost is its encoded size; credits are held until the task thread
  CONSUMES the result, so the budget bounds decoded-ahead memory, not
  just concurrent decodes.  A block larger than the whole budget
  clamps to it and decodes alone rather than deadlocking.
- :class:`DecodeStream` — one per reader: readers submit raw block
  payloads from the transport's ``on_success`` callbacks (decode
  starts AS BLOCKS LAND, while the task thread is still blocked on
  earlier results) and consume :class:`DecodeTicket` results in their
  own order.  Large blocks split at the serializer's frame boundaries
  (``frame_spans``) so one block fans out across workers.
- Deadlock freedom WITHOUT admission ordering: a consumer that reaches
  a ticket whose decode has not started yet STEALS it and decodes
  inline on the task thread (bit-exact same result, no credits
  needed).  The consumer therefore only ever blocks on a decode that
  is actively running; workers blocked on credits always drain once
  the consumer consumes or closes.  ``close()`` poisons the stream
  idempotently: queued tickets cancel, finished-but-unconsumed tickets
  release their credits, in-flight decodes release on completion — a
  mid-decode ``FetchFailedError`` never strands a worker.

Serial fallback: ``decodeThreads=0`` (the default on single-core
hosts, the ``bulkPipelineWindows`` convention) keeps the legacy
task-thread decode; its output is bit-exact with the pipelined path.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from sparkrdma_tpu.faults.injector import FAULTS
from sparkrdma_tpu.metrics import counter, gauge
from sparkrdma_tpu.obs import RECORDER, fr_event
from sparkrdma_tpu.qos import CreditLedger
from sparkrdma_tpu.utils.dbglock import dbg_condition
from sparkrdma_tpu.utils.ledger import NOOP_TICKET, ledger_acquire
from sparkrdma_tpu.utils.serde import as_view
from sparkrdma_tpu.utils.statemachine import StateMachine

# blocks at or above this size are considered for frame-boundary
# splitting across workers; span groups aim for at least _SPLIT_CHUNK
# bytes each so tiny frames don't drown the pool in task overhead
_SPLIT_MIN_BYTES = 1 << 20
_SPLIT_CHUNK_BYTES = 256 << 10

# ticket states (guarded by the pool's condition)
_QUEUED, _DECODING, _STOLEN, _DONE, _CANCELLED = (
    "queued", "decoding", "stolen", "done", "cancelled")


class DecodeTicket(StateMachine):
    """One submitted block (or block fragment) flowing through the
    pool.  ``len(ticket)`` is the encoded payload size, so reader
    byte accounting works on tickets and raw payloads alike."""

    __slots__ = (
        "_pool", "_stream", "_fn", "_data", "cost", "nbytes",
        "_state", "_held", "_event", "_result", "_error", "_abandoned",
        "_tkt",
    )

    MACHINE = "decode.ticket"
    STATES = (_QUEUED, _DECODING, _STOLEN, _DONE, _CANCELLED)
    INITIAL = _QUEUED
    TERMINAL = (_DONE, _CANCELLED)
    TRANSITIONS = {
        "queued": ("decoding", "stolen", "cancelled"),
        "decoding": ("done",),   # worker finishes (even on decode error)
        "stolen": ("done",),     # consumer's inline decode finishes
    }

    def __init__(self, pool: "DecodePool", stream: "DecodeStream",
                 fn: Callable, data, cost: int):
        self._pool = pool
        self._stream = stream
        self._fn = fn
        self._data = data
        self.cost = cost
        self.nbytes = cost
        self._state = _QUEUED  # state: decode.ticket guarded-by: DecodePool._cv
        self._held = 0
        self._tkt = NOOP_TICKET  # this ticket's held-credit reservation
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self._abandoned = False

    def __len__(self) -> int:
        return self.nbytes

    def get(self):
        """Block until decoded; returns the decode_fn result, re-raises
        its error.  A ticket whose decode has not been admitted yet is
        decoded INLINE here (the steal that makes the credit budget
        deadlock-free); a ticket already decoded when the consumer
        arrives is a decode-ahead hit."""
        pool = self._pool
        with pool._cv:
            if self._state == _QUEUED:
                self._transition(_STOLEN, frm=_QUEUED)
                pool._cv.notify_all()  # unblock a worker credit-waiting on it
                steal = True
            else:
                steal = False
        if steal:
            pool._m_steals.inc()
            if RECORDER.enabled:
                fr_event("decode", "ticket_steal", bytes=self.nbytes)
            self._run_inline()
        elif self._event.is_set():
            pool._m_ahead_hits.inc()
        self._event.wait()
        with pool._cv:
            self._settle_locked()
        self._fn = self._data = None
        if self._error is not None:
            raise self._error
        result, self._result = self._result, None
        return result

    def _run_inline(self) -> None:
        t0 = time.monotonic()
        try:
            self._result = self._fn(self._data)
        except BaseException as e:
            self._error = e
        self._pool._observe(self.nbytes, time.monotonic() - t0)
        with self._pool._cv:
            self._transition(_DONE, frm=_STOLEN)
        self._event.set()

    def discard(self) -> None:
        """Drop a ticket nobody will consume (a sibling fragment of a
        split block already failed): queued work cancels WITHOUT being
        decoded, finished work releases its credits, in-flight decodes
        release on completion — never burns task-thread CPU the way a
        steal-decode would."""
        pool = self._pool
        with pool._cv:
            if self._state == _QUEUED:
                self._transition(_CANCELLED, frm=_QUEUED)
                self._error = RuntimeError("decode ticket discarded")
                self._settle_locked()
                self._event.set()
            elif self._state in (_DONE, _CANCELLED):
                self._settle_locked()
            else:  # decoding right now: the worker settles it
                self._abandoned = True

    def _settle_locked(self) -> None:
        """Release held credits and drop the stream's reference —
        idempotent, caller holds the pool condition."""
        if self._held:
            self._pool._ledger.put(self._stream._tenant, self._held)
            self._held = 0
            tkt, self._tkt = self._tkt, NOOP_TICKET
            tkt.release()  # releases: decode.credit_bytes  # one-shot
            self._pool._cv.notify_all()
        self._stream._tickets.discard(self)


class _CompositeTicket:
    """A block split at frame boundaries: sub-tickets decode in
    parallel, ``get`` reassembles their results in frame order so
    per-block framing is preserved exactly — by concatenation, or by
    the stream's ``combine_fn`` when fragment results need a real
    merge (the per-fragment sort of a block holding SEVERAL sorted
    runs, e.g. concatenated spill chunks: fragment-wise stable sorts
    concatenate to a non-sorted sequence, but stable-merged in
    fragment order they equal the stable sort of the whole block)."""

    __slots__ = ("_parts", "nbytes", "_combine")

    def __init__(self, parts: List[DecodeTicket], nbytes: int,
                 combine_fn=None):
        self._parts = parts
        self.nbytes = nbytes
        self._combine = combine_fn

    def __len__(self) -> int:
        return self.nbytes

    def get(self):
        results: list = []
        err: Optional[BaseException] = None
        for part in self._parts:
            if err is not None:
                # a sibling already failed: discard instead of get() —
                # undecoded fragments cancel rather than steal-decode
                part.discard()
                continue
            try:
                results.append(part.get())
            except BaseException as e:
                err = e
        if err is not None:
            raise err
        if self._combine is not None:
            return self._combine(results)
        items: list = []
        records = 0
        for got, n in results:
            items.extend(got)
            records += n
        return items, records


class DecodeStream(StateMachine):
    """Per-reader handle onto the shared pool.  ``decode_fn(data)``
    must return ``(items, record_count)`` for one self-contained
    payload; ``split_fn(data)`` (optional — the serializer's
    ``frame_spans``) yields the frame boundaries used to fan one large
    block out across workers."""

    MACHINE = "decode.stream"
    STATES = ("open", "closed")
    INITIAL = "open"
    TERMINAL = ("closed",)
    TRANSITIONS = {"open": ("closed",)}

    def __init__(self, pool: "DecodePool", decode_fn: Callable,
                 split_fn: Optional[Callable] = None,
                 combine_fn: Optional[Callable] = None,
                 tenant=None):
        self._pool = pool
        self._decode_fn = decode_fn
        self._split_fn = split_fn
        self._combine_fn = combine_fn
        # qos/: the reader's tenant — credit admission runs through
        # the pool's weighted ledger under it (None = plain credits)
        self._tenant = tenant
        self._tickets: set = set()  # guarded-by: (pool) _cv
        self._state = "open"  # state: decode.stream guarded-by: DecodePool._cv

    def submit(self, data, cost: Optional[int] = None) -> DecodeTicket:
        """Enqueue one payload for decode; never blocks (transport
        completion callbacks post here)."""
        n = len(data) if cost is None else cost
        t = DecodeTicket(self._pool, self, self._decode_fn, data, n)
        pool = self._pool
        with pool._cv:
            if self._state == "closed" or pool._stopped:
                t._transition(_CANCELLED, frm=_QUEUED)
                t._error = RuntimeError("decode stream closed")
                t._event.set()
                return t
            self._tickets.add(t)
            pool._m_depth.inc()
            pool._queue.put(t)
        return t

    def submit_block(self, data):
        """Submit one block, splitting at the serializer's frame
        boundaries when it is large enough to be worth fanning out."""
        n = len(data)
        if (self._split_fn is None or n < _SPLIT_MIN_BYTES
                or self._pool.workers <= 1):
            return self.submit(data, n)
        try:
            spans = self._split_fn(data)
        except Exception:
            # undecodable framing surfaces through the normal decode
            # path (one ticket) so the error reaches the consumer
            return self.submit(data, n)
        groups = _group_spans(spans, _SPLIT_CHUNK_BYTES)
        if len(groups) <= 1:
            return self.submit(data, n)
        view = as_view(data)
        counter("shuffle_decode_block_splits_total").inc()
        parts = [
            self.submit(view[a:b], b - a) for a, b in groups
        ]
        return _CompositeTicket(parts, n, self._combine_fn)

    def close(self) -> None:
        """Poison the stream: queued decodes cancel, finished ones
        release their credits, in-flight ones release on completion.
        Idempotent; safe from any thread (the reader's cleanup path
        calls it on success, fetch failure AND abandoned iteration)."""
        pool = self._pool
        with pool._cv:
            if self._state == "closed":
                return
            self._transition("closed", frm="open")
            for t in list(self._tickets):
                if t._state == _QUEUED:
                    t._transition(_CANCELLED, frm=_QUEUED)
                    t._error = RuntimeError("decode stream closed")
                    t._event.set()
                t._settle_locked()
            self._tickets.clear()
            pool._cv.notify_all()


class DecodePool:
    """Bounded decode pool shared by every reader of one manager (the
    ``_ServePool`` shape): fixed workers, FIFO task queue, byte-credit
    admission."""

    def __init__(self, name: str, workers: int, credit_bytes: int,
                 init_fn=None, qos=None):
        self.workers = max(1, int(workers))
        self._budget = max(int(credit_bytes), 1)
        # credit policy core (qos/): weighted max-min per-tenant when
        # a registry is attached, a plain budget counter otherwise —
        # all access under _cv
        # resource: decode.credit_bytes (held decode-ahead credits)
        self._ledger = CreditLedger("decode", self._budget, qos=qos)
        # tenants currently credit-waiting (name → (tenant, waiters)):
        # the ledger's reclaim-on-demand needs to see deprived waiters
        self._waiting: Dict[str, tuple] = {}  # guarded-by: _cv
        self._cv = dbg_condition("decode.credits", 51)
        self._queue: "queue.Queue" = queue.Queue()
        self._stopped = False  # guarded-by: _cv
        self._m_depth = gauge("shuffle_decode_queue_depth")
        self._m_tasks = counter("shuffle_decode_tasks_total")
        self._m_us = counter("shuffle_decode_us_total")
        self._m_bytes = counter("shuffle_decode_bytes_total")
        self._m_credit_waits = counter("shuffle_decode_credit_waits_total")
        self._m_ahead_hits = counter("shuffle_decode_ahead_hits_total")
        self._m_steals = counter("shuffle_decode_steals_total")
        self._threads = [
            threading.Thread(
                target=self._run, daemon=True,
                name=f"decode-{name}-{i}", args=(init_fn,),
            )
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()

    @property
    def _credits(self) -> int:
        """Free credit bytes (the pre-ledger attribute, kept for tests
        and debugging; the condition's lock is reentrant)."""
        with self._cv:
            return self._ledger.free

    def stream(self, decode_fn: Callable,
               split_fn: Optional[Callable] = None,
               combine_fn: Optional[Callable] = None,
               tenant=None) -> DecodeStream:
        return DecodeStream(self, decode_fn, split_fn, combine_fn,
                            tenant=tenant)

    def _waiting_view(self) -> Dict:
        """name → Tenant of currently credit-waiting tenants (cv
        held) — the ledger's deprived-waiter input."""
        w = self._waiting  # noqa: CK03 - caller holds _cv
        return {k: t for k, (t, _n) in w.items()}

    def _waiting_add(self, tenant) -> None:
        t, n = self._waiting.get(tenant.name, (tenant, 0))  # noqa: CK03 - held
        self._waiting[tenant.name] = (t, n + 1)  # noqa: CK03 - held

    def _waiting_remove(self, tenant) -> None:
        t, n = self._waiting.get(tenant.name, (tenant, 1))  # noqa: CK03 - held
        if n <= 1:
            self._waiting.pop(tenant.name, None)  # noqa: CK03 - caller holds _cv
        else:
            self._waiting[tenant.name] = (t, n - 1)  # noqa: CK03 - caller holds _cv

    def _observe(self, nbytes: int, seconds: float) -> None:
        self._m_tasks.inc()
        self._m_bytes.inc(nbytes)
        self._m_us.inc(int(seconds * 1e6))

    def _run(self, init_fn) -> None:
        if init_fn is not None:
            init_fn()
        while True:
            item = self._queue.get()
            if item is None:
                return
            with self._cv:
                self._m_depth.dec()
                if item._state != _QUEUED:
                    continue  # stolen by the consumer, or cancelled
                cost = min(item.cost, self._budget)
                tenant = item._stream._tenant
                waited = False
                while (not self._ledger.can_take(
                            tenant, cost, self._waiting_view())
                       and not self._stopped
                       and item._state == _QUEUED
                       and item._stream._state == "open"):
                    if not waited:
                        waited = True
                        self._m_credit_waits.inc()
                        if RECORDER.enabled:
                            fr_event(
                                "decode", "credit_wait", bytes=cost,
                            )
                        if tenant is not None:
                            self._waiting_add(tenant)
                    self._cv.wait(timeout=0.5)
                if waited and tenant is not None:
                    self._waiting_remove(tenant)
                if item._state != _QUEUED:
                    continue  # stolen mid-wait: the consumer owns it now
                if self._stopped or item._stream._state == "closed":
                    item._transition(_CANCELLED, frm=_QUEUED)
                    item._error = RuntimeError("decode stream closed")
                    item._settle_locked()
                    item._event.set()
                    continue
                self._ledger.take(tenant, cost)
                item._held = cost
                # held until the consumer settles the ticket (get /
                # discard / stream close / worker completion-after-
                # abandon all funnel through _settle_locked)
                # owns: decode.credit_bytes -> _settle_locked
                item._tkt = ledger_acquire(
                    "decode.credit_bytes", cost
                )  # acquires: decode.credit_bytes
                item._transition(_DECODING, frm=_QUEUED)
            t0 = time.monotonic()
            try:
                if FAULTS.enabled:
                    # models a poisoned payload: surfaces through the
                    # ticket's error slot like any decode_fn raise
                    FAULTS.check("decode")
                item._result = item._fn(item._data)
            except BaseException as e:
                item._error = e
            dt = time.monotonic() - t0
            self._observe(item.nbytes, dt)
            if RECORDER.enabled:
                fr_event(
                    "decode", "decode_done",
                    bytes=item.nbytes, us=int(dt * 1e6),
                    err=1 if item._error is not None else 0,
                )
            with self._cv:
                item._transition(_DONE, frm=_DECODING)
                if item._stream._state == "closed" or item._abandoned:
                    # consumer is gone: nobody will get() — release now
                    item._settle_locked()
            item._event.set()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        # cancel abandoned queued tickets and keep the depth gauge
        # honest, then send one sentinel per worker
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            with self._cv:
                self._m_depth.dec()
                if item._state == _QUEUED:
                    item._transition(_CANCELLED, frm=_QUEUED)
                    item._error = RuntimeError("decode pool stopped")
                    item._settle_locked()
                    item._event.set()
        for _ in self._threads:
            self._queue.put(None)
        for t in self._threads:
            t.join(timeout=2.0)


def _group_spans(spans: List[Tuple[int, int]],
                 min_bytes: int) -> List[Tuple[int, int]]:
    """Coalesce adjacent frame spans into contiguous groups of at least
    ``min_bytes`` (the framing is concatenation-safe, so any contiguous
    group decodes independently)."""
    groups: List[Tuple[int, int]] = []
    start = None
    end = 0
    for a, b in spans:
        if start is None:
            start, end = a, b
        else:
            end = b
        if end - start >= min_bytes:
            groups.append((start, end))
            start = None
    if start is not None:
        groups.append((start, end))
    return groups


def open_decode_stream(manager, handle, columnar: bool):
    """Build a reader's decode stream from the manager's pool — or
    ``None`` when ``decodeThreads=0`` (the serial fallback).  The
    decode_fn bakes in the reader's record plane AND the per-block
    transform that parallelizes the read-side sort/combine:

    - tuple plane, ``key_ordering`` with no aggregator: each block's
      records sort once inside the worker (the per-block sorted runs
      the streaming k-way merge consumes),
    - columnar plane, same shape: unsorted batches stable-sort per
      block in the worker (map-side-sorted batches pass through),
    - columnar reducing aggregator: each batch pre-combines in the
      worker (``combine_columns`` is what postprocess would run per
      block anyway — same association, bit-exact result, now parallel).

    Returns ``(items, record_count)`` per payload with record_count
    taken BEFORE any combining, so ``records_read`` matches the serial
    path exactly.
    """
    pool = manager.get_decode_pool()
    if pool is None:
        return None
    tenant = manager.qos_tenant_for(handle)
    serializer = manager.serializer
    agg = handle.aggregator
    split_fn = getattr(serializer, "frame_spans", None)
    if columnar:
        deser = serializer.deserialize_columns
        kind = getattr(agg, "kind", None)
        presort = handle.key_ordering and agg is None
        if kind is not None and kind != "group":
            from sparkrdma_tpu.utils.columns import combine_columns

            def decode_fn(data, _d=deser, _k=kind):
                batches = list(_d(data))
                n = sum(len(b) for b in batches)
                return [combine_columns(b, _k) for b in batches], n
        elif presort:
            from sparkrdma_tpu.utils.columns import sort_batch

            def decode_fn(data, _d=deser):
                batches = list(_d(data))
                n = sum(len(b) for b in batches)
                return [
                    b if b.key_sorted else sort_batch(b) for b in batches
                ], n
        else:
            def decode_fn(data, _d=deser):
                batches = list(_d(data))
                return batches, sum(len(b) for b in batches)
    else:
        deser = serializer.deserialize
        if handle.key_ordering and agg is None:
            import heapq

            def decode_fn(data, _d=deser):
                recs = list(_d(data))
                recs.sort(key=lambda kv: kv[0])
                return recs, len(recs)

            def combine_fn(results):
                # fragments of a SPLIT block sorted independently: a
                # concat is NOT sorted when the block held several
                # sorted runs (spilled map outputs) — stable-merge the
                # fragment results so the composite equals the stable
                # sort of the whole block, which is what the reader's
                # presorted k-way merge downstream relies on
                merged = list(heapq.merge(
                    *[items for items, _n in results],
                    key=lambda kv: kv[0],
                ))
                return merged, sum(n for _i, n in results)

            return pool.stream(decode_fn, split_fn, combine_fn,
                               tenant=tenant)

        def decode_fn(data, _d=deser):
            recs = list(_d(data))
            return recs, len(recs)
    return pool.stream(decode_fn, split_fn, tenant=tenant)


def iter_decoded_ahead(stream: DecodeStream, payloads: Iterator,
                       ahead_bytes: int) -> Iterator:
    """Pull-driven decode-ahead over an iterator of raw payloads (the
    local-block and windowed-plane shape, where the task thread itself
    produces the payloads): submit up to ``ahead_bytes`` of payloads
    before consuming the first ticket, then keep the window full.
    Yields tickets in submission order — the caller's ``get()`` order
    is its consumption order, exactly like the push-driven remote
    path."""
    from collections import deque

    pending: "deque" = deque()
    ahead = 0
    for data in payloads:
        n = len(data)
        while pending and ahead + n > ahead_bytes:
            t = pending.popleft()
            ahead -= len(t)
            yield t
        pending.append(stream.submit_block(data))
        ahead += n
    while pending:
        yield pending.popleft()
