"""Host-side partitioners for the record path.

The reference reuses Spark's ``dependency.partitioner``
(RdmaWrapperShuffleWriter.scala:126-128); these are the standalone
equivalents.  The device path uses sparkrdma_tpu.ops.partition instead.
"""

from __future__ import annotations

import bisect
import pickle
import struct
import zlib
from typing import Any, List, Sequence

import numpy as np

_MASK64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """SplitMix64 finalizer — the scalar twin of
    :func:`_splitmix64_array`; the two MUST agree bit for bit so the
    tuple record plane and the columnar plane route any given key to
    the same partition."""
    z = (x + 0x9E3779B97F4A7C15) & _MASK64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
    return z ^ (z >> 31)


def _splitmix64_array(x: np.ndarray) -> np.ndarray:
    # in-place with one scratch buffer: the naive expression allocates
    # six N-element temporaries and the allocator cost shows at 1M+ keys
    z = x.astype(np.uint64)  # the one copy (also detaches caller's data)
    t = np.empty_like(z)
    z += np.uint64(0x9E3779B97F4A7C15)
    np.right_shift(z, np.uint64(30), out=t)
    z ^= t
    z *= np.uint64(0xBF58476D1CE4E5B9)
    np.right_shift(z, np.uint64(27), out=t)
    z ^= t
    z *= np.uint64(0x94D049BB133111EB)
    np.right_shift(z, np.uint64(31), out=t)
    z ^= t
    return z


def stable_hash(key: Any) -> int:
    """Process-stable hash: Python's builtin ``hash`` is salted per
    interpreter (PYTHONHASHSEED), so map tasks in different executor
    processes would disagree on key → partition.  64-bit-range ints and
    floats use SplitMix64 over their bit patterns (vectorizable — the
    columnar plane computes the identical value with numpy); other
    primitives hash a canonical byte encoding; everything else a
    fixed-protocol pickle."""
    if isinstance(key, bool):  # bool before int: True/1 must collide as in dicts
        key = int(key)
    if isinstance(key, (int, np.integer)):
        key = int(key)
        if -(1 << 63) <= key < (1 << 64):
            return _splitmix64(key & _MASK64)
        data = key.to_bytes(
            max(1, (key.bit_length() + 8) // 8), "little", signed=True
        )
    elif isinstance(key, (float, np.floating)):
        (bits,) = struct.unpack("<Q", struct.pack("<d", float(key)))
        return _splitmix64(bits)
    elif isinstance(key, str):
        data = key.encode("utf-8")
    elif isinstance(key, (bytes, bytearray)):
        data = bytes(key)
    elif isinstance(key, tuple):
        data = b"".join(
            (stable_hash(k) & 0xFFFFFFFF).to_bytes(4, "little") for k in key
        )
    else:
        data = pickle.dumps(key, protocol=4)
    return zlib.crc32(data)


def stable_hash_array(keys: np.ndarray) -> np.ndarray:
    """Vectorized :func:`stable_hash` over a numeric column — exact
    elementwise match with the scalar function (the cross-plane
    consistency contract).  Non-numeric dtypes fall back to a scalar
    loop over the extracted Python values."""
    keys = np.asarray(keys)
    if keys.dtype == np.bool_:
        keys = keys.astype(np.int64)
    if np.issubdtype(keys.dtype, np.integer):
        # two's-complement bit pattern, matching `key & _MASK64`
        bits = keys.astype(np.int64, copy=False).view(np.uint64) \
            if np.issubdtype(keys.dtype, np.signedinteger) \
            else keys.astype(np.uint64, copy=False)
        return _splitmix64_array(bits)
    if np.issubdtype(keys.dtype, np.floating):
        bits = keys.astype(np.float64, copy=False).view(np.uint64)
        return _splitmix64_array(bits)
    return np.fromiter(
        (stable_hash(k) for k in keys.tolist()),
        dtype=np.uint64, count=len(keys),
    )


class Partitioner:
    num_partitions: int

    def partition(self, key: Any) -> int:  # pragma: no cover - interface
        raise NotImplementedError

    def partition_array(self, keys: np.ndarray) -> np.ndarray:
        """Vectorized partition over a key column (columnar plane).
        MUST agree elementwise with :meth:`partition` — a shuffle whose
        map tasks mix the tuple and columnar planes still routes every
        key to one reducer.  Base fallback: scalar loop."""
        return np.fromiter(
            (self.partition(k) for k in np.asarray(keys).tolist()),
            dtype=np.int32, count=len(keys),
        )


class HashPartitioner(Partitioner):
    def __init__(self, num_partitions: int):
        if num_partitions <= 0:
            raise ValueError(f"num_partitions must be > 0: {num_partitions}")
        self.num_partitions = num_partitions

    def partition(self, key: Any) -> int:
        return stable_hash(key) % self.num_partitions

    def partition_array(self, keys: np.ndarray) -> np.ndarray:
        return (
            stable_hash_array(keys) % np.uint64(self.num_partitions)
        ).astype(np.int32)


class RangePartitioner(Partitioner):
    """Equal-frequency range partitioner from a key sample (sortByKey)."""

    def __init__(self, num_partitions: int, sample: Sequence[Any]):
        if num_partitions <= 0:
            raise ValueError(f"num_partitions must be > 0: {num_partitions}")
        self.num_partitions = num_partitions
        s = sorted(sample)
        if not s:
            self.splitters: List[Any] = []
        else:
            self.splitters = [
                s[min(len(s) - 1, (i * len(s)) // num_partitions)]
                for i in range(1, num_partitions)
            ]

    def partition(self, key: Any) -> int:
        return bisect.bisect_right(self.splitters, key)

    def partition_array(self, keys: np.ndarray) -> np.ndarray:
        if not self.splitters:
            return np.zeros(len(keys), np.int32)
        try:
            splitters = np.asarray(self.splitters)
        except Exception:
            return super().partition_array(keys)
        if splitters.dtype.hasobject:
            return super().partition_array(keys)
        # bisect_right == searchsorted side='right', elementwise
        return np.searchsorted(splitters, keys, side="right").astype(np.int32)
