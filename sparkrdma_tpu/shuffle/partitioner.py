"""Host-side partitioners for the record path.

The reference reuses Spark's ``dependency.partitioner``
(RdmaWrapperShuffleWriter.scala:126-128); these are the standalone
equivalents.  The device path uses sparkrdma_tpu.ops.partition instead.
"""

from __future__ import annotations

import bisect
import pickle
import zlib
from typing import Any, List, Sequence


def stable_hash(key: Any) -> int:
    """Process-stable hash: Python's builtin ``hash`` is salted per
    interpreter (PYTHONHASHSEED), so map tasks in different executor
    processes would disagree on key → partition.  Primitives hash via a
    canonical byte encoding; everything else via a fixed-protocol pickle."""
    if isinstance(key, bool):  # bool before int: True/1 must collide as in dicts
        key = int(key)
    if isinstance(key, int):
        data = key.to_bytes(
            max(1, (key.bit_length() + 8) // 8), "little", signed=True
        )
    elif isinstance(key, str):
        data = key.encode("utf-8")
    elif isinstance(key, (bytes, bytearray)):
        data = bytes(key)
    elif isinstance(key, float):
        import struct as _s

        data = _s.pack("<d", key)
    elif isinstance(key, tuple):
        data = b"".join(stable_hash(k).to_bytes(4, "little") for k in key)
    else:
        data = pickle.dumps(key, protocol=4)
    return zlib.crc32(data)


class Partitioner:
    num_partitions: int

    def partition(self, key: Any) -> int:  # pragma: no cover - interface
        raise NotImplementedError


class HashPartitioner(Partitioner):
    def __init__(self, num_partitions: int):
        if num_partitions <= 0:
            raise ValueError(f"num_partitions must be > 0: {num_partitions}")
        self.num_partitions = num_partitions

    def partition(self, key: Any) -> int:
        return stable_hash(key) % self.num_partitions


class RangePartitioner(Partitioner):
    """Equal-frequency range partitioner from a key sample (sortByKey)."""

    def __init__(self, num_partitions: int, sample: Sequence[Any]):
        if num_partitions <= 0:
            raise ValueError(f"num_partitions must be > 0: {num_partitions}")
        self.num_partitions = num_partitions
        s = sorted(sample)
        if not s:
            self.splitters: List[Any] = []
        else:
            self.splitters = [
                s[min(len(s) - 1, (i * len(s)) // num_partitions)]
                for i in range(1, num_partitions)
            ]

    def partition(self, key: Any) -> int:
        return bisect.bisect_right(self.splitters, key)
