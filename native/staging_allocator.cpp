// Host staging allocator: size-class pooled, page-aligned buffers.
//
// Native analog of the reference's RdmaBufferManager
// (RdmaBufferManager.java:35-209): power-of-two size-class stacks of
// reusable buffers (min class 16 KiB), a global allocation budget, and
// idle-pool trimming — when idle bytes exceed 90% of the budget the pool
// frees least-recently-used stacks down to 65% (the cleanLRUStacks
// policy, RdmaBufferManager.java:150-188).
//
// These buffers stage serialized shuffle partitions on their way to HBM
// (the role registered MRs play for the NIC in the reference): they are
// page-aligned so dlpack/numpy views and DMA engines see friendly
// addresses.
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in this image).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <new>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint64_t kMinBlockSize = 16 * 1024;       // min size class
constexpr uint64_t kAlignment = 4096;               // page alignment
constexpr double kTrimTriggerFrac = 0.90;           // idle > 90% -> trim
constexpr double kTrimTargetFrac = 0.65;            // free down to 65%

uint64_t round_up_class(uint64_t n) {
  uint64_t c = kMinBlockSize;
  while (c < n) c <<= 1;
  return c;
}

struct SizeClassStack {
  std::vector<void*> free_list;
  uint64_t block_size = 0;
  uint64_t total_blocks = 0;     // blocks ever created and still owned
  uint64_t total_allocs = 0;     // user allocs served (stats)
  uint64_t last_use_tick = 0;    // LRU stamp
};

struct Pool {
  std::mutex mu;
  std::map<uint64_t, SizeClassStack> stacks;     // by block size
  std::unordered_map<void*, uint64_t> block_class;  // ptr -> block size
  uint64_t max_bytes = 0;        // allocation budget (0 = unlimited)
  uint64_t owned_bytes = 0;      // all blocks owned (free + in use)
  uint64_t in_use_bytes = 0;     // handed out to callers
  uint64_t tick = 0;             // monotonic op counter for LRU
  std::atomic<uint64_t> failed_allocs{0};
};

void* raw_alloc(uint64_t size) {
  void* p = nullptr;
  if (posix_memalign(&p, kAlignment, size) != 0) return nullptr;
  return p;
}

// Frees whole idle stacks, least-recently-used first, until idle bytes
// fall to `target_idle`.
void trim_locked(Pool* pool, uint64_t target_idle) {
  // collect (last_use_tick, block_size) for stacks with idle blocks
  std::vector<std::pair<uint64_t, uint64_t>> order;
  for (auto& [size, st] : pool->stacks)
    if (!st.free_list.empty()) order.emplace_back(st.last_use_tick, size);
  std::sort(order.begin(), order.end());
  uint64_t idle = pool->owned_bytes - pool->in_use_bytes;
  for (auto& [tick, size] : order) {
    if (idle <= target_idle) break;
    auto& st = pool->stacks[size];
    for (void* p : st.free_list) {
      pool->block_class.erase(p);
      free(p);
      pool->owned_bytes -= size;
      st.total_blocks--;
      idle -= size;
    }
    st.free_list.clear();
  }
}

}  // namespace

extern "C" {

void* staging_pool_create(uint64_t max_bytes) {
  auto* pool = new (std::nothrow) Pool();
  if (pool) pool->max_bytes = max_bytes;
  return pool;
}

void staging_pool_destroy(void* handle) {
  auto* pool = static_cast<Pool*>(handle);
  if (!pool) return;
  {
    std::lock_guard<std::mutex> lock(pool->mu);
    for (auto& [ptr, size] : pool->block_class) free(ptr);
    pool->block_class.clear();
    pool->stacks.clear();
  }
  delete pool;
}

// Returns an aligned buffer of at least `size` bytes (rounded up to a
// power-of-two class, min 16 KiB), or null if the budget is exhausted.
void* staging_alloc(void* handle, uint64_t size) {
  auto* pool = static_cast<Pool*>(handle);
  if (!pool || size == 0) return nullptr;
  uint64_t cls = round_up_class(size);
  std::lock_guard<std::mutex> lock(pool->mu);
  pool->tick++;
  auto& st = pool->stacks[cls];
  st.block_size = cls;
  st.last_use_tick = pool->tick;
  st.total_allocs++;
  if (!st.free_list.empty()) {
    void* p = st.free_list.back();
    st.free_list.pop_back();
    pool->in_use_bytes += cls;
    return p;
  }
  if (pool->max_bytes && pool->owned_bytes + cls > pool->max_bytes) {
    // over budget: try trimming idle blocks first
    trim_locked(pool, 0);
    if (pool->owned_bytes + cls > pool->max_bytes) {
      pool->failed_allocs++;
      return nullptr;
    }
  }
  void* p = raw_alloc(cls);
  if (!p) {
    pool->failed_allocs++;
    return nullptr;
  }
  pool->block_class[p] = cls;
  pool->owned_bytes += cls;
  pool->in_use_bytes += cls;
  st.total_blocks++;
  return p;
}

// Returns a buffer to its size-class stack; trims LRU stacks if idle
// bytes exceed the trigger fraction of the budget.
int staging_free(void* handle, void* ptr) {
  auto* pool = static_cast<Pool*>(handle);
  if (!pool || !ptr) return -1;
  std::lock_guard<std::mutex> lock(pool->mu);
  auto it = pool->block_class.find(ptr);
  if (it == pool->block_class.end()) return -1;  // double free / foreign ptr
  uint64_t cls = it->second;
  pool->tick++;
  auto& st = pool->stacks[cls];
  st.free_list.push_back(ptr);
  st.last_use_tick = pool->tick;
  pool->in_use_bytes -= cls;
  if (pool->max_bytes) {
    uint64_t idle = pool->owned_bytes - pool->in_use_bytes;
    if (idle > static_cast<uint64_t>(kTrimTriggerFrac * pool->max_bytes)) {
      trim_locked(pool,
                  static_cast<uint64_t>(kTrimTargetFrac * pool->max_bytes));
    }
  }
  return 0;
}

uint64_t staging_block_size(void* handle, void* ptr) {
  auto* pool = static_cast<Pool*>(handle);
  if (!pool || !ptr) return 0;
  std::lock_guard<std::mutex> lock(pool->mu);
  auto it = pool->block_class.find(ptr);
  return it == pool->block_class.end() ? 0 : it->second;
}

// stats[0]=owned, [1]=in_use, [2]=idle, [3]=num_classes, [4]=failed_allocs,
// [5]=total_allocs
void staging_pool_stats(void* handle, uint64_t* stats) {
  auto* pool = static_cast<Pool*>(handle);
  if (!pool || !stats) return;
  std::lock_guard<std::mutex> lock(pool->mu);
  uint64_t total_allocs = 0;
  for (auto& [size, st] : pool->stacks) total_allocs += st.total_allocs;
  stats[0] = pool->owned_bytes;
  stats[1] = pool->in_use_bytes;
  stats[2] = pool->owned_bytes - pool->in_use_bytes;
  stats[3] = pool->stacks.size();
  stats[4] = pool->failed_allocs.load();
  stats[5] = total_allocs;
}

// Force-trim idle blocks down to `target_idle_bytes`.
void staging_pool_trim(void* handle, uint64_t target_idle_bytes) {
  auto* pool = static_cast<Pool*>(handle);
  if (!pool) return;
  std::lock_guard<std::mutex> lock(pool->mu);
  trim_locked(pool, target_idle_bytes);
}

}  // extern "C"

// Row gather with software prefetch: dst[i] = src[idx[i]] for `row`-byte
// rows.  The record plane's hottest kernel (random 64-byte payload
// gathers are cache-miss bound).  Prefetch with L2 residency (locality
// hint 1): the non-temporal hint (0) evicts lines before the ~32-row
// pipeline distance catches up and measured 1.8x SLOWER on the 1M x
// 64B shape (19.1 ms vs 10.6; hint sweep in BASELINE.md round 4).
// Non-temporal stores also lose here (23.7 ms) — the destination is
// sequential and write-combines fine through the cache.  Specialized
// small-row cases let the compiler inline the copy.
// one tuning site for both the specialized and generic paths
// (locality: 0=NT, 1=L2, 3=L1 — see the hint-sweep note above)
static constexpr uint64_t GATHER_PF = 32;
#define GATHER_PF_HINT 1

template <uint64_t ROW>
static void row_gather_fixed(const uint8_t* src, uint8_t* dst,
                             const int64_t* idx, uint64_t n) {
  constexpr uint64_t PF = GATHER_PF;
  for (uint64_t i = 0; i < n; i++) {
    if (i + PF < n)
      __builtin_prefetch(src + static_cast<uint64_t>(idx[i + PF]) * ROW, 0,
                         GATHER_PF_HINT);
    memcpy(dst + i * ROW, src + static_cast<uint64_t>(idx[i]) * ROW, ROW);
  }
}

extern "C" void row_gather(const uint8_t* src, uint8_t* dst,
                           const int64_t* idx, uint64_t n, uint64_t row) {
  const uint64_t PF = GATHER_PF;
  switch (row) {
    case 8:  row_gather_fixed<8>(src, dst, idx, n); return;
    case 16: row_gather_fixed<16>(src, dst, idx, n); return;
    case 32: row_gather_fixed<32>(src, dst, idx, n); return;
    case 64: row_gather_fixed<64>(src, dst, idx, n); return;
    default:
      for (uint64_t i = 0; i < n; i++) {
        if (i + PF < n)
          __builtin_prefetch(
              src + static_cast<uint64_t>(idx[i + PF]) * row, 0,
              GATHER_PF_HINT);
        memcpy(dst + i * row, src + static_cast<uint64_t>(idx[i]) * row, row);
      }
  }
}

// Fused map-side partition pass for integer keys under a SplitMix64
// hash partitioner: ONE kernel computes pid = splitmix64(key) % P, the
// composite rank comp = pid * krange + (key - kmin), its histogram,
// per-pid counts, and the stable pid-major key-ascending order via a
// counting sort — replacing a numpy pipeline of ~6 full-column passes
// plus a radix argsort (the record plane's second-biggest cost after
// the row gather).  Caller guarantees P * krange <= 65536 so comp fits
// uint16 and the histogram stays cache-resident.
static inline uint64_t splitmix64_one(uint64_t z) {
  // bit-exact twin of partitioner._splitmix64 / _splitmix64_array
  z += 0x9E3779B97F4A7C15ULL;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

extern "C" int hash_partition_order(
    const int64_t* keys, uint64_t n, uint64_t P,
    int64_t kmin, uint64_t krange,
    int64_t* counts_out,   // [P] records per partition
    int64_t* order_out) {  // [n] stable pid-major, key-asc within pid
  const uint64_t buckets = P * krange;
  if (buckets == 0 || buckets > 65536) return -1;
  uint16_t* comp = static_cast<uint16_t*>(malloc(n * sizeof(uint16_t)));
  if (!comp && n) return -2;
  uint64_t* hist =
      static_cast<uint64_t*>(calloc(buckets + 1, sizeof(uint64_t)));
  if (!hist) {
    free(comp);
    return -2;
  }
  for (uint64_t i = 0; i < n; i++) {
    uint64_t pid = splitmix64_one(static_cast<uint64_t>(keys[i])) % P;
    uint64_t c = pid * krange + static_cast<uint64_t>(keys[i] - kmin);
    if (c >= buckets) {  // stale kmin/krange: error, not heap smash
      free(hist);
      free(comp);
      return -3;
    }
    comp[i] = static_cast<uint16_t>(c);
    hist[c + 1]++;
  }
  for (uint64_t p = 0; p < P; p++) {
    int64_t cnt = 0;
    for (uint64_t k = 0; k < krange; k++)
      cnt += static_cast<int64_t>(hist[p * krange + k + 1]);
    counts_out[p] = cnt;
  }
  for (uint64_t b = 1; b <= buckets; b++) hist[b] += hist[b - 1];
  for (uint64_t i = 0; i < n; i++)
    order_out[hist[comp[i]]++] = static_cast<int64_t>(i);
  free(hist);
  free(comp);
  return 0;
}

// Stable LSD radix argsort for int64 keys (order-preserving unsigned
// transform), 4 x 16-bit digit passes with constant-digit passes
// skipped — numpy's stable argsort falls back to timsort (~86 ms/M)
// for int64 columns whose range exceeds the uint16 rebase, and the
// key-sort is the record plane's cost ceiling for wide-range keys
// (the TeraSort shape).  Scratch persists per thread so repeated maps
// reuse warm pages.
static thread_local uint64_t* rs_keys[2] = {nullptr, nullptr};
static thread_local int64_t* rs_idx[2] = {nullptr, nullptr};
static thread_local uint64_t rs_cap = 0;
// retention cap: scratch above this (32 B/row -> 64 MiB) is freed after
// the sort so a pool of writer threads cannot pin hundreds of MB for
// the process lifetime; smaller batches keep warm pages
static constexpr uint64_t RS_RETAIN_ROWS = 1ULL << 21;

static void rs_free_scratch() {
  for (int b = 0; b < 2; b++) {
    free(rs_keys[b]);
    free(rs_idx[b]);
    rs_keys[b] = nullptr;
    rs_idx[b] = nullptr;
  }
  rs_cap = 0;
}

// explicit per-thread trim hook (callers that know they are done
// sorting can release even sub-threshold scratch)
extern "C" void radix_scratch_trim() { rs_free_scratch(); }

extern "C" int radix_argsort_i64(const int64_t* keys, uint64_t n,
                                 int64_t* order_out) {
  if (n == 0) return 0;
  if (n > rs_cap) {
    uint64_t cap = rs_cap ? rs_cap : 4096;
    while (cap < n) cap *= 2;
    for (int b = 0; b < 2; b++) {
      free(rs_keys[b]);
      free(rs_idx[b]);
      rs_keys[b] = static_cast<uint64_t*>(malloc(cap * 8));
      rs_idx[b] = static_cast<int64_t*>(malloc(cap * 8));
      if (!rs_keys[b] || !rs_idx[b]) {
        rs_free_scratch();
        return -2;
      }
    }
    rs_cap = cap;
  }
  // all 8 byte-digit histograms in ONE pass over the keys (8-bit
  // digits beat 16-bit here: 256 write streams stay cache/TLB
  // resident during the scatter — measured 54ms vs 74ms per 1M)
  static thread_local uint64_t hist[8][256];
  memset(hist, 0, sizeof(hist));
  constexpr uint64_t SIGN = 0x8000000000000000ULL;
  for (uint64_t i = 0; i < n; i++) {
    uint64_t k = static_cast<uint64_t>(keys[i]) ^ SIGN;
    rs_keys[0][i] = k;
    rs_idx[0][i] = static_cast<int64_t>(i);
    for (int d = 0; d < 8; d++) hist[d][(k >> (8 * d)) & 0xFF]++;
  }
  int cur = 0;
  for (int pass = 0; pass < 8; pass++) {
    uint64_t* h = hist[pass];
    const int shift = 8 * pass;
    if (h[(rs_keys[cur][0] >> shift) & 0xFF] == n) continue;
    uint64_t sum = 0;
    for (uint32_t b = 0; b < 256; b++) {
      uint64_t c = h[b];
      h[b] = sum;
      sum += c;
    }
    const uint64_t* sk = rs_keys[cur];
    const int64_t* si = rs_idx[cur];
    uint64_t* dk = rs_keys[cur ^ 1];
    int64_t* di = rs_idx[cur ^ 1];
    for (uint64_t i = 0; i < n; i++) {
      uint64_t pos = h[(sk[i] >> shift) & 0xFF]++;
      dk[pos] = sk[i];
      di[pos] = si[i];
    }
    cur ^= 1;
  }
  memcpy(order_out, rs_idx[cur], n * 8);
  if (rs_cap > RS_RETAIN_ROWS) rs_free_scratch();
  return 0;
}

// Stable k-way merge of pre-sorted int64 runs via a loser tree —
// the read side's sorted-run combine (key-sorted shuffle blocks merge
// in K log K comparisons per element instead of a full re-sort; for
// K=8 that is 3 compares/row vs the radix sort's 8 digit passes).
// keys = concatenation of the runs; run r occupies
// [run_offsets[r], run_offsets[r+1]).  order_out receives the gather
// order such that keys[order_out] is sorted; ties emit lower-run
// (= lower concat position) first, bit-exact with numpy's stable
// argsort over the concatenation.
extern "C" int kway_merge_i64(const int64_t* keys,
                              const int64_t* run_offsets,
                              uint64_t n_runs, int64_t* order_out) {
  if (n_runs == 0) return 0;
  const int64_t n_total = run_offsets[n_runs];
  if (n_total == 0) return 0;
  if (n_runs == 1) {
    for (int64_t i = 0; i < n_total; i++) order_out[i] = i;
    return 0;
  }
  // leaves: current position per run; the loser tree holds run ids,
  // winner bubbles to the top.  K is padded to a power of two.
  uint64_t k = 1;
  while (k < n_runs) k <<= 1;
  std::vector<int64_t> pos(n_runs);
  for (uint64_t r = 0; r < n_runs; r++) pos[r] = run_offsets[r];
  // head key per (padded) run; exhausted runs sort last via a flag
  auto exhausted = [&](uint64_t r) {
    return r >= n_runs || pos[r] >= run_offsets[r + 1];
  };
  // less(a, b): does run a's head precede run b's head?
  auto less = [&](uint64_t a, uint64_t b) {
    const bool ea = exhausted(a), eb = exhausted(b);
    if (ea != eb) return eb;
    if (ea) return a < b;
    const int64_t ka = keys[pos[a]], kb = keys[pos[b]];
    if (ka != kb) return ka < kb;
    return a < b;  // tie: lower run = lower concat position (stable)
  };
  // tree[1..k-1] hold LOSERS; winner kept separately
  std::vector<uint64_t> tree(k, UINT64_MAX);
  // initialize by playing all leaves upward
  std::vector<uint64_t> winners(2 * k);
  for (uint64_t r = 0; r < k; r++) winners[k + r] = r;
  for (uint64_t i = k - 1; i >= 1; i--) {
    uint64_t a = winners[2 * i], b = winners[2 * i + 1];
    if (less(a, b)) {
      winners[i] = a;
      tree[i] = b;
    } else {
      winners[i] = b;
      tree[i] = a;
    }
  }
  uint64_t winner = winners[1];
  for (int64_t out = 0; out < n_total; out++) {
    order_out[out] = pos[winner]++;
    // replay from the winner's leaf to the root
    uint64_t node = (k + winner) >> 1;
    while (node >= 1) {
      if (less(tree[node], winner)) {
        std::swap(tree[node], winner);
      }
      node >>= 1;
    }
  }
  return 0;
}

// Fused group-by-key merge over key-sorted runs: the read side's
// groupByKey combine for blocks committed key-sorted (each map task's
// block for a partition is one run).  Replaces the per-key Python
// dict + np.concatenate loop (which re-copies every value byte through
// small allocations) with ONE streaming pass: for each distinct key,
// each run's contiguous slice of that key is memcpy'd in run order —
// sequential reads, sequential writes, |runs| big copies per key
// instead of one small allocation per key.  Output values for a key
// are run-0's rows then run-1's ... (bit-exact with the Python merge's
// batch order).  Returns the number of groups g; out_keys[0..g),
// out_offs[0..g] hold the group keys and value-row offsets
// (out_offs[g] = total rows).
extern "C" int64_t merge_runs_groups_i64(
    const int64_t* const* run_keys, const uint8_t* const* run_vals,
    const int64_t* run_len, uint64_t n_runs, uint64_t row,
    uint8_t* out_vals, int64_t* out_keys, int64_t* out_offs) {
  std::vector<int64_t> pos(n_runs, 0);
  int64_t g = 0;
  int64_t written = 0;
  for (;;) {
    bool any = false;
    int64_t k = 0;
    for (uint64_t r = 0; r < n_runs; r++) {
      if (pos[r] < run_len[r]) {
        const int64_t h = run_keys[r][pos[r]];
        if (!any || h < k) {
          k = h;
          any = true;
        }
      }
    }
    if (!any) break;
    out_keys[g] = k;
    out_offs[g] = written;
    for (uint64_t r = 0; r < n_runs; r++) {
      const int64_t len = run_len[r];
      int64_t p = pos[r];
      if (p >= len || run_keys[r][p] != k) continue;
      int64_t e = p + 1;
      const int64_t* kk = run_keys[r];
      while (e < len && kk[e] == k) e++;
      memcpy(out_vals + static_cast<uint64_t>(written) * row,
             run_vals[r] + static_cast<uint64_t>(p) * row,
             static_cast<uint64_t>(e - p) * row);
      written += e - p;
      pos[r] = e;
    }
    g++;
  }
  out_offs[g] = written;
  return g;
}

// Cardinality-aware rank compression for wide-RANGE, LOW-CARDINALITY
// int64 key columns (the groupByKey shape: few thousand distinct keys
// scattered over the full int64 space).  The LSD radix argsort pays
// all four 16-bit digit passes on such columns; compressing each key
// to its dense SORTED rank (uint16) lets the caller ride numpy's
// uint16 radix argsort instead — same stable order at ~1/3 the cost.
// One open-addressing pass collects distincts (aborting past 65536),
// the sorted distincts give rank order, a second pass emits ranks.
// Returns the distinct count, or -1 when cardinality exceeds 65536
// (caller falls back to the full radix argsort).
extern "C" int64_t rank_compress_i64(const int64_t* keys, uint64_t n,
                                     uint16_t* ranks_out) {
  constexpr uint64_t CAP = 1ULL << 18;  // 4x max load for 65536 keys
  constexpr uint64_t MASK = CAP - 1;
  constexpr int64_t EMPTY = INT64_MIN;
  // EMPTY sentinel means INT64_MIN needs a side slot
  std::vector<int64_t> slots(CAP, EMPTY);
  bool has_min = false;
  uint64_t distinct = 0;
  for (uint64_t i = 0; i < n; i++) {
    const int64_t k = keys[i];
    if (k == EMPTY) {
      if (!has_min) {
        has_min = true;
        if (++distinct > 65536) return -1;
      }
      continue;
    }
    uint64_t h = splitmix64_one(static_cast<uint64_t>(k)) & MASK;
    for (;;) {
      const int64_t s = slots[h];
      if (s == k) break;
      if (s == EMPTY) {
        slots[h] = k;
        if (++distinct > 65536) return -1;
        break;
      }
      h = (h + 1) & MASK;
    }
  }
  // sorted distincts -> rank; reuse the table to store ranks via a
  // parallel array (rank lookup must stay O(1) for the emit pass)
  std::vector<int64_t> uniq;
  uniq.reserve(distinct);
  if (has_min) uniq.push_back(EMPTY);
  for (uint64_t h = 0; h < CAP; h++)
    if (slots[h] != EMPTY) uniq.push_back(slots[h]);
  std::sort(uniq.begin(), uniq.end());
  std::vector<uint16_t> rank_of(CAP, 0);
  uint16_t min_rank = 0;  // INT64_MIN sorts first when present
  for (uint64_t r = 0; r < uniq.size(); r++) {
    const int64_t k = uniq[r];
    if (k == EMPTY) {
      min_rank = static_cast<uint16_t>(r);  // r is always 0 here
      continue;
    }
    uint64_t h = splitmix64_one(static_cast<uint64_t>(k)) & MASK;
    while (slots[h] != k) h = (h + 1) & MASK;
    rank_of[h] = static_cast<uint16_t>(r);
  }
  for (uint64_t i = 0; i < n; i++) {
    const int64_t k = keys[i];
    if (k == EMPTY) {
      ranks_out[i] = min_rank;
      continue;
    }
    uint64_t h = splitmix64_one(static_cast<uint64_t>(k)) & MASK;
    while (slots[h] != k) h = (h + 1) & MASK;
    ranks_out[i] = rank_of[h];
  }
  return static_cast<int64_t>(distinct);
}

// ---------------------------------------------------------------------------
// Hot-loop relief kernels: frame walking, batched checksums, block
// gather.  The serde frame walkers, the exchange-row block gather and
// the per-frame CRC loops all iterated per-frame in PYTHON (one
// unpack_from + compare + append, or one numpy slice assignment, per
// frame/block) — interpreter overhead that scales with frame count,
// not byte count, and holds the GIL the whole walk.  Each kernel below
// replaces one such loop with a single C call over the whole payload.

// Length-prefixed frame walk: a frame is `prefix` opaque header bytes
// (0 for the pickle serializer's bare batches, 1 for the codec-tag
// byte of the compressed framing) + 4B little-endian body length +
// body.  Writes (start, end) pairs into spans_out.  Returns the span
// count, -1 on a truncated header/body (caller re-walks in Python for
// the detailed error message), -2 when max_spans is too small (caller
// grows and retries).  Little-endian hosts only (every deployment
// target; the Python walker is the portable path).
extern "C" int64_t frame_spans_lp(const uint8_t* buf, uint64_t total,
                                  uint64_t prefix, int64_t* spans_out,
                                  uint64_t max_spans) {
  const uint64_t hdr = prefix + 4;
  uint64_t off = 0, n_spans = 0;
  while (off < total) {
    if (off + hdr > total) return -1;
    uint32_t n;
    memcpy(&n, buf + off + prefix, 4);
    const uint64_t end = off + hdr + n;
    if (end > total) return -1;
    if (n_spans == max_spans) return -2;
    spans_out[2 * n_spans] = static_cast<int64_t>(off);
    spans_out[2 * n_spans + 1] = static_cast<int64_t>(end);
    n_spans++;
    off = end;
  }
  return static_cast<int64_t>(n_spans);
}

// numpy dtype-string itemsize for the fixed-width codes the columnar
// plane uses ("<i8", "|u1", "<f4", "S5", ...).  Anything fancier
// (unicode 'U' scales by 4, datetimes carry a unit suffix) answers 0
// and the caller falls back to np.dtype in Python.
static inline uint64_t dtype_itemsize(const uint8_t* s, uint64_t len) {
  uint64_t i = 0;
  if (i < len && (s[i] == '<' || s[i] == '>' || s[i] == '=' || s[i] == '|'))
    i++;
  if (i >= len) return 0;
  const uint8_t code = s[i++];
  if (code != 'b' && code != 'i' && code != 'u' && code != 'f' &&
      code != 'c' && code != 'S' && code != 'V')
    return 0;
  if (i >= len) return 0;
  uint64_t v = 0;
  for (; i < len; i++) {
    if (s[i] < '0' || s[i] > '9') return 0;
    v = v * 10 + (s[i] - '0');
    if (v > (1u << 20)) return 0;
  }
  return v;
}

// Columnar frame walk (serde.ColumnarSerializer framing): 0xC2 frames
// are magic | flags | key-dtype | val-dtype | 4B count | columns;
// 0xC3 frames are the pickle fallback (magic + 4B len + body).
// Returns the span count, -1 on truncation, -2 when max_spans is too
// small, -3 on a dtype string this side won't parse, -4 on a bad
// magic — every negative answer sends the caller back to the Python
// walker (which raises the detailed error or handles the dtype).
extern "C" int64_t columnar_frame_spans(const uint8_t* buf, uint64_t total,
                                        int64_t* spans_out,
                                        uint64_t max_spans) {
  uint64_t off = 0, n_spans = 0;
  while (off < total) {
    const uint64_t start = off;
    uint64_t end;
    if (buf[off] == 0xC3) {
      if (off + 5 > total) return -1;
      uint32_t n;
      memcpy(&n, buf + off + 1, 4);
      end = off + 5 + n;
    } else if (buf[off] == 0xC2) {
      uint64_t p = off + 2;  // magic + flags
      if (p + 1 > total) return -1;
      const uint64_t nk = buf[p];
      p += 1;
      if (p + nk + 1 > total) return -1;
      const uint64_t ksz = dtype_itemsize(buf + p, nk);
      p += nk;
      const uint64_t nv = buf[p];
      p += 1;
      if (p + nv + 4 > total) return -1;
      const uint64_t vsz = dtype_itemsize(buf + p, nv);
      p += nv;
      if (!ksz || !vsz) return -3;
      uint32_t count;
      memcpy(&count, buf + p, 4);
      p += 4;
      end = p + static_cast<uint64_t>(count) * (ksz + vsz);
    } else {
      return -4;
    }
    if (end > total) return -1;
    if (n_spans == max_spans) return -2;
    spans_out[2 * n_spans] = static_cast<int64_t>(start);
    spans_out[2 * n_spans + 1] = static_cast<int64_t>(end);
    n_spans++;
    off = end;
  }
  return static_cast<int64_t>(n_spans);
}

// Slice-by-8 CRC32 (the zlib polynomial, bit-exact with zlib.crc32):
// one table init at load, then 8 bytes per table round.  The win over
// per-span zlib.crc32 calls is the BATCH — one C call checksums every
// frame of a block, instead of one Python call (argument packing,
// buffer-protocol negotiation) per frame.
static uint32_t crc_tab[8][256];
static void crc_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_tab[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int t = 1; t < 8; t++)
      crc_tab[t][i] =
          crc_tab[0][crc_tab[t - 1][i] & 0xFF] ^ (crc_tab[t - 1][i] >> 8);
}
namespace {
struct CrcInitGuard {
  CrcInitGuard() { crc_init(); }
} crc_init_guard;
}  // namespace

static uint32_t crc32_one(const uint8_t* p, uint64_t len, uint32_t crc) {
  crc = ~crc;
  while (len && (reinterpret_cast<uintptr_t>(p) & 7)) {
    crc = crc_tab[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    len--;
  }
  while (len >= 8) {
    uint32_t lo, hi;
    memcpy(&lo, p, 4);
    memcpy(&hi, p + 4, 4);
    lo ^= crc;
    crc = crc_tab[7][lo & 0xFF] ^ crc_tab[6][(lo >> 8) & 0xFF] ^
          crc_tab[5][(lo >> 16) & 0xFF] ^ crc_tab[4][lo >> 24] ^
          crc_tab[3][hi & 0xFF] ^ crc_tab[2][(hi >> 8) & 0xFF] ^
          crc_tab[1][(hi >> 16) & 0xFF] ^ crc_tab[0][hi >> 24];
    p += 8;
    len -= 8;
  }
  while (len--) crc = crc_tab[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// out[i] = crc32(buf[spans[2i] : spans[2i+1]]) for every span.  The
// caller bounds-checks the spans against the buffer (the kernel
// trusts them).
extern "C" void crc32_spans(const uint8_t* buf, const int64_t* spans,
                            uint64_t n_spans, uint32_t* out) {
  for (uint64_t i = 0; i < n_spans; i++) {
    const int64_t a = spans[2 * i], b = spans[2 * i + 1];
    out[i] = crc32_one(buf + a, static_cast<uint64_t>(b - a), 0);
  }
}

// Batched block gather: dst[dst_offs[i] : dst_offs[i]+lens[i]] =
// src_ptrs[i] — one C call assembles a whole exchange source row
// instead of one numpy slice assignment per map-output block (the
// bulk._assemble hot loop; slice assignment costs ~1 us of
// dispatch per block regardless of size).  The caller pins the
// source arrays for the duration and pre-validates every span
// against the destination row.  Returns total bytes copied.
extern "C" int64_t gather_blocks(const uint64_t* src_ptrs,
                                 const int64_t* lens, uint8_t* dst,
                                 const int64_t* dst_offs, uint64_t n) {
  int64_t copied = 0;
  for (uint64_t i = 0; i < n; i++) {
    memcpy(dst + dst_offs[i],
           reinterpret_cast<const void*>(
               static_cast<uintptr_t>(src_ptrs[i])),
           static_cast<size_t>(lens[i]));
    copied += lens[i];
  }
  return copied;
}
