# Build/test entry points (the pom.xml analog).

.PHONY: all native test bench dryrun clean

all: native

native:
	$(MAKE) -C native

test: native
	python -m pytest tests/ -x -q

bench: native
	python bench.py

dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	python -c "import jax; jax.config.update('jax_platforms','cpu'); \
	import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun OK')"

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
