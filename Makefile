# Build/test entry points (the pom.xml analog).

.PHONY: all native lint concheck flowcheck wirecheck statecheck test bench bench-smoke bench-cluster bench-device chaos chaos-shake dryrun clean

all: native

native:
	$(MAKE) -C native

# style gate failing the build — the checkstyle/scalastyle analog
# (reference pom.xml:93-141 runs both at validate, failsOnError=true)
# — plus the concurrency lock-discipline gate (tools/concheck.py),
# the resource-lifecycle gate (tools/flowcheck.py), the wire-protocol
# conformance gate (tools/wirecheck.py) and the lifecycle
# state-machine gate (tools/statecheck.py)
lint:
	python tools/lint.py
	python tools/concheck.py
	python tools/flowcheck.py
	python tools/wirecheck.py
	python tools/statecheck.py

# the concurrency gate alone: lock-order cycles/rank inversions (CK01),
# blocking-under-lock (CK02), guarded-by discipline (CK03), unranked
# locks (CK04) across sparkrdma_tpu/
concheck:
	python tools/concheck.py

# the resource-lifecycle gate alone: acquire-without-release (FC01),
# double-release (FC02), release-without-acquire (FC03), undeclared
# resources (FC04) across sparkrdma_tpu/
flowcheck:
	python tools/flowcheck.py

# the wire-protocol gate alone: pack/unpack asymmetry (WC01), MSG_TYPE
# registry integrity (WC02), opcode/handler parity (WC03), magic sizes
# (WC04), bounds discipline (WC05) across the wire surface
wirecheck:
	python tools/wirecheck.py

# the lifecycle state-machine gate alone: raw state writes (SC01),
# undeclared transitions (SC02), unguarded branch reads (SC03),
# terminal escapes (SC04), annotated-but-undeclared machines (SC05)
# across the ~13 declared machines
statecheck:
	python tools/statecheck.py

test: native lint
	python -m pytest tests/ -x -q

bench: native
	python bench.py

# tier-2 sanity gate: the reduce-loopback bench (record plane, striped
# fetch, decode pipeline) plus the out-of-core tier sweep, in tiny
# configs — same code paths, seconds not minutes, JSON written to /tmp
# so committed results stay intact
bench-smoke:
	BENCH_SMOKE=1 SPARKRDMA_TPU_BENCH_SPOOFED=1 JAX_PLATFORMS=cpu \
	python benchmarks/bench_reduce_loopback.py
	BENCH_SMOKE=1 SPARKRDMA_TPU_BENCH_SPOOFED=1 JAX_PLATFORMS=cpu \
	python benchmarks/bench_terasort.py --out-of-core
	BENCH_SMOKE=1 SPARKRDMA_TPU_BENCH_SPOOFED=1 JAX_PLATFORMS=cpu \
	python benchmarks/bench_qos.py
	BENCH_SMOKE=1 SPARKRDMA_TPU_BENCH_SPOOFED=1 JAX_PLATFORMS=cpu \
	python benchmarks/bench_skew.py
	BENCH_SMOKE=1 SPARKRDMA_TPU_BENCH_SPOOFED=1 JAX_PLATFORMS=cpu \
	python benchmarks/bench_cluster.py
	BENCH_SMOKE=1 SPARKRDMA_TPU_BENCH_SPOOFED=1 JAX_PLATFORMS=cpu \
	python benchmarks/bench_push.py
	BENCH_SMOKE=1 SPARKRDMA_TPU_BENCH_SPOOFED=1 JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	python benchmarks/bench_device_exchange.py
	python tools/bench_gate.py
	$(MAKE) chaos
	$(MAKE) chaos-shake

# the multi-process cluster tier alone (real executor processes over
# TCP + the native hot-path kernel microbench); full config writes
# BENCH_cluster.json at the repo root
bench-cluster: native
	JAX_PLATFORMS=cpu python benchmarks/bench_cluster.py

# the device-native exchange tier alone (padded collective plane,
# bucketized headline, end-to-end loopback clusters) on a spoofed
# ≥2-device CPU mesh; full config writes BENCH_device_exchange.json
# at the repo root
bench-device:
	BENCH_SMOKE=1 SPARKRDMA_TPU_BENCH_SPOOFED=1 JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	python benchmarks/bench_device_exchange.py

# the seeded chaos soak alone (faults/, conf faultInject): the full
# engine matrix — loopback / tcp-threaded / tcp-async × decode
# threads × skew — under a mixed fault spec with resourceDebug +
# lockDebug on; every run must be bit-exact or a clean
# FetchFailedError with zero leaks and zero rank violations
chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/test_faults.py -q \
	-p no:cacheprovider -k chaos

# the chaos soak + push drills under the deterministic schedule shaker
# (conf schedShake, utils/statemachine.py): every validated state
# transition injects a seeded 0-2ms yield to widen race windows, with
# stateDebug + lockDebug + resourceDebug all on — zero illegal
# transitions, zero leaks, zero rank violations required
chaos-shake:
	SCHED_SHAKE=20260807 JAX_PLATFORMS=cpu python -m pytest \
	tests/test_faults.py -q -p no:cacheprovider -k chaos
	SCHED_SHAKE=20260807 JAX_PLATFORMS=cpu python -m pytest \
	tests/test_push.py -q -p no:cacheprovider

dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	python -c "import jax; jax.config.update('jax_platforms','cpu'); \
	import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun OK')"

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
