#!/usr/bin/env python
"""Concurrency lock-discipline analyzer — the deadlock/race gate.

``make lint`` runs this next to tools/lint.py.  The library's threaded
planes (per-peer stripe lanes, the bounded serve pool, double-buffered
window assembly, heartbeat + dispatcher threads) hang off ~40
Lock/RLock/Condition sites; this pass discovers every one of them in
``sparkrdma_tpu/`` plus every ``with <lock>:`` region, and enforces:

  CK01  lock-order violation: the nested-acquisition graph (built from
        syntactic nesting AND one class's self-call closure) must be
        acyclic and must agree with the declared ``# lock-order`` ranks
        — an inner acquisition's rank must be strictly greater than
        every held rank.  Nested re-acquisition of a non-reentrant
        ``Lock`` is a guaranteed deadlock and flags immediately.
  CK02  blocking while locked: socket ``sendall``/``sendmsg``/``recv``/
        ``recv_into``/``accept``/``connect``, ``Thread.join``,
        ``Event.wait``, ``queue.Queue.get`` (not ``get_nowait``),
        ``subprocess.*``, a disk-read entry point of the tiered block
        store's cold tier (``pread``/``preadv``/``ensure_mapped``/
        ``_disk_read``/``_load_row`` — memory/tier.py: a cold read
        hiding under a lock serializes every hot hit behind the disk),
        or a ``Condition.wait`` on anything but the innermost held
        lock, inside a held ``with`` region — directly or through a
        same-class method call.  Deliberate cases carry a code-scoped
        ``# noqa: CK02`` with a justification comment.
  CK03  unguarded shared state: an attribute declared
        ``self._x = ...  # guarded-by: _lock`` may only be read or
        written inside a ``with <owner>._lock:`` region (or in
        ``__init__``, before the object escapes its creating thread).
  CK04  undeclared lock: every lock attribute must carry a rank — a
        ``# lock-order: N`` comment on its creation line, or the rank
        argument of a ``dbg_lock``/``dbg_rlock``/``dbg_condition`` call
        (utils/dbglock.py validates the same ranks at runtime).
  CK05  blocking on the event loop: a method marked ``# on-loop`` (it
        runs on the async transport dispatcher's single event-loop
        thread, transport/dispatcher.py) must never take a blocking
        action — ``sendall``/``connect``/``create_connection``,
        ``Thread.join``, ``Event.wait``, ``Condition.wait``,
        ``queue.get`` (not ``get_nowait``), ``subprocess.*`` or
        ``time.sleep`` — directly or through a same-class method call
        (CK02's blocking analysis re-aimed at the loop's callback
        plane).  Non-blocking socket data ops
        (``recv``/``recv_into``/``sendmsg``/``accept``) are the loop's
        job and stay allowed.

Annotation grammar::

    self._lock = threading.Lock()  # lock-order: 42
    self._lock = dbg_lock("node.active", 42)        # rank from the call
    self._cache = {}  # guarded-by: _lock
    def on_readable(self):  # on-loop

Suppressions are code-scoped: ``# noqa: CK02`` silences only CK02 on
that line; a bare ``# noqa`` silences everything (discouraged).

Usage: ``python tools/concheck.py [paths...]`` (default: the library).
Exit status 1 on any finding.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

ROOT = pathlib.Path(__file__).resolve().parent.parent
LIB = ROOT / "sparkrdma_tpu"

SOCKET_BLOCKING = {"sendall", "sendmsg", "recv", "recv_into", "accept",
                   "connect", "create_connection"}
# the tiered block store's disk-read entry points (memory/tier.py /
# memory/mapped_file.py): cold-tier reads must never run under a lock —
# a promotion's pread hiding inside a locked region would serialize
# every concurrent hot hit behind the disk
DISK_BLOCKING = {"pread", "preadv", "ensure_mapped", "_disk_read",
                 "_load_row"}

ONLOOP_RE = re.compile(r"#\s*on-loop\b")

# op tags CK02 never flags (sleep-under-lock predates the tagging;
# waiting on one's OWN condition releases it — not a CK02 hold)
CK02_EXCLUDED_OPS = {"sleep", "cond-wait-self"}
# op tags that block an event loop no matter what is held; the
# non-blocking-capable socket data ops (recv/recv_into/sendmsg/accept)
# are exactly what on-loop code exists to call
CK05_OPS = {"sendall", "connect", "create_connection", "subprocess",
            "join", "queue-get", "event-wait", "cond-wait",
            "cond-wait-self", "sleep"}

# the shared gate plumbing (noqa grammar, finding shape, file walking,
# lock declaration + guard resolution) lives in tools/gatelib.py; the
# historical local names are bound here so the analysis passes and the
# gate's tests read unchanged
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
from gatelib import (  # noqa: E402
    ClassInfo,
    Finding,
    Held as _Held,
    LockDecl,
    LockId,
    ModuleInfo,
    Suppressor as _Suppressor,
    collect_module as _collect_module,
    ctor_of as _ctor_of,
    lock_ctor as _lock_ctor,
    resolve_lock as _resolve_lock_expr,
    walk_py as _walk_py,
)


class _FnScan(ast.NodeVisitor):
    """Scan one function body with a held-lock stack.  Nested function
    and lambda bodies run on other threads/later — they are queued and
    scanned as fresh contexts, never under the enclosing holds."""

    def __init__(self, analyzer: "Analyzer", mod: ModuleInfo,
                 cls: Optional[ClassInfo], fn_name: str):
        self.an = analyzer
        self.mod = mod
        self.cls = cls
        self.fn_name = fn_name
        self.held: List[_Held] = []
        self.on_loop = False
        self.direct_locks: Set[LockId] = set()
        # (line, message, op-tag) — op routes CK02 vs CK05 emission
        self.direct_blocking: List[Tuple[int, str, str]] = []
        self.self_calls: List[Tuple[str, int, Tuple[LockId, ...]]] = []
        self.local_locks: Set[str] = set()
        self.local_events: Set[str] = set()
        self.local_queues: Set[str] = set()
        self.local_threads: Set[str] = set()
        self.nested: List[ast.AST] = []

    # -- resolution ---------------------------------------------------------
    def _resolve_lock(self, expr: ast.expr):
        """(key, decl-or-None) for a with-item that looks like a lock;
        None when it is not lock-shaped at all (gatelib.resolve_lock)."""
        return _resolve_lock_expr(self.mod, self.cls, self.local_locks,
                                  expr)

    # -- traversal ----------------------------------------------------------
    def visit_ClassDef(self, node):
        # nested classes are scanned separately under their OWN
        # ClassInfo by _scan_functions' walk — descending here would
        # scan their methods under the wrong class
        pass

    def visit_FunctionDef(self, node):
        self.nested.append(node)

    def visit_AsyncFunctionDef(self, node):
        self.nested.append(node)

    def visit_Lambda(self, node):
        self.nested.append(node)

    def visit_Assign(self, node):
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if _lock_ctor(node.value) is not None:
                self.local_locks.add(name)
            elif _ctor_of(node.value, "threading", {"Event"}):
                self.local_events.add(name)
            elif _ctor_of(node.value, "queue", {"Queue", "SimpleQueue"}):
                self.local_queues.add(name)
            elif _ctor_of(node.value, "threading", {"Thread", "Timer"}):
                self.local_threads.add(name)
        self.generic_visit(node)

    def visit_With(self, node):
        self._with(node)

    def visit_AsyncWith(self, node):
        self._with(node)

    def _with(self, node):
        pushed = 0
        for item in node.items:
            # the context expression itself is evaluated unlocked-first
            self.visit(item.context_expr)
            r = self._resolve_lock(item.context_expr)
            if r is None:
                continue
            key, decl = r
            lock_id = decl.lock_id if decl is not None else None
            if lock_id is not None:
                self.direct_locks.add(lock_id)
                already = next(
                    (h for h in self.held if h.lock_id == lock_id), None
                )
                if already is not None:
                    if decl.kind == "Lock":
                        self.an.emit(
                            self.mod.rel, item.context_expr.lineno,
                            "CK01",
                            f"nested acquisition of non-reentrant lock "
                            f"{decl.name} (held since line "
                            f"{already.line}) — guaranteed deadlock",
                        )
                else:
                    for h in self.held:
                        if h.lock_id is not None:
                            self.an.add_edge(
                                h.lock_id, lock_id, self.mod.rel,
                                item.context_expr.lineno,
                            )
            self.held.append(_Held(
                key, lock_id, decl.kind if decl else None,
                item.context_expr.lineno,
            ))
            pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def visit_Call(self, node):
        # classify blocking calls unconditionally: direct_blocking
        # feeds the caller-side closure check even when THIS function
        # holds no lock; emit CK02 only when one is held here
        self._check_blocking(node)
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self" and self.cls is not None \
                and f.attr in self.cls.methods:
            self.self_calls.append((
                f.attr, node.lineno,
                tuple(h.lock_id for h in self.held
                      if h.lock_id is not None),
            ))
            if self.held:
                self.an.held_self_calls.append((
                    self.mod.rel, self.cls.name, f.attr, node.lineno,
                    tuple(h.lock_id for h in self.held
                          if h.lock_id is not None),
                ))
        self.generic_visit(node)

    def _innermost(self) -> Optional[_Held]:
        return self.held[-1] if self.held else None

    def _check_blocking(self, node: ast.Call) -> None:
        f = node.func
        line = node.lineno
        hold = self._innermost()
        holder = (
            f"{'.'.join(k for k in hold.key if k)}"
            if hold else "no lock"
        )
        if isinstance(f, ast.Attribute):
            attr = f.attr
            recv_name = f.value.id if isinstance(f.value, ast.Name) \
                else None
            recv_attr = (
                f.value.attr if isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "self" else None
            )
            if attr in SOCKET_BLOCKING and not isinstance(
                    f.value, ast.Constant):
                self._blocking(
                    line,
                    f"blocking socket call .{attr}() while holding "
                    f"{holder}",
                    attr,
                )
                return
            if attr in DISK_BLOCKING and not isinstance(
                    f.value, ast.Constant):
                self._blocking(
                    line,
                    f"cold-tier disk read .{attr}() while holding "
                    f"{holder} (every hot hit would queue behind the "
                    f"disk — resolve residency under the lock, read "
                    f"outside it)",
                    attr,
                )
                return
            if attr == "sleep" and recv_name == "time":
                self._blocking(
                    line, f"time.sleep while holding {holder}", "sleep"
                )
                return
            if recv_name == "subprocess" or (
                isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "subprocess"
            ):
                self._blocking(
                    line, f"subprocess call while holding {holder}",
                    "subprocess",
                )
                return
            target = recv_attr if recv_attr is not None else recv_name
            if target is None:
                return
            cls = self.cls
            is_self_attr = recv_attr is not None
            if attr == "join":
                threads = (cls.threads if cls and is_self_attr
                           else self.local_threads)
                if target in threads:
                    self._blocking(
                        line,
                        f"Thread.join on {target} while holding {holder}",
                        "join",
                    )
            elif attr == "get":
                queues = (cls.queues if cls and is_self_attr
                          else self.local_queues)
                if target in queues:
                    self._blocking(
                        line,
                        f"queue.get() on {target} while holding "
                        f"{holder} (use get_nowait or move it outside "
                        f"the lock)",
                        "queue-get",
                    )
            elif attr == "wait":
                events = (cls.events if cls and is_self_attr
                          else self.local_events)
                if target in events:
                    self._blocking(
                        line,
                        f"Event.wait on {target} while holding {holder}",
                        "event-wait",
                    )
                    return
                if cls and is_self_attr and target in cls.locks \
                        and cls.locks[target].kind == "Condition":
                    others = [h for h in self.held
                              if h.key[1] != target]
                    if others:
                        held_names = ", ".join(
                            ".".join(k for k in h.key if k)
                            for h in others
                        )
                        self._blocking(
                            line,
                            f"Condition.wait on {target} while also "
                            f"holding {held_names} — waiting releases "
                            f"only {target}, everything else stays "
                            f"held",
                            "cond-wait",
                        )
                    else:
                        # waiting on one's own condition is fine under
                        # a lock (it releases) but still parks the
                        # thread — poison for on-loop code (CK05)
                        self._blocking(
                            line,
                            f"Condition.wait on {target} while holding "
                            f"{holder}",
                            "cond-wait-self",
                        )

    def _blocking(self, line: int, msg: str, op: str) -> None:
        self.direct_blocking.append((line, msg, op))
        if self.held and op not in CK02_EXCLUDED_OPS:
            self.an.emit(self.mod.rel, line, "CK02", msg)

    def visit_Attribute(self, node):
        # CK03: guarded attribute access
        if self.cls is not None and isinstance(node.value, ast.Name) \
                and node.attr in self.cls.guarded \
                and self.fn_name != "__init__":
            recv = node.value.id
            required, _decl_line = self.cls.guarded[node.attr]
            ok = any(h.key == (recv, required) for h in self.held)
            if not ok:
                self.an.emit(
                    self.mod.rel, node.lineno, "CK03",
                    f"access to {recv}.{node.attr} outside "
                    f"'with {recv}.{required}:' (declared guarded-by "
                    f"{required})",
                )
        self.generic_visit(node)


class Analyzer:
    def __init__(self, root: pathlib.Path = ROOT):
        self.root = root
        self.findings: List[Finding] = []
        self.modules: Dict[str, ModuleInfo] = {}
        self.decls: Dict[LockId, LockDecl] = {}
        # edges: (outer, inner) -> first (rel, line) site
        self.edges: Dict[Tuple[LockId, LockId], Tuple[str, int]] = {}
        self.held_self_calls: List[Tuple] = []
        # (module, class-or-"", function) -> scan result
        self.fn_scans: Dict[Tuple[str, str, str], _FnScan] = {}
        # transitive blocking sets, filled by _closure_checks
        self._blocking_map: Dict[Tuple[str, str, str], List] = {}
        self._sups: Dict[str, _Suppressor] = {}

    def emit(self, rel: str, line: int, code: str, msg: str) -> None:
        sup = self._sups.get(rel)
        if sup is not None and sup.suppressed(line, code):
            return
        self.findings.append((rel, line, code, msg))

    def add_edge(self, outer: LockId, inner: LockId, rel: str,
                 line: int) -> None:
        self.edges.setdefault((outer, inner), (rel, line))

    # -- entry points --------------------------------------------------------
    def analyze_paths(self, paths) -> List[Finding]:
        files = _walk_py(paths)
        for f in files:
            self._load(f)
        for f in files:
            self._scan_functions(f)
        self._closure_checks()
        self._onloop_checks()
        self._graph_checks()
        self.findings.sort(key=lambda x: (str(x[0]), x[1], x[2]))
        return self.findings

    def _rel(self, path: pathlib.Path) -> str:
        try:
            return str(path.relative_to(self.root))
        except ValueError:
            return str(path)

    def _load(self, path: pathlib.Path) -> None:
        rel = self._rel(path)
        try:
            text = path.read_text()
            tree = ast.parse(text)
        except (UnicodeDecodeError, SyntaxError):
            return  # tools/lint.py owns PY01
        lines = text.splitlines()
        sup = self._sups[rel] = _Suppressor(lines)
        mod = _collect_module(rel, tree, lines, self.findings, sup)
        self.modules[rel] = mod
        for cls in mod.classes.values():
            for decl in cls.locks.values():
                self.decls[decl.lock_id] = decl
            for attr, (guard, line) in cls.guarded.items():
                if guard not in cls.locks and guard not in mod.locks:
                    self.emit(
                        rel, line, "CK03",
                        f"{cls.name}.{attr} declares guarded-by "
                        f"{guard}, but {guard} is not a lock of "
                        f"{cls.name}",
                    )
        for decl in mod.locks.values():
            self.decls[decl.lock_id] = decl

    def _scan_functions(self, path: pathlib.Path) -> None:
        rel = self._rel(path)
        mod = self.modules.get(rel)
        if mod is None:
            return
        tree = mod.tree
        # module-level functions
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_fn(mod, None, stmt.name, stmt)
        # EVERY class — top-level, class-in-class, class-in-function —
        # scans its methods under its own ClassInfo (matching the
        # ast.walk collection pass; _FnScan skips inner ClassDefs so
        # nothing is scanned twice or under the wrong class)
        for stmt in ast.walk(tree):
            if isinstance(stmt, ast.ClassDef):
                cls = mod.classes.get(stmt.name)
                if cls is None:
                    continue
                for item in stmt.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._scan_fn(mod, cls, item.name, item)

    @staticmethod
    def _fn_on_loop(mod: ModuleInfo, node) -> bool:
        """True when the def-line span (signature lines, up to the
        first body statement) carries an ``# on-loop`` marker."""
        if isinstance(node, ast.Lambda) or not getattr(node, "body", None):
            return False
        end = max(node.lineno, node.body[0].lineno - 1)
        for i in range(node.lineno, end + 1):
            if i <= len(mod.lines) and ONLOOP_RE.search(mod.lines[i - 1]):
                return True
        return False

    def _scan_fn(self, mod: ModuleInfo, cls: Optional[ClassInfo],
                 name: str, node) -> None:
        scan = _FnScan(self, mod, cls, name)
        scan.on_loop = self._fn_on_loop(mod, node)
        body = node.body if hasattr(node, "body") else [node]
        if isinstance(node, ast.Lambda):
            scan.visit(node.body)
        else:
            for stmt in body:
                scan.visit(stmt)
        if cls is not None:
            self.fn_scans[(mod.rel, cls.name, name)] = scan
        else:
            self.fn_scans[(mod.rel, "", name)] = scan
        # nested functions/lambdas run elsewhere: fresh held context,
        # same class scope (closures over self)
        queued = list(scan.nested)
        seen = 0
        while seen < len(queued):
            inner = queued[seen]
            seen += 1
            # nested bodies run on other threads/later: they are NOT
            # __init__ even when defined there, so the CK03 __init__
            # exemption must not leak into them
            sub = _FnScan(self, mod, cls, f"{name}.<nested>")
            sub.local_locks = set(scan.local_locks)
            sub.local_events = set(scan.local_events)
            sub.local_queues = set(scan.local_queues)
            sub.local_threads = set(scan.local_threads)
            if isinstance(inner, ast.Lambda):
                sub.visit(inner.body)
            else:
                for stmt in inner.body:
                    sub.visit(stmt)
            queued.extend(sub.nested)

    # -- interprocedural closure ---------------------------------------------
    def _closure_checks(self) -> None:
        # transitive lock sets per (module, class, method)
        all_locks: Dict[Tuple[str, str, str], Set[LockId]] = {
            k: set(s.direct_locks) for k, s in self.fn_scans.items()
        }
        changed = True
        while changed:
            changed = False
            for k, scan in self.fn_scans.items():
                mine = all_locks[k]
                before = len(mine)
                for callee, _line, _held in scan.self_calls:
                    ck = (k[0], k[1], callee)
                    if ck in all_locks:
                        mine |= all_locks[ck]
                if len(mine) != before:
                    changed = True
        # edges from self-calls made while holding locks
        for rel, cls_name, callee, line, held in self.held_self_calls:
            ck = (rel, cls_name, callee)
            for inner in all_locks.get(ck, ()):
                for outer in held:
                    if outer != inner:
                        self.add_edge(outer, inner, rel, line)
                    else:
                        decl = self.decls.get(inner)
                        if decl is not None and decl.kind == "Lock":
                            self.emit(
                                rel, line, "CK01",
                                f"call to self.{callee}() re-acquires "
                                f"non-reentrant lock {decl.name} "
                                f"already held here — guaranteed "
                                f"deadlock",
                            )
        # CK02 through one-class call chains: a held self-call whose
        # transitive callees block
        blocking: Dict[
            Tuple[str, str, str], List[Tuple[int, str, str]]
        ] = {
            k: list(s.direct_blocking) for k, s in self.fn_scans.items()
        }
        changed = True
        while changed:
            changed = False
            for k, scan in self.fn_scans.items():
                mine = blocking[k]
                have = len(mine)
                for callee, _line, _held in scan.self_calls:
                    ck = (k[0], k[1], callee)
                    for item in blocking.get(ck, ()):
                        if item not in mine:
                            mine.append(item)
                if len(mine) != have:
                    changed = True
        self._blocking_map = blocking
        for rel, cls_name, callee, line, held in self.held_self_calls:
            ck = (rel, cls_name, callee)
            items = [i for i in blocking.get(ck, ())
                     if i[2] not in CK02_EXCLUDED_OPS]
            if items:
                bline, bmsg, _op = items[0]
                self.emit(
                    rel, line, "CK02",
                    f"call to self.{callee}() blocks while a lock is "
                    f"held ({bmsg.split(' while holding')[0]} at line "
                    f"{bline})",
                )

    def _onloop_checks(self) -> None:
        """CK05: ``# on-loop`` methods (dispatcher event-loop context)
        must not block — directly or through same-class callees."""
        for k, scan in self.fn_scans.items():
            if not scan.on_loop:
                continue
            rel, cls_name, name = k
            for line, msg, op in scan.direct_blocking:
                if op in CK05_OPS:
                    self.emit(
                        rel, line, "CK05",
                        f"{msg.split(' while holding')[0].split(' while also')[0]} "
                        f"in on-loop code — {name}() runs on the "
                        f"dispatcher event loop and must never block",
                    )
            for callee, line, _held in scan.self_calls:
                ck = (rel, cls_name, callee)
                callee_scan = self.fn_scans.get(ck)
                if callee_scan is not None and callee_scan.on_loop:
                    continue  # flagged at its own definition
                items = [i for i in self._blocking_map.get(ck, ())
                         if i[2] in CK05_OPS]
                if items:
                    bline, bmsg, _op = items[0]
                    self.emit(
                        rel, line, "CK05",
                        f"call to self.{callee}() from on-loop code "
                        f"blocks ({bmsg.split(' while holding')[0]} at "
                        f"line {bline}) — {name}() runs on the "
                        f"dispatcher event loop",
                    )

    # -- global graph checks --------------------------------------------------
    def _graph_checks(self) -> None:
        for (outer, inner), (rel, line) in sorted(
            self.edges.items(), key=lambda kv: (kv[1][0], kv[1][1])
        ):
            do = self.decls.get(outer)
            di = self.decls.get(inner)
            if do is None or di is None:
                continue
            if do.rank is not None and di.rank is not None \
                    and di.rank <= do.rank:
                self.emit(
                    rel, line, "CK01",
                    f"lock-order inversion: {di.name} (rank {di.rank}) "
                    f"acquired while holding {do.name} (rank "
                    f"{do.rank}) — ranks must strictly increase inward",
                )
        # cycle detection over the acquisition graph
        adj: Dict[LockId, List[LockId]] = {}
        for (outer, inner) in self.edges:
            adj.setdefault(outer, []).append(inner)
        WHITE, GREY, BLACK = 0, 1, 2
        color: Dict[LockId, int] = {}
        stack: List[LockId] = []

        def dfs(n: LockId) -> Optional[List[LockId]]:
            color[n] = GREY
            stack.append(n)
            for m in adj.get(n, ()):
                c = color.get(m, WHITE)
                if c == GREY:
                    return stack[stack.index(m):] + [m]
                if c == WHITE:
                    cyc = dfs(m)
                    if cyc is not None:
                        return cyc
            stack.pop()
            color[n] = BLACK
            return None

        for n in sorted(adj):
            if color.get(n, WHITE) == WHITE:
                cyc = dfs(n)
                if cyc is not None:
                    names = " -> ".join(
                        self.decls[x].name if x in self.decls else str(x)
                        for x in cyc
                    )
                    first_edge = (cyc[0], cyc[1])
                    rel, line = self.edges.get(first_edge, ("?", 0))
                    self.emit(
                        rel, line, "CK01",
                        f"lock acquisition cycle: {names} — a thread "
                        f"pair interleaving these acquisitions "
                        f"deadlocks",
                    )
                    break


def analyze(paths, root: pathlib.Path = ROOT) -> List[Finding]:
    return Analyzer(root=root).analyze_paths(paths)


def main(argv) -> int:
    paths = [pathlib.Path(a) for a in argv[1:]] or [LIB]
    an = Analyzer()
    findings = an.analyze_paths(paths)
    for rel, line, code, msg in findings:
        print(f"{rel}:{line}: {code} {msg}")
    if findings:
        print(f"concheck: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print(f"concheck: clean ({len(an.decls)} lock(s) ranked, "
          f"acquisition graph acyclic)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
