#!/usr/bin/env python
"""One-shot TPU profiling sweep for the sort-bound benches.

Answers, with trustworthy tiny-slice fences (benchmarks/common.py):
  1. what ONE 16.7M-pair lax.sort really costs on the chip,
  2. what the terasort D=1 step adds on top (capacity pad, masks),
  3. whether batched row-sort + merge beats the flat 1-D sort,
  4. what the fused TPC-DS stage saves vs the unfused pair.

Run on the real chip: `python tools/profile_tpu_sort.py [log2]`.
"""

import functools
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks.common import fence


def bench(name, fn, *args, iters=10, nbytes=None):
    out = fn(*args)
    fence(jax.tree.leaves(out)[-1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    fence(jax.tree.leaves(out)[-1])
    dt = (time.perf_counter() - t0) / iters
    gbps = (nbytes or 0) / dt / 1e9
    print(f"{name:48s} {dt * 1e3:9.2f} ms  {gbps:7.2f} GB/s", flush=True)
    return dt


def main():
    log2 = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    n = 1 << log2
    rng = np.random.default_rng(7)
    k = jnp.asarray(rng.integers(0, 1 << 31, n, dtype=np.int32))
    v = jnp.asarray(rng.integers(0, 1 << 31, n, dtype=np.int32))
    nbytes = n * 8

    @jax.jit
    def sort_pair(k, v):
        return jax.lax.sort((k, v), num_keys=1, is_stable=False)

    @jax.jit
    def sort_pair_stable(k, v):
        return jax.lax.sort((k, v), num_keys=1, is_stable=True)

    @jax.jit
    def sort_keys(k):
        return jax.lax.sort((k,), num_keys=1, is_stable=False)

    @jax.jit
    def sort_triple(k, v):
        return jax.lax.sort((k, v, v), num_keys=2, is_stable=False)

    @functools.partial(jax.jit, static_argnums=(2,))
    def sort_rows(k, v, b):
        return jax.lax.sort(
            (k.reshape(b, -1), v.reshape(b, -1)), num_keys=1,
            is_stable=False,
        )

    bench("lax.sort (k,v) 1-D", sort_pair, k, v, nbytes=nbytes)
    bench("lax.sort (k,v) 1-D stable", sort_pair_stable, k, v,
          nbytes=nbytes)
    bench("lax.sort keys only", sort_keys, k, nbytes=nbytes)
    bench("lax.sort (k,role,pay) 3-operand", sort_triple, k, v,
          nbytes=nbytes)
    for b in (8, 32, 128):
        bench(f"row sort [{b}, {n // b}]", sort_rows, k, v, b,
              nbytes=nbytes)

    # the terasort D=1 step (sort + capacity pad) for overhead delta
    from sparkrdma_tpu.models.terasort import TeraSorter
    from sparkrdma_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    sorter = TeraSorter(mesh)
    kk = jax.device_put(k, sorter.sharding)
    vv = jax.device_put(v, sorter.sharding)

    def step():
        (sk, sv, n_valid, _), _cap = sorter.sort_device(kk, vv)
        return sk

    bench("terasort sort_device step", step, nbytes=nbytes)

    # experimental pallas bitonic sort (ops/sort_kernel.py): blocks
    # alone, then the full two-phase sort — compare vs lax.sort above
    try:
        from sparkrdma_tpu.ops.sort_kernel import (
            sort_pairs_blocks,
            sort_pairs_full,
        )

        for br in (256, 512, 1024):
            if n % (br * 128) == 0:
                bench(
                    f"pallas block sort (R={br})",
                    lambda k, v, b=br: sort_pairs_blocks(
                        k, v, block_rows=b
                    ),
                    k, v, nbytes=nbytes,
                )
        full = jax.jit(
            lambda k, v: sort_pairs_full(
                k, v, block_rows=512, n_buckets=16
            )[:3]
        )
        out = full(k, v)
        ok, _ov, valid = out
        m = np.asarray(jax.device_get(valid)) > 0
        got = np.asarray(jax.device_get(ok))[m]
        assert (np.diff(got) >= 0).all() and m.sum() == n, "full sort bad"
        bench("pallas full 2-phase sort", full, k, v, nbytes=nbytes)
    except Exception as e:  # Mosaic lowering may reject it — report
        print(f"pallas sort unavailable: {type(e).__name__}: {e}",
              flush=True)

    def step_tight():
        (sk, sv, n_valid, _), _cap = sorter.sort_device(
            kk, vv, capacity=n
        )
        return sk

    bench("terasort step, capacity=n (no pad)", step_tight, nbytes=nbytes)


if __name__ == "__main__":
    main()
