#!/usr/bin/env python
"""Render flight-recorder dumps as a trace waterfall / Chrome trace.

Usage:
    python tools/trace_report.py DUMP [DUMP ...] [--chrome OUT.json]
                                 [--plane PLANE] [--limit N]

``DUMP`` is a flight-recorder JSON dump — written automatically on a
failure (``flightRecorderDumpPath``), on demand from the metrics HTTP
server's ``/flightrecorder`` endpoint, or at fixture/simfleet teardown
via ``sparkrdma_tpu.obs.collect.write_dump``.  Several dumps merge
into ONE cross-process report: every event carries its origin
pid/host, so the requester's fetch spans and the server's serve spans
of one ``trace_id`` interleave on the shared epoch clock.

The text report prints

- a per-plane event census (with ring-drop counts per process, so a
  truncated picture says so),
- the injected-fault and auto-dump context (which fault points fired,
  what reason each dump was written for),
- one waterfall per ``trace_id`` — events time-offset from the
  trace's first event, tagged with pid/host and their key fields —
  followed by the untraced remainder.

``--chrome OUT.json`` additionally writes the merged events in Chrome
tracing format (load in ``chrome://tracing`` or Perfetto): events
carrying a ``us`` duration field render as complete spans, the rest as
instants; rows group by process and plane.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from sparkrdma_tpu.obs.collect import (  # noqa: E402
    load_dump,
    merge_dumps,
    merged_events,
)

#: fields rendered specially (identity / timing), not as plain k=v
_SPECIAL = {"trace_id", "span_id", "us"}


def load(paths) -> dict:
    if len(paths) == 1:
        return load_dump(paths[0])
    return merge_dumps(paths)


def _fmt_fields(fields: dict) -> str:
    parts = []
    sid = fields.get("span_id")
    if sid:
        parts.append(f"span={sid:#x}")
    for k in sorted(fields):
        if k in _SPECIAL:
            continue
        parts.append(f"{k}={fields[k]}")
    us = fields.get("us")
    if us is not None:
        parts.append(f"took={_fmt_us(us)}")
    return "  ".join(parts)


def _fmt_us(us: float) -> str:
    if us >= 1e6:
        return f"{us / 1e6:.2f}s"
    if us >= 1e3:
        return f"{us / 1e3:.1f}ms"
    return f"{us:.0f}us"


def _procs(doc: dict):
    return doc["processes"] if doc.get("merged") else [doc]


def render_census(doc: dict, events: list) -> list:
    """Per-plane event counts plus per-process ring drops and the
    reason each dump was written (auto-dumps name their trigger)."""
    out = []
    procs = _procs(doc)
    label = "merged dump" if doc.get("merged") else "dump"
    out.append(
        f"{label}: {len(events)} event(s) across {len(procs)} process(es)"
    )
    for proc in procs:
        reason = proc.get("reason", "?")
        drops = {
            plane: rec.get("dropped", 0)
            for plane, rec in proc.get("planes", {}).items()
            if rec.get("dropped")
        }
        line = (
            f"  pid={proc.get('pid')} host={proc.get('host')} "
            f"reason={reason}"
        )
        if drops:
            per = "  ".join(
                f"{p}={n}" for p, n in sorted(drops.items()))
            line += f"  RING DROPS: {per} (picture incomplete)"
        out.append(line)
    counts: dict = {}
    for e in events:
        key = (e["plane"], e["name"])
        counts[key] = counts.get(key, 0) + 1
    if counts:
        out.append("event census")
        width = max(len(f"{p}/{n}") for p, n in counts) + 2
        for (plane, name) in sorted(counts):
            out.append(
                f"  {f'{plane}/{name}':<{width}}{counts[(plane, name)]:>8}"
            )
    return out


def render_faults(events: list) -> list:
    """Name every injected fault point that fired — the line a chaos
    run's post-mortem greps for."""
    points: dict = {}
    for e in events:
        if e["plane"] == "faults" and e["name"] == "fault_fired":
            pt = e["fields"].get("point", "?")
            points[pt] = points.get(pt, 0) + 1
    if not points:
        return []
    per = "  ".join(f"{p}={n}" for p, n in sorted(points.items()))
    return [f"injected fault points: {per}"]


def render_waterfall(events: list, limit: int = 0) -> list:
    """One waterfall per trace_id (events offset from the trace's
    first event), then the untraced remainder on the epoch clock."""
    traces: dict = {}
    untraced = []
    for e in events:
        tid = e["fields"].get("trace_id") or 0
        if tid:
            traces.setdefault(tid, []).append(e)
        else:
            untraced.append(e)
    out = []
    for tid in sorted(traces, key=lambda t: traces[t][0]["t"]):
        evs = traces[tid]
        t0, t1 = evs[0]["t"], evs[-1]["t"]
        procs = sorted({(e["pid"], e["host"]) for e in evs})
        out.append(
            f"trace {tid:#018x}  {len(evs)} event(s)  "
            f"{len(procs)} process(es)  span {(t1 - t0) * 1e3:.3f}ms"
        )
        out.extend(_rows(evs, t0, limit))
    if untraced:
        out.append(f"untraced events ({len(untraced)})")
        out.extend(_rows(untraced, untraced[0]["t"], limit))
    return out


def _rows(evs: list, t0: float, limit: int) -> list:
    shown = evs if not limit else evs[:limit]
    rows = []
    for e in shown:
        origin = f"{e['pid']}@{e['host']}"
        rows.append(
            f"  +{(e['t'] - t0) * 1e3:>10.3f}ms  {origin:<18} "
            f"{e['plane']}/{e['name']:<18} {_fmt_fields(e['fields'])}"
        )
    if limit and len(evs) > limit:
        rows.append(f"  ... {len(evs) - limit} more (raise --limit)")
    return rows


def chrome_trace(events: list) -> dict:
    """Merged events in Chrome tracing format: ``us``-carrying events
    as complete spans ending at their record time, the rest as
    instants; one row per (process, plane)."""
    trace_events = []
    for e in events:
        fields = e["fields"]
        common = {
            "name": e["name"],
            "cat": e["plane"],
            "pid": e["pid"] or 0,
            "tid": e["plane"],
            "args": dict(fields),
        }
        us = fields.get("us")
        if us:
            common.update(
                ph="X", ts=(e["t"] * 1e6) - us, dur=us,
            )
        else:
            common.update(ph="i", ts=e["t"] * 1e6, s="p")
        trace_events.append(common)
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def main(argv) -> int:
    args = list(argv[1:])
    chrome_out = None
    plane = None
    limit = 0
    for flag in ("--chrome", "--plane", "--limit"):
        if flag in args:
            i = args.index(flag)
            try:
                val = args[i + 1]
            except IndexError:
                print(f"{flag} needs a value", file=sys.stderr)
                return 2
            del args[i:i + 2]
            if flag == "--chrome":
                chrome_out = val
            elif flag == "--plane":
                plane = val
            else:
                limit = int(val)
    if not args:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    doc = load(args)
    events = merged_events(doc)
    if plane is not None:
        events = [e for e in events if e["plane"] == plane]
    lines = render_census(doc, events)
    lines.extend(render_faults(events))
    lines.extend(render_waterfall(events, limit))
    print("\n".join(lines))
    if chrome_out is not None:
        with open(chrome_out, "w") as f:
            json.dump(chrome_trace(events), f)
        print(f"chrome trace: {chrome_out} "
              f"({len(events)} event(s); open in chrome://tracing)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
