#!/bin/bash
# One-shot on-chip sweep: kernel validation first, then every bench.
# Appends all JSON lines + timings to tools/bench_results_$(date).log
# so BASELINE.md can be updated from one artifact.
set -uo pipefail
cd "$(dirname "$0")/.."
out="tools/bench_results_$(date +%m%d_%H%M).log"
run() {
  echo "== $* ==" | tee -a "$out"
  timeout 1200 "$@" 2>&1 | grep -v -E "WARNING|^I[0-9]" | tee -a "$out"
}
run python tools/profile_tpu_scans.py 22
run python tools/profile_tpu_sort.py 24
run python bench.py
run python benchmarks/bench_join.py
run python benchmarks/bench_sort_wordcount.py
run python benchmarks/bench_tpcds.py
run python benchmarks/bench_attention.py
run python benchmarks/bench_terasort.py
echo "results in $out"
