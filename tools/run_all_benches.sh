#!/bin/bash
# One-shot on-chip sweep: probe, kernel validation, then every bench.
# Appends all JSON lines + timings to tools/bench_results_$(date).log
# so BASELINE.md can be updated from one artifact.
#
# Designed to make a chip window un-wasteable (VERDICT r3 item 1):
# - a DISPOSABLE subprocess probes the backend first; a wedged grant
#   aborts the sweep in 150s instead of hanging each step for 20min
# - a scan-kernel validation failure exports
#   SPARKRDMA_TPU_DISABLE_SCAN_KERNELS=1 for the remaining steps
#   (jnp log-step fallbacks are exact), so one Mosaic rejection never
#   poisons the rest of the sweep
# - every step runs under its own timeout; failures don't stop later
#   steps (bench.py additionally self-hedges: the proven 8B shape is
#   emitted if the wide path fails or hangs)
set -uo pipefail
cd "$(dirname "$0")/.."
out="tools/bench_results_$(date +%m%d_%H%M).log"

# probe to a file, grep the file AFTER the pipeline: grep -q in the
# pipeline would SIGPIPE tee on post-ALIVE teardown output and
# pipefail would read a healthy probe as wedged.  Output is appended
# to $out either way — a wedged probe's error IS the diagnostic.
probe_backend() {
  local probe_log
  probe_log=$(mktemp)
  timeout 150 python -c \
    "import jax, jax.numpy as jnp; assert int(jnp.sum(jnp.arange(100))) == 4950; print('ALIVE')" \
    > "$probe_log" 2>&1
  cat "$probe_log" >> "$out"
  if ! grep -q ALIVE "$probe_log"; then
    rm -f "$probe_log"
    return 1
  fi
  rm -f "$probe_log"
  return 0
}

echo "== backend probe ==" | tee -a "$out"
if ! probe_backend; then
  echo "backend unreachable (wedged grant?) — aborting sweep; see tools/TPU_TODO.md" | tee -a "$out"
  exit 3
fi

run() {
  echo "== $* ==" | tee -a "$out"
  timeout 1200 "$@" 2>&1 | grep -v -E "WARNING|^I[0-9]" | tee -a "$out"
  local rc="${PIPESTATUS[0]}"
  if [ "$rc" -eq 124 ]; then
    # a step timing out may mean the grant wedged mid-RPC (the SIGTERM
    # itself can wedge it — tools/TPU_TODO.md); re-probe before letting
    # the remaining steps burn 1200s each against a dead backend
    if ! probe_backend; then
      echo "backend wedged after a step timeout — aborting the sweep" | tee -a "$out"
      exit 3
    fi
  fi
  return "$rc"
}

# ---- SAFE PHASE: proven pure-XLA paths only.  The never-on-silicon
# Pallas kernels (scan/sort/attention) are DISABLED here so a Mosaic
# compile hang cannot burn the window before the headline JSON lands
# (that is exactly how the first round-4 window was lost: the scan
# validation step led the sweep, hung for 1200s, and the timeout
# SIGTERM re-wedged the grant before bench.py ever ran).
export SPARKRDMA_TPU_DISABLE_SCAN_KERNELS=1
export SPARKRDMA_TPU_DISABLE_SORT_KERNEL=1

run python bench.py
run python benchmarks/bench_terasort.py
run python benchmarks/bench_join.py
run python benchmarks/bench_sort_wordcount.py
run python benchmarks/bench_tpcds.py
run env SPARKRDMA_BENCH_DEVICE=1 python benchmarks/bench_assembled_10gb.py

# Late-window recoveries (chip_watcher.sh safe tier) must stop here:
# the risky Mosaic-compile phase is the documented grant-wedging hazard
# right before the driver's official end-of-round run.
if [ -n "${SPARKRDMA_SWEEP_SAFE_ONLY:-}" ]; then
  echo "SPARKRDMA_SWEEP_SAFE_ONLY set — skipping the risky Mosaic phase" | tee -a "$out"
  echo "results in $out"
  exit 0
fi

# ---- RISKY PHASE: first-ever Mosaic compiles.  Each step re-probes on
# timeout; a hang here costs only the remaining (optional) steps.
if run env -u SPARKRDMA_TPU_DISABLE_SCAN_KERNELS python tools/profile_tpu_scans.py 22; then
  unset SPARKRDMA_TPU_DISABLE_SCAN_KERNELS
  echo "scan kernels validated: re-running the kernel-consuming benches" | tee -a "$out"
  run python benchmarks/bench_join.py
  run python benchmarks/bench_sort_wordcount.py
  run python benchmarks/bench_tpcds.py
else
  echo "scan kernels failed validation: jnp fallbacks stand" | tee -a "$out"
fi
run python benchmarks/bench_attention.py
# the profile exits 0 even when Mosaic rejects the kernel (its pallas
# section is try/except'd), so gate the engine-enabled re-run on the
# pallas timing line actually having been printed
run python tools/profile_tpu_sort.py 24
if grep -q "pallas full 2-phase sort" "$out"; then
  unset SPARKRDMA_TPU_DISABLE_SORT_KERNEL
  export SPARKRDMA_TPU_ENABLE_SORT_KERNEL=1
  echo "pallas sort compiled and timed: re-running the headline with the engine enabled" | tee -a "$out"
  run python bench.py
else
  echo "pallas sort unavailable: headline stands on lax.sort" | tee -a "$out"
fi
echo "results in $out"
