#!/usr/bin/env python
"""On-chip validation + micro-bench for the Pallas scan kernels.

Run FIRST when the real chip is reachable after touching
ops/scan_kernels.py: compiled-mode correctness vs the jnp log-step
references, then kernel-vs-jnp timing for fill / segmented max /
cumsum at bench sizes.  `python tools/profile_tpu_scans.py [log2]`.
"""

import os
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from benchmarks.common import fence


def bench(name, fn, *args, iters=10):
    out = fn(*args)
    fence(jax.tree.leaves(out)[-1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    fence(jax.tree.leaves(out)[-1])
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:44s} {dt * 1e3:9.2f} ms", flush=True)
    return dt


def main():
    from sparkrdma_tpu.ops import scan_kernels as sk
    from sparkrdma_tpu.ops import segment as seg

    log2 = int(sys.argv[1]) if len(sys.argv) > 1 else 22
    n = 1 << log2
    rng = np.random.default_rng(5)
    flag_h = rng.random(n) < 0.01
    a_h = rng.integers(-(1 << 30), 1 << 30, n, dtype=np.int32)
    b_h = rng.integers(-(1 << 30), 1 << 30, n, dtype=np.int32)
    flag = jnp.asarray(flag_h)
    a = jnp.asarray(a_h)
    b = jnp.asarray(b_h)

    assert sk.use_scan_kernels(), (
        "scan kernels disabled on this backend — nothing to validate"
    )

    # -- compiled-mode correctness (kernel vs jnp references) ---------------
    os.environ["SPARKRDMA_TPU_DISABLE_SCAN_KERNELS"] = "1"
    want_f, (wa, wb) = seg._ff_run_carry(flag, (a, b))
    want_max = seg.segmented_scan(
        a, flag, jnp.maximum, np.iinfo(np.int32).min
    )
    want_cs = jnp.cumsum(a)
    wf_h, wa_h, wb_h = (
        np.asarray(want_f), np.asarray(wa), np.asarray(wb)
    )
    wmax_h, wcs_h = np.asarray(want_max), np.asarray(want_cs)
    del os.environ["SPARKRDMA_TPU_DISABLE_SCAN_KERNELS"]

    got_f, (ga, gb) = sk.scan_flagged("fill", flag, (a, b))
    gf_h = np.asarray(got_f)
    np.testing.assert_array_equal(gf_h, wf_h)
    np.testing.assert_array_equal(np.asarray(ga)[wf_h], wa_h[wf_h])
    np.testing.assert_array_equal(np.asarray(gb)[wf_h], wb_h[wf_h])
    print("fill kernel: compiled-mode correctness OK", flush=True)

    _f, (gmax,) = sk.scan_flagged("max", flag, (a,))
    np.testing.assert_array_equal(np.asarray(gmax), wmax_h)
    _f, (gcs,) = sk.scan_flagged("add", jnp.zeros(n, bool), (a,))
    np.testing.assert_array_equal(np.asarray(gcs), wcs_h)
    print("max/add kernels: compiled-mode correctness OK", flush=True)

    # -- timing: kernel vs jnp log-step -------------------------------------
    jfill = jax.jit(
        lambda f, x, y: _jnp_fill_body(f, (x, y))
    )
    kfill = jax.jit(lambda f, x, y: sk.scan_flagged("fill", f, (x, y)))
    jcs = jax.jit(jnp.cumsum)
    kcs = jax.jit(lambda x: sk.cumsum_1d(x))

    bench("fill jnp log-step (2 cols)", jfill, flag, a, b)
    bench("fill pallas one-pass (2 cols)", kfill, flag, a, b)
    bench("cumsum jnp", jcs, a)
    bench("cumsum pallas", kcs, a)

    from sparkrdma_tpu.models.join import _probe_fill  # noqa: F401
    print("done", flush=True)


def _jnp_fill_body(flag, cols):
    """The raw log-step loop, inlined so the jit traces the jnp path
    regardless of the dispatch gate."""
    cols = list(cols)
    f = flag
    n = int(f.shape[0])
    s = 1
    while s < n:
        pf = jnp.concatenate([f[:s], f[:-s]])
        prev = [jnp.concatenate([c[:s], c[:-s]]) for c in cols]
        need = ~f
        cols = [jnp.where(need, p, c) for p, c in zip(prev, cols)]
        f = f | pf
        s <<= 1
    return f, cols


if __name__ == "__main__":
    main()
