#!/usr/bin/env python
"""Shared plumbing for the AST gates (lint / concheck / flowcheck /
wirecheck / statecheck).

Four near-identical copies of the same scaffolding had grown across
the gates; this module is the single home for:

* the code-scoped ``# noqa`` grammar — :func:`noqa_codes`,
  :func:`suppressed`, :class:`Suppressor` (tools/lint.py re-exports
  ``_suppressed`` for backwards compatibility, so every gate keeps ONE
  suppression decision);
* the finding shape — :class:`Finding`, a ``(rel, line, code, msg)``
  named tuple that sorts and unpacks exactly like the plain tuples the
  gates historically used;
* file walking — :func:`walk_py` (dirs rglob to ``*.py``, files pass
  through) and :func:`py_files` (lint's repo-wide walk);
* statement-span helpers — :func:`span_search` (trailing annotation
  comments on multi-line statements), :func:`stmt_header_span`
  (compound-statement headers), :func:`string_lines` (docstring spans
  to exclude from comment-grammar scans);
* concheck's guard-lock resolution machinery — :class:`LockDecl`,
  :class:`ClassInfo`, :class:`ModuleInfo`, :func:`collect_module`, and
  :func:`resolve_lock` — so any gate that needs "is this read under
  ``with <recv>._lock:``?" (CK03, SC03) resolves locks the same way.

Nothing here prints or exits; the gates own their own CLIs.
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

ROOT = pathlib.Path(__file__).resolve().parent.parent

# -- code-scoped noqa grammar -------------------------------------------------

NOQA_RE = re.compile(r"#\s*noqa\b(?:\s*:\s*(?P<codes>[^#]*))?", re.I)
_CODE_TOKEN_RE = re.compile(r"[A-Za-z]+\d+")
# foreign linter codes accepted as aliases for ours
CODE_ALIASES = {"PY05": {"F401"}}


def noqa_codes(line: str):
    """None = no noqa on the line; empty set = bare ``# noqa``
    (suppresses everything); else the set of named codes.  Code
    tokens (letters+digits, comma/space separated) may be followed by
    a justification — ``# noqa: CK02 serialized frame writes`` scopes
    to CK02; prose with no leading code degrades to a bare noqa."""
    m = NOQA_RE.search(line)
    if m is None:
        return None
    spec = m.group("codes")
    if spec is None:
        return set()
    codes = set()
    for tok in re.split(r"[,\s]+", spec.strip()):
        if _CODE_TOKEN_RE.fullmatch(tok):
            codes.add(tok.upper())
        else:
            break  # justification prose starts here
    return codes


def suppressed(lines, lineno: int, code: str) -> bool:
    """Code-scoped noqa check for a finding at ``lineno``."""
    if not (1 <= lineno <= len(lines)):
        return False
    codes = noqa_codes(lines[lineno - 1])
    if codes is None:
        return False
    if not codes:
        return True  # bare noqa
    return bool(codes & ({code} | CODE_ALIASES.get(code, set())))


class Suppressor:
    """Per-file suppression decision bound to its line list."""

    def __init__(self, lines: List[str]):
        self._lines = lines

    def suppressed(self, lineno: int, code: str) -> bool:
        return suppressed(self._lines, lineno, code)


# -- the finding shape --------------------------------------------------------

class Finding(NamedTuple):
    """One gate finding.  A tuple subclass: unpacks, indexes, sorts and
    compares exactly like the ``(rel, line, code, msg)`` tuples the
    gates historically appended."""

    rel: object
    line: int
    code: str
    msg: str


# -- file walking -------------------------------------------------------------

PY_DIRS = ["sparkrdma_tpu", "tests", "benchmarks", "tools"]


def py_files(root: pathlib.Path = ROOT):
    """The repo-wide python walk (lint's scope): the library, tests,
    benches, tools, plus repo-root scripts."""
    for d in PY_DIRS:
        yield from sorted((root / d).rglob("*.py"))
    yield from sorted(root.glob("*.py"))


def walk_py(paths) -> List[pathlib.Path]:
    """Expand a path list the way the analyzers do: directories rglob
    to every ``*.py`` under them (sorted), files pass through."""
    files: List[pathlib.Path] = []
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        else:
            files.append(p)
    return files


def rel_to(path: pathlib.Path, root: pathlib.Path) -> str:
    try:
        return str(path.relative_to(root))
    except ValueError:
        return str(path)


# -- statement-span helpers ---------------------------------------------------

def span_search(pattern: re.Pattern, lines: List[str], lineno: int,
                end_lineno: Optional[int]):
    """Search a statement's whole line span (multi-line assignments
    carry their trailing annotation comment on the LAST line)."""
    for i in range(lineno, (end_lineno or lineno) + 1):
        if i <= len(lines):
            m = pattern.search(lines[i - 1])
            if m is not None:
                return m
    return None


COMPOUND_STMTS = (ast.If, ast.While, ast.For, ast.AsyncFor, ast.With,
                  ast.AsyncWith, ast.Try)


def stmt_header_span(stmt: ast.stmt) -> Tuple[int, int]:
    """Line span carrying a statement's trailing annotation: the whole
    span for simple statements, only the header line(s) for compound
    ones (their bodies' annotations belong to the inner statements)."""
    if isinstance(stmt, COMPOUND_STMTS):
        first_body = stmt.body[0].lineno if stmt.body else stmt.lineno
        return stmt.lineno, max(stmt.lineno, first_body - 1)
    return stmt.lineno, stmt.end_lineno or stmt.lineno


def string_lines(tree: ast.Module) -> Set[int]:
    """Lines covered by multi-line string constants (docstrings,
    embedded text): annotation grammar EXAMPLES live there — never
    live annotations — so every scan skips these lines."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) \
                and isinstance(node.value, str) \
                and node.end_lineno is not None \
                and node.end_lineno > node.lineno:
            out.update(range(node.lineno, node.end_lineno + 1))
    return out


# -- guard-lock resolution (concheck's declaration machinery) -----------------

THREADING_LOCKS = {"Lock": "Lock", "RLock": "RLock",
                   "Condition": "Condition"}
DBG_CTORS = {"dbg_lock": "Lock", "dbg_rlock": "RLock",
             "dbg_condition": "Condition"}

RANK_RE = re.compile(r"#\s*lock-order:\s*(-?\d+)")
GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

LockId = Tuple[str, ...]


class LockDecl:
    __slots__ = ("lock_id", "kind", "rank", "line", "group", "name")

    def __init__(self, lock_id: LockId, kind: str, rank: Optional[int],
                 line: int, group: bool, name: str):
        self.lock_id = lock_id
        self.kind = kind
        self.rank = rank
        self.line = line
        self.group = group
        self.name = name


class ClassInfo:
    def __init__(self, name: str):
        self.name = name
        self.locks: Dict[str, LockDecl] = {}
        self.events: Set[str] = set()
        self.queues: Set[str] = set()
        self.threads: Set[str] = set()
        self.guarded: Dict[str, Tuple[str, int]] = {}  # attr -> (lock, line)
        self.methods: Dict[str, ast.AST] = {}


class ModuleInfo:
    def __init__(self, rel: str, lines: List[str], tree: ast.Module):
        self.rel = rel
        self.lines = lines
        self.tree = tree  # parsed once, shared by both passes
        self.locks: Dict[str, LockDecl] = {}  # module-level, by name
        self.classes: Dict[str, ClassInfo] = {}


def call_name(func: ast.expr) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def lock_ctor(node: ast.expr) -> Optional[Tuple[str, Optional[int]]]:
    """(kind, dbg rank or None) when ``node`` constructs a lock."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and f.value.id == "threading"
            and f.attr in THREADING_LOCKS):
        return THREADING_LOCKS[f.attr], None
    name = call_name(f)
    if name in DBG_CTORS:
        rank = None
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, int):
            rank = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "rank" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, int):
                rank = kw.value.value
        return DBG_CTORS[name], rank
    return None


def lock_group_ctor(node: ast.expr) -> Optional[str]:
    """Kind when ``node`` builds a list of locks (lock striping)."""
    elts: List[ast.expr] = []
    if isinstance(node, (ast.List, ast.Tuple)):
        elts = list(node.elts)
    elif isinstance(node, ast.ListComp):
        elts = [node.elt]
    for e in elts:
        got = lock_ctor(e)
        if got is not None:
            return got[0]
    return None


def ctor_of(node: ast.expr, module: str, names: Set[str]) -> bool:
    """``node`` is a call to module.name() or a bare name() in names."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and f.value.id == module and f.attr in names):
        return True
    return isinstance(f, ast.Name) and f.id in names


def make_decl(lock_id: LockId, kind: str, dbg_rank: Optional[int],
              lineno: int, group: bool, name: str, lines: List[str],
              findings: List[Finding], sup: Suppressor,
              rel: str, end_lineno: Optional[int] = None,
              rank_findings: bool = True) -> LockDecl:
    """Build one LockDecl, resolving its rank from the ``# lock-order``
    annotation or the dbg ctor argument.  Rank-discipline findings
    (CK04) are concheck's to emit — a gate reusing the collection for
    resolution only passes ``rank_findings=False``."""
    m = span_search(RANK_RE, lines, lineno, end_lineno)
    rank = int(m.group(1)) if m else None
    if rank is not None and dbg_rank is not None and rank != dbg_rank \
            and rank_findings:
        if not sup.suppressed(lineno, "CK04"):
            findings.append((rel, lineno, "CK04",
                             f"lock {name}: # lock-order comment ({rank}) "
                             f"disagrees with dbg rank ({dbg_rank})"))
    if rank is None:
        rank = dbg_rank
    if rank is None and rank_findings \
            and not sup.suppressed(lineno, "CK04"):
        findings.append(
            (rel, lineno, "CK04",
             f"lock {name} has no rank — annotate its creation line "
             f"with '# lock-order: N' (or create it via dbg_lock/"
             f"dbg_rlock/dbg_condition with a rank argument)")
        )
    return LockDecl(lock_id, kind, rank, lineno, group, name)


def collect_class(rel: str, cls: ast.ClassDef, lines: List[str],
                  findings: List[Finding], sup: Suppressor,
                  rank_findings: bool = True) -> ClassInfo:
    info = ClassInfo(cls.name)
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[item.name] = item
    for meth in info.methods.values():
        for node in ast.walk(meth):
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    target, value = tgt.attr, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Attribute) \
                    and isinstance(node.target.value, ast.Name) \
                    and node.target.value.id == "self" \
                    and node.value is not None:
                target, value = node.target.attr, node.value
            if target is None:
                continue
            got = lock_ctor(value)
            group_kind = lock_group_ctor(value) if got is None else None
            if got is not None or group_kind is not None:
                kind, dbg_rank = got if got is not None \
                    else (group_kind, None)
                info.locks[target] = make_decl(
                    ("attr", rel, cls.name, target), kind, dbg_rank,
                    node.lineno, got is None, f"{cls.name}.{target}",
                    lines, findings, sup, rel, node.end_lineno,
                    rank_findings,
                )
                continue
            if ctor_of(value, "threading", {"Event"}):
                info.events.add(target)
            elif ctor_of(value, "queue", {"Queue", "SimpleQueue",
                                          "LifoQueue", "PriorityQueue"}):
                info.queues.add(target)
            elif ctor_of(value, "threading", {"Thread", "Timer"}):
                info.threads.add(target)
            g = span_search(GUARD_RE, lines, node.lineno,
                            node.end_lineno)
            if g is not None:
                info.guarded[target] = (g.group(1), node.lineno)
    return info


def collect_module(rel: str, tree: ast.Module,
                   lines: List[str], findings: List[Finding],
                   sup: Suppressor,
                   rank_findings: bool = True) -> ModuleInfo:
    """Pass 1 of concheck's analysis: every module/class lock
    declaration plus guarded-by annotations — the resolution index
    both CK03 and SC03 check held regions against."""
    mod = ModuleInfo(rel, lines, tree)
    for stmt in tree.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            target, value = stmt.targets[0].id, stmt.value
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name) \
                and stmt.value is not None:
            target, value = stmt.target.id, stmt.value
        if target is None:
            continue
        got = lock_ctor(value)
        if got is not None:
            kind, dbg_rank = got
            mod.locks[target] = make_decl(
                ("mod", rel, target), kind, dbg_rank, stmt.lineno,
                False, target, lines, findings, sup, rel,
                stmt.end_lineno, rank_findings,
            )
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef):
            mod.classes[stmt.name] = collect_class(
                rel, stmt, lines, findings, sup, rank_findings
            )
    # nested classes (e.g. helper classes defined inside functions) are
    # rare; classes nested one level inside classes are picked up too
    for stmt in ast.walk(tree):
        if isinstance(stmt, ast.ClassDef) and stmt.name not in mod.classes:
            mod.classes[stmt.name] = collect_class(
                rel, stmt, lines, findings, sup, rank_findings
            )
    return mod


class Held:
    """One entry of a held-lock stack: ``key`` is the syntactic
    ``(receiver, attr)`` identity a guarded read is checked against."""

    __slots__ = ("key", "lock_id", "kind", "line")

    def __init__(self, key, lock_id, kind, line):
        self.key = key        # (receiver, attr) or ("", name)
        self.lock_id = lock_id
        self.kind = kind
        self.line = line


def resolve_lock(mod: ModuleInfo, cls: Optional[ClassInfo],
                 local_locks: Set[str], expr: ast.expr):
    """(key, decl-or-None) for a with-item that looks like a lock;
    None when it is not lock-shaped at all.  Attribute locks resolve
    through the current class first, then any unique owner class in
    the module (non-self receivers like ``pool._lock``)."""
    if isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name):
        recv, attr = expr.value.id, expr.attr
        decl = None
        if cls is not None and attr in cls.locks:
            decl = cls.locks[attr]
        else:
            owners = [
                c for c in mod.classes.values()
                if attr in c.locks
            ]
            if len(owners) == 1:
                decl = owners[0].locks[attr]
        if decl is not None or attr.endswith("lock") \
                or attr.endswith("_cv"):
            return (recv, attr), decl
        return None
    if isinstance(expr, ast.Name):
        if expr.id in mod.locks:
            return ("", expr.id), mod.locks[expr.id]
        if expr.id in local_locks:
            return ("", expr.id), None
    return None

