#!/bin/bash
# Poll the tunneled TPU grant; the moment a disposable probe answers,
# fire the full bench sweep (tools/run_all_benches.sh) exactly once.
#
# Rationale (tools/TPU_TODO.md): the grant wedges for hours after any
# client dies mid-RPC and recovers on its own schedule.  A probe that
# hangs at backend INIT is queued, not holding the grant, so killing it
# at 150s is safe.  Polling every 10 min converts "the chip came back
# at 3am" into numbers instead of a missed window.
set -u
cd "$(dirname "$0")/.."
log=tools/chip_watcher.log
echo "$(date +%F_%T) watcher start" >> "$log"
while true; do
  if timeout 150 python -c \
    "import jax, jax.numpy as jnp; assert int(jnp.sum(jnp.arange(100))) == 4950; print('ALIVE')" \
    >> "$log" 2>&1; then
    echo "$(date +%F_%T) chip ALIVE — launching sweep" >> "$log"
    bash tools/run_all_benches.sh >> "$log" 2>&1
    echo "$(date +%F_%T) sweep finished (rc=$?)" >> "$log"
    exit 0
  fi
  echo "$(date +%F_%T) still wedged" >> "$log"
  sleep 600
done
