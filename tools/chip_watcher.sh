#!/bin/bash
# Poll the tunneled TPU grant; when a disposable probe answers, spend
# the window according to how much round time is left, exactly once.
#
# Rationale (tools/TPU_TODO.md): the grant wedges for hours after any
# client dies mid-RPC and recovers on its own schedule.  A probe that
# hangs at backend INIT is queued, not holding the grant, so killing it
# at 150s is safe.  Polling every 10 min converts "the chip came back
# at 3am" into numbers instead of a missed window.
#
# Deadline policy: the driver runs the OFFICIAL `python bench.py` on
# the real chip when the round ends; a 1-2h sweep straddling that
# moment would contend with it on the single grant.  So: full sweep
# while >2.5h remain, headline-only while >1h remains, then stand down
# and leave the window to the driver.
set -u
cd "$(dirname "$0")/.."
log=tools/chip_watcher.log
# round 5 started ~15:45 UTC Jul 31 with a ~12h budget
FULL_SWEEP_UNTIL=$(date -d "2026-08-01 01:15 UTC" +%s)
SAFE_SWEEP_UNTIL=$(date -d "2026-08-01 02:00 UTC" +%s)
HEADLINE_UNTIL=$(date -d "2026-08-01 02:45 UTC" +%s)
echo "$(date +%F_%T) watcher start" >> "$log"
while true; do
  now=$(date +%s)
  if [ "$now" -ge "$HEADLINE_UNTIL" ]; then
    echo "$(date +%F_%T) past deadline — standing down (driver owns the window)" >> "$log"
    exit 0
  fi
  if timeout 150 python -c \
    "import jax, jax.numpy as jnp; assert int(jnp.sum(jnp.arange(100))) == 4950; print('ALIVE')" \
    >> "$log" 2>&1; then
    now=$(date +%s)
    if [ "$now" -lt "$FULL_SWEEP_UNTIL" ]; then
      echo "$(date +%F_%T) chip ALIVE — launching full sweep" >> "$log"
      bash tools/run_all_benches.sh >> "$log" 2>&1
      rc=$?
      echo "$(date +%F_%T) sweep finished (rc=$rc)" >> "$log"
    elif [ "$now" -lt "$SAFE_SWEEP_UNTIL" ]; then
      echo "$(date +%F_%T) chip ALIVE — safe-phase sweep only (late window)" >> "$log"
      SPARKRDMA_SWEEP_SAFE_ONLY=1 bash tools/run_all_benches.sh >> "$log" 2>&1
      rc=$?
      echo "$(date +%F_%T) safe sweep finished (rc=$rc)" >> "$log"
    else
      echo "$(date +%F_%T) chip ALIVE late — headline bench only" >> "$log"
      # NO external timeout: killing bench.py mid-RPC would wedge the
      # grant right before the driver's official run; bench.py bounds
      # itself (pre-flight probe, 600s init watchdog, 1800s wide-path
      # hang timer, each ending in a clean emit + exit)
      python bench.py >> "$log" 2>&1
      rc=$?
      echo "$(date +%F_%T) headline finished (rc=$rc)" >> "$log"
    fi
    exit 0
  fi
  echo "$(date +%F_%T) still wedged" >> "$log"
  sleep 600
done
