#!/usr/bin/env python
"""Render a metrics-registry JSON snapshot as a human-readable table.

Usage:
    python tools/metrics_report.py SNAPSHOT.json [BASELINE.json]

With one argument, renders the snapshot (written by
``TpuShuffleConf metricsJsonPath`` at manager stop, or
``sparkrdma_tpu.metrics.write_json_snapshot``).  With two, renders
``SNAPSHOT - BASELINE`` (counter/histogram deltas; gauges keep the new
reading) so one run's activity can be isolated from a warm process.

Histograms print count/sum plus approximate p50/p95/p99 interpolated
from the bucket counts, and the nonzero buckets.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from sparkrdma_tpu.metrics import diff_snapshots  # noqa: E402


def _fmt_series(rec) -> str:
    labels = rec.get("labels") or {}
    if not labels:
        return rec["name"]
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{rec['name']}{{{inner}}}"


def _fmt_num(v: float) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return f"{int(f):,}"
    return f"{f:,.3f}"


def _percentile(edges, counts, total, q) -> float:
    """Approximate quantile from bucket counts: linear interpolation
    inside the bucket that crosses rank q*total (the overflow bucket
    reports its lower edge)."""
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0.0
    lo = 0.0
    for i, c in enumerate(counts):
        hi = edges[i] if i < len(edges) else lo
        if cum + c >= rank and c > 0:
            if i >= len(edges):
                return lo  # open-ended overflow bucket
            frac = (rank - cum) / c
            return lo + (hi - lo) * frac
        cum += c
        if i < len(edges):
            lo = edges[i]
    return lo


def render(snap: dict, title: str = "") -> str:
    lines = []
    if title:
        lines.append(title)
    counters = [c for c in snap.get("counters", [])]
    gauges = [g for g in snap.get("gauges", [])]
    hists = [h for h in snap.get("histograms", [])]
    width = max(
        [len(_fmt_series(r)) for r in counters + gauges + hists] + [20]
    )
    if counters:
        lines.append("counters")
        for c in counters:
            lines.append(
                f"  {_fmt_series(c):<{width}}  {_fmt_num(c['value']):>16}"
            )
    if gauges:
        lines.append("gauges")
        for g in gauges:
            lines.append(
                f"  {_fmt_series(g):<{width}}  {_fmt_num(g['value']):>16}"
            )
    if hists:
        lines.append("histograms")
        for h in hists:
            total = h["count"]
            p50 = _percentile(h["edges"], h["counts"], total, 0.50)
            p95 = _percentile(h["edges"], h["counts"], total, 0.95)
            p99 = _percentile(h["edges"], h["counts"], total, 0.99)
            lines.append(
                f"  {_fmt_series(h):<{width}}  count={total} "
                f"sum={_fmt_num(h['sum'])} "
                f"p50~{p50:.3g} p95~{p95:.3g} p99~{p99:.3g}"
            )
            nonzero = []
            lo = 0.0
            for i, c in enumerate(h["counts"]):
                if i < len(h["edges"]):
                    span = f"[{lo:g}-{h['edges'][i]:g})"
                    lo = h["edges"][i]
                else:
                    span = f"[{lo:g}+)"
                if c:
                    nonzero.append(f"{span}: {c}")
            if nonzero:
                lines.append(f"    {', '.join(nonzero)}")
    if len(lines) <= (1 if title else 0):
        lines.append("(empty snapshot)")
    return "\n".join(lines)


def main(argv) -> int:
    if len(argv) not in (2, 3):
        print(__doc__.strip(), file=sys.stderr)
        return 2
    with open(argv[1]) as f:
        snap = json.load(f)
    title = f"metrics snapshot: {argv[1]}"
    if len(argv) == 3:
        with open(argv[2]) as f:
            base = json.load(f)
        snap = diff_snapshots(snap, base)
        title += f" (diff vs {argv[2]})"
    print(render(snap, title))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
