#!/usr/bin/env python
"""Render a metrics-registry JSON snapshot as a human-readable table.

Usage:
    python tools/metrics_report.py SNAPSHOT.json [BASELINE.json]

With one argument, renders the snapshot (written by
``TpuShuffleConf metricsJsonPath`` at manager stop, or
``sparkrdma_tpu.metrics.write_json_snapshot``).  With two, renders
``SNAPSHOT - BASELINE`` (counter/histogram deltas; gauges keep the new
reading) so one run's activity can be isolated from a warm process.

Histograms print count/sum plus approximate p50/p95/p99 interpolated
from the bucket counts, and the nonzero buckets.

``lock_hold_us`` histograms (the lockDebug sanitizer's hold-time
series, utils/dbglock.py) additionally render as one compact
"lock hold times" table — one row per lock, sorted by total held time —
so a snapshot diff shows exactly which locks a run leaned on.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from sparkrdma_tpu.metrics import diff_snapshots  # noqa: E402


def _fmt_series(rec) -> str:
    labels = rec.get("labels") or {}
    if not labels:
        return rec["name"]
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{rec['name']}{{{inner}}}"


def _fmt_num(v: float) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return f"{int(f):,}"
    return f"{f:,.3f}"


def _percentile(edges, counts, total, q) -> float:
    """Approximate quantile from bucket counts: linear interpolation
    inside the bucket that crosses rank q*total (the overflow bucket
    reports its lower edge)."""
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0.0
    lo = 0.0
    for i, c in enumerate(counts):
        hi = edges[i] if i < len(edges) else lo
        if cum + c >= rank and c > 0:
            if i >= len(edges):
                return lo  # open-ended overflow bucket
            frac = (rank - cum) / c
            return lo + (hi - lo) * frac
        cum += c
        if i < len(edges):
            lo = edges[i]
    return lo


def _fmt_us(us: float) -> str:
    """Human microseconds: 850us, 12.4ms, 1.07s."""
    if us >= 1e6:
        return f"{us / 1e6:.2f}s"
    if us >= 1e3:
        return f"{us / 1e3:.1f}ms"
    return f"{us:.0f}us"


def render_lock_holds(hists: list) -> list:
    """Compact per-lock hold-time table over the ``lock_hold_us``
    series (written by the lockDebug sanitizer): acquire count, total
    held, mean and p99 hold — sorted by total held time so the
    heaviest lock tops the diff."""
    rows = []
    for h in hists:
        total = h["count"]
        if total <= 0:
            continue
        name = (h.get("labels") or {}).get("lock", "?")
        p50 = _percentile(h["edges"], h["counts"], total, 0.50)
        p99 = _percentile(h["edges"], h["counts"], total, 0.99)
        rows.append((h["sum"], name, total, p50, p99))
    if not rows:
        return []
    rows.sort(reverse=True)
    width = max(len(r[1]) for r in rows)
    out = ["lock hold times (lock_hold_us)"]
    for hsum, name, total, p50, p99 in rows:
        out.append(
            f"  {name:<{width}}  acquires={total:<8} "
            f"held={_fmt_us(hsum):>8}  mean={_fmt_us(hsum / total):>8}  "
            f"p50~{_fmt_us(p50):>8}  p99~{_fmt_us(p99):>8}"
        )
    return out


def render_decode_pipeline(counters: list) -> list:
    """Compact view of the reduce-side decode pipeline's instruments
    (shuffle/decode.py): decoded volume and time, the decode-ahead hit
    rate (tickets already decoded when the task thread asked) vs
    steals (task thread decoded inline because no worker had started),
    credit waits, and the task thread's wire-wait vs decode-wait
    split."""
    vals = {}
    for c in counters:
        if c.get("labels"):
            continue
        vals[c["name"]] = c["value"]
    tasks = vals.get("shuffle_decode_tasks_total", 0)
    if not tasks:
        return []
    hits = vals.get("shuffle_decode_ahead_hits_total", 0)
    steals = vals.get("shuffle_decode_steals_total", 0)
    out = ["decode pipeline (shuffle/decode.py)"]
    out.append(
        f"  decoded {_fmt_num(vals.get('shuffle_decode_bytes_total', 0))}B "
        f"in {tasks:,.0f} task(s), "
        f"{_fmt_us(vals.get('shuffle_decode_us_total', 0))} decode time"
    )
    out.append(
        f"  decode-ahead hits={hits:,.0f} ({hits / tasks:.0%})  "
        f"inline steals={steals:,.0f}  "
        f"credit waits={vals.get('shuffle_decode_credit_waits_total', 0):,.0f}  "
        f"block splits={vals.get('shuffle_decode_block_splits_total', 0):,.0f}"
    )
    wire = vals.get("shuffle_fetch_wait_ms_total")
    dec = vals.get("shuffle_decode_wait_ms_total")
    if wire is not None or dec is not None:
        out.append(
            f"  task-thread wait split: wire={_fmt_us((wire or 0) * 1e3)} "
            f"decode={_fmt_us((dec or 0) * 1e3)}"
        )
    return out


def render_tier(counters: list, gauges: list) -> list:
    """Compact view of the tiered block store (memory/tier.py): hot
    hit rate on the serve path, promote/demote traffic, prefetch
    usefulness (predicted blocks actually consumed hot), eviction
    refusals (pinned under an in-flight serve), and the bytes
    committed but never read (what lazy per-span registration saved
    over the old eager whole-output mmap)."""
    vals = {}
    for c in counters:
        if not c.get("labels"):
            vals[c["name"]] = c["value"]
    hits = vals.get("tier_hits_total", 0)
    misses = vals.get("tier_misses_total", 0)
    if not hits and not misses and not vals.get("tier_commit_bytes_total"):
        return []
    served = hits + misses
    out = ["tiered block store (memory/tier.py)"]
    hot = next(
        (g["value"] for g in gauges
         if g["name"] == "tier_hot_bytes" and not g.get("labels")), 0,
    )
    out.append(
        f"  committed {_fmt_num(vals.get('tier_commit_bytes_total', 0))}B"
        f"  hot now {_fmt_num(hot)}B"
        f"  never-read {_fmt_num(vals.get('tier_bytes_never_read_total', 0))}B"
    )
    rate = f" ({hits / served:.0%})" if served else ""
    out.append(
        f"  serves: hits={hits:,.0f}{rate}  misses={misses:,.0f}  "
        f"cold bytes={_fmt_num(vals.get('tier_cold_read_bytes_total', 0))}B"
    )
    out.append(
        f"  promote {vals.get('tier_promotes_total', 0):,.0f}"
        f"/{_fmt_num(vals.get('tier_promote_bytes_total', 0))}B  "
        f"demote {vals.get('tier_demotes_total', 0):,.0f}"
        f"/{_fmt_num(vals.get('tier_demote_bytes_total', 0))}B  "
        f"evict refusals={vals.get('tier_evict_refusals_total', 0):,.0f}"
    )
    pf = vals.get("tier_prefetch_tasks_total", 0)
    useful = vals.get("tier_prefetch_useful_total", 0)
    use = f" ({useful / pf:.0%} useful)" if pf else ""
    out.append(
        f"  prefetch tasks={pf:,.0f}{use}  "
        f"hint msgs={vals.get('tier_hint_msgs_total', 0):,.0f}  "
        f"hinted blocks={vals.get('tier_hint_blocks_total', 0):,.0f}"
    )
    return out


def render(snap: dict, title: str = "") -> str:
    lines = []
    if title:
        lines.append(title)
    counters = [c for c in snap.get("counters", [])]
    gauges = [g for g in snap.get("gauges", [])]
    all_hists = [h for h in snap.get("histograms", [])]
    lock_hists = [h for h in all_hists if h["name"] == "lock_hold_us"]
    hists = [h for h in all_hists if h["name"] != "lock_hold_us"]
    lines.extend(render_lock_holds(lock_hists))
    lines.extend(render_decode_pipeline(counters))
    lines.extend(render_tier(counters, gauges))
    width = max(
        [len(_fmt_series(r)) for r in counters + gauges + hists] + [20]
    )
    if counters:
        lines.append("counters")
        for c in counters:
            lines.append(
                f"  {_fmt_series(c):<{width}}  {_fmt_num(c['value']):>16}"
            )
    if gauges:
        lines.append("gauges")
        for g in gauges:
            lines.append(
                f"  {_fmt_series(g):<{width}}  {_fmt_num(g['value']):>16}"
            )
    if hists:
        lines.append("histograms")
        for h in hists:
            total = h["count"]
            p50 = _percentile(h["edges"], h["counts"], total, 0.50)
            p95 = _percentile(h["edges"], h["counts"], total, 0.95)
            p99 = _percentile(h["edges"], h["counts"], total, 0.99)
            lines.append(
                f"  {_fmt_series(h):<{width}}  count={total} "
                f"sum={_fmt_num(h['sum'])} "
                f"p50~{p50:.3g} p95~{p95:.3g} p99~{p99:.3g}"
            )
            nonzero = []
            lo = 0.0
            for i, c in enumerate(h["counts"]):
                if i < len(h["edges"]):
                    span = f"[{lo:g}-{h['edges'][i]:g})"
                    lo = h["edges"][i]
                else:
                    span = f"[{lo:g}+)"
                if c:
                    nonzero.append(f"{span}: {c}")
            if nonzero:
                lines.append(f"    {', '.join(nonzero)}")
    if len(lines) <= (1 if title else 0):
        lines.append("(empty snapshot)")
    return "\n".join(lines)


def main(argv) -> int:
    if len(argv) not in (2, 3):
        print(__doc__.strip(), file=sys.stderr)
        return 2
    with open(argv[1]) as f:
        snap = json.load(f)
    title = f"metrics snapshot: {argv[1]}"
    if len(argv) == 3:
        with open(argv[2]) as f:
            base = json.load(f)
        snap = diff_snapshots(snap, base)
        title += f" (diff vs {argv[2]})"
    print(render(snap, title))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
