#!/usr/bin/env python
"""Render a metrics-registry snapshot as a human-readable table.

Usage:
    python tools/metrics_report.py SNAPSHOT [BASELINE] [--tenant NAME]

``SNAPSHOT``/``BASELINE`` each accept any of:

- a JSON snapshot file (``metricsJsonPath`` at manager stop, or
  ``sparkrdma_tpu.metrics.write_json_snapshot``),
- a Prometheus text-exposition file (``metricsPromPath``, or a saved
  ``curl`` of the live endpoint),
- an ``http(s)://`` URL — scraped live from a running manager's
  ``metricsHttpPort`` endpoint (qos/http.py).

With a baseline, renders ``SNAPSHOT - BASELINE`` (counter/histogram
deltas; gauges keep the new reading) so one run's activity can be
isolated from a warm process.

Histograms print count/sum plus approximate p50/p95/p99 interpolated
from the bucket counts, and the nonzero buckets.

``lock_hold_us`` histograms (the lockDebug sanitizer's hold-time
series, utils/dbglock.py) additionally render as one compact
"lock hold times" table — one row per lock, sorted by total held time —
so a snapshot diff shows exactly which locks a run leaned on.

Tenant-labeled QoS series (qos/broker.py) render as a per-tenant
summary table (bytes served/decoded, in-flight, credit-wait time,
admission rejections, degraded flag); ``--tenant NAME`` narrows every
table to that tenant's series.

Wire-validator series (utils/wiredbg.py, conf ``wireDebug``) render as
a wire-health table — frames validated/rejected per engine and opcode,
unknown-frame counts by kind, hello version rejections — so a snapshot
diff shows exactly what the frame validator saw during a run.

State-machine series (utils/statemachine.py, conf ``stateDebug``)
render as a per-machine lifecycle table — validated transitions with
the hottest edge, terminal-entry census, and any ILLEGAL transition
attempts the runtime validator refused — so a shaken soak's report
shows exactly which lifecycles moved and that none moved illegally.

Observability-plane series (obs/ + utils/trace.py) render as an
obs-health table — tracer events dropped at the ring cap
(``trace_dropped_total``, formerly a silent loss), flight-recorder
events dropped per plane (``obs_events_dropped_total``), recorder
dumps written by reason, and wire-version downgrades — so a run whose
trace or flight-recorder data is INCOMPLETE says so in the report
instead of rendering a silently truncated picture.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from sparkrdma_tpu.metrics import diff_snapshots  # noqa: E402


def _fmt_series(rec) -> str:
    labels = rec.get("labels") or {}
    if not labels:
        return rec["name"]
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{rec['name']}{{{inner}}}"


def _fmt_num(v: float) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return f"{int(f):,}"
    return f"{f:,.3f}"


def _percentile(edges, counts, total, q) -> float:
    """Approximate quantile from bucket counts: linear interpolation
    inside the bucket that crosses rank q*total (the overflow bucket
    reports its lower edge)."""
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0.0
    lo = 0.0
    for i, c in enumerate(counts):
        hi = edges[i] if i < len(edges) else lo
        if cum + c >= rank and c > 0:
            if i >= len(edges):
                return lo  # open-ended overflow bucket
            frac = (rank - cum) / c
            return lo + (hi - lo) * frac
        cum += c
        if i < len(edges):
            lo = edges[i]
    return lo


def _fmt_us(us: float) -> str:
    """Human microseconds: 850us, 12.4ms, 1.07s."""
    if us >= 1e6:
        return f"{us / 1e6:.2f}s"
    if us >= 1e3:
        return f"{us / 1e3:.1f}ms"
    return f"{us:.0f}us"


def render_lock_holds(hists: list) -> list:
    """Compact per-lock hold-time table over the ``lock_hold_us``
    series (written by the lockDebug sanitizer): acquire count, total
    held, mean and p99 hold — sorted by total held time so the
    heaviest lock tops the diff."""
    rows = []
    for h in hists:
        total = h["count"]
        if total <= 0:
            continue
        name = (h.get("labels") or {}).get("lock", "?")
        p50 = _percentile(h["edges"], h["counts"], total, 0.50)
        p99 = _percentile(h["edges"], h["counts"], total, 0.99)
        rows.append((h["sum"], name, total, p50, p99))
    if not rows:
        return []
    rows.sort(reverse=True)
    width = max(len(r[1]) for r in rows)
    out = ["lock hold times (lock_hold_us)"]
    for hsum, name, total, p50, p99 in rows:
        out.append(
            f"  {name:<{width}}  acquires={total:<8} "
            f"held={_fmt_us(hsum):>8}  mean={_fmt_us(hsum / total):>8}  "
            f"p50~{_fmt_us(p50):>8}  p99~{_fmt_us(p99):>8}"
        )
    return out


def parse_prometheus(text: str) -> dict:
    """Prometheus text exposition → the JSON-snapshot dict shape, so
    a live scrape renders (and diffs) exactly like a stop-time
    snapshot.  Histograms rebuild from their cumulative ``_bucket``
    series (the ``+Inf`` bucket becomes the overflow count)."""
    import re

    kinds = {}
    series = []  # (name, labels dict, value)
    lab_re = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3]
            continue
        series_str, _sp, value = line.rpartition(" ")
        if not series_str:
            continue
        name, labels = series_str, {}
        if "{" in series_str:
            name, rest = series_str.split("{", 1)
            labels = {
                k: v.replace('\\"', '"').replace("\\\\", "\\")
                for k, v in lab_re.findall(rest.rsplit("}", 1)[0])
            }
        try:
            series.append((name, labels, float(value)))
        except ValueError:
            continue
    out = {"counters": [], "gauges": [], "histograms": []}
    hists = {}
    for name, labels, value in series:
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) \
                    and kinds.get(name[: -len(suffix)]) == "histogram":
                base = name[: -len(suffix)]
                break
        if base is not None:
            key_labels = {k: v for k, v in labels.items() if k != "le"}
            key = (base, tuple(sorted(key_labels.items())))
            h = hists.setdefault(key, {
                "name": base, "labels": key_labels,
                "buckets": [], "sum": 0.0, "count": 0,
            })
            if name.endswith("_bucket"):
                le = labels.get("le", "+Inf")
                edge = float("inf") if le == "+Inf" else float(le)
                h["buckets"].append((edge, value))
            elif name.endswith("_sum"):
                h["sum"] = value
            else:
                h["count"] = int(value)
        elif kinds.get(name) == "gauge":
            out["gauges"].append(
                {"name": name, "labels": labels, "value": value}
            )
        else:
            out["counters"].append(
                {"name": name, "labels": labels, "value": value}
            )
    for h in hists.values():
        h["buckets"].sort(key=lambda ev: ev[0])
        edges = [e for e, _v in h["buckets"] if e != float("inf")]
        counts, prev = [], 0.0
        for _e, cum in h["buckets"]:
            counts.append(int(cum - prev))
            prev = cum
        out["histograms"].append({
            "name": h["name"], "labels": h["labels"], "edges": edges,
            "counts": counts, "sum": h["sum"], "count": h["count"],
        })
    return out


def load_snapshot(src: str) -> dict:
    """Load a snapshot from a JSON file, a Prometheus text file, or a
    live ``http(s)://`` scrape URL."""
    if src.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(src, timeout=10) as resp:
            text = resp.read().decode("utf-8", "replace")
    else:
        with open(src) as f:
            text = f.read()
    try:
        return json.loads(text)
    except ValueError:
        return parse_prometheus(text)


def render_tenants(counters: list, gauges: list) -> list:
    """Per-tenant QoS summary over the brokered instruments
    (qos/broker.py, qos/registry.py): bytes served (serve pool) and
    decoded (decode pool), live brokered in-flight bytes, total
    credit-wait time, admission rejections, and the degraded flag."""
    tenants: dict = {}

    def row(name):
        return tenants.setdefault(name, {
            "served": 0.0, "decoded": 0.0, "inflight": 0.0,
            "wait_ms": 0.0, "rejects": 0.0, "registered": 0.0,
            "degraded": 0.0,
        })

    for c in counters:
        labels = c.get("labels") or {}
        t = labels.get("tenant")
        if not t:
            continue
        r = row(t)
        if c["name"] == "qos_granted_bytes_total":
            pool = labels.get("pool", "")
            if pool == "serve":
                r["served"] += c["value"]
            elif pool == "decode":
                r["decoded"] += c["value"]
        elif c["name"] == "qos_credit_wait_ms_total":
            r["wait_ms"] += c["value"]
        elif c["name"] == "qos_admission_rejections_total":
            r["rejects"] += c["value"]
    for g in gauges:
        labels = g.get("labels") or {}
        t = labels.get("tenant")
        if not t:
            continue
        r = row(t)
        if g["name"] == "qos_in_flight_bytes":
            r["inflight"] += g["value"]
        elif g["name"] == "qos_tenant_registered_bytes":
            r["registered"] = g["value"]
        elif g["name"] == "qos_tenant_degraded":
            r["degraded"] = max(r["degraded"], g["value"])
    if not tenants:
        return []
    width = max(len(t) for t in tenants) + 2
    out = ["tenants (qos/)"]
    for name in sorted(tenants):
        r = tenants[name]
        flag = "  DEGRADED" if r["degraded"] else ""
        out.append(
            f"  {name:<{width}}"
            f"served={_fmt_num(r['served'])}B  "
            f"decoded={_fmt_num(r['decoded'])}B  "
            f"in-flight={_fmt_num(r['inflight'])}B  "
            f"registered={_fmt_num(r['registered'])}B  "
            f"credit-wait={_fmt_us(r['wait_ms'] * 1e3)}  "
            f"admission-rejects={r['rejects']:,.0f}{flag}"
        )
    return out


def filter_tenant(snap: dict, tenant: str) -> dict:
    """Keep only series labeled with this tenant (the --tenant view)."""
    def keep(rec):
        return (rec.get("labels") or {}).get("tenant") == tenant

    return {
        "ts": snap.get("ts"),
        "counters": [c for c in snap.get("counters", []) if keep(c)],
        "gauges": [g for g in snap.get("gauges", []) if keep(g)],
        "histograms": [
            h for h in snap.get("histograms", []) if keep(h)
        ],
    }


def render_decode_pipeline(counters: list) -> list:
    """Compact view of the reduce-side decode pipeline's instruments
    (shuffle/decode.py): decoded volume and time, the decode-ahead hit
    rate (tickets already decoded when the task thread asked) vs
    steals (task thread decoded inline because no worker had started),
    credit waits, and the task thread's wire-wait vs decode-wait
    split."""
    vals = {}
    for c in counters:
        if c.get("labels"):
            continue
        vals[c["name"]] = c["value"]
    tasks = vals.get("shuffle_decode_tasks_total", 0)
    if not tasks:
        return []
    hits = vals.get("shuffle_decode_ahead_hits_total", 0)
    steals = vals.get("shuffle_decode_steals_total", 0)
    out = ["decode pipeline (shuffle/decode.py)"]
    out.append(
        f"  decoded {_fmt_num(vals.get('shuffle_decode_bytes_total', 0))}B "
        f"in {tasks:,.0f} task(s), "
        f"{_fmt_us(vals.get('shuffle_decode_us_total', 0))} decode time"
    )
    out.append(
        f"  decode-ahead hits={hits:,.0f} ({hits / tasks:.0%})  "
        f"inline steals={steals:,.0f}  "
        f"credit waits={vals.get('shuffle_decode_credit_waits_total', 0):,.0f}  "
        f"block splits={vals.get('shuffle_decode_block_splits_total', 0):,.0f}"
    )
    wire = vals.get("shuffle_fetch_wait_ms_total")
    dec = vals.get("shuffle_decode_wait_ms_total")
    if wire is not None or dec is not None:
        out.append(
            f"  task-thread wait split: wire={_fmt_us((wire or 0) * 1e3)} "
            f"decode={_fmt_us((dec or 0) * 1e3)}"
        )
    return out


def render_tier(counters: list, gauges: list) -> list:
    """Compact view of the tiered block store (memory/tier.py): hot
    hit rate on the serve path, promote/demote traffic, prefetch
    usefulness (predicted blocks actually consumed hot), eviction
    refusals (pinned under an in-flight serve), and the bytes
    committed but never read (what lazy per-span registration saved
    over the old eager whole-output mmap)."""
    vals = {}
    for c in counters:
        if not c.get("labels"):
            vals[c["name"]] = c["value"]
    hits = vals.get("tier_hits_total", 0)
    misses = vals.get("tier_misses_total", 0)
    if not hits and not misses and not vals.get("tier_commit_bytes_total"):
        return []
    served = hits + misses
    out = ["tiered block store (memory/tier.py)"]
    hot = next(
        (g["value"] for g in gauges
         if g["name"] == "tier_hot_bytes" and not g.get("labels")), 0,
    )
    out.append(
        f"  committed {_fmt_num(vals.get('tier_commit_bytes_total', 0))}B"
        f"  hot now {_fmt_num(hot)}B"
        f"  never-read {_fmt_num(vals.get('tier_bytes_never_read_total', 0))}B"
    )
    rate = f" ({hits / served:.0%})" if served else ""
    out.append(
        f"  serves: hits={hits:,.0f}{rate}  misses={misses:,.0f}  "
        f"cold bytes={_fmt_num(vals.get('tier_cold_read_bytes_total', 0))}B"
    )
    out.append(
        f"  promote {vals.get('tier_promotes_total', 0):,.0f}"
        f"/{_fmt_num(vals.get('tier_promote_bytes_total', 0))}B  "
        f"demote {vals.get('tier_demotes_total', 0):,.0f}"
        f"/{_fmt_num(vals.get('tier_demote_bytes_total', 0))}B  "
        f"evict refusals={vals.get('tier_evict_refusals_total', 0):,.0f}"
    )
    pf = vals.get("tier_prefetch_tasks_total", 0)
    useful = vals.get("tier_prefetch_useful_total", 0)
    use = f" ({useful / pf:.0%} useful)" if pf else ""
    out.append(
        f"  prefetch tasks={pf:,.0f}{use}  "
        f"hint msgs={vals.get('tier_hint_msgs_total', 0):,.0f}  "
        f"hinted blocks={vals.get('tier_hint_blocks_total', 0):,.0f}"
    )
    return out


def render_resources(counters: list, gauges: list) -> list:
    """Resource-ledger census (utils/ledger.py, conf resourceDebug):
    one row per tracked resource — lifetime acquires, units still
    outstanding, units reported leaked at ledger stop — plus the
    global double-release count.  A healthy run shows zero in the
    outstanding and leaked columns."""
    rows: dict = {}

    def row(name):
        return rows.setdefault(
            name, {"acquires": 0.0, "outstanding": 0.0, "leaked": 0.0}
        )

    doubles = 0.0
    for c in counters:
        labels = c.get("labels") or {}
        if c["name"] == "resource_acquires_total" and "resource" in labels:
            row(labels["resource"])["acquires"] += c["value"]
        elif c["name"] == "resource_leaked_total" and "resource" in labels:
            row(labels["resource"])["leaked"] += c["value"]
        elif c["name"] == "resource_double_release_total":
            doubles += c["value"]
    for g in gauges:
        labels = g.get("labels") or {}
        if g["name"] == "resource_outstanding" and "resource" in labels:
            row(labels["resource"])["outstanding"] += g["value"]
    if not rows and not doubles:
        return []
    out = ["resource ledger (utils/ledger.py)"]
    width = max([len(r) for r in rows] + [8]) + 2
    for name in sorted(rows):
        r = rows[name]
        leak = (f"  LEAKED={r['leaked']:,.0f}" if r["leaked"] else "")
        out.append(
            f"  {name:<{width}}"
            f"acquires={r['acquires']:,.0f}  "
            f"outstanding={r['outstanding']:,.0f}{leak}"
        )
    if doubles:
        out.append(f"  double releases: {doubles:,.0f}")
    return out


def render_skew(counters: list, hists: list) -> list:
    """Skew-adaptive partitioning census (skew/): split decisions
    (partitions split, sub-blocks committed, bytes re-routed through
    sub-blocks), the write-time detection histogram
    (``skew_partition_bytes`` — the distribution the split threshold
    cuts), the writer-side split fan-out, and the reader's merge
    fan-in (sub-blocks re-sequenced per split partition).  A uniform
    run renders only the detection histogram; a Zipfian run with
    splitting on shows all four."""
    vals = {}
    for c in counters:
        if not c.get("labels"):
            vals[c["name"]] = c["value"]
    by_name = {
        h["name"]: h for h in hists if not h.get("labels")
    }
    detect = by_name.get("skew_partition_bytes")
    splits = vals.get("skew_partitions_split_total", 0)
    if (detect is None or detect["count"] <= 0) and not splits:
        return []
    out = ["skew-adaptive partitioning (skew/)"]
    out.append(
        f"  partitions split={splits:,.0f}  "
        f"sub-blocks={vals.get('skew_sub_blocks_total', 0):,.0f}  "
        f"split bytes={_fmt_num(vals.get('skew_split_bytes_total', 0))}B"
    )
    if detect is not None and detect["count"] > 0:
        n = detect["count"]
        p50 = _percentile(detect["edges"], detect["counts"], n, 0.50)
        p99 = _percentile(detect["edges"], detect["counts"], n, 0.99)
        line = (
            f"  detection: {n:,.0f} nonzero partition(s), "
            f"{_fmt_num(detect['sum'])}B total"
        )
        if p50 > 0:
            line += (
                f", p50~{_fmt_num(p50)}B p99~{_fmt_num(p99)}B "
                f"(p99/p50 {p99 / p50:.1f}x)"
            )
        out.append(line)
    for name, label in (
        ("skew_split_fanout", "writer split fan-out"),
        ("skew_merge_fanin", "reader merge fan-in"),
    ):
        h = by_name.get(name)
        if h is not None and h["count"] > 0:
            out.append(
                f"  {label}: {h['count']:,.0f} partition(s), "
                f"mean {h['sum'] / h['count']:.1f} sub-block(s)"
            )
    return out


def render_push(counters: list) -> list:
    """Push-based merged shuffle census (shuffle/push.py): the writer's
    push fan-out (sub-blocks and bytes pushed, local vs remote merger
    targets), the merger's assembly outcome (blocks and bytes merged,
    drops by reason — dup/late/cap/fault), and the reader's resulting
    RPC mix: fetch RPCs by mode (pull / push / location / merge_status)
    with the bytes they moved — the table the M×R→sequential claim is
    read off — plus the degradation rows (version skips, send failures,
    merge-status timeouts, merged-fetch fallbacks).  Renders nothing
    when push never engaged."""
    vals: dict = {}
    drops: dict = {}
    pushes: dict = {}
    rpcs: dict = {}
    rpc_bytes: dict = {}
    for c in counters:
        labels = c.get("labels") or {}
        if c["name"] == "push_drops_total" and "reason" in labels:
            drops[labels["reason"]] = (
                drops.get(labels["reason"], 0.0) + c["value"])
        elif c["name"] == "push_pushes_total" and "target" in labels:
            pushes[labels["target"]] = (
                pushes.get(labels["target"], 0.0) + c["value"])
        elif c["name"] == "shuffle_fetch_rpcs_total" and "mode" in labels:
            rpcs[labels["mode"]] = rpcs.get(labels["mode"], 0.0) + c["value"]
        elif c["name"] == "shuffle_fetch_rpc_bytes" and "mode" in labels:
            rpc_bytes[labels["mode"]] = (
                rpc_bytes.get(labels["mode"], 0.0) + c["value"])
        elif not labels:
            vals[c["name"]] = c["value"]
    pushed = vals.get("push_sub_blocks_sent_total", 0)
    merged = vals.get("push_merged_blocks_total", 0)
    if not pushed and not merged and not pushes:
        return []
    out = ["push-based merged shuffle (shuffle/push.py)"]
    out.append(
        f"  pushed: {pushed:,.0f} sub-block(s), "
        f"{_fmt_num(vals.get('push_bytes_sent_total', 0))}B  "
        f"(partitions local={pushes.get('local', 0):,.0f} "
        f"remote={pushes.get('remote', 0):,.0f})"
    )
    out.append(
        f"  merged: {merged:,.0f} block(s), "
        f"{_fmt_num(vals.get('push_merged_bytes_total', 0))}B"
    )
    if drops:
        per = "  ".join(
            f"{r}={n:,.0f}" for r, n in sorted(drops.items()))
        out.append(f"  merger drops: {per}")
    if rpcs:
        out.append("  reader fetch RPCs by mode:")
        for mode in sorted(rpcs):
            by = rpc_bytes.get(mode)
            tail = f"  {_fmt_num(by)}B" if by else ""
            out.append(f"    {mode:<13} {rpcs[mode]:>10,.0f}{tail}")
        pull, push = rpcs.get("pull", 0), rpcs.get("push", 0)
        if pull and push:
            # the headline: merged spans fetched vs the random pulls
            # that still happened — pure-push runs show pull=0 instead
            out.append(
                f"    push:pull ratio 1:{pull / push:.1f}"
            )
    degraded = []
    for name, label in (
        ("push_version_skips_total", "pre-v3 skips"),
        ("push_send_failures_total", "send failures"),
        ("push_merge_query_failures_total", "query failures"),
        ("push_merge_timeouts_total", "status timeouts"),
        ("push_merged_fetch_fallbacks_total", "fetch fallbacks"),
    ):
        n = vals.get(name, 0)
        if n:
            degraded.append(f"{label}={n:,.0f}")
    if degraded:
        out.append(f"  degradations: {'  '.join(degraded)}")
    return out


def render_recovery(counters: list) -> list:
    """Recovery census (faults/ + the reader retry plane): injected
    faults per point (conf ``faultInject``), in-task fetch retries and
    the backoff time they spent, terminal fetch failures, stripe
    demotions and per-peer breaker trips.  A fault-free run with
    retries enabled renders nothing — every counter here moves only
    when something actually failed."""
    injected: dict = {}
    trips_by_peer: dict = {}
    vals: dict = {}
    for c in counters:
        labels = c.get("labels") or {}
        if c["name"] == "fault_injected_total" and "point" in labels:
            injected[labels["point"]] = (
                injected.get(labels["point"], 0.0) + c["value"])
        elif c["name"] == "transport_breaker_trips_total":
            peer = labels.get("peer", "?")
            trips_by_peer[peer] = trips_by_peer.get(peer, 0.0) + c["value"]
        elif not labels:
            vals[c["name"]] = c["value"]
    retries = vals.get("shuffle_fetch_retries_total", 0)
    failures = vals.get("shuffle_fetch_failures_total", 0)
    demotions = vals.get("transport_stripe_demotions_total", 0)
    if not injected and not trips_by_peer and not retries \
            and not failures and not demotions \
            and not vals.get("transport_accept_transient_errors_total"):
        return []
    out = ["recovery (faults/ + in-task fetch retry)"]
    if injected:
        total = sum(injected.values())
        per_point = "  ".join(
            f"{p}={n:,.0f}" for p, n in sorted(injected.items()))
        out.append(f"  faults injected: {total:,.0f}  ({per_point})")
    out.append(
        f"  fetch retries={retries:,.0f}  "
        f"backoff={vals.get('shuffle_fetch_retry_ms_total', 0):,.0f}ms  "
        f"terminal failures={failures:,.0f}"
    )
    out.append(f"  stripe demotions={demotions:,.0f}")
    aborted = vals.get("transport_accept_transient_errors_total", 0)
    if aborted:
        out.append(f"  transient accept errors survived={aborted:,.0f}")
    if trips_by_peer:
        per_peer = "  ".join(
            f"{p}={n:,.0f}" for p, n in sorted(trips_by_peer.items()))
        out.append(f"  breaker trips: {per_peer}")
    return out


def render_wire_health(counters: list) -> list:
    """Wire-health census (utils/wiredbg.py, conf wireDebug): one row
    per engine/opcode pair — frames validated vs rejected — plus the
    unknown-frame counts by kind (bad opcode, unknown msg_type,
    malformed payload) and handshake version rejections.  A healthy
    run shows zeros everywhere right of the validated column."""
    rows: dict = {}
    unknowns: dict = {}
    version_rejects = 0.0
    for c in counters:
        labels = c.get("labels") or {}
        if c["name"] in (
            "wire_frames_validated_total", "wire_frames_rejected_total"
        ):
            key = (labels.get("engine", "?"), labels.get("opcode", "?"))
            r = rows.setdefault(key, {"validated": 0.0, "rejected": 0.0})
            field = (
                "validated"
                if c["name"] == "wire_frames_validated_total"
                else "rejected"
            )
            r[field] += c["value"]
        elif c["name"] == "wire_unknown_frames_total":
            k = (labels.get("engine", "?"), labels.get("kind", "?"))
            unknowns[k] = unknowns.get(k, 0.0) + c["value"]
        elif c["name"] == "wire_version_rejects_total":
            version_rejects += c["value"]
    if not rows and not unknowns and not version_rejects:
        return []
    out = ["wire health (utils/wiredbg.py)"]
    if rows:
        width = max(
            [len(f"{e}/{op}") for e, op in rows] + [12]
        ) + 2
        for (engine, opcode) in sorted(rows):
            r = rows[(engine, opcode)]
            rej = (
                f"  REJECTED={r['rejected']:,.0f}" if r["rejected"] else ""
            )
            out.append(
                f"  {f'{engine}/{opcode}':<{width}}"
                f"validated={r['validated']:,.0f}{rej}"
            )
    for (engine, kind) in sorted(unknowns):
        out.append(
            f"  unknown frames ({engine}, {kind}): "
            f"{unknowns[(engine, kind)]:,.0f}"
        )
    if version_rejects:
        out.append(f"  hello version rejects: {version_rejects:,.0f}")
    return out


def render_obs_health(counters: list) -> list:
    """Observability-plane census (obs/ + utils/trace.py): dropped
    tracer events, per-plane flight-recorder ring drops, dumps written
    by reason, and wire-version downgrades.  Nonzero drop rows mean
    the trace/recorder picture for the run is incomplete — size the
    rings up (``flightRecorderRingSize``) before trusting a report."""
    tracer_dropped = 0.0
    ring_drops: dict = {}
    dumps: dict = {}
    downgrades: dict = {}
    for c in counters:
        labels = c.get("labels") or {}
        if c["name"] == "trace_dropped_total":
            tracer_dropped += c["value"]
        elif c["name"] == "obs_events_dropped_total":
            plane = labels.get("plane", "?")
            ring_drops[plane] = ring_drops.get(plane, 0.0) + c["value"]
        elif c["name"] == "obs_dumps_total":
            reason = labels.get("reason", "?")
            dumps[reason] = dumps.get(reason, 0.0) + c["value"]
        elif c["name"] == "wire_version_downgrades_total":
            tr = labels.get("transport", "?")
            downgrades[tr] = downgrades.get(tr, 0.0) + c["value"]
    if not tracer_dropped and not ring_drops and not dumps \
            and not downgrades:
        return []
    out = ["observability health (obs/ + utils/trace.py)"]
    if tracer_dropped:
        out.append(
            f"  tracer events dropped at ring cap: {tracer_dropped:,.0f} "
            f"(trace incomplete — raise the tracer ring size)"
        )
    if ring_drops:
        per_plane = "  ".join(
            f"{p}={n:,.0f}" for p, n in sorted(ring_drops.items()))
        out.append(f"  flight-recorder ring drops: {per_plane}")
    if dumps:
        per_reason = "  ".join(
            f"{r}={n:,.0f}" for r, n in sorted(dumps.items()))
        out.append(f"  recorder dumps written: {per_reason}")
    if downgrades:
        per_tr = "  ".join(
            f"{t}={n:,.0f}" for t, n in sorted(downgrades.items()))
        out.append(f"  wire-version downgrades: {per_tr}")
    return out


def render_state_machines(counters: list) -> list:
    """Lifecycle state-machine census (utils/statemachine.py, conf
    stateDebug): one row per machine — validated transitions, terminal
    entries by state, and any ILLEGAL transition attempts the runtime
    validator refused.  The busiest edge per machine is named so a
    diff shows what a run's lifecycles actually did.  A healthy run
    shows zeros in the illegal column; renders nothing when the
    validator was off."""
    rows: dict = {}

    def row(machine):
        return rows.setdefault(machine, {
            "transitions": 0.0, "illegal": 0.0,
            "edges": {}, "terminal": {},
        })

    for c in counters:
        labels = c.get("labels") or {}
        m = labels.get("machine")
        if not m:
            continue
        if c["name"] == "state_transitions_total":
            r = row(m)
            r["transitions"] += c["value"]
            edge = f"{labels.get('from', '?')}->{labels.get('to', '?')}"
            r["edges"][edge] = r["edges"].get(edge, 0.0) + c["value"]
        elif c["name"] == "state_transitions_illegal_total":
            row(m)["illegal"] += c["value"]
        elif c["name"] == "state_terminal_total":
            r = row(m)
            st = labels.get("state", "?")
            r["terminal"][st] = r["terminal"].get(st, 0.0) + c["value"]
    if not rows:
        return []
    out = ["state machines (utils/statemachine.py)"]
    width = max([len(m) for m in rows] + [16]) + 2
    for machine in sorted(rows):
        r = rows[machine]
        hot = max(r["edges"].items(), key=lambda kv: kv[1]) \
            if r["edges"] else None
        term = "  ".join(
            f"{s}={n:,.0f}" for s, n in sorted(r["terminal"].items()))
        line = (
            f"  {machine:<{width}}"
            f"transitions={r['transitions']:,.0f}"
        )
        if hot is not None:
            line += f"  top={hot[0]} ({hot[1]:,.0f})"
        if term:
            line += f"  terminal: {term}"
        if r["illegal"]:
            line += f"  ILLEGAL={r['illegal']:,.0f}"
        out.append(line)
    return out


def render(snap: dict, title: str = "") -> str:
    lines = []
    if title:
        lines.append(title)
    counters = [c for c in snap.get("counters", [])]
    gauges = [g for g in snap.get("gauges", [])]
    all_hists = [h for h in snap.get("histograms", [])]
    lock_hists = [h for h in all_hists if h["name"] == "lock_hold_us"]
    hists = [h for h in all_hists if h["name"] != "lock_hold_us"]
    lines.extend(render_lock_holds(lock_hists))
    lines.extend(render_tenants(counters, gauges))
    lines.extend(render_decode_pipeline(counters))
    lines.extend(render_tier(counters, gauges))
    lines.extend(render_resources(counters, gauges))
    lines.extend(render_skew(counters, hists))
    lines.extend(render_push(counters))
    lines.extend(render_recovery(counters))
    lines.extend(render_wire_health(counters))
    lines.extend(render_state_machines(counters))
    lines.extend(render_obs_health(counters))
    width = max(
        [len(_fmt_series(r)) for r in counters + gauges + hists] + [20]
    )
    if counters:
        lines.append("counters")
        for c in counters:
            lines.append(
                f"  {_fmt_series(c):<{width}}  {_fmt_num(c['value']):>16}"
            )
    if gauges:
        lines.append("gauges")
        for g in gauges:
            lines.append(
                f"  {_fmt_series(g):<{width}}  {_fmt_num(g['value']):>16}"
            )
    if hists:
        lines.append("histograms")
        for h in hists:
            total = h["count"]
            p50 = _percentile(h["edges"], h["counts"], total, 0.50)
            p95 = _percentile(h["edges"], h["counts"], total, 0.95)
            p99 = _percentile(h["edges"], h["counts"], total, 0.99)
            lines.append(
                f"  {_fmt_series(h):<{width}}  count={total} "
                f"sum={_fmt_num(h['sum'])} "
                f"p50~{p50:.3g} p95~{p95:.3g} p99~{p99:.3g}"
            )
            nonzero = []
            lo = 0.0
            for i, c in enumerate(h["counts"]):
                if i < len(h["edges"]):
                    span = f"[{lo:g}-{h['edges'][i]:g})"
                    lo = h["edges"][i]
                else:
                    span = f"[{lo:g}+)"
                if c:
                    nonzero.append(f"{span}: {c}")
            if nonzero:
                lines.append(f"    {', '.join(nonzero)}")
    if len(lines) <= (1 if title else 0):
        lines.append("(empty snapshot)")
    return "\n".join(lines)


def main(argv) -> int:
    args = list(argv[1:])
    tenant = None
    if "--tenant" in args:
        i = args.index("--tenant")
        try:
            tenant = args[i + 1]
        except IndexError:
            print("--tenant needs a name", file=sys.stderr)
            return 2
        del args[i : i + 2]
    if len(args) not in (1, 2):
        print(__doc__.strip(), file=sys.stderr)
        return 2
    snap = load_snapshot(args[0])
    title = f"metrics snapshot: {args[0]}"
    if len(args) == 2:
        base = load_snapshot(args[1])
        snap = diff_snapshots(snap, base)
        title += f" (diff vs {args[1]})"
    if tenant is not None:
        snap = filter_tenant(snap, tenant)
        title += f" (tenant={tenant})"
    print(render(snap, title))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
