#!/usr/bin/env python
"""Resource-lifecycle analyzer — the leak/double-release gate.

``make lint`` runs this next to tools/lint.py and tools/concheck.py.
The library hands out a deep hierarchy of countable resources — serve
credits, lane tokens, tier view pins, reader in-flight window bytes,
arena registered bytes, accepted/connected fds, dispatcher send
descriptors, QoS admitted bytes — and the review history shows nearly
every one of them shipped (or nearly shipped) a lifecycle bug found by
hand.  fabric-lib makes descriptor lifecycle — post, complete exactly
once, never leak a posted op — the core correctness contract of an
RDMA transport; this pass turns that contract into a machine-checked
invariant, exactly as concheck/dbglock did for lock ordering.  The
runtime half is sparkrdma_tpu/utils/ledger.py (conf
``spark.shuffle.tpu.resourceDebug``).

Every resource is DECLARED once (the census registry) and every
acquire/release site carries a trailing annotation; the pass checks:

  FC01  acquire without release on all paths: a function that acquires
        a declared resource must release it in a ``finally`` suite, or
        register the release as a finalizer (a ``*.finalize(...)`` call
        annotated as the release site), or explicitly hand the duty on
        with an ownership-transfer annotation
        ``# owns: <resource> -> <function-or-Class.method>``.
  FC02  double release: two releases of the same resource reachable on
        one path — sequentially in one suite, or once in a try body /
        except handler AND again in that try's ``finally`` — without a
        ``# one-shot`` guard annotation on either site.
  FC03  release under wrong conditions: a function releases a resource
        it never acquired, and no ownership-transfer annotation
        anywhere in the tree names it as the receiver.
  FC04  unannotated resource: an ``# acquires:`` / ``# releases:`` /
        ``# owns:`` annotation names a resource that no
        ``# resource:`` declaration registers — the census must stay
        complete (the CK04 idiom).

Annotation grammar::

    self._pool = _LanePool(n)        # resource: node.lane_tokens
    got = pool.try_borrow(want)      # acquires: node.lane_tokens
    pool.release(got)                # releases: node.lane_tokens
    weakref.finalize(v, unpin, b)    # releases: tier.pins
    token.pop().release()            # releases: serve.credits  # one-shot
    n = pool.try_borrow(w)  # acquires: x  # owns: x -> release_lanes

``# acquires:`` / ``# releases:`` take a comma-separated resource
list and must trail the statement (any line of a multi-line
statement's span).  ``# owns:`` may trail any statement line of the
owning function; the named receiver is matched by bare function name
or ``Class.method``.  A ``# one-shot`` on a release statement marks a
guarded (at-most-once) release closure, escaping FC02.

Suppressions are code-scoped: ``# noqa: FC01`` silences only FC01 on
that line; a bare ``# noqa`` silences everything (discouraged).

Usage: ``python tools/flowcheck.py [paths...]`` (default: the
library).  Exit status 1 on any finding; on success prints the
resource census.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

ROOT = pathlib.Path(__file__).resolve().parent.parent
LIB = ROOT / "sparkrdma_tpu"

_NAME = r"[A-Za-z_][A-Za-z0-9_.\-]*"
RES_RE = re.compile(rf"#\s*resource:\s*({_NAME})")
ACQ_RE = re.compile(rf"#\s*acquires:\s*({_NAME}(?:\s*,\s*{_NAME})*)")
REL_RE = re.compile(rf"#\s*releases:\s*({_NAME}(?:\s*,\s*{_NAME})*)")
OWNS_RE = re.compile(
    rf"#\s*owns:\s*({_NAME})\s*->\s*([A-Za-z_][A-Za-z0-9_.]*)"
)
ONESHOT_RE = re.compile(r"#\s*one-shot\b")

# the shared gate plumbing (noqa grammar, finding shape, file walking,
# span helpers) lives in tools/gatelib.py; the historical local names
# are bound here so the analysis passes and the gate's tests read
# unchanged
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
from gatelib import (  # noqa: E402
    Finding,
    Suppressor as _Suppressor,
    stmt_header_span as _stmt_header_span,
    string_lines as _string_lines,
    walk_py as _walk_py,
)


class Site:
    """One annotated acquire or release statement."""

    __slots__ = ("resources", "line", "in_finally", "suite_id",
                 "prot_trys", "fin_trys", "one_shot", "is_finalizer")

    def __init__(self, resources: List[str], line: int,
                 in_finally: bool, suite_id: int,
                 prot_trys: frozenset, fin_trys: frozenset,
                 one_shot: bool, is_finalizer: bool):
        self.resources = resources
        self.line = line
        self.in_finally = in_finally
        self.suite_id = suite_id
        self.prot_trys = prot_trys  # try-nodes this site is protected by
        self.fin_trys = fin_trys    # try-nodes whose finally holds it
        self.one_shot = one_shot
        self.is_finalizer = is_finalizer


class FnInfo:
    """Lifecycle sites of one function/method (nested defs get their
    own FnInfo under their actual def name, so closure receivers like
    ``release_lanes`` are addressable ownership-transfer targets)."""

    def __init__(self, rel: str, cls_name: str, fn_name: str,
                 line: int):
        self.rel = rel
        self.cls_name = cls_name
        self.fn_name = fn_name
        self.line = line
        self.acquires: List[Site] = []
        self.releases: List[Site] = []
        # resource -> receiver names this function hands the duty to
        self.owns: Dict[str, Set[str]] = {}
        self.owns_lines: Dict[str, int] = {}


_COMPOUND = (ast.If, ast.While, ast.For, ast.AsyncFor, ast.With,
             ast.AsyncWith, ast.Try)


def _split_names(spec: str) -> List[str]:
    return [s.strip() for s in spec.split(",") if s.strip()]


def _span_find(pattern: re.Pattern, lines: List[str], lo: int,
               hi: int, skip: Set[int] = frozenset()
               ) -> Optional[re.Match]:
    for i in range(lo, hi + 1):
        if i <= len(lines) and i not in skip:
            m = pattern.search(lines[i - 1])
            if m is not None:
                return m
    return None


def _is_docstring(stmt: ast.stmt) -> bool:
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, str))


def _has_finalize_call(stmt: ast.stmt) -> bool:
    """The statement registers a finalizer (``weakref.finalize(...)``
    or any ``*.finalize(...)`` / ``finalize(...)`` call) — a release
    annotation on it means 'released by the finalizer', which counts
    as released-on-all-paths for FC01."""
    if isinstance(stmt, _COMPOUND):
        return False
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if name == "finalize":
                return True
    return False


class _FnWalk:
    """Walk one function body collecting annotated lifecycle sites
    with their control-flow context (finally-ness, suite identity,
    try-structure membership).  Nested defs are queued and scanned as
    their own functions; their line spans are excluded from this
    function's ownership-transfer scan."""

    def __init__(self, mod: "ModuleInfo", info: FnInfo):
        self.mod = mod
        self.info = info
        self.suite_counter = 0
        self.nested: List[ast.stmt] = []

    def walk_suite(self, body: List[ast.stmt], in_finally: bool,
                   prot: frozenset, fin: frozenset) -> None:
        self.suite_counter += 1
        sid = self.suite_counter
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self.nested.append(stmt)
                continue
            if isinstance(stmt, ast.ClassDef):
                continue  # methods scanned under their own class pass
            if not _is_docstring(stmt):
                self._collect_sites(stmt, in_finally, sid, prot, fin)
            self._recurse(stmt, in_finally, prot, fin)

    def _collect_sites(self, stmt: ast.stmt, in_finally: bool,
                       sid: int, prot: frozenset,
                       fin: frozenset) -> None:
        lo, hi = _stmt_header_span(stmt)
        lines = self.mod.lines
        skip = self.mod.string_lines
        acq = _span_find(ACQ_RE, lines, lo, hi, skip)
        rel = _span_find(REL_RE, lines, lo, hi, skip)
        if acq is None and rel is None:
            return
        one_shot = _span_find(ONESHOT_RE, lines, lo, hi, skip) is not None
        if acq is not None:
            self.info.acquires.append(Site(
                _split_names(acq.group(1)), lo, in_finally, sid,
                prot, fin, one_shot, False,
            ))
        if rel is not None:
            self.info.releases.append(Site(
                _split_names(rel.group(1)), lo, in_finally, sid,
                prot, fin, one_shot, _has_finalize_call(stmt),
            ))

    def _recurse(self, stmt: ast.stmt, in_finally: bool,
                 prot: frozenset, fin: frozenset) -> None:
        if isinstance(stmt, ast.Try):
            tid = id(stmt)
            tprot = prot | {tid}
            self.walk_suite(stmt.body, in_finally, tprot, fin)
            for h in stmt.handlers:
                self.walk_suite(h.body, in_finally, tprot, fin)
            self.walk_suite(stmt.orelse, in_finally, tprot, fin)
            self.walk_suite(stmt.finalbody, True, prot, fin | {tid})
        elif isinstance(stmt, (ast.If, ast.While, ast.For,
                               ast.AsyncFor)):
            self.walk_suite(stmt.body, in_finally, prot, fin)
            self.walk_suite(stmt.orelse, in_finally, prot, fin)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self.walk_suite(stmt.body, in_finally, prot, fin)
        elif hasattr(ast, "Match") and isinstance(stmt, ast.Match):
            for case in stmt.cases:
                self.walk_suite(case.body, in_finally, prot, fin)


class ModuleInfo:
    def __init__(self, rel: str, lines: List[str], tree: ast.Module):
        self.rel = rel
        self.lines = lines
        self.tree = tree
        self.string_lines = _string_lines(tree)


class Analyzer:
    def __init__(self, root: pathlib.Path = ROOT):
        self.root = root
        self.findings: List[Finding] = []
        self.modules: Dict[str, ModuleInfo] = {}
        # resource name -> first declaration site (rel, line)
        self.decls: Dict[str, Tuple[str, int]] = {}
        self.fns: List[FnInfo] = []
        # resource -> receiver names granted the release duty
        self.owns_targets: Dict[str, Set[str]] = {}
        self._sups: Dict[str, _Suppressor] = {}

    def emit(self, rel: str, line: int, code: str, msg: str) -> None:
        sup = self._sups.get(rel)
        if sup is not None and sup.suppressed(line, code):
            return
        self.findings.append((rel, line, code, msg))

    # -- entry points --------------------------------------------------------
    def analyze_paths(self, paths) -> List[Finding]:
        files = _walk_py(paths)
        for f in files:
            self._load(f)
        for mod in self.modules.values():
            self._scan_module(mod)
        self._rule_checks()
        self.findings.sort(key=lambda x: (str(x[0]), x[1], x[2]))
        return self.findings

    def _rel(self, path: pathlib.Path) -> str:
        try:
            return str(path.relative_to(self.root))
        except ValueError:
            return str(path)

    def _load(self, path: pathlib.Path) -> None:
        rel = self._rel(path)
        try:
            text = path.read_text()
            tree = ast.parse(text)
        except (UnicodeDecodeError, SyntaxError):
            return  # tools/lint.py owns PY01
        lines = text.splitlines()
        self._sups[rel] = _Suppressor(lines)
        self.modules[rel] = ModuleInfo(rel, lines, tree)
        # pass 1: the declaration registry (raw-line scan — a
        # declaration may trail any statement, including class bodies)
        mod = self.modules[rel]
        for i, line in enumerate(lines, 1):
            if i in mod.string_lines:
                continue
            m = RES_RE.search(line)
            if m is not None:
                self.decls.setdefault(m.group(1), (rel, i))

    # -- pass 2: per-function site collection --------------------------------
    def _scan_module(self, mod: ModuleInfo) -> None:
        for stmt in mod.tree.body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self._scan_fn(mod, "", stmt)
        for stmt in ast.walk(mod.tree):
            if isinstance(stmt, ast.ClassDef):
                for item in stmt.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._scan_fn(mod, stmt.name, item)

    def _scan_fn(self, mod: ModuleInfo, cls_name: str, node) -> None:
        queued = [node]
        seen = 0
        while seen < len(queued):
            fn = queued[seen]
            seen += 1
            info = FnInfo(mod.rel, cls_name, fn.name, fn.lineno)
            walker = _FnWalk(mod, info)
            walker.walk_suite(fn.body, False, frozenset(), frozenset())
            self._collect_owns(mod, info, fn, walker.nested)
            self.fns.append(info)
            queued.extend(walker.nested)

    def _collect_owns(self, mod: ModuleInfo, info: FnInfo, fn,
                      nested: List[ast.stmt]) -> None:
        """Ownership-transfer annotations over the function's own line
        span, excluding nested defs' spans (those own their lines)."""
        skip: Set[int] = set(mod.string_lines)
        for n in nested:
            skip.update(range(n.lineno, (n.end_lineno or n.lineno) + 1))
        for i in range(fn.lineno, (fn.end_lineno or fn.lineno) + 1):
            if i in skip or i > len(mod.lines):
                continue
            for m in OWNS_RE.finditer(mod.lines[i - 1]):
                resource, target = m.group(1), m.group(2)
                info.owns.setdefault(resource, set()).add(target)
                info.owns_lines.setdefault(resource, i)
                self.owns_targets.setdefault(resource, set()).add(
                    target
                )

    # -- rule evaluation -----------------------------------------------------
    def _rule_checks(self) -> None:
        for fn in self.fns:
            self._check_fc04(fn)
            self._check_fc01(fn)
            self._check_fc02(fn)
            self._check_fc03(fn)

    def _check_fc04(self, fn: FnInfo) -> None:
        for site in fn.acquires + fn.releases:
            for r in site.resources:
                if r not in self.decls:
                    self.emit(
                        fn.rel, site.line, "FC04",
                        f"annotation names undeclared resource {r} — "
                        f"register it with a '# resource: {r}' "
                        f"declaration so the census stays complete",
                    )
        for r, line in fn.owns_lines.items():
            if r not in self.decls:
                self.emit(
                    fn.rel, line, "FC04",
                    f"ownership transfer names undeclared resource "
                    f"{r} — register it with a '# resource: {r}' "
                    f"declaration",
                )

    def _check_fc01(self, fn: FnInfo) -> None:
        for site in fn.acquires:
            for r in site.resources:
                if r not in self.decls:
                    continue  # FC04 already said it
                released = any(
                    r in rs.resources
                    and (rs.in_finally or rs.is_finalizer)
                    for rs in fn.releases
                )
                if released or r in fn.owns:
                    continue
                self.emit(
                    fn.rel, site.line, "FC01",
                    f"{r} acquired here but not released on all "
                    f"paths — release it in a finally, register the "
                    f"release as a finalizer, or annotate the "
                    f"handoff with '# owns: {r} -> <receiver>'",
                )

    def _check_fc02(self, fn: FnInfo) -> None:
        by_res: Dict[str, List[Site]] = {}
        for site in fn.releases:
            if site.is_finalizer:
                continue  # a registration, not an immediate release
            for r in site.resources:
                by_res.setdefault(r, []).append(site)
        for r, sites in by_res.items():
            sites.sort(key=lambda s: s.line)
            for i, a in enumerate(sites):
                for b in sites[i + 1:]:
                    if a.one_shot or b.one_shot:
                        continue
                    if a.suite_id == b.suite_id:
                        self.emit(
                            fn.rel, b.line, "FC02",
                            f"{r} released twice on one path (also "
                            f"released at line {a.line}) — guard one "
                            f"site or annotate the guarded closure "
                            f"with '# one-shot'",
                        )
                    elif a.prot_trys & b.fin_trys:
                        self.emit(
                            fn.rel, b.line, "FC02",
                            f"{r} released in this finally AND in its "
                            f"protected region (line {a.line}) — both "
                            f"run on the non-raising path; guard one "
                            f"site or annotate '# one-shot'",
                        )

    def _check_fc03(self, fn: FnInfo) -> None:
        acquired: Set[str] = set()
        for site in fn.acquires:
            acquired.update(site.resources)
        names = {fn.fn_name}
        if fn.cls_name:
            names.add(f"{fn.cls_name}.{fn.fn_name}")
        for site in fn.releases:
            for r in site.resources:
                if r not in self.decls or r in acquired:
                    continue
                if names & self.owns_targets.get(r, set()):
                    continue
                self.emit(
                    fn.rel, site.line, "FC03",
                    f"{r} released here but never acquired in "
                    f"{fn.fn_name}(), and no '# owns: {r} -> "
                    f"{fn.fn_name}' transfer annotation hands it in",
                )


def analyze(paths, root: pathlib.Path = ROOT) -> List[Finding]:
    return Analyzer(root=root).analyze_paths(paths)


def main(argv) -> int:
    paths = [pathlib.Path(a) for a in argv[1:]] or [LIB]
    an = Analyzer()
    findings = an.analyze_paths(paths)
    for rel, line, code, msg in findings:
        print(f"{rel}:{line}: {code} {msg}")
    if findings:
        print(f"flowcheck: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    n_acq = sum(len(f.acquires) for f in an.fns)
    n_rel = sum(len(f.releases) for f in an.fns)
    print(f"flowcheck: clean ({len(an.decls)} resource(s) declared, "
          f"{n_acq} acquire / {n_rel} release site(s) balanced)")
    for name in sorted(an.decls):
        rel, line = an.decls[name]
        print(f"  {name:28s} {rel}:{line}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
