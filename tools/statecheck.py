#!/usr/bin/env python
"""Lifecycle state-machine analyzer — the transition-discipline gate.

``make lint`` runs this fifth, next to lint/concheck/flowcheck/
wirecheck.  The library's lifecycle-bearing objects (channel connect
states, the dispatcher's send ops and recv framing machine, decode
tickets/streams, push merges, breaker trip states, tier residency,
reader phases, manager/cluster teardown, ledger tickets) each declare
an explicit machine — ``STATES`` / ``INITIAL`` / ``TERMINAL`` /
``TRANSITIONS`` class attributes bound to a state field by a
``# state:`` annotation on its ``__init__`` seeding line — and
``sparkrdma_tpu/utils/statemachine.py`` validates the same tables at
runtime under conf ``stateDebug``.  This pass discovers every declared
machine and enforces:

  SC01  raw state write: a declared state field may only be assigned
        inside a ``_transition()`` helper (the ``StateMachine`` mixin
        or a hand-rolled ``_transition_<table>``), or on its annotated
        ``__init__`` seeding line.  Any other store — even of a legal
        state — bypasses the runtime validator, the transition
        counters, and the schedule shaker.  Deliberate raw writes
        carry a justified ``# noqa: SC01``.
  SC02  undeclared transition: every statically-resolvable
        ``_transition(X)`` / ``_transition(X, frm=Y)`` call site must
        name a declared state, and with ``frm=`` given the edge
        ``Y -> X`` must exist in the table (self-edges are legal
        no-ops).  The seeded initial value must equal ``INITIAL``.
        Arguments that do not resolve to a constant (variables,
        parameters) are the runtime validator's job and are skipped.
  SC03  unguarded branch read: a machine declaring
        ``guarded-by: <lock>`` promises its state is only *branched
        on* while that lock is held — inside the declaring class for
        own-class guards, and inside the owning class for
        ``Owner._lock``-style external guards (non-``self``
        receivers).  Reads in ``__init__`` and ``_transition*``
        helpers are exempt; deliberate racy reads carry a justified
        ``# noqa: SC03``.
  SC04  terminal escape: a ``TRANSITIONS`` table with an outgoing
        edge from a declared ``TERMINAL`` state, a call site
        transitioning ``frm=`` a terminal state, or a second
        transition lexically following a terminal-entering one on the
        same straight-line path.
  SC05  undeclared machine: a ``# state:`` annotation whose class has
        no (or an inconsistent) table — missing ``STATES`` /
        ``TRANSITIONS``, a ``MACHINE`` name disagreeing with the
        annotation, tokens outside ``STATES``, or an unresolvable
        ``INITIAL``.

Annotation grammar (the seeding line in ``__init__``)::

    self._state = "closed"  # state: faults.breaker guarded-by: _lock
    self._state = _QUEUED   # state: decode.ticket guarded-by: DecodePool._cv
    self._rx_state = self._HDR  # state: channel.recv table: RX

``table: RX`` binds the field to the prefixed ``RX_STATES`` /
``RX_TRANSITIONS`` attributes (a class hosting a secondary machine)
and to the hand-rolled ``_transition_rx`` helper.  ``guarded-by:``
takes either an own-class lock attribute or ``OwnerClass.attr`` when
the object's state is guarded by another class's lock (tickets under
their pool's condition, merges under their merger's lock).

State tokens resolve through string literals, module/class constants
(including tuple unpacks), and ``EnumClass.MEMBER`` (lowered member
name — the runtime's ``state_token``).

Suppressions are code-scoped: ``# noqa: SC01`` silences only SC01 on
that line; a bare ``# noqa`` silences everything (discouraged).

Usage: ``python tools/statecheck.py [paths...]`` (default: the
library).  Exit status 1 on any finding.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

ROOT = pathlib.Path(__file__).resolve().parent.parent
LIB = ROOT / "sparkrdma_tpu"

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
from gatelib import (  # noqa: E402
    COMPOUND_STMTS,
    Finding,
    Suppressor,
    span_search,
    walk_py,
)

STATE_RE = re.compile(
    r"#\s*state:\s*(?P<name>[A-Za-z_][\w.\-]*)"
    r"(?:\s+table:\s*(?P<table>[A-Za-z_]\w*))?"
    r"(?:\s+guarded-by:\s*(?P<guard>[A-Za-z_]\w*(?:\.[A-Za-z_]\w*)?))?"
)
TRANSITION_HELPER_RE = re.compile(r"^_transition(?:_(?P<suffix>\w+))?$")

# the runtime half: its mixin IS the blessed writer, so its own store
# through setattr / its docstring grammar examples are never findings
RUNTIME_MODULE = "statemachine.py"


class Machine:
    """One declared machine: annotation + resolved table."""

    __slots__ = ("name", "rel", "cls_name", "field", "prefix", "guard",
                 "guard_owner", "guard_attr", "states", "initial",
                 "terminal", "transitions", "decl_line", "table_line",
                 "seed_token", "seed_fn", "complete")

    def __init__(self, name: str, rel: str, cls_name: str, field: str,
                 prefix: str, guard: Optional[str], decl_line: int):
        self.name = name
        self.rel = rel
        self.cls_name = cls_name
        self.field = field
        self.prefix = prefix  # "" or e.g. "RX_"
        self.guard = guard
        self.guard_owner: Optional[str] = None
        self.guard_attr: Optional[str] = None
        if guard is not None:
            if "." in guard:
                self.guard_owner, self.guard_attr = guard.split(".", 1)
            else:
                self.guard_attr = guard
        self.states: Set[str] = set()
        self.initial: Optional[str] = None
        self.terminal: Set[str] = set()
        self.transitions: Dict[str, Tuple[str, ...]] = {}
        self.decl_line = decl_line
        self.table_line = decl_line
        self.seed_token: Optional[str] = None
        self.seed_fn: Optional[str] = None
        self.complete = False

    def dests(self) -> Set[str]:
        out: Set[str] = set()
        for vals in self.transitions.values():
            out.update(vals)
        return out


class _Consts:
    """Constant-resolution index for one module: module/class string
    constants (incl. tuple unpacks) and enum classes."""

    def __init__(self, tree: ast.Module):
        self.mod: Dict[str, str] = {}
        self.cls: Dict[str, Dict[str, str]] = {}
        self.enums: Set[str] = set()
        self._collect(tree.body, self.mod)
        for stmt in ast.walk(tree):
            if not isinstance(stmt, ast.ClassDef):
                continue
            if any(
                (isinstance(b, ast.Attribute) and b.attr in
                 ("Enum", "IntEnum", "Flag", "IntFlag"))
                or (isinstance(b, ast.Name) and b.id in
                    ("Enum", "IntEnum", "Flag", "IntFlag"))
                for b in stmt.bases
            ):
                self.enums.add(stmt.name)
                continue
            table = self.cls.setdefault(stmt.name, {})
            self._collect(stmt.body, table)

    @staticmethod
    def _collect(body, table: Dict[str, str]) -> None:
        for stmt in body:
            if not isinstance(stmt, ast.Assign):
                continue
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name) \
                        and isinstance(stmt.value, ast.Constant) \
                        and isinstance(stmt.value.value, str):
                    table[tgt.id] = stmt.value.value
                elif isinstance(tgt, ast.Tuple) \
                        and isinstance(stmt.value, (ast.Tuple, ast.List)) \
                        and len(tgt.elts) == len(stmt.value.elts):
                    for name, val in zip(tgt.elts, stmt.value.elts):
                        if isinstance(name, ast.Name) \
                                and isinstance(val, ast.Constant) \
                                and isinstance(val.value, str):
                            table[name.id] = val.value

    def token(self, node: ast.expr, cls_name: Optional[str],
              class_scope: bool = False) -> Optional[str]:
        """Resolve an expression to a state token, or None (dynamic).
        ``class_scope`` is set when resolving CLASS-BODY expressions,
        where bare names see the class's own constants; method bodies
        do not (python scoping), so call sites resolve module-only."""
        if isinstance(node, ast.Constant):
            return node.value if isinstance(node.value, str) else None
        if isinstance(node, ast.Name):
            if class_scope and cls_name is not None:
                got = self.cls.get(cls_name, {}).get(node.id)
                if got is not None:
                    return got
            return self.mod.get(node.id)
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name):
            recv = node.value.id
            if recv in self.enums:
                # the runtime's state_token: member NAME, lowered
                return node.attr.lower()
            if recv == "self" and cls_name is not None:
                return self.cls.get(cls_name, {}).get(node.attr)
            return self.cls.get(recv, {}).get(node.attr)
        return None


class ModuleScan:
    def __init__(self, rel: str, tree: ast.Module, lines: List[str]):
        self.rel = rel
        self.tree = tree
        self.lines = lines
        self.consts = _Consts(tree)
        self.machines: List[Machine] = []
        # field -> machines declaring it (SC01's write index)
        self.fields: Dict[str, List[Machine]] = {}
        # class name -> machines it declares
        self.by_class: Dict[str, List[Machine]] = {}


class Analyzer:
    def __init__(self, root: pathlib.Path = ROOT):
        self.root = root
        self.findings: List[Finding] = []
        self.modules: Dict[str, ModuleScan] = {}
        self._sups: Dict[str, Suppressor] = {}
        self.transition_sites = 0

    # -- plumbing ------------------------------------------------------------
    def emit(self, rel: str, line: int, code: str, msg: str) -> None:
        sup = self._sups.get(rel)
        if sup is not None and sup.suppressed(line, code):
            return
        self.findings.append(Finding(rel, line, code, msg))

    def _rel(self, path: pathlib.Path) -> str:
        try:
            return str(path.relative_to(self.root))
        except ValueError:
            return str(path)

    # -- entry ---------------------------------------------------------------
    def analyze_paths(self, paths) -> List[Finding]:
        files = walk_py(paths)
        for f in files:
            self._load(f)
        for scan in self.modules.values():
            self._check_module(scan)
        self.findings.sort(key=lambda x: (str(x[0]), x[1], x[2]))
        return self.findings

    @property
    def machines(self) -> List[Machine]:
        out: List[Machine] = []
        for scan in self.modules.values():
            out.extend(scan.machines)
        return out

    # -- collection ----------------------------------------------------------
    def _load(self, path: pathlib.Path) -> None:
        rel = self._rel(path)
        if path.name == RUNTIME_MODULE:
            return  # the validator itself: grammar examples, setattr
        try:
            text = path.read_text()
            tree = ast.parse(text)
        except (UnicodeDecodeError, SyntaxError):
            return  # tools/lint.py owns PY01
        lines = text.splitlines()
        self._sups[rel] = Suppressor(lines)
        scan = ModuleScan(rel, tree, lines)
        for stmt in ast.walk(tree):
            if isinstance(stmt, ast.ClassDef):
                self._collect_class(scan, stmt)
        self.modules[rel] = scan

    def _collect_class(self, scan: ModuleScan, cls: ast.ClassDef) -> None:
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(item):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                tgt = node.targets[0]
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                m = span_search(STATE_RE, scan.lines, node.lineno,
                                node.end_lineno)
                if m is None:
                    continue
                mach = Machine(
                    m.group("name"), scan.rel, cls.name, tgt.attr,
                    (m.group("table") + "_") if m.group("table") else "",
                    m.group("guard"), node.lineno,
                )
                mach.seed_token = scan.consts.token(node.value, cls.name)
                mach.seed_fn = item.name
                self._resolve_table(scan, cls, mach)
                scan.machines.append(mach)
                scan.fields.setdefault(mach.field, []).append(mach)
                scan.by_class.setdefault(cls.name, []).append(mach)

    def _resolve_table(self, scan: ModuleScan, cls: ast.ClassDef,
                       mach: Machine) -> None:
        """Pull {prefix}STATES / INITIAL / TERMINAL / TRANSITIONS off
        the class body and validate internal consistency (SC05)."""
        p = mach.prefix
        attrs: Dict[str, ast.expr] = {}
        attr_lines: Dict[str, int] = {}
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                attrs[stmt.targets[0].id] = stmt.value
                attr_lines[stmt.targets[0].id] = stmt.lineno

        def tok(node: ast.expr) -> Optional[str]:
            return scan.consts.token(node, cls.name, class_scope=True)

        def tok_seq(node: ast.expr) -> Optional[List[str]]:
            if not isinstance(node, (ast.Tuple, ast.List)):
                return None
            out: List[str] = []
            for e in node.elts:
                t = tok(e)
                if t is None:
                    return None
                out.append(t)
            return out

        states_node = attrs.get(p + "STATES")
        trans_node = attrs.get(p + "TRANSITIONS")
        if states_node is None or trans_node is None:
            self.emit(
                scan.rel, mach.decl_line, "SC05",
                f"machine {mach.name}: field {mach.field} is annotated "
                f"'# state:' but class {cls.name} declares no "
                f"{p}STATES/{p}TRANSITIONS table",
            )
            return
        mach.table_line = attr_lines.get(p + "TRANSITIONS",
                                         mach.decl_line)
        states = tok_seq(states_node)
        if states is None:
            self.emit(
                scan.rel, attr_lines[p + "STATES"], "SC05",
                f"machine {mach.name}: {p}STATES does not resolve to a "
                f"tuple of state tokens",
            )
            return
        mach.states = set(states)
        if not p:
            declared = attrs.get("MACHINE")
            dname = tok(declared) if declared is not None else None
            if dname is not None and dname != mach.name:
                self.emit(
                    scan.rel, mach.decl_line, "SC05",
                    f"annotation names machine {mach.name} but "
                    f"{cls.name}.MACHINE says {dname}",
                )
        init_node = attrs.get(p + "INITIAL")
        if init_node is not None:
            mach.initial = tok(init_node)
            if mach.initial is None or mach.initial not in mach.states:
                self.emit(
                    scan.rel, attr_lines[p + "INITIAL"], "SC05",
                    f"machine {mach.name}: {p}INITIAL is not one of "
                    f"{p}STATES",
                )
        term_node = attrs.get(p + "TERMINAL")
        if term_node is not None:
            terms = tok_seq(term_node)
            if terms is None or not set(terms) <= mach.states:
                self.emit(
                    scan.rel, attr_lines[p + "TERMINAL"], "SC05",
                    f"machine {mach.name}: {p}TERMINAL lists states "
                    f"outside {p}STATES",
                )
            else:
                mach.terminal = set(terms)
        if not isinstance(trans_node, ast.Dict):
            self.emit(
                scan.rel, mach.table_line, "SC05",
                f"machine {mach.name}: {p}TRANSITIONS is not a dict "
                f"literal",
            )
            return
        ok = True
        for k, v in zip(trans_node.keys, trans_node.values):
            src = tok(k) if k is not None else None
            dsts = tok_seq(v)
            if src is None or src not in mach.states or dsts is None \
                    or not set(dsts) <= mach.states:
                self.emit(
                    scan.rel, (k or v).lineno, "SC05",
                    f"machine {mach.name}: {p}TRANSITIONS entry uses "
                    f"states outside {p}STATES",
                )
                ok = False
                continue
            mach.transitions[src] = tuple(dsts)
        if not ok:
            return
        mach.complete = True
        # SC04 at the table itself: terminal states with outgoing edges
        for term in sorted(mach.terminal):
            if mach.transitions.get(term):
                self.emit(
                    scan.rel, mach.table_line, "SC04",
                    f"machine {mach.name}: terminal state '{term}' has "
                    f"outgoing transitions declared — terminal states "
                    f"must be sinks",
                )
        # the seed must be INITIAL (when both are statically known)
        if mach.seed_token is not None and mach.initial is not None \
                and mach.seed_token != mach.initial:
            self.emit(
                scan.rel, mach.decl_line, "SC02",
                f"machine {mach.name}: seeded with "
                f"'{mach.seed_token}' but {p}INITIAL is "
                f"'{mach.initial}'",
            )

    # -- per-module checks ----------------------------------------------------
    def _check_module(self, scan: ModuleScan) -> None:
        for stmt in ast.walk(scan.tree):
            if isinstance(stmt, ast.ClassDef):
                for item in stmt.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._check_fn(scan, stmt.name, item)
        for stmt in scan.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_fn(scan, None, stmt)

    def _check_fn(self, scan: ModuleScan, cls_name: Optional[str],
                  fn) -> None:
        helper = TRANSITION_HELPER_RE.match(fn.name)
        visitor = _FnScan(self, scan, cls_name, fn.name,
                          in_helper=helper is not None)
        for stmt in fn.body:
            visitor.visit(stmt)
        if helper is None:
            self._terminal_paths(scan, cls_name, fn)

    # -- SC04: straight-line terminal escapes ---------------------------------
    def _terminal_paths(self, scan: ModuleScan, cls_name: Optional[str],
                        fn) -> None:
        """Within each statement list, a transition lexically after a
        terminal-entering one on the same receiver is dead or illegal."""
        for node in ast.walk(fn):
            for body in ("body", "orelse", "finalbody"):
                stmts = getattr(node, body, None)
                if not isinstance(stmts, list) or len(stmts) < 2:
                    continue
                # receiver-source -> (machine, line of terminal entry)
                dead: Dict[str, Tuple[Machine, int]] = {}
                for stmt in stmts:
                    if not isinstance(stmt, ast.stmt):
                        continue
                    if isinstance(stmt, COMPOUND_STMTS):
                        # a compound statement's branches/iterations
                        # are NOT the same straight-line path; its
                        # body lists get their own scan
                        continue
                    if isinstance(stmt, ast.Assign):
                        # re-binding a receiver name starts a fresh
                        # object: its terminal marker dies with it
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name):
                                dead.pop(tgt.id, None)
                    for call in ast.walk(stmt):
                        t = self._transition_call(scan, cls_name, call)
                        if t is None:
                            continue
                        recv_src, cands, to, _frm = t
                        if recv_src in dead and to is not None:
                            mach, tline = dead[recv_src]
                            self.emit(
                                scan.rel, call.lineno, "SC04",
                                f"machine {mach.name}: transition after "
                                f"the terminal transition at line "
                                f"{tline} on the same path",
                            )
                            continue
                        if to is not None and any(
                                to in m.terminal for m in cands):
                            mach = next(m for m in cands
                                        if to in m.terminal)
                            dead.setdefault(recv_src,
                                            (mach, call.lineno))

    def _transition_call(self, scan: ModuleScan,
                         cls_name: Optional[str], node):
        """(receiver-src, candidate machines, to, frm) when ``node``
        is a _transition*/check_named call; None otherwise."""
        if not isinstance(node, ast.Call):
            return None
        f = node.func
        if isinstance(f, ast.Attribute):
            m = TRANSITION_HELPER_RE.match(f.attr)
            if m is None:
                return None
            suffix = m.group("suffix")
            recv = f.value
            recv_src = ast.unparse(recv)
            is_self = isinstance(recv, ast.Name) and recv.id == "self"
            cands = self._candidates(scan, cls_name, is_self, suffix)
            to = scan.consts.token(node.args[0], cls_name) \
                if node.args else None
            frm = None
            if len(node.args) > 1:
                frm = scan.consts.token(node.args[1], cls_name)
            for kw in node.keywords:
                if kw.arg == "frm":
                    frm = scan.consts.token(kw.value, cls_name)
            return recv_src, cands, to, frm
        if isinstance(f, ast.Name) and f.id == "check_named" \
                and len(node.args) >= 2:
            name = None
            for kw in node.keywords:
                if kw.arg == "name":
                    name = scan.consts.token(kw.value, cls_name)
            cands = [m for m in scan.machines if m.name == name] \
                if name else []
            to = scan.consts.token(node.args[1], cls_name)
            frm = None
            for kw in node.keywords:
                if kw.arg == "frm":
                    frm = scan.consts.token(kw.value, cls_name)
            return ast.unparse(node.args[0]), cands, to, frm
        return None

    def _candidates(self, scan: ModuleScan, cls_name: Optional[str],
                    is_self: bool, suffix: Optional[str]
                    ) -> List[Machine]:
        if is_self and cls_name is not None:
            pool = scan.by_class.get(cls_name, [])
            # a class with no machine of its own forwarding self._xx
            # falls back to the module population (mixin hosts)
            if not pool:
                pool = scan.machines
        else:
            pool = scan.machines
        if suffix is not None:
            return [m for m in pool if m.prefix and m.complete
                    and m.prefix[:-1].lower() == suffix.lower()]
        return [m for m in pool if not m.prefix and m.complete]


class _FnScan(ast.NodeVisitor):
    """One function body: held-lock attr stack + SC01/SC02/SC03."""

    def __init__(self, an: Analyzer, scan: ModuleScan,
                 cls_name: Optional[str], fn_name: str,
                 in_helper: bool):
        self.an = an
        self.scan = scan
        self.cls_name = cls_name
        self.fn_name = fn_name
        self.in_helper = in_helper
        self.held: List[str] = []  # lock attr/name per with-item

    # nested defs/classes: scanned separately (their own _check_fn /
    # _collect pass); a nested function's writes still count as raw
    # writes, so descend into FunctionDef but not ClassDef
    def visit_ClassDef(self, node):
        pass

    # -- held-lock tracking ---------------------------------------------------
    def _lock_name(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Subscript):
            expr = expr.value
        if isinstance(expr, ast.Attribute):
            return expr.attr
        if isinstance(expr, ast.Name):
            return expr.id
        return None

    def visit_With(self, node):
        pushed = 0
        for item in node.items:
            self.visit(item.context_expr)
            name = self._lock_name(item.context_expr)
            if name is not None and ("lock" in name.lower()
                                     or name.endswith("_cv")):
                self.held.append(name)
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    visit_AsyncWith = visit_With

    # -- SC01: raw writes -----------------------------------------------------
    def _check_write(self, tgt: ast.expr, node: ast.stmt) -> None:
        if not isinstance(tgt, ast.Attribute):
            return
        machines = self.scan.fields.get(tgt.attr)
        if not machines:
            return
        if self.in_helper:
            return  # the blessed writer
        is_self = isinstance(tgt.value, ast.Name) \
            and tgt.value.id == "self"
        owners = [m for m in machines if m.cls_name == self.cls_name]
        if is_self and self.cls_name is not None and not owners:
            return  # another class's unrelated same-named field
        ann = span_search(STATE_RE, self.scan.lines, node.lineno,
                          getattr(node, "end_lineno", None))
        if ann is not None and self.fn_name == "__init__":
            return  # the annotated seeding line
        mach = (owners or machines)[0]
        self.an.emit(
            self.scan.rel, node.lineno, "SC01",
            f"raw write to state field {tgt.attr} (machine "
            f"{mach.name}) outside a _transition helper — bypasses "
            f"the table validator, counters, and shaker",
        )

    def visit_Assign(self, node):
        for tgt in node.targets:
            self._check_write(tgt, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._check_write(node.target, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_write(node.target, node)
        self.generic_visit(node)

    # -- SC02 / SC04 at call sites --------------------------------------------
    def visit_Call(self, node):
        t = self.an._transition_call(self.scan, self.cls_name, node)
        if t is not None:
            _recv, cands, to, frm = t
            self.an.transition_sites += 1
            self._check_edge(node, cands, to, frm)
        self.generic_visit(node)

    def _check_edge(self, node: ast.Call, cands: List[Machine],
                    to: Optional[str], frm: Optional[str]) -> None:
        if to is None or not cands:
            return  # dynamic argument or unresolvable receiver:
            #         the runtime validator's job
        line = node.lineno
        rel = self.scan.rel
        if not any(to in m.states for m in cands):
            names = ", ".join(sorted({m.name for m in cands}))
            self.an.emit(
                rel, line, "SC02",
                f"transition to undeclared state '{to}' (not in "
                f"STATES of {names})",
            )
            return
        if frm is not None:
            if any(frm in m.terminal and frm in m.states
                   for m in cands) and not any(
                       to == frm or to in m.transitions.get(frm, ())
                       for m in cands):
                mach = next(m for m in cands if frm in m.terminal)
                self.an.emit(
                    rel, line, "SC04",
                    f"machine {mach.name}: transition out of terminal "
                    f"state '{frm}'",
                )
                return
            if not any(to == frm or to in m.transitions.get(frm, ())
                       for m in cands):
                names = ", ".join(sorted({m.name for m in cands}))
                self.an.emit(
                    rel, line, "SC02",
                    f"transition '{frm}' -> '{to}' is not in the "
                    f"declared table of {names}",
                )
            return
        dests: Set[str] = set()
        for m in cands:
            dests |= m.dests()
        if to not in dests:
            names = ", ".join(sorted({m.name for m in cands}))
            self.an.emit(
                rel, line, "SC02",
                f"no declared edge into state '{to}' (machine "
                f"{names})",
            )

    # -- SC03: branch reads ---------------------------------------------------
    def visit_If(self, node):
        self._check_branch(node.test)
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_branch(node.test)
        self.generic_visit(node)

    def visit_IfExp(self, node):
        self._check_branch(node.test)
        self.generic_visit(node)

    def visit_Assert(self, node):
        self._check_branch(node.test)
        self.generic_visit(node)

    def _check_branch(self, test: ast.expr) -> None:
        if self.in_helper or self.fn_name == "__init__":
            return
        for node in ast.walk(test):
            if not isinstance(node, ast.Attribute) \
                    or not isinstance(node.ctx, ast.Load):
                continue
            machines = self.scan.fields.get(node.attr)
            if not machines:
                continue
            is_self = isinstance(node.value, ast.Name) \
                and node.value.id == "self"
            for mach in machines:
                if mach.guard_attr is None:
                    continue
                if mach.guard_owner is None:
                    # own-class guard: reads of self.<field> inside
                    # the declaring class
                    if not (is_self and self.cls_name == mach.cls_name):
                        continue
                elif not (self.cls_name == mach.guard_owner
                          and not is_self):
                    # external guard: non-self receivers inside the
                    # owning class
                    continue
                if mach.guard_attr not in self.held:
                    self.an.emit(
                        self.scan.rel, node.lineno, "SC03",
                        f"branch on state field "
                        f"{ast.unparse(node)} (machine {mach.name}) "
                        f"without holding its declared guard "
                        f"{mach.guard}",
                    )
                break


def analyze(paths, root: pathlib.Path = ROOT) -> List[Finding]:
    return Analyzer(root=root).analyze_paths(paths)


def main(argv) -> int:
    paths = [pathlib.Path(a) for a in argv[1:]] or [LIB]
    an = Analyzer()
    findings = an.analyze_paths(paths)
    for rel, line, code, msg in findings:
        print(f"{rel}:{line}: {code} {msg}")
    if findings:
        print(f"statecheck: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    machines = sorted(an.machines, key=lambda m: m.name)
    edges = sum(len(d) for m in machines for d in m.transitions.values())
    print(f"statecheck: clean ({len(machines)} machine(s), {edges} "
          f"declared edge(s), {an.transition_sites} transition "
          f"site(s))")
    for m in machines:
        guard = f" guarded-by {m.guard}" if m.guard else ""
        print(f"  {m.name}: {len(m.states)} states, "
              f"{sum(len(d) for d in m.transitions.values())} edges"
              f"{guard}  [{m.rel}]")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
