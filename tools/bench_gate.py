#!/usr/bin/env python
"""Bench regression gate: fresh BENCH_*.json vs the committed copies.

Usage:
    python tools/bench_gate.py [BENCH_file.json ...]

The smoke benches overwrite their ``BENCH_*.json`` headline files in
place, so after a ``make bench-smoke`` the working tree holds the
fresh numbers and ``git show HEAD:<file>`` holds the committed
baseline.  This gate diffs the two, metric by metric (matched on the
``metric`` string), and FAILS when any metric regresses by more than
the threshold:

- throughput-like units (anything per second: ``GB/s``, ``rows/s``)
  regress when the fresh value is LOWER,
- latency-like units (``ns``/``us``/``ms``/``s``) regress when the
  fresh value is HIGHER,
- other units are reported but never gate.

With no file arguments it gates every ``BENCH_*.json`` that differs
from HEAD (``git diff --name-only``) — the ``make bench-smoke`` wiring.
Files new to the tree (no committed baseline yet) and metrics new to a
file are noted and skipped, never failed.

A bench doc (or one of its per-process-count ``clusters`` tiers) may
declare ``"min_cores": N``: its metrics were measured with real
parallelism and are meaningless on a smaller host, so on hosts with
fewer cores they are skipped with an explicit note instead of gating
garbage (the 1-core CI hosts would otherwise "regress" every
multi-process number).  Host cores = the scheduling affinity mask when
available, else ``os.cpu_count()``.

A doc/tier may likewise declare ``"min_devices": N`` for metrics
measured on an N-device mesh (the device-native exchange bench): hosts
whose accelerator census — ``sparkrdma_tpu.conf.device_census()``,
which honors an ``XLA_FLAGS --xla_force_host_platform_device_count``
forcing on cpu-pinned processes — falls short skip those metrics with
a note, exactly like ``min_cores``.

Knobs (documented in the README "Observability" section):

- ``BENCH_GATE_PCT`` — allowed regression percent (default 35: the
  1-core CI hosts are noisy; tighten locally for real perf work),
- ``BENCH_GATE=off`` — skip the gate entirely (exploratory runs).

Exit status: 1 when any gated metric regresses past the threshold.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent

DEFAULT_PCT = 35.0

_LATENCY_UNITS = {"ns", "us", "ms", "s"}


def _direction(unit: str):
    """+1 higher-is-better, -1 lower-is-better, None ungated."""
    u = (unit or "").strip()
    if u.endswith("/s"):
        return 1
    if u in _LATENCY_UNITS:
        return -1
    return None


def _committed(path: pathlib.Path):
    """The HEAD copy of ``path`` as parsed JSON, or None when the file
    is new to the tree (or we are not in a git checkout)."""
    rel = path.resolve().relative_to(ROOT)
    try:
        out = subprocess.run(
            ["git", "show", f"HEAD:{rel.as_posix()}"],
            cwd=ROOT, capture_output=True, text=True,
        )
    except OSError:
        return None
    if out.returncode != 0:
        return None
    try:
        return json.loads(out.stdout)
    except ValueError:
        return None


def _changed_bench_files():
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", "HEAD", "--", "BENCH_*.json"],
            cwd=ROOT, capture_output=True, text=True,
        )
    except OSError:
        return []
    if out.returncode != 0:
        return []
    return [ROOT / line for line in out.stdout.splitlines() if line]


def _host_cores() -> int:
    """Cores this process may actually schedule on — the affinity mask
    when the platform exposes it (a containerized CI host often pins
    fewer cores than it advertises), else ``os.cpu_count()``."""
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def _min_cores(doc: dict) -> int:
    try:
        return int(doc.get("min_cores", 0) or 0)
    except (TypeError, ValueError):
        return 0


def _host_devices() -> int:
    """Accelerator devices this host's benches would see — the conf
    module's census (reads XLA_FLAGS forcing without initializing jax;
    asks jax.device_count() otherwise; 1 when jax is unavailable)."""
    try:
        sys.path.insert(0, str(ROOT))
        from sparkrdma_tpu.conf import device_census

        return int(device_census())
    except Exception:
        return 1


def _min_devices(doc: dict) -> int:
    try:
        return int(doc.get("min_devices", 0) or 0)
    except (TypeError, ValueError):
        return 0


def _metrics(doc: dict) -> dict:
    return {
        r["metric"]: r for r in doc.get("results", [])
        if isinstance(r, dict) and "metric" in r and "value" in r
    }


def _all_metrics(doc: dict) -> dict:
    """Flat results plus any per-process-count tiers: the cluster
    bench nests ``"clusters": {"2": {"results": [...]}, ...}`` so a
    2-process and an 8-process run of the same metric gate
    independently — fold each tier in under an ``[Nproc]`` prefix.
    Each record carries the strictest ``min_cores``/``min_devices``
    declared on its doc/tier as ``_min_cores``/``_min_devices``."""
    doc_min = _min_cores(doc)
    doc_min_dev = _min_devices(doc)
    out = {
        metric: dict(rec, _min_cores=doc_min, _min_devices=doc_min_dev)
        for metric, rec in _metrics(doc).items()
    }
    clusters = doc.get("clusters")
    if isinstance(clusters, dict):
        for nproc, sub in sorted(clusters.items()):
            if isinstance(sub, dict):
                tier_min = max(doc_min, _min_cores(sub))
                tier_min_dev = max(doc_min_dev, _min_devices(sub))
                for metric, rec in _metrics(sub).items():
                    out[f"[{nproc}proc] {metric}"] = dict(
                        rec, _min_cores=tier_min,
                        _min_devices=tier_min_dev)
    return out


def gate_file(path: pathlib.Path, pct: float):
    """(failures, notes) for one bench file."""
    failures, notes = [], []
    name = path.name
    try:
        fresh = _all_metrics(json.loads(path.read_text()))
    except (OSError, ValueError) as e:
        failures.append(f"{name}: unreadable fresh file ({e})")
        return failures, notes
    base_doc = _committed(path)
    if base_doc is None:
        notes.append(f"{name}: no committed baseline (new bench) — skipped")
        return failures, notes
    base = _all_metrics(base_doc)
    cores = _host_cores()
    devices = None  # resolved lazily: the census may import jax
    for metric, rec in fresh.items():
        req = int(rec.get("_min_cores", 0) or 0)
        if req > cores:
            notes.append(
                f"{name}: {metric}: needs >= {req} cores, host has "
                f"{cores} — skipped (multi-core-only number)")
            continue
        req_dev = int(rec.get("_min_devices", 0) or 0)
        if req_dev > 1:
            if devices is None:
                devices = _host_devices()
            if req_dev > devices:
                notes.append(
                    f"{name}: {metric}: needs >= {req_dev} devices, "
                    f"host census is {devices} — skipped "
                    f"(multi-device-only number)")
                continue
        if metric not in base:
            notes.append(f"{name}: new metric {metric!r} — skipped")
            continue
        d = _direction(rec.get("unit", ""))
        if d is None:
            continue
        old, new = float(base[metric]["value"]), float(rec["value"])
        if old <= 0:
            continue
        # positive delta = regression, in the unit's bad direction
        delta = (old - new) / old * 100.0 if d > 0 else \
            (new - old) / old * 100.0
        line = (
            f"{name}: {metric}: {old:g} -> {new:g} {rec.get('unit', '')} "
            f"({'-' if d > 0 else '+'}{abs(delta):.1f}%)"
        )
        if delta > pct:
            failures.append(f"{line}  REGRESSION > {pct:g}%")
        elif delta > pct / 2:
            notes.append(f"{line}  (within threshold)")
    return failures, notes


def main(argv) -> int:
    if os.environ.get("BENCH_GATE", "").lower() in ("off", "0", "no"):
        print("bench_gate: BENCH_GATE=off — skipped")
        return 0
    try:
        pct = float(os.environ.get("BENCH_GATE_PCT", DEFAULT_PCT))
    except ValueError:
        print(f"bench_gate: bad BENCH_GATE_PCT "
              f"{os.environ['BENCH_GATE_PCT']!r}", file=sys.stderr)
        return 2
    paths = [pathlib.Path(a) for a in argv[1:]]
    if not paths:
        paths = _changed_bench_files()
        if not paths:
            print("bench_gate: no BENCH_*.json changed vs HEAD — "
                  "nothing to gate")
            return 0
    failures, notes = [], []
    gated = 0
    for p in paths:
        f, n = gate_file(p, pct)
        failures.extend(f)
        notes.extend(n)
        gated += 1
    for n in notes:
        print(f"bench_gate: note: {n}")
    for f in failures:
        print(f"bench_gate: FAIL: {f}", file=sys.stderr)
    if failures:
        print(
            f"bench_gate: {len(failures)} regression(s) past "
            f"{pct:g}% across {gated} file(s) "
            f"(override: BENCH_GATE_PCT=<pct> or BENCH_GATE=off)",
            file=sys.stderr,
        )
        return 1
    print(f"bench_gate: clean ({gated} file(s), threshold {pct:g}%)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
