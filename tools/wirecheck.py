#!/usr/bin/env python
"""Wire-protocol conformance analyzer — the codec-symmetry gate.

``make lint`` runs this next to tools/lint.py, tools/concheck.py and
tools/flowcheck.py.  The control plane frames every message as ``4B
length + 4B type`` over ``1B opcode + 4B length`` transport framing,
and the review history of hand-written codecs is the usual one: a pack
whose unpack reads one field fewer, a count field trusted before the
bytes behind it exist, an offset advanced by a literal that silently
drifts from the struct it mirrors.  rpc/messages.py now declares each
message's layout as a ``WIRE_SCHEMA`` field table from which the codec
pair is DERIVED — symmetry true by construction — and this pass checks
everything the construction can't: the hand-written codecs, the
type/opcode registries, and the bounds discipline of every decode
path.  The runtime half is sparkrdma_tpu/utils/wiredbg.py (conf
``spark.shuffle.tpu.wireDebug``).

Findings:

  WC01  pack/unpack asymmetry: a derived-schema class hand-writing
        (shadowing) its codec, a custom-schema class missing one half
        of the pair, encode/decode halves of a hand-written codec
        using different struct layouts, a non-little-endian (no ``<``
        prefix) struct format anywhere on the wire, or a
        ``pack``/``unpack`` call whose argument/target count disagrees
        with its struct's field count.
  WC02  MSG_TYPE registry integrity: duplicate ids, a message class
        the ``MSG_TYPES`` registry doesn't list, or a registered type
        the receive dispatcher (``_receive``) never handles.
  WC03  opcode/handler parity: every OP_* consumed by the threaded
        reader loop must be consumed by the async recv machine with
        the same sub-header structs, and the loopback plane must carry
        both analogs (``dispatch_frame`` / ``read_local_blocks``).
  WC04  hand-written magic sizes: a ``*_SIZE`` constant assigned an
        integer literal, or offset arithmetic advancing by a literal,
        where the value must derive from ``struct.Struct(...).size``.
  WC05  bounds discipline: a count/length unpacked from the wire used
        to size a loop, slice or allocation before any validation
        against the received buffer (``_require``/``_check_count``, an
        ``if``-guard that raises/returns, or a containing
        ``try``/``except``).

Suppressions are code-scoped: ``# noqa: WC05`` silences only WC05 on
that line; a bare ``# noqa`` silences everything (discouraged).

Usage: ``python tools/wirecheck.py [paths...]`` (default: the wire
surface — rpc/, transport/, utils/types.py, utils/wiredbg.py,
shuffle/manager.py).  Exit status 1 on any finding; on success prints
the schema/opcode census.
"""

from __future__ import annotations

import ast
import pathlib
import sys
from typing import Dict, List, Optional, Set, Tuple

ROOT = pathlib.Path(__file__).resolve().parent.parent
LIB = ROOT / "sparkrdma_tpu"
DEFAULT_PATHS = [
    LIB / "rpc",
    LIB / "transport",
    LIB / "utils" / "types.py",
    LIB / "utils" / "wiredbg.py",
    LIB / "shuffle" / "manager.py",
]

# ONE noqa grammar + suppression decision for all five gates:
# tools/gatelib.py owns the definition (code-scoped sets, bare-noqa =
# everything, alias handling) plus the finding shape and file walking
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
from gatelib import (  # noqa: E402
    Finding,
    suppressed as _lint_suppressed,
    walk_py as _walk_py,
)

# sub-header structs whose consumption arity must match across engines
_WIRE_HDRS = {"_HDR", "_REQ_HDR", "_RESP_HDR", "_LEN"}
_GUARD_CALLS = {"_require", "_check_count"}
_UNPACKS = {"unpack", "unpack_from"}


def _last_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _fmt_value_count(fmt: str) -> Optional[int]:
    """Number of Python values one struct format packs/unpacks."""
    count, digits = 0, ""
    for c in fmt.lstrip("<>=!@"):
        if c.isdigit():
            digits += c
            continue
        if c in "sp":
            count += 1  # one bytes value regardless of repeat
        elif c == "x":
            pass  # pad byte: no value
        elif c.isalpha() or c in "?":
            count += int(digits) if digits else 1
        else:
            return None  # unrecognized (shouldn't happen on literals)
        digits = ""
    return count


def _normalize_fmt(fmt: str) -> str:
    """Layout signature for symmetry comparison: endianness prefix +
    the letter codes, repeat counts dropped (``<4sBHH`` → ``<sBHH``,
    ``<{e * e}q`` → ``<q``)."""
    out = "<" if fmt.startswith("<") else ""
    for c in fmt.lstrip("<>=!@"):
        if c.isalpha() or c == "?":
            out += c
    return out


def _literal_fmt(node: ast.AST) -> Optional[str]:
    """Extract a format string from a Constant or an f-string whose
    constant pieces carry the layout (placeholders are repeat counts)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        return "".join(
            v.value for v in node.values
            if isinstance(v, ast.Constant) and isinstance(v.value, str)
        )
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class MsgClass:
    """One message class: its MSG_TYPE, schema shape, codec methods."""

    def __init__(self, rel: str, name: str, line: int):
        self.rel = rel
        self.name = name
        self.line = line
        self.msg_type: Optional[int] = None
        self.msg_type_line = line
        self.schema_line = line
        self.has_schema = False
        self.has_custom = False
        self.methods: Dict[str, ast.FunctionDef] = {}


class ModuleInfo:
    def __init__(self, rel: str, lines: List[str], tree: ast.Module):
        self.rel = rel
        self.lines = lines
        self.tree = tree
        self.structs: Dict[str, Tuple[str, int]] = {}  # name -> (fmt, line)
        self.classes: List[MsgClass] = []
        self.registry: Optional[List[str]] = None  # MSG_TYPES class names
        self.registry_line = 0
        self.dispatch_names: Optional[Set[str]] = None  # _receive isinstance
        self.dispatch_line = 0
        self.op_consts: Dict[str, int] = {}
        self.op_lines: Dict[str, int] = {}
        self.fns: Dict[str, ast.FunctionDef] = {}  # flat, by name
        self.has_loopback = False
        self.loopback_line = 0


class Analyzer:
    def __init__(self, root: pathlib.Path = ROOT):
        self.root = root
        self.findings: List[Finding] = []
        self.modules: Dict[str, ModuleInfo] = {}
        # merged struct registry: bare name -> set of formats seen
        self.struct_fmts: Dict[str, Set[str]] = {}
        self.schema_count = 0

    def emit(self, rel: str, line: int, code: str, msg: str) -> None:
        mod = self.modules.get(rel)
        if mod is not None and _lint_suppressed(mod.lines, line, code):
            return
        self.findings.append((rel, line, code, msg))

    # -- entry ---------------------------------------------------------------
    def analyze_paths(self, paths) -> List[Finding]:
        files = _walk_py(paths)
        for f in files:
            self._load(f)
        for mod in self.modules.values():
            self._scan_structure(mod)
        for mod in self.modules.values():
            self._check_module(mod)
        self._check_wc02()
        self._check_wc03()
        self.findings.sort(key=lambda x: (x[0], x[1], x[2]))
        return self.findings

    def _rel(self, path: pathlib.Path) -> str:
        try:
            return str(path.relative_to(self.root))
        except ValueError:
            return str(path)

    def _load(self, path: pathlib.Path) -> None:
        rel = self._rel(path)
        try:
            text = path.read_text()
            tree = ast.parse(text)
        except (UnicodeDecodeError, SyntaxError):
            return  # tools/lint.py owns PY01
        self.modules[rel] = ModuleInfo(rel, text.splitlines(), tree)

    # -- structure pass ------------------------------------------------------
    def _scan_structure(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign):
                self._scan_assign(mod, node)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                plain = ast.Assign(targets=[node.target], value=node.value)
                ast.copy_location(plain, node)
                self._scan_assign(mod, plain)
            elif isinstance(node, ast.FunctionDef):
                mod.fns.setdefault(node.name, node)
                if node.name == "_receive":
                    mod.dispatch_names = self._isinstance_names(node)
                    mod.dispatch_line = node.lineno
            elif isinstance(node, ast.ClassDef):
                if node.name == "LoopbackChannel":
                    mod.has_loopback = True
                    mod.loopback_line = node.lineno
                self._scan_class(mod, node)

    def _scan_assign(self, mod: ModuleInfo, node: ast.Assign) -> None:
        if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
            return
        name = node.targets[0].id
        v = node.value
        if (isinstance(v, ast.Call) and _last_name(v.func) == "Struct"
                and v.args):
            fmt = _literal_fmt(v.args[0])
            if fmt is not None:
                mod.structs[name] = (fmt, node.lineno)
                self.struct_fmts.setdefault(name, set()).add(fmt)
        elif name == "MSG_TYPES":
            mod.registry = self._registry_names(v)
            mod.registry_line = node.lineno
        elif (name.startswith("OP_")
              and isinstance(v, ast.Constant) and isinstance(v.value, int)):
            mod.op_consts[name] = v.value
            mod.op_lines[name] = node.lineno

    @staticmethod
    def _registry_names(v: ast.AST) -> List[str]:
        """Class names a MSG_TYPES registry lists — dict comprehension
        over a tuple of classes, or a plain dict literal."""
        if isinstance(v, ast.DictComp) and v.generators:
            it = v.generators[0].iter
            if isinstance(it, (ast.Tuple, ast.List)):
                return [_last_name(e) for e in it.elts]
        if isinstance(v, ast.Dict):
            return [_last_name(e) for e in v.values]
        return []

    @staticmethod
    def _isinstance_names(fn: ast.FunctionDef) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and _last_name(node.func) == "isinstance"
                    and len(node.args) == 2):
                t = node.args[1]
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                out.update(_last_name(e) for e in elts)
        return out

    def _scan_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        cls = MsgClass(mod.rel, node.name, node.lineno)
        for item in node.body:
            if isinstance(item, ast.FunctionDef):
                cls.methods[item.name] = item
                mod.fns.setdefault(f"{node.name}.{item.name}", item)
            target = value = None
            if isinstance(item, ast.Assign) and len(item.targets) == 1:
                target, value = item.targets[0], item.value
            elif isinstance(item, ast.AnnAssign) and item.value is not None:
                target, value = item.target, item.value
            if not isinstance(target, ast.Name):
                continue
            if (target.id == "MSG_TYPE"
                    and isinstance(value, ast.Constant)
                    and isinstance(value.value, int)):
                cls.msg_type = value.value
                cls.msg_type_line = item.lineno
            elif target.id == "WIRE_SCHEMA" and isinstance(
                    value, (ast.Tuple, ast.List)) and value.elts:
                cls.has_schema = True
                cls.schema_line = item.lineno
                for e in value.elts:
                    if isinstance(e, ast.Call) and \
                            _last_name(e.func) == "custom":
                        cls.has_custom = True
        if cls.msg_type is not None or cls.has_schema:
            mod.classes.append(cls)
            if cls.has_schema:
                self.schema_count += 1

    # -- per-module rules ----------------------------------------------------
    def _check_module(self, mod: ModuleInfo) -> None:
        self._check_wc01_formats(mod)
        self._check_wc01_arity(mod)
        self._check_wc04(mod)
        for cls in mod.classes:
            self._check_wc01_class(mod, cls)
        for fn in mod.fns.values():
            self._check_wc05(mod, fn)

    # .. WC01: endianness of every wire format .............................
    def _check_wc01_formats(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _last_name(node.func)
            if name == "Struct" or (
                name in {"pack", "pack_into", "calcsize"} | _UNPACKS
                and isinstance(node.func, ast.Attribute)
                and _last_name(node.func.value) == "struct"
            ):
                if not node.args:
                    continue
                fmt = _literal_fmt(node.args[0])
                if fmt is not None and not fmt.startswith("<"):
                    self.emit(
                        mod.rel, node.lineno, "WC01",
                        f"wire struct format {fmt!r} is not explicit "
                        f"little-endian — prefix it with '<' (native "
                        f"alignment/endianness is not a wire contract)",
                    )

    # .. WC01: pack/unpack arity vs the struct's field count ...............
    def _resolve_fmt(self, node: ast.AST) -> Optional[str]:
        """Format of the struct object a ``X.pack``/``X.unpack`` call
        targets — only when the bare name resolves unambiguously."""
        fmts = self.struct_fmts.get(_last_name(node))
        return next(iter(fmts)) if fmts is not None and len(fmts) == 1 \
            else None

    def _check_wc01_arity(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute):
                meth = node.func.attr
                if meth not in ("pack", "pack_into"):
                    continue
                fmt = self._resolve_fmt(node.func.value)
                want = _fmt_value_count(fmt) if fmt is not None else None
                if want is None:
                    continue
                args = node.args[2 if meth == "pack_into" else 0:]
                if any(isinstance(a, ast.Starred) for a in args):
                    continue
                if len(args) != want:
                    self.emit(
                        mod.rel, node.lineno, "WC01",
                        f"{_last_name(node.func.value)}.{meth} packs "
                        f"{len(args)} value(s) but format {fmt!r} "
                        f"carries {want}",
                    )
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Tuple) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr in _UNPACKS:
                fmt = self._resolve_fmt(node.value.func.value)
                want = _fmt_value_count(fmt) if fmt is not None else None
                got = len(node.targets[0].elts)
                if want is not None and got != want:
                    self.emit(
                        mod.rel, node.lineno, "WC01",
                        f"{_last_name(node.value.func.value)}."
                        f"{node.value.func.attr} unpacks into {got} "
                        f"name(s) but format {fmt!r} carries {want}",
                    )

    # .. WC01: schema/codec shape + hand-written symmetry ...................
    def _check_wc01_class(self, mod: ModuleInfo, cls: MsgClass) -> None:
        if not cls.has_schema:
            return
        codec = {"_payload", "_decode_payload", "_payload_size"}
        written = codec & set(cls.methods)
        if not cls.has_custom:
            for m in sorted(written):
                self.emit(
                    mod.rel, cls.methods[m].lineno, "WC01",
                    f"{cls.name}.{m} hand-writes a codec the derived "
                    f"WIRE_SCHEMA already provides — delete it or mark "
                    f"the varying section as a custom field",
                )
            return
        for m in sorted(codec - written):
            self.emit(
                mod.rel, cls.schema_line, "WC01",
                f"{cls.name} declares custom wire sections but does "
                f"not hand-write {m} — a one-sided codec cannot stay "
                f"symmetric",
            )
        enc = cls.methods.get("_payload")
        dec = cls.methods.get("_decode_payload")
        if enc is None or dec is None:
            return
        enc_sig = self._codec_signature(enc, encode=True)
        dec_sig = self._codec_signature(dec, encode=False)
        for sig in sorted(enc_sig - dec_sig):
            self.emit(
                mod.rel, enc.lineno, "WC01",
                f"{cls.name}._payload writes layout {sig!r} that "
                f"_decode_payload never reads — pack/unpack asymmetry",
            )
        for sig in sorted(dec_sig - enc_sig):
            self.emit(
                mod.rel, dec.lineno, "WC01",
                f"{cls.name}._decode_payload reads layout {sig!r} that "
                f"_payload never writes — pack/unpack asymmetry",
            )

    def _codec_signature(self, fn: ast.FunctionDef,
                         encode: bool) -> Set[str]:
        """Normalized struct layouts one codec half touches.  Named
        structs resolve through the registry; inline ``struct.*``
        formats normalize directly; self-delimiting object codecs
        (``x.write(buf)`` / ``Type.read(view, off)``) count as one
        ``objcodec`` token."""
        sigs: Set[str] = set()
        half = ("pack", "pack_into") if encode else tuple(_UNPACKS)
        obj_meth = "write" if encode else "read"
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            meth = node.func.attr
            if meth in half:
                if _last_name(node.func.value) == "struct":
                    fmt = _literal_fmt(node.args[0]) if node.args else None
                    if fmt is not None:
                        sigs.add(_normalize_fmt(fmt))
                else:
                    fmt = self._resolve_fmt(node.func.value)
                    if fmt is not None:
                        sigs.add(_normalize_fmt(fmt))
            elif meth == obj_meth:
                sigs.add("objcodec")
        return sigs

    # .. WC02: MSG_TYPE registry integrity ..................................
    def _check_wc02(self) -> None:
        by_type: Dict[int, MsgClass] = {}
        registry: Optional[Set[str]] = None
        reg_mod: Optional[ModuleInfo] = None
        dispatch: Optional[Set[str]] = None
        all_classes: List[MsgClass] = []
        for mod in self.modules.values():
            all_classes.extend(mod.classes)
            if mod.registry is not None:
                registry = set(mod.registry)
                reg_mod = mod
            if mod.dispatch_names is not None:
                dispatch = mod.dispatch_names
        for cls in all_classes:
            if not cls.msg_type:  # base class (0) is not a wire type
                continue
            prior = by_type.get(cls.msg_type)
            if prior is not None:
                self.emit(
                    cls.rel, cls.msg_type_line, "WC02",
                    f"duplicate MSG_TYPE {cls.msg_type}: {cls.name} "
                    f"collides with {prior.name} "
                    f"({prior.rel}:{prior.msg_type_line})",
                )
            else:
                by_type[cls.msg_type] = cls
            if registry is not None and cls.name not in registry:
                self.emit(
                    cls.rel, cls.msg_type_line, "WC02",
                    f"{cls.name} (MSG_TYPE {cls.msg_type}) is not "
                    f"listed in the MSG_TYPES registry — unregistered "
                    f"frames decode as unknown-type errors",
                )
        if registry is not None and dispatch is not None and \
                reg_mod is not None:
            names = {c.name for c in all_classes}
            for name in sorted(registry & names - dispatch):
                self.emit(
                    reg_mod.rel, reg_mod.registry_line, "WC02",
                    f"registered type {name} has no isinstance handler "
                    f"in the receive dispatcher (_receive) — its "
                    f"frames decode and then vanish silently",
                )

    # .. WC03: opcode/handler parity across engines .........................
    @staticmethod
    def _consumed_ops(fn: ast.FunctionDef) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Compare):
                for side in [node.left] + list(node.comparators):
                    name = _last_name(side)
                    if name.startswith("OP_"):
                        out.add(name)
        return out

    def _hdr_structs(self, fns: List[ast.FunctionDef]) -> Set[str]:
        out: Set[str] = set()
        for fn in fns:
            for node in ast.walk(fn):
                name = _last_name(node) if isinstance(
                    node, (ast.Name, ast.Attribute)) else ""
                if name in _WIRE_HDRS and name in self.struct_fmts:
                    out.add(name)
        return out

    def _check_wc03(self) -> None:
        threaded = async_mod = loopback = None
        for mod in self.modules.values():
            if "_read_loop" in mod.fns or any(
                    k.endswith("._read_loop") for k in mod.fns):
                if mod.op_consts:
                    threaded = mod
            if any(k.split(".")[-1] == "_rx_dispatch" for k in mod.fns):
                async_mod = mod
            if mod.has_loopback:
                loopback = mod
        if threaded is None:
            return
        t_fns = [fn for k, fn in threaded.fns.items()
                 if k.split(".")[-1] in ("_read_loop", "_recv_read_resp")]
        t_ops: Set[str] = set()
        for fn in t_fns:
            t_ops |= self._consumed_ops(fn)
        defined = set(threaded.op_consts)
        for op in sorted(defined - t_ops):
            self.emit(
                threaded.rel, threaded.op_lines[op], "WC03",
                f"{op} is defined but the threaded reader loop never "
                f"consumes it — dead opcode or missing handler branch",
            )
        if async_mod is not None:
            a_fns = [fn for k, fn in async_mod.fns.items()
                     if k.split(".")[-1].startswith("_rx_")]
            a_ops: Set[str] = set()
            for fn in a_fns:
                a_ops |= self._consumed_ops(fn)
            line = next(
                (fn.lineno for k, fn in async_mod.fns.items()
                 if k.split(".")[-1] == "_rx_dispatch"), 1)
            for op in sorted((defined & t_ops) - a_ops):
                self.emit(
                    async_mod.rel, line, "WC03",
                    f"{op} is consumed by the threaded reader loop but "
                    f"not by the async recv machine — the engines "
                    f"speak different protocols",
                )
            t_hdrs = self._hdr_structs(t_fns)
            a_hdrs = self._hdr_structs(a_fns)
            if t_hdrs != a_hdrs:
                self.emit(
                    async_mod.rel, line, "WC03",
                    f"recv sub-header arity mismatch: threaded engine "
                    f"reads {sorted(t_hdrs)}, async engine reads "
                    f"{sorted(a_hdrs)}",
                )
        if loopback is not None:
            called = {
                _last_name(n.func) for n in ast.walk(loopback.tree)
                if isinstance(n, ast.Call)
            }
            for analog, role in (
                ("dispatch_frame", "the OP_RPC dispatch plane"),
                ("read_local_blocks", "the OP_READ_REQ serve plane"),
            ):
                if analog not in called:
                    self.emit(
                        loopback.rel, loopback.loopback_line, "WC03",
                        f"loopback engine never calls {analog} — "
                        f"{role} has no in-process analog",
                    )

    # .. WC04: magic sizes ..................................................
    def _check_wc04(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.isupper() \
                    and node.targets[0].id.endswith("SIZE") \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, int):
                self.emit(
                    mod.rel, node.lineno, "WC04",
                    f"{node.targets[0].id} is a hand-written integer "
                    f"literal — derive it from struct.Struct(...).size "
                    f"so it cannot drift from the layout",
                )
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.op, ast.Add) \
                    and self._is_offset(node.target) \
                    and self._magic_int(node.value):
                self.emit(
                    mod.rel, node.lineno, "WC04",
                    f"offset advanced by integer literal — advance by "
                    f"the struct's .size so the stride cannot drift "
                    f"from the layout",
                )
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.Add) \
                    and (self._is_offset(node.left)
                         and self._magic_int(node.right)
                         or self._is_offset(node.right)
                         and self._magic_int(node.left)):
                self.emit(
                    mod.rel, node.lineno, "WC04",
                    f"offset arithmetic with an integer literal — use "
                    f"the struct's .size so the stride cannot drift "
                    f"from the layout",
                )

    @staticmethod
    def _is_offset(node: ast.AST) -> bool:
        name = _last_name(node)
        return name in ("off", "offset") or name.endswith(("_off",
                                                           "_offset"))

    @staticmethod
    def _magic_int(node: ast.AST) -> bool:
        return (isinstance(node, ast.Constant)
                and isinstance(node.value, int)
                and node.value >= 2)

    # .. WC05: bounds discipline ............................................
    def _check_wc05(self, mod: ModuleInfo, fn: ast.FunctionDef) -> None:
        tainted: Set[str] = set()
        guarded: Set[str] = set()

        def live(names: Set[str]) -> Set[str]:
            return {n for n in names & tainted if n not in guarded}

        def guard_stmt(stmt: ast.stmt) -> None:
            # a _require/_check_count call mentioning a tainted name
            # validates it; an if-test mentioning one whose body
            # raises/returns/continues is an inline guard
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and \
                        _last_name(node.func) in _GUARD_CALLS:
                    for a in node.args:
                        guarded.update(_names_in(a) & tainted)
            if isinstance(stmt, ast.If) and any(
                isinstance(n, (ast.Raise, ast.Return, ast.Continue))
                for b in stmt.body for n in ast.walk(b)
            ):
                guarded.update(_names_in(stmt.test) & tainted)

        def use_sites(stmt: ast.stmt, contained: bool) -> None:
            if contained:
                return  # a surrounding except handler fail-scopes it
            for node in ast.walk(stmt):
                bad: Set[str] = set()
                where = ""
                if isinstance(node, ast.Call):
                    name = _last_name(node.func)
                    if name in ("range", "bytearray"):
                        for a in node.args:
                            bad |= live(_names_in(a))
                        where = f"{name}()"
                elif isinstance(node, ast.Subscript) and isinstance(
                        node.slice, ast.Slice):
                    for part in (node.slice.lower, node.slice.upper,
                                 node.slice.step):
                        if part is not None:
                            bad |= live(_names_in(part))
                    where = "a slice"
                for n in sorted(bad):
                    guarded.add(n)  # report each name once
                    self.emit(
                        mod.rel, node.lineno, "WC05",
                        f"wire-supplied value {n!r} sizes {where} "
                        f"before any bounds check against the received "
                        f"buffer — validate it first (_require / "
                        f"_check_count / an if-guard that raises)",
                    )

        def walk(body: List[ast.stmt], contained: bool) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue  # scanned as their own functions
                guard_stmt(stmt)
                if isinstance(stmt, ast.Assign):
                    v = stmt.value
                    is_unpack = (isinstance(v, ast.Call)
                                 and isinstance(v.func, ast.Attribute)
                                 and v.func.attr in _UNPACKS)
                    targets: Set[str] = set()
                    for t in stmt.targets:
                        targets |= _names_in(t)
                    if is_unpack:
                        tainted.update(targets)
                        guarded.difference_update(targets)
                    elif live(_names_in(v)):
                        tainted.update(targets)  # taint propagates
                        guarded.difference_update(targets)
                # compound statements: only their header expressions are
                # use sites here — their suites get their own visit (with
                # the right try-containment) via the recursion below
                if isinstance(stmt, (ast.If, ast.While)):
                    use_sites(stmt.test, contained)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    use_sites(stmt.iter, contained)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        use_sites(item.context_expr, contained)
                elif not isinstance(stmt, ast.Try):
                    use_sites(stmt, contained)
                if isinstance(stmt, ast.Try):
                    inner = contained or bool(stmt.handlers)
                    walk(stmt.body, inner)
                    for h in stmt.handlers:
                        walk(h.body, contained)
                    walk(stmt.orelse, inner)
                    walk(stmt.finalbody, contained)
                elif isinstance(stmt, (ast.If, ast.While, ast.For,
                                       ast.AsyncFor, ast.With,
                                       ast.AsyncWith)):
                    walk(stmt.body, contained)
                    walk(getattr(stmt, "orelse", []), contained)

        walk(fn.body, False)


def analyze(paths, root: pathlib.Path = ROOT) -> List[Finding]:
    return Analyzer(root=root).analyze_paths(paths)


def main(argv) -> int:
    paths = [pathlib.Path(a) for a in argv[1:]] or DEFAULT_PATHS
    an = Analyzer()
    findings = an.analyze_paths(paths)
    for rel, line, code, msg in findings:
        print(f"{rel}:{line}: {code} {msg}")
    if findings:
        print(f"wirecheck: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    n_ops = sum(len(m.op_consts) for m in an.modules.values())
    n_reg = sum(len(m.registry or ()) for m in an.modules.values())
    print(f"wirecheck: clean ({an.schema_count} message schema(s), "
          f"{n_reg} registered type(s), {n_ops} opcode(s), "
          f"{len(an.struct_fmts)} named wire struct(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
