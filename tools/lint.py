#!/usr/bin/env python
"""Style gate failing the build — the checkstyle/scalastyle analog.

The reference runs checkstyle + scalastyle at Maven's ``validate``
phase with ``failsOnError=true`` (pom.xml:93-141); this is the
same gate for the rebuild, implemented on the stdlib because the
environment ships no third-party linter.  Rules:

Python (sparkrdma_tpu/, tests/, benchmarks/, tools/, repo-root *.py):
  PY01  file does not parse (SyntaxError)
  PY02  line longer than 88 characters
  PY03  tab character in indentation
  PY04  trailing whitespace
  PY05  unused import (skipped in __init__.py re-export files; suppress
        with a trailing ``# noqa`` on the import line)
  PY06  bare ``except:`` (use ``except BaseException:`` when you truly
        mean everything)
  PY07  ``print(`` in library code (sparkrdma_tpu/ only; benches, tests
        and tools print by design)
  PY08  ``time.perf_counter()`` in library code outside
        sparkrdma_tpu/metrics/ and sparkrdma_tpu/utils/trace.py —
        metric timing must flow through the registry/tracer (use
        ``Histogram.time()`` or ``time.monotonic()`` for plain
        interval math)
  PY09  ``.tobytes()`` call or ``b"".join`` in the exchange hot paths
        (sparkrdma_tpu/parallel/exchange.py, sparkrdma_tpu/shuffle/
        bulk.py) — the zero-copy data path stages into preallocated
        contiguous rows; per-block ``bytes`` materialization there is
        a regression (suppress a deliberate one with ``# noqa``)
  PY10  payload concatenation / materialization on the TCP transport
        hot paths (sparkrdma_tpu/transport/tcp.py): ``sendall(a + b)``
        or ``sendall(b"".join(...))`` anywhere in the file, and
        ``bytes(...)`` calls inside the hot send/serve/receive
        functions — frames go out as sendmsg iovecs and land via
        recv_into; an intermediate copy there is a regression
        (suppress a deliberate one with ``# noqa``)

C++ (native/):
  CC01  line longer than 100 characters
  CC02  trailing whitespace

Exit status 1 on any finding; ``make test`` depends on this.
"""

from __future__ import annotations

import ast
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
PY_MAX_LINE = 88
CC_MAX_LINE = 100

PY_DIRS = ["sparkrdma_tpu", "tests", "benchmarks", "tools"]
LIB_DIR = ROOT / "sparkrdma_tpu"


def py_files():
    for d in PY_DIRS:
        yield from sorted((ROOT / d).rglob("*.py"))
    yield from sorted(ROOT.glob("*.py"))


def cc_files():
    native = ROOT / "native"
    if native.is_dir():
        for pat in ("*.cpp", "*.cc", "*.h", "*.hpp"):
            yield from sorted(native.rglob(pat))


class _ImportUsage(ast.NodeVisitor):
    """Collect imported names and every name/attribute root used."""

    def __init__(self):
        self.imports = {}  # name -> (lineno, stmt is noqa-exempt?)
        self.used = set()

    def visit_Import(self, node):
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports[name] = node.lineno

    def visit_ImportFrom(self, node):
        for a in node.names:
            if a.name == "*":
                continue
            self.imports[a.asname or a.name] = node.lineno

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


# zero-copy exchange hot paths: PY09 bans per-block bytes
# materialization (.tobytes() / b"".join) inside these files
HOT_PATHS = (
    pathlib.Path("sparkrdma_tpu/parallel/exchange.py"),
    pathlib.Path("sparkrdma_tpu/shuffle/bulk.py"),
)


def _is_hot_path_copy(node: ast.Call) -> bool:
    """``x.tobytes(...)`` or ``b"".join(...)``."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr == "tobytes":
        return True
    return (
        f.attr == "join"
        and isinstance(f.value, ast.Constant)
        and f.value.value == b""
    )


# TCP transport hot paths: PY10 bans concat-into-sendall anywhere in
# the file and per-frame bytes() materialization inside these functions
TCP_HOT_PATH = pathlib.Path("sparkrdma_tpu/transport/tcp.py")
TCP_HOT_FUNCS = {
    "_send_msg", "_sendmsg_all", "_serve_read", "_recv_read_resp",
    "_recv_payload", "_recv_into", "_read_loop",
}


def _is_bytes_join(node: ast.expr) -> bool:
    """``b"".join(...)``"""
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "join"
        and isinstance(node.func.value, ast.Constant)
        and node.func.value.value == b""
    )


def _is_sendall_concat(node: ast.Call) -> bool:
    """``<sock>.sendall(a + b)`` / ``<sock>.sendall(b"".join(...))``."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr == "sendall"):
        return False
    return any(
        isinstance(a, ast.BinOp) and isinstance(a.op, ast.Add)
        or _is_bytes_join(a)
        for a in node.args
    )


def _tcp_hot_func_lines(tree: ast.AST) -> set:
    """Line ranges of the TCP hot-path functions."""
    lines = set()
    for node in ast.walk(tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in TCP_HOT_FUNCS):
            lines.update(range(node.lineno, (node.end_lineno or node.lineno) + 1))
    return lines


def _perf_counter_exempt(path: pathlib.Path, lib_dir: pathlib.Path) -> bool:
    """PY08 applies to library code only; the registry (metrics/) and
    the tracer (utils/trace.py) are the sanctioned timing sources."""
    if lib_dir not in path.parents:
        return True
    if lib_dir / "metrics" in path.parents:
        return True
    return path == lib_dir / "utils" / "trace.py"


def _is_perf_counter_call(node: ast.Call) -> bool:
    f = node.func
    if (isinstance(f, ast.Attribute) and f.attr == "perf_counter"
            and isinstance(f.value, ast.Name) and f.value.id == "time"):
        return True
    return isinstance(f, ast.Name) and f.id == "perf_counter"


def lint_python(path: pathlib.Path, findings: list,
                root: pathlib.Path = ROOT) -> None:
    lib_dir = root / "sparkrdma_tpu"
    rel = path.relative_to(root)
    try:
        text = path.read_text()
    except UnicodeDecodeError as e:
        findings.append((rel, 0, "PY01", f"not utf-8: {e}"))
        return
    lines = text.splitlines()
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        findings.append((rel, e.lineno or 0, "PY01", f"syntax error: {e.msg}"))
        return

    for i, line in enumerate(lines, 1):
        if len(line) > PY_MAX_LINE:
            findings.append(
                (rel, i, "PY02", f"line too long ({len(line)} > {PY_MAX_LINE})")
            )
        stripped_nl = line.rstrip("\n")
        indent = stripped_nl[: len(stripped_nl) - len(stripped_nl.lstrip())]
        if "\t" in indent:
            findings.append((rel, i, "PY03", "tab in indentation"))
        if stripped_nl != stripped_nl.rstrip():
            findings.append((rel, i, "PY04", "trailing whitespace"))

    # unused imports (module-level only; __init__ files re-export)
    if path.name != "__init__.py":
        usage = _ImportUsage()
        usage.visit(tree)
        # names in __all__ / string annotations count as used
        for name in usage.imports:
            if name in usage.used or name == "annotations":
                continue
            lineno = usage.imports[name]
            src_line = lines[lineno - 1] if lineno <= len(lines) else ""
            if "# noqa" in src_line:
                continue
            if name in text.replace(f"import {name}", "", 1):
                # crude but effective: referenced in a docstring/comment
                # only counts if it appears outside the import stmt; a
                # name used in type comments or __all__ strings passes
                if f'"{name}"' in text or f"'{name}'" in text:
                    continue
            findings.append((rel, lineno, "PY05", f"unused import: {name}"))

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(
                (rel, node.lineno, "PY06",
                 "bare except: (name the exception type)")
            )
        if (
            lib_dir in path.parents
            and isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            findings.append(
                (rel, node.lineno, "PY07",
                 "print() in library code (use logging)")
            )
        if (
            isinstance(node, ast.Call)
            and _is_perf_counter_call(node)
            and not _perf_counter_exempt(path, lib_dir)
        ):
            findings.append(
                (rel, node.lineno, "PY08",
                 "time.perf_counter() in library code (metric timing "
                 "goes through metrics/ or utils/trace.py)")
            )
        if (
            rel in HOT_PATHS
            and isinstance(node, ast.Call)
            and _is_hot_path_copy(node)
            and "# noqa" not in (
                lines[node.lineno - 1] if node.lineno <= len(lines)
                else ""
            )
        ):
            findings.append(
                (rel, node.lineno, "PY09",
                 'per-block bytes materialization (.tobytes()/b"".join)'
                 " in an exchange hot path (stage into preallocated "
                 "rows instead)")
            )

    if rel == TCP_HOT_PATH:
        hot_lines = _tcp_hot_func_lines(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            src_line = (
                lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            )
            if "# noqa" in src_line:
                continue
            if _is_sendall_concat(node):
                findings.append(
                    (rel, node.lineno, "PY10",
                     "payload concatenation into sendall (send the "
                     "parts as one sendmsg iovec instead)")
                )
            elif (
                node.lineno in hot_lines
                and isinstance(node.func, ast.Name)
                and node.func.id == "bytes"
            ):
                findings.append(
                    (rel, node.lineno, "PY10",
                     "per-frame bytes() materialization on a TCP hot "
                     "path (use buffer views / recv_into instead)")
                )


def lint_cpp(path: pathlib.Path, findings: list) -> None:
    rel = path.relative_to(ROOT)
    for i, line in enumerate(path.read_text().splitlines(), 1):
        if len(line) > CC_MAX_LINE:
            findings.append(
                (rel, i, "CC01", f"line too long ({len(line)} > {CC_MAX_LINE})")
            )
        if line != line.rstrip():
            findings.append((rel, i, "CC02", "trailing whitespace"))


def main() -> int:
    findings: list = []
    for f in py_files():
        lint_python(f, findings)
    for f in cc_files():
        lint_cpp(f, findings)
    for rel, line, code, msg in findings:
        print(f"{rel}:{line}: {code} {msg}")
    if findings:
        print(f"lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print(f"lint: clean ({sum(1 for _ in py_files())} py, "
          f"{sum(1 for _ in cc_files())} c++ files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
