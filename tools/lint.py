#!/usr/bin/env python
"""Style gate failing the build — the checkstyle/scalastyle analog.

The reference runs checkstyle + scalastyle at Maven's ``validate``
phase with ``failsOnError=true`` (pom.xml:93-141); this is the
same gate for the rebuild, implemented on the stdlib because the
environment ships no third-party linter.  Rules:

Suppressions are CODE-SCOPED: ``# noqa: PY10`` silences only PY10 on
that line (comma-separate several codes); a bare ``# noqa`` still
silences everything, but a scoped escape can never blanket-silence an
unrelated hot-path rule.  ``F401`` is accepted as an alias for PY05
(flake8 compatibility).

Python (sparkrdma_tpu/, tests/, benchmarks/, tools/, repo-root *.py):
  PY01  file does not parse (SyntaxError)
  PY02  line longer than 88 characters
  PY03  tab character in indentation
  PY04  trailing whitespace
  PY05  unused import, via AST usage tracking (attribute roots,
        decorators, string annotations, ``__all__`` exports all count
        as uses; skipped in __init__.py re-export files; suppress on
        the import statement line OR — for multi-line
        ``from x import (a, b)`` statements — on the imported name's
        own line)
  PY06  bare ``except:`` (use ``except BaseException:`` when you truly
        mean everything)
  PY07  ``print(`` in library code (sparkrdma_tpu/ only; benches, tests
        and tools print by design)
  PY08  ``time.perf_counter()`` in library code outside
        sparkrdma_tpu/metrics/ and sparkrdma_tpu/utils/trace.py —
        metric timing must flow through the registry/tracer (use
        ``Histogram.time()`` or ``time.monotonic()`` for plain
        interval math)
  PY09  ``.tobytes()`` call or ``b"".join`` in the exchange hot paths
        (sparkrdma_tpu/parallel/exchange.py, sparkrdma_tpu/shuffle/
        bulk.py) — the zero-copy data path stages into preallocated
        contiguous rows; per-block ``bytes`` materialization there is
        a regression (suppress a deliberate one with ``# noqa``)
  PY10  payload concatenation / materialization on the TCP transport
        hot paths (sparkrdma_tpu/transport/tcp.py): ``sendall(a + b)``
        or ``sendall(b"".join(...))`` anywhere in the file, and
        ``bytes(...)`` calls inside the hot send/serve/receive
        functions — frames go out as sendmsg iovecs and land via
        recv_into; an intermediate copy there is a regression
        (suppress a deliberate one with ``# noqa``)
  PY11  conf-key drift, both directions.  Every full
        ``spark.shuffle.tpu.<key>`` / ``spark.shuffle.rdma.<key>``
        reference in sparkrdma_tpu/ must name a key DECLARED in
        conf.py (a str first argument to ``self.get``/``self.set``/
        ``_int_in_range``/``_bytes_in_range``/``_bool``/``_time_ms``;
        rdma-namespace references resolve through LEGACY_RENAMES
        first).  And every declared key must appear in a README.md
        conf table — as the backticked short key (`` `tierHotBytes` ``)
        or the full dotted key — so no knob ships undocumented.
  PY12  flight-recorder event drift.  Every ``fr_event(plane, event,
        ...)`` call in sparkrdma_tpu/ must pass the plane and event as
        string LITERALS naming an entry declared in the
        ``obs/events.py`` ``EVENTS`` registry — dashboards and
        ``tools/trace_report.py`` group by these names, so a dynamic
        or undeclared name is silent drift.  Declare first, then emit.
  PY13  host materialization on the device-exchange hot paths:
        ``.tobytes()``, ``np.asarray(...)``, or ``jax.device_get(...)``
        inside the named device-native exchange functions
        (``DEVICE_HOT_FUNCS`` — the padded staging/framing/assembly
        seam).  The device path's whole contract is ZERO intermediate
        host copies between assembly and the destination views; the
        few sanctioned zero-copy shard reads carry a scoped
        ``# noqa: PY13`` with justification.

C++ (native/):
  CC01  line longer than 100 characters
  CC02  trailing whitespace

Exit status 1 on any finding; ``make test`` depends on this.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
PY_MAX_LINE = 88
CC_MAX_LINE = 100

# the noqa grammar + file walking live in tools/gatelib.py (shared by
# every gate); the historical private names are re-exported here so
# the other gates' ``from lint import _suppressed`` keeps meaning ONE
# suppression decision
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
from gatelib import (  # noqa: PY05 _noqa_codes re-exported for tests
    PY_DIRS,
    noqa_codes as _noqa_codes,
    suppressed as _suppressed,
)

_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")

LIB_DIR = ROOT / "sparkrdma_tpu"


def py_files():
    for d in PY_DIRS:
        yield from sorted((ROOT / d).rglob("*.py"))
    yield from sorted(ROOT.glob("*.py"))


def cc_files():
    native = ROOT / "native"
    if native.is_dir():
        for pat in ("*.cpp", "*.cc", "*.h", "*.hpp"):
            yield from sorted(native.rglob(pat))


class _ImportUsage(ast.NodeVisitor):
    """Collect imported names and every use: plain names, attribute
    roots (via the root Name leaf), decorators (ordinary expressions),
    identifiers inside STRING annotations, and ``__all__`` exports."""

    def __init__(self):
        # name -> (name's own line, import statement's first line)
        self.imports = {}
        self.used = set()

    def visit_Import(self, node):
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports[name] = (
                getattr(a, "lineno", node.lineno), node.lineno
            )

    def visit_ImportFrom(self, node):
        for a in node.names:
            if a.name == "*":
                continue
            self.imports[a.asname or a.name] = (
                getattr(a, "lineno", node.lineno), node.lineno
            )

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)

    # -- string annotations -------------------------------------------------
    def _ann_strings(self, ann) -> None:
        """Names inside a (possibly quoted) annotation count as used —
        ``x: "np.ndarray"`` keeps its numpy import."""
        if ann is None:
            return
        for n in ast.walk(ann):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                self.used.update(_IDENT_RE.findall(n.value))

    def visit_FunctionDef(self, node):
        # argument annotations are handled by visit_arg (generic_visit
        # dispatches it per arg); only the return annotation is ours
        self._ann_strings(node.returns)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_AnnAssign(self, node):
        self._ann_strings(node.annotation)
        self.generic_visit(node)

    def visit_arg(self, node):
        self._ann_strings(node.annotation)
        self.generic_visit(node)

    def visit_Assign(self, node):
        # __all__ re-exports: the listed names are used by definition
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == "__all__":
                for e in ast.walk(node.value):
                    if isinstance(e, ast.Constant) \
                            and isinstance(e.value, str):
                        self.used.add(e.value)
        self.generic_visit(node)


# zero-copy exchange hot paths: PY09 bans per-block bytes
# materialization (.tobytes() / b"".join) inside these files
HOT_PATHS = (
    pathlib.Path("sparkrdma_tpu/parallel/exchange.py"),
    pathlib.Path("sparkrdma_tpu/shuffle/bulk.py"),
)


def _is_hot_path_copy(node: ast.Call) -> bool:
    """``x.tobytes(...)`` or ``b"".join(...)``."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr == "tobytes":
        return True
    return (
        f.attr == "join"
        and isinstance(f.value, ast.Constant)
        and f.value.value == b""
    )


# TCP transport hot paths: PY10 bans concat-into-sendall anywhere in
# the file and per-frame bytes() materialization inside these functions
TCP_HOT_PATH = pathlib.Path("sparkrdma_tpu/transport/tcp.py")
TCP_HOT_FUNCS = {
    "_send_msg", "_sendmsg_all", "_serve_read", "_recv_read_resp",
    "_recv_payload", "_recv_into", "_read_loop",
}


def _is_bytes_join(node: ast.expr) -> bool:
    """``b"".join(...)``"""
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "join"
        and isinstance(node.func.value, ast.Constant)
        and node.func.value.value == b""
    )


def _is_sendall_concat(node: ast.Call) -> bool:
    """``<sock>.sendall(a + b)`` / ``<sock>.sendall(b"".join(...))``."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr == "sendall"):
        return False
    return any(
        isinstance(a, ast.BinOp) and isinstance(a.op, ast.Add)
        or _is_bytes_join(a)
        for a in node.args
    )


def _tcp_hot_func_lines(tree: ast.AST) -> set:
    """Line ranges of the TCP hot-path functions."""
    lines = set()
    for node in ast.walk(tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in TCP_HOT_FUNCS):
            lines.update(range(node.lineno, (node.end_lineno or node.lineno) + 1))
    return lines


# device-native exchange hot paths: PY13 bans host materialization
# (.tobytes() / np.asarray / jax.device_get) inside these functions —
# the padded device path promises zero intermediate host copies
# between assembly and the destination views; deliberate zero-copy
# shard reads get a scoped ``# noqa: PY13`` with a justification
DEVICE_HOT_FUNCS = {
    pathlib.Path("sparkrdma_tpu/parallel/exchange.py"): {
        "exchange_padded",
    },
    pathlib.Path("sparkrdma_tpu/shuffle/bulk.py"): {
        "_assemble", "_exchange_contributed", "_make_round_emitter",
        "_iter_residual_blocks",
    },
    pathlib.Path("sparkrdma_tpu/memory/device_arena.py"): {
        "as_words", "alloc_row", "to_device",
    },
}


def _is_device_host_copy(node: ast.Call):
    """The banned-call label for PY13, or None."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    if f.attr == "tobytes":
        return ".tobytes()"
    if (f.attr == "asarray" and isinstance(f.value, ast.Name)
            and f.value.id in ("np", "numpy")):
        return "np.asarray()"
    if (f.attr == "device_get" and isinstance(f.value, ast.Name)
            and f.value.id == "jax"):
        return "jax.device_get()"
    return None


def _named_func_lines(tree: ast.AST, names: set) -> set:
    """Line ranges of the named functions (the TCP hot-func pattern)."""
    lines = set()
    for node in ast.walk(tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in names):
            lines.update(
                range(node.lineno, (node.end_lineno or node.lineno) + 1)
            )
    return lines


def _perf_counter_exempt(path: pathlib.Path, lib_dir: pathlib.Path) -> bool:
    """PY08 applies to library code only; the registry (metrics/) and
    the tracer (utils/trace.py) are the sanctioned timing sources."""
    if lib_dir not in path.parents:
        return True
    if lib_dir / "metrics" in path.parents:
        return True
    return path == lib_dir / "utils" / "trace.py"


def _is_perf_counter_call(node: ast.Call) -> bool:
    f = node.func
    if (isinstance(f, ast.Attribute) and f.attr == "perf_counter"
            and isinstance(f.value, ast.Name) and f.value.id == "time"):
        return True
    return isinstance(f, ast.Name) and f.id == "perf_counter"


def lint_python(path: pathlib.Path, findings: list,
                root: pathlib.Path = ROOT) -> None:
    lib_dir = root / "sparkrdma_tpu"
    rel = path.relative_to(root)
    try:
        text = path.read_text()
    except UnicodeDecodeError as e:
        findings.append((rel, 0, "PY01", f"not utf-8: {e}"))
        return
    lines = text.splitlines()
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        findings.append((rel, e.lineno or 0, "PY01", f"syntax error: {e.msg}"))
        return

    out: list = []  # pre-suppression findings

    for i, line in enumerate(lines, 1):
        if len(line) > PY_MAX_LINE:
            out.append(
                (rel, i, "PY02", f"line too long ({len(line)} > {PY_MAX_LINE})")
            )
        stripped_nl = line.rstrip("\n")
        indent = stripped_nl[: len(stripped_nl) - len(stripped_nl.lstrip())]
        if "\t" in indent:
            out.append((rel, i, "PY03", "tab in indentation"))
        if stripped_nl != stripped_nl.rstrip():
            out.append((rel, i, "PY04", "trailing whitespace"))

    # unused imports (AST usage tracking; __init__ files re-export)
    if path.name != "__init__.py":
        usage = _ImportUsage()
        usage.visit(tree)
        for name, (lineno, stmt_lineno) in usage.imports.items():
            if name in usage.used or name == "annotations":
                continue
            # honor the escape on the import statement's first line AND
            # on the imported name's own line (multi-line from-imports)
            if _suppressed(lines, stmt_lineno, "PY05"):
                continue
            out.append((rel, lineno, "PY05", f"unused import: {name}"))

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            out.append(
                (rel, node.lineno, "PY06",
                 "bare except: (name the exception type)")
            )
        if (
            lib_dir in path.parents
            and isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            out.append(
                (rel, node.lineno, "PY07",
                 "print() in library code (use logging)")
            )
        if (
            isinstance(node, ast.Call)
            and _is_perf_counter_call(node)
            and not _perf_counter_exempt(path, lib_dir)
        ):
            out.append(
                (rel, node.lineno, "PY08",
                 "time.perf_counter() in library code (metric timing "
                 "goes through metrics/ or utils/trace.py)")
            )
        if (
            rel in HOT_PATHS
            and isinstance(node, ast.Call)
            and _is_hot_path_copy(node)
        ):
            out.append(
                (rel, node.lineno, "PY09",
                 'per-block bytes materialization (.tobytes()/b"".join)'
                 " in an exchange hot path (stage into preallocated "
                 "rows instead)")
            )

    if rel == TCP_HOT_PATH:
        hot_lines = _tcp_hot_func_lines(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_sendall_concat(node):
                out.append(
                    (rel, node.lineno, "PY10",
                     "payload concatenation into sendall (send the "
                     "parts as one sendmsg iovec instead)")
                )
            elif (
                node.lineno in hot_lines
                and isinstance(node.func, ast.Name)
                and node.func.id == "bytes"
            ):
                out.append(
                    (rel, node.lineno, "PY10",
                     "per-frame bytes() materialization on a TCP hot "
                     "path (use buffer views / recv_into instead)")
                )

    dev_funcs = DEVICE_HOT_FUNCS.get(rel)
    if dev_funcs:
        dev_lines = _named_func_lines(tree, dev_funcs)
        for node in ast.walk(tree):
            if (not isinstance(node, ast.Call)
                    or node.lineno not in dev_lines):
                continue
            label = _is_device_host_copy(node)
            if label:
                out.append(
                    (rel, node.lineno, "PY13",
                     f"{label} on a device-exchange hot path (keep the"
                     " padded payload device-resident / zero-copy)")
                )

    # one code-scoped suppression gate for every rule
    for rel_, lineno, code, msg in out:
        if not _suppressed(lines, lineno, code):
            findings.append((rel_, lineno, code, msg))


# PY11: conf-key drift.  The declaration side is conf.py's accessor
# calls; the reference side is every full dotted key in library text
# (docstrings included — a doc pointing at a key that does not exist
# is exactly the drift this rule exists to catch).
_CONF_GETTERS = {"get", "set", "_int_in_range", "_bytes_in_range",
                 "_bool", "_time_ms", "_float_in_range"}
_CONF_KEY_RE = re.compile(
    r"spark\.shuffle\.(tpu|rdma)\.([A-Za-z_][A-Za-z0-9_]*)"
)


def _declared_conf_keys(conf_path: pathlib.Path):
    """(declared short keys, legacy→tpu rename map) from conf.py's AST."""
    tree = ast.parse(conf_path.read_text())
    declared: set = set()
    renames: dict = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _CONF_GETTERS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            declared.add(node.args[0].value)
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "LEGACY_RENAMES":
                    for k, v in zip(node.value.keys, node.value.values):
                        if (isinstance(k, ast.Constant)
                                and isinstance(v, ast.Constant)):
                            renames[k.value] = v.value
    return declared, renames


def lint_conf_keys(findings: list, root: pathlib.Path = ROOT) -> None:
    """PY11 — see the module docstring."""
    lib = root / "sparkrdma_tpu"
    conf_path = lib / "conf.py"
    if not conf_path.is_file():
        return
    declared, renames = _declared_conf_keys(conf_path)
    for path in sorted(lib.rglob("*.py")):
        rel = path.relative_to(root)
        lines = path.read_text().splitlines()
        for i, line in enumerate(lines, 1):
            for m in _CONF_KEY_RE.finditer(line):
                ns, short = m.group(1), m.group(2)
                key = renames.get(short, short) if ns == "rdma" else short
                if key in declared:
                    continue
                if _suppressed(lines, i, "PY11"):
                    continue
                findings.append(
                    (rel, i, "PY11",
                     f"conf key {m.group(0)} is not declared in conf.py")
                )
    readme = root / "README.md"
    text = readme.read_text() if readme.is_file() else ""
    for key in sorted(declared):
        if f"`{key}`" in text or f"spark.shuffle.tpu.{key}" in text:
            continue
        findings.append(
            (readme.relative_to(root) if readme.is_file()
             else pathlib.Path("README.md"), 0, "PY11",
             f"declared conf key {key} missing from the README conf tables")
        )


# PY12: flight-recorder event drift.  The declaration side is the
# EVENTS dict literal in obs/events.py; the reference side is every
# fr_event(plane, event, ...) call in library code.  Same shape as
# PY11 — registry parsed from the AST, call sites walked per file.
def _declared_events(events_path: pathlib.Path):
    """``{plane: {event, ...}}`` from the EVENTS dict literal."""
    tree = ast.parse(events_path.read_text())
    declared: dict = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Dict)):
            continue
        for t in node.targets:
            if not (isinstance(t, ast.Name) and t.id == "EVENTS"):
                continue
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and isinstance(v, (ast.Tuple, ast.List, ast.Set))):
                    continue
                declared[k.value] = {
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                }
    return declared


def lint_fr_events(findings: list, root: pathlib.Path = ROOT) -> None:
    """PY12 — see the module docstring."""
    lib = root / "sparkrdma_tpu"
    events_path = lib / "obs" / "events.py"
    if not events_path.is_file():
        return
    declared = _declared_events(events_path)
    for path in sorted(lib.rglob("*.py")):
        rel = path.relative_to(root)
        text = path.read_text()
        if "fr_event" not in text:
            continue
        lines = text.splitlines()
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue  # PY01 already owns this finding
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "fr_event"):
                continue
            msg = None
            if (len(node.args) < 2
                    or not all(isinstance(a, ast.Constant)
                               and isinstance(a.value, str)
                               for a in node.args[:2])):
                msg = ("fr_event(plane, event, ...) must pass plane and "
                       "event as string literals")
            else:
                plane, event = node.args[0].value, node.args[1].value
                if plane not in declared:
                    msg = (f"fr_event plane {plane!r} is not declared "
                           f"in obs/events.py EVENTS")
                elif event not in declared[plane]:
                    msg = (f"fr_event event {plane!r}/{event!r} is not "
                           f"declared in obs/events.py EVENTS")
            if msg is not None and not _suppressed(lines, node.lineno,
                                                   "PY12"):
                findings.append((rel, node.lineno, "PY12", msg))


def lint_cpp(path: pathlib.Path, findings: list) -> None:
    rel = path.relative_to(ROOT)
    for i, line in enumerate(path.read_text().splitlines(), 1):
        if len(line) > CC_MAX_LINE:
            findings.append(
                (rel, i, "CC01", f"line too long ({len(line)} > {CC_MAX_LINE})")
            )
        if line != line.rstrip():
            findings.append((rel, i, "CC02", "trailing whitespace"))


def main() -> int:
    findings: list = []
    for f in py_files():
        lint_python(f, findings)
    for f in cc_files():
        lint_cpp(f, findings)
    lint_conf_keys(findings)
    lint_fr_events(findings)
    for rel, line, code, msg in findings:
        print(f"{rel}:{line}: {code} {msg}")
    if findings:
        print(f"lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print(f"lint: clean ({sum(1 for _ in py_files())} py, "
          f"{sum(1 for _ in cc_files())} c++ files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
