#!/usr/bin/env python
"""Benchmark: distributed-sort (TeraSort-style) shuffle throughput per chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference's headline result is HiBench TeraSort over 100 GbE RoCE
(README.md:7-19): its shuffle data plane is bounded by the NIC line rate
of 12.5 GB/s per node.  Here the same sortByKey pipeline (sample →
range-partition → all_to_all → local sort) runs as one XLA program with
the exchange riding ICI/HBM, so the comparable per-chip figure is
end-to-end sorted bytes per second; vs_baseline divides by the
reference's 12.5 GB/s per-node line rate ceiling.

Runs on whatever devices are visible (the driver provides one real TPU
chip; multi-chip scaling is validated separately by
__graft_entry__.dryrun_multichip).
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from sparkrdma_tpu.models.terasort import TeraSorter
from sparkrdma_tpu.parallel.mesh import make_mesh

# 100 GbE RoCE line rate, the reference data plane's per-node ceiling (GB/s)
BASELINE_GBPS = 12.5

N_RECORDS = 1 << 24  # 16.7M records x 8B (int32 key + int32 val) = 134 MB
WARMUP = 2
ITERS = 20

_PROBE_CODE = (
    "import jax, jax.numpy as jnp; "
    "assert int(jnp.sum(jnp.arange(100))) == 4950; "
    "print('BACKEND_OK', flush=True)"
)


def _probe_backend(timeout_s=240, attempts=2):
    """Liveness-check the device backend in a DISPOSABLE subprocess.

    The tunneled backend can hang indefinitely at init when the remote
    grant is wedged (a client SIGTERM'd mid-RPC holds it for hours —
    see tools/TPU_TODO.md).  Probing in a throwaway child means the
    main bench process never issues a device RPC until the backend is
    known-good, and is never the process that gets killed mid-RPC.
    Returns None when alive, else a short diagnostic string.

    Killing a timed-out probe is safe: a client hanging at backend
    INIT is queued on the grant, not holding it (observed during the
    round-2 wedge — fresh sessions just queue); the dangerous kill is
    of a client holding the grant mid-computation, and the probe's
    compute window after init is <1s.  The generous timeout still
    comfortably covers a slow-but-healthy cold init (~20-40s compile).
    """
    last = "unknown"
    for attempt in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True, text=True, timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            last = (f"probe attempt {attempt + 1}: no response in "
                    f"{timeout_s}s (backend init hang — wedged grant?)")
            print(f"# {last}", file=sys.stderr, flush=True)
            continue
        if r.returncode == 0 and "BACKEND_OK" in r.stdout:
            return None
        tail = (r.stderr or r.stdout or "").strip().splitlines()[-3:]
        last = (f"probe attempt {attempt + 1}: rc={r.returncode} "
                + " | ".join(tail))
        print(f"# {last}", file=sys.stderr, flush=True)
        time.sleep(5)
    return last


def main():
    err = _probe_backend()
    if err is not None:
        # structured record the driver can tell apart from a perf
        # regression: value/vs_baseline null, error names the cause
        print(json.dumps({
            "metric": "terasort shuffle+sort throughput per chip",
            "value": None,
            "unit": "GB/s/chip",
            "vs_baseline": None,
            "error": f"backend_unreachable: {err}",
        }))
        return

    import threading

    # second line of defense: the probe passed but the grant could
    # still wedge mid-run; abort loudly rather than hang the driver
    def _watchdog():
        print(
            "bench.py: device backend unresponsive for 600s after a "
            "successful pre-flight probe — aborting; see "
            "tools/TPU_TODO.md",
            file=sys.stderr, flush=True,
        )
        os._exit(3)

    timer = threading.Timer(600, _watchdog)
    timer.daemon = True
    timer.start()
    mesh = make_mesh()
    jax.block_until_ready(jnp.zeros(8))  # backend truly alive
    timer.cancel()
    sorter = TeraSorter(mesh)
    rng = np.random.default_rng(42)
    keys = jnp.asarray(
        rng.integers(0, 1 << 31, size=N_RECORDS, dtype=np.int32)
    )
    vals = jnp.asarray(
        rng.integers(0, 1 << 31, size=N_RECORDS, dtype=np.int32)
    )
    keys = jax.device_put(keys, sorter.sharding)
    vals = jax.device_put(vals, sorter.sharding)

    def run_once():
        (sk, sv, n_valid, _), _cap = sorter.sort_device(keys, vals)
        return sk, n_valid

    def fence(x):
        # on the axon platform block_until_ready can return before the
        # computation drains, so a device_get is the only trustworthy
        # fence; device execution is in-order, so fetching the LAST
        # dispatch's output fences every prior one too
        np.asarray(jax.device_get(x))

    for _ in range(WARMUP):
        sk, n_valid = run_once()
    fence(n_valid)
    # sanity: every record accounted for
    assert int(jnp.sum(n_valid)) == N_RECORDS, "records lost in exchange"

    # dispatch all iterations asynchronously and fence once: the host
    # round trip (~10s of ms through the device tunnel) would otherwise
    # dominate and measure latency, not shuffle throughput
    t0 = time.perf_counter()
    for _ in range(ITERS):
        _, n_valid = run_once()
    fence(n_valid)
    dt = (time.perf_counter() - t0) / ITERS
    engine = "lax.sort"

    # single-chip: try the experimental Pallas sort engine
    # (ops/sort_kernel.py) — adopted ONLY if it verifies exact on this
    # hardware AND beats the lax.sort step.  OPT-IN
    # (SPARKRDMA_TPU_ENABLE_SORT_KERNEL=1, exported by the sweep's
    # risky phase after tools/profile_tpu_sort.py survives): the
    # kernel has never Mosaic-compiled on silicon, a hung remote
    # compile here would stall the driver's unattended end-of-round
    # bench run with no watchdog, and killing a client mid-compile is
    # exactly what wedges the grant for hours (tools/TPU_TODO.md)
    n_chips = len(list(mesh.devices.flat))
    if n_chips == 1 and os.environ.get(
        "SPARKRDMA_TPU_ENABLE_SORT_KERNEL"
    ) and not os.environ.get("SPARKRDMA_TPU_DISABLE_SORT_KERNEL"):
        try:
            dt_p = _try_pallas_engine(keys, vals, dt)
            if dt_p is not None and dt_p < dt:
                dt = dt_p
                engine = "pallas 2-phase sort"
        except Exception as e:  # Mosaic may reject it — keep lax
            print(f"# pallas engine unavailable: {e!r}",
                  flush=True)

    bytes_per_iter = N_RECORDS * 8  # key + value
    gbps = bytes_per_iter / dt / 1e9
    per_chip = gbps / n_chips
    print(
        f"# terasort 8B-record shape ({N_RECORDS} records, {engine}): "
        f"{per_chip:.3f} GB/s/chip "
        f"(vs_baseline {per_chip / BASELINE_GBPS:.3f})",
        flush=True,
    )

    # headline metric: the HiBench record shape the reference's 175 GB
    # result is measured on (10B key + 90B value ≈ 100B records,
    # /root/reference/README.md:7-19) — the sort cost is per RECORD, so
    # wide values are the honest sorted-bytes/s comparison against the
    # NIC line rate.  The wide path must never be a single point of
    # failure for the round's number: if it is rejected by the compiler,
    # overflows, or trips any backend quirk, fall back to emitting the
    # 8B-shape figure measured above so a JSON line ALWAYS lands.
    def _fallback_record(reason):
        return json.dumps(
            {
                "metric": "terasort shuffle+sort throughput per "
                          f"chip, 8B records ({N_RECORDS} records, "
                          f"{n_chips} chip(s), {engine}; wide-path "
                          "fallback)",
                "value": round(per_chip, 3),
                "unit": "GB/s/chip",
                "vs_baseline": round(per_chip / BASELINE_GBPS, 3),
                "fallback_reason": reason,
            }
        )

    # a wedged grant mid-wide-path hangs in device_get without raising;
    # this timer converts that hang into the 8B fallback line + exit.
    # _emit_once makes timer and main path mutually exclusive so the
    # ONE-JSON-line contract holds even if the timer races completion;
    # 1800s is generous enough that a slow-but-progressing run (two
    # compiles + warmup + ITERS wide sorts through the tunnel) is not
    # mislabeled as a hang
    emit_lock = threading.Lock()
    emitted = [False]

    def _emit_once(line):
        with emit_lock:
            if emitted[0]:
                return False
            emitted[0] = True
        print(line, flush=True)
        return True

    def _wide_hang():
        if _emit_once(_fallback_record("wide_path_hang")):
            print("bench.py: wide path unresponsive for 1800s — "
                  "emitted 8B-shape fallback, aborting",
                  file=sys.stderr, flush=True)
            os._exit(0)

    wtimer = threading.Timer(1800, _wide_hang)
    wtimer.daemon = True
    wtimer.start()
    try:
        wide_chip = _bench_wide(mesh, fence)
    except Exception as e:
        wtimer.cancel()
        print(f"# wide path failed ({e!r}); emitting 8B-shape fallback",
              file=sys.stderr, flush=True)
        _emit_once(_fallback_record(f"wide_path_error: {e!r}"))
        return
    wtimer.cancel()
    _emit_once(
        json.dumps(
            {
                "metric": "terasort shuffle+sort throughput per chip, "
                          f"HiBench 100B records ({N_WIDE} records, "
                          f"{n_chips} chip(s), key sort + payload "
                          f"gather)",
                "value": round(wide_chip, 3),
                "unit": "GB/s/chip",
                "vs_baseline": round(wide_chip / BASELINE_GBPS, 3),
            }
        )
    )


N_WIDE = 1 << 22       # 4.2M records
WIDE_WORDS = 24        # 96B payload + 4B key = 100B (HiBench ~100B)


def _bench_wide(mesh, fence):
    """Time the wide-record sort (models/terasort.py wide path);
    returns GB/s per chip.  Retries once with a higher capacity factor
    on bucket overflow."""
    from sparkrdma_tpu.models.terasort import TeraSorter

    rng = np.random.default_rng(7)
    keys = jnp.asarray(
        rng.integers(0, 1 << 31, N_WIDE, dtype=np.int32)
    )
    payload = jnp.asarray(
        rng.integers(0, 1 << 31, (N_WIDE, WIDE_WORDS), dtype=np.int32)
    )
    n_chips = len(list(mesh.devices.flat))
    for factor in (1.3, 2.0):
        sorter = TeraSorter(mesh, capacity_factor=factor)
        (sk, sp, n_valid, max_fill), cap = sorter.sort_device_wide(
            keys, payload
        )
        fence(n_valid)
        if int(np.max(np.asarray(jax.device_get(max_fill)))) > cap:
            continue  # overflow: retry with more headroom
        assert int(np.asarray(jax.device_get(n_valid)).sum()) == N_WIDE
        t0 = time.perf_counter()
        for _ in range(ITERS):
            (sk, sp, n_valid, _mf), _ = sorter.sort_device_wide(
                keys, payload
            )
        fence(n_valid)
        dt = (time.perf_counter() - t0) / ITERS
        record_bytes = 4 + 4 * WIDE_WORDS
        return N_WIDE * record_bytes / dt / 1e9 / n_chips
    raise AssertionError("wide sort overflowed even at factor 2.0")


def _try_pallas_engine(keys, vals, dt_lax):
    """Time the Pallas two-phase sort; returns secs/iter or None.
    Verifies exactness (count + sortedness on a sampled stride) before
    trusting any number."""
    from sparkrdma_tpu.ops.sort_kernel import bucket_cap, sort_pairs_full

    def run(k, v):
        ok, ov, valid, _fn, overflow = sort_pairs_full(
            k, v, block_rows=512, n_buckets=16
        )
        return ok, ov, valid, overflow

    fn = jax.jit(run)

    def fence1(x):
        np.asarray(jax.device_get(x.reshape(-1)[-1:]))

    ok, ov, valid, overflow = fn(keys, vals)
    fence1(valid)
    # overflow contract (ops/sort_kernel.py): outputs are garbage if
    # any bucket exceeded cap
    if int(jax.device_get(overflow)) > bucket_cap(N_RECORDS, 16):
        return None
    valid_h = np.asarray(jax.device_get(valid))
    if int(valid_h.sum()) != N_RECORDS:
        return None
    ok_h = np.asarray(jax.device_get(ok))[valid_h > 0]
    if not (np.diff(ok_h[:: max(1, len(ok_h) // 100000)]) >= 0).all():
        return None
    if not (np.diff(ok_h) >= 0).all():
        return None
    t0 = time.perf_counter()
    for _ in range(ITERS):
        ok, ov, valid, overflow = fn(keys, vals)
    fence1(valid)
    return (time.perf_counter() - t0) / ITERS


if __name__ == "__main__":
    main()
