#!/usr/bin/env python
"""Benchmark: distributed-sort (TeraSort-style) shuffle throughput per chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference's headline result is HiBench TeraSort over 100 GbE RoCE
(README.md:7-19): its shuffle data plane is bounded by the NIC line rate
of 12.5 GB/s per node.  Here the same sortByKey pipeline (sample →
range-partition → all_to_all → local sort) runs as one XLA program with
the exchange riding ICI/HBM, so the comparable per-chip figure is
end-to-end sorted bytes per second; vs_baseline divides by the
reference's 12.5 GB/s per-node line rate ceiling.

Runs on whatever devices are visible (the driver provides one real TPU
chip; multi-chip scaling is validated separately by
__graft_entry__.dryrun_multichip).
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from sparkrdma_tpu.models.terasort import TeraSorter
from sparkrdma_tpu.parallel.mesh import make_mesh

# 100 GbE RoCE line rate, the reference data plane's per-node ceiling (GB/s)
BASELINE_GBPS = 12.5

N_RECORDS = 1 << 24  # 16.7M records x 8B (int32 key + int32 val) = 134 MB
WARMUP = 2
ITERS = 20


def main():
    # the tunneled backend can hang indefinitely at init when the
    # remote grant is wedged (see tools/TPU_TODO.md); fail loudly with
    # a diagnostic instead of hanging the driver's bench run
    import os
    import sys
    import threading

    def _watchdog():
        print(
            "bench.py: device backend unresponsive for 300s "
            "(tunneled TPU grant wedged?) — aborting instead of "
            "hanging; see tools/TPU_TODO.md",
            file=sys.stderr, flush=True,
        )
        os._exit(3)

    timer = threading.Timer(300, _watchdog)
    timer.daemon = True
    timer.start()
    mesh = make_mesh()
    jax.block_until_ready(jnp.zeros(8))  # backend truly alive
    timer.cancel()
    sorter = TeraSorter(mesh)
    rng = np.random.default_rng(42)
    keys = jnp.asarray(
        rng.integers(0, 1 << 31, size=N_RECORDS, dtype=np.int32)
    )
    vals = jnp.asarray(
        rng.integers(0, 1 << 31, size=N_RECORDS, dtype=np.int32)
    )
    keys = jax.device_put(keys, sorter.sharding)
    vals = jax.device_put(vals, sorter.sharding)

    def run_once():
        (sk, sv, n_valid, _), _cap = sorter.sort_device(keys, vals)
        return sk, n_valid

    def fence(x):
        # on the axon platform block_until_ready can return before the
        # computation drains, so a device_get is the only trustworthy
        # fence; device execution is in-order, so fetching the LAST
        # dispatch's output fences every prior one too
        np.asarray(jax.device_get(x))

    for _ in range(WARMUP):
        sk, n_valid = run_once()
    fence(n_valid)
    # sanity: every record accounted for
    assert int(jnp.sum(n_valid)) == N_RECORDS, "records lost in exchange"

    # dispatch all iterations asynchronously and fence once: the host
    # round trip (~10s of ms through the device tunnel) would otherwise
    # dominate and measure latency, not shuffle throughput
    t0 = time.perf_counter()
    for _ in range(ITERS):
        _, n_valid = run_once()
    fence(n_valid)
    dt = (time.perf_counter() - t0) / ITERS
    engine = "lax.sort"

    # single-chip: try the experimental Pallas sort engine
    # (ops/sort_kernel.py) — adopted ONLY if it verifies exact on this
    # hardware AND beats the lax.sort step (it has never run on real
    # silicon when slower/broken, the lax number above stands)
    n_chips = len(list(mesh.devices.flat))
    if n_chips == 1:
        try:
            dt_p = _try_pallas_engine(keys, vals, dt)
            if dt_p is not None and dt_p < dt:
                dt = dt_p
                engine = "pallas 2-phase sort"
        except Exception as e:  # Mosaic may reject it — keep lax
            print(f"# pallas engine unavailable: {e!r}",
                  flush=True)

    bytes_per_iter = N_RECORDS * 8  # key + value
    gbps = bytes_per_iter / dt / 1e9
    per_chip = gbps / n_chips
    print(
        json.dumps(
            {
                "metric": "terasort shuffle+sort throughput per chip "
                          f"({N_RECORDS} records, {n_chips} chip(s), "
                          f"{engine})",
                "value": round(per_chip, 3),
                "unit": "GB/s/chip",
                "vs_baseline": round(per_chip / BASELINE_GBPS, 3),
            }
        )
    )


def _try_pallas_engine(keys, vals, dt_lax):
    """Time the Pallas two-phase sort; returns secs/iter or None.
    Verifies exactness (count + sortedness on a sampled stride) before
    trusting any number."""
    from sparkrdma_tpu.ops.sort_kernel import sort_pairs_full

    fn = jax.jit(
        lambda k, v: sort_pairs_full(
            k, v, block_rows=512, n_buckets=16
        )[:3]
    )

    def fence1(x):
        np.asarray(jax.device_get(x.reshape(-1)[-1:]))

    ok, ov, valid = fn(keys, vals)
    fence1(valid)
    valid_h = np.asarray(jax.device_get(valid))
    if int(valid_h.sum()) != N_RECORDS:
        return None
    ok_h = np.asarray(jax.device_get(ok))[valid_h > 0]
    if not (np.diff(ok_h[:: max(1, len(ok_h) // 100000)]) >= 0).all():
        return None
    if not (np.diff(ok_h) >= 0).all():
        return None
    t0 = time.perf_counter()
    for _ in range(ITERS):
        ok, ov, valid = fn(keys, vals)
    fence1(valid)
    return (time.perf_counter() - t0) / ITERS


if __name__ == "__main__":
    main()
