"""Device-native model pipelines (TeraSort, WordCount) on the 8-device
CPU mesh — the flagship workloads (SURVEY.md §6 benchmarks)."""

import numpy as np
import pytest

from sparkrdma_tpu.models import TeraSorter, WordCounter
from sparkrdma_tpu.parallel import make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def test_terasort_uniform(mesh, devices):
    sorter = TeraSorter(mesh)
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 31, size=100_000, dtype=np.int32)
    vals = rng.integers(0, 1 << 31, size=100_000, dtype=np.int32)
    sk, sv = sorter.sort(keys, vals)
    order = np.argsort(keys, kind="stable")
    np.testing.assert_array_equal(sk, keys[order])
    np.testing.assert_array_equal(np.sort(sv), np.sort(vals))
    # key-value alignment preserved through the exchange
    kv = dict()
    for k, v in zip(keys.tolist(), vals.tolist()):
        kv.setdefault(k, []).append(v)
    for k, v in zip(sk[:100].tolist(), sv[:100].tolist()):
        assert v in kv[k]


def test_terasort_skewed_overflow_retry(mesh, devices):
    sorter = TeraSorter(mesh, capacity_factor=1.05)
    rng = np.random.default_rng(1)
    # 60% of keys in a tiny range → one device's bucket overflows at
    # factor 1.05 and the host must retry with doubled capacity
    a = rng.integers(0, 100, size=60_000, dtype=np.int32)
    b = rng.integers(0, 1 << 30, size=40_000, dtype=np.int32)
    keys = np.concatenate([a, b])
    rng.shuffle(keys)
    sk, _ = sorter.sort(keys, keys)
    np.testing.assert_array_equal(sk, np.sort(keys))


def test_terasort_ragged_length_and_empty(mesh, devices):
    sorter = TeraSorter(mesh)
    keys = np.array([5, 3, 9], dtype=np.int32)  # not divisible by 8
    sk, sv = sorter.sort(keys, keys * 10)
    np.testing.assert_array_equal(sk, [3, 5, 9])
    np.testing.assert_array_equal(sv, [30, 50, 90])
    ek, ev = sorter.sort(np.array([], dtype=np.int32))
    assert ek.size == 0 and ev.size == 0


def test_wordcount(mesh, devices):
    wc = WordCounter(mesh)
    rng = np.random.default_rng(2)
    keys = rng.integers(0, 1000, size=50_000, dtype=np.int32)
    got = wc.count(keys)
    expect = {int(k): int(c) for k, c in zip(*np.unique(keys, return_counts=True))}
    assert got == expect


def test_wordcount_weighted_values(mesh, devices):
    wc = WordCounter(mesh)
    keys = np.array([1, 2, 1, 3, 2, 1], dtype=np.int32)
    vals = np.array([10, 20, 30, 40, 50, 60], dtype=np.int32)
    assert wc.count(keys, vals) == {1: 100, 2: 70, 3: 40}


def test_wordcount_single_hot_key(mesh, devices):
    # extreme skew: every record hits one key on one device
    wc = WordCounter(mesh, capacity_factor=1.1)
    keys = np.full(10_000, 77, dtype=np.int32)
    assert wc.count(keys) == {77: 10_000}


def test_max_value_keys_not_confused_with_padding(mesh, devices):
    # reviewer finding: keys equal to iinfo.max must survive both models
    sentinel = np.iinfo(np.int32).max
    wc = WordCounter(mesh)
    k = np.array([sentinel, sentinel, 5], dtype=np.int32)  # ragged: pads added
    assert wc.count(k) == {sentinel: 2, 5: 1}

    sorter = TeraSorter(mesh)
    keys = np.array([sentinel, 1, sentinel, 3, 2], dtype=np.int32)
    vals = np.array([10, 11, 12, 13, 14], dtype=np.int32)
    sk, sv = sorter.sort(keys, vals)
    np.testing.assert_array_equal(sk, [1, 2, 3, sentinel, sentinel])
    assert sv[0] == 11 and sv[1] == 14 and sv[2] == 13
    assert sorted(sv[3:]) == [10, 12]  # max-key values kept, not pad zeros


def test_sort_device_arbitrary_valid_column(mesh, devices):
    """sort_device must honor a valid column whose invalid slots carry
    ARBITRARY keys (not pre-set to the dtype max): invalid records are
    dropped, all real records survive."""
    import jax.numpy as jnp

    sorter = TeraSorter(mesh)
    rng = np.random.default_rng(7)
    n = 8 * 1024
    keys = rng.integers(0, 1 << 31, size=n, dtype=np.int32)
    vals = rng.integers(0, 1 << 31, size=n, dtype=np.int32)
    valid = (rng.random(n) < 0.7).astype(np.int32)
    (sk, sv, n_valid, _), cap = sorter.sort_device(
        jnp.asarray(keys), jnp.asarray(vals), jnp.asarray(valid)
    )
    D = sorter.n_devices
    sk_h = np.asarray(sk).reshape(D, -1)
    sv_h = np.asarray(sv).reshape(D, -1)
    nv = np.asarray(n_valid).reshape(-1)
    out_k = np.concatenate([sk_h[d, : nv[d]] for d in range(D)])
    out_v = np.concatenate([sv_h[d, : nv[d]] for d in range(D)])
    real = valid > 0
    np.testing.assert_array_equal(out_k, np.sort(keys[real], kind="stable"))
    np.testing.assert_array_equal(np.sort(out_v), np.sort(vals[real]))


def _join_case(seed, n_fact, n_dim, key_space):
    rng = np.random.default_rng(seed)
    dim_keys = rng.choice(key_space, size=n_dim, replace=False).astype(np.int32)
    dim_vals = rng.integers(0, 1 << 30, size=n_dim, dtype=np.int32)
    fact_keys = rng.integers(0, key_space, size=n_fact, dtype=np.int32)
    fact_vals = rng.integers(0, 1 << 30, size=n_fact, dtype=np.int32)
    lookup = dict(zip(dim_keys.tolist(), dim_vals.tolist()))
    expected = sorted(
        (int(k), int(v), lookup[int(k)])
        for k, v in zip(fact_keys, fact_vals) if int(k) in lookup
    )
    return fact_keys, fact_vals, dim_keys, dim_vals, expected


@pytest.mark.parametrize("joiner_cls", ["hash", "broadcast"])
def test_device_join(joiner_cls, mesh, devices):
    from sparkrdma_tpu.models.join import BroadcastJoiner, HashJoiner

    fk, fv, dk, dv, expected = _join_case(5, 4000, 300, 1000)
    j = (HashJoiner if joiner_cls == "hash" else BroadcastJoiner)(mesh)
    k, lv, rv = j.join(fk, fv, dk, dv)
    got = sorted(zip(k.tolist(), lv.tolist(), rv.tolist()))
    assert got == expected


def test_hash_join_skewed_overflow_retry(mesh, devices):
    from sparkrdma_tpu.models.join import HashJoiner

    rng = np.random.default_rng(9)
    # 70% of fact keys identical -> one device's bucket overflows
    hot = np.full(7000, 42, np.int32)
    cold = rng.integers(0, 500, size=3000, dtype=np.int32)
    fk = np.concatenate([hot, cold])
    fv = np.arange(10000, dtype=np.int32)
    dk = np.arange(500, dtype=np.int32)
    dv = dk * 3
    j = HashJoiner(mesh, capacity_factor=1.1)
    k, lv, rv = j.join(fk, fv, dk, dv)
    assert len(k) == 10000  # every fact key exists in dim
    assert (rv == k * 3).all()


@pytest.mark.parametrize("joiner_cls", ["hash", "broadcast"])
def test_join_dtype_max_fact_key(joiner_cls, mesh, devices):
    # reviewer finding: a fact key equal to iinfo.max must not match a
    # sentinel-masked padding/fill slot (validity of the hit is checked)
    from sparkrdma_tpu.models.join import BroadcastJoiner, HashJoiner

    imax = np.iinfo(np.int32).max
    fk = np.array([1, 2, imax, 5], np.int32)
    fv = np.array([10, 20, 30, 50], np.int32)
    dk = np.array([1, 2, 3], np.int32)
    dv = np.array([100, 200, 300], np.int32)
    j = (HashJoiner if joiner_cls == "hash" else BroadcastJoiner)(mesh)
    k, lv, rv = j.join(fk, fv, dk, dv)
    got = sorted(zip(k.tolist(), lv.tolist(), rv.tolist()))
    assert got == [(1, 10, 100), (2, 20, 200)]


@pytest.mark.parametrize("joiner_cls", ["hash", "broadcast"])
def test_join_dtype_max_dim_key_matches(joiner_cls, mesh, devices):
    # a REAL dim key equal to iinfo.max must still be matchable
    from sparkrdma_tpu.models.join import BroadcastJoiner, HashJoiner

    imax = np.iinfo(np.int32).max
    fk = np.array([imax, 7], np.int32)
    fv = np.array([1, 2], np.int32)
    dk = np.array([imax, 7], np.int32)
    dv = np.array([111, 77], np.int32)
    j = (HashJoiner if joiner_cls == "hash" else BroadcastJoiner)(mesh)
    k, lv, rv = j.join(fk, fv, dk, dv)
    got = sorted(zip(k.tolist(), lv.tolist(), rv.tolist()))
    assert got == [(7, 2, 77), (imax, 1, 111)]


@pytest.mark.parametrize("joiner_cls", ["hash", "broadcast"])
def test_join_empty_dimension(joiner_cls, mesh, devices):
    # reviewer finding: empty dimension side -> empty result, not a crash
    from sparkrdma_tpu.models.join import BroadcastJoiner, HashJoiner

    fk = np.array([1, 2, 3, 4], np.int32)
    fv = np.array([10, 20, 30, 40], np.int32)
    j = (HashJoiner if joiner_cls == "hash" else BroadcastJoiner)(mesh)
    k, lv, rv = j.join(fk, fv, np.array([], np.int32), np.array([], np.int32))
    assert len(k) == 0 and len(lv) == 0 and len(rv) == 0


def test_keyed_aggregator_full_stats(mesh, devices):
    from sparkrdma_tpu.models.aggregate import KeyedAggregator

    rng = np.random.default_rng(12)
    n = 20000
    keys = rng.integers(0, 300, n).astype(np.int32)
    vals = rng.integers(-1000, 1000, n).astype(np.int32)
    agg = KeyedAggregator(mesh)
    out = agg.aggregate(keys, vals)
    assert set(out) == set(np.unique(keys).tolist())
    for k in np.unique(keys):
        sel = vals[keys == k]
        st = out[int(k)]
        assert st.sum == int(sel.sum())
        assert st.count == len(sel)
        assert st.min == int(sel.min())
        assert st.max == int(sel.max())
        assert abs(st.mean - sel.mean()) < 1e-9


def test_keyed_aggregator_sentinel_key_and_padding(mesh, devices):
    from sparkrdma_tpu.models.aggregate import KeyedAggregator

    imax = np.iinfo(np.int32).max
    # a real key equal to the sentinel, with a size forcing padding
    keys = np.array([imax, 5, imax, 5, imax], np.int32)
    vals = np.array([7, -2, 3, 4, -9], np.int32)
    out = KeyedAggregator(mesh).aggregate(keys, vals)
    assert out[imax] == (1, 3, -9, 7)
    assert out[5] == (2, 2, -2, 4)


def test_keyed_aggregator_skew_retry(mesh, devices):
    from sparkrdma_tpu.models.aggregate import KeyedAggregator

    rng = np.random.default_rng(13)
    hot = np.full(9000, 17, np.int32)
    cold = rng.integers(0, 50, 1000).astype(np.int32)
    keys = np.concatenate([hot, cold])
    vals = np.arange(10000, dtype=np.int32)
    out = KeyedAggregator(mesh, capacity_factor=1.1).aggregate(keys, vals)
    sel = vals[keys == 17]
    assert out[17] == (int(sel.sum()), len(sel), int(sel.min()), int(sel.max()))


def test_keyed_aggregator_rejects_silent_int64_truncation(mesh, devices):
    from sparkrdma_tpu.models.aggregate import KeyedAggregator
    import jax as _jax

    if _jax.config.jax_enable_x64:
        pytest.skip("x64 enabled: int64 is exact, nothing to reject")
    keys = np.zeros(8, np.int32)
    vals = np.full(8, 2**40, np.int64)
    with pytest.raises(ValueError, match="int64"):
        KeyedAggregator(mesh).aggregate(keys, vals)


def test_wordcount_rejects_silent_int64_truncation(mesh, devices):
    # reviewer finding: the guard must cover every keyed model and BOTH
    # columns (int64 keys collide after a silent int32 downcast)
    from sparkrdma_tpu.models.wordcount import WordCounter
    from sparkrdma_tpu.models.aggregate import KeyedAggregator
    import jax as _jax

    if _jax.config.jax_enable_x64:
        pytest.skip("x64 enabled: int64 is exact, nothing to reject")
    with pytest.raises(ValueError, match="int64 vals"):
        WordCounter(mesh).count(
            np.zeros(8, np.int32), np.full(8, 2**40, np.int64)
        )
    with pytest.raises(ValueError, match="int64 keys"):
        KeyedAggregator(mesh).aggregate(
            np.array([2**33 + 1, 1] * 4, np.int64), np.ones(8, np.int32)
        )


@pytest.mark.parametrize("joiner_cls", ["hash", "broadcast"])
def test_join_mixed_dtype_fact_vals_exact(joiner_cls, mesh, devices):
    # reviewer finding: int32 fact values joined against float32 dim
    # values must come back EXACT (no silent promotion through the sort)
    from sparkrdma_tpu.models.join import BroadcastJoiner, HashJoiner

    fk = np.array([1, 2, 3], np.int32)
    fv = np.array([2**24 + 1, 7, 9], np.int32)  # 2^24+1 not float32-exact
    dk = np.array([1, 2], np.int32)
    dv = np.array([0.5, 1.5], np.float32)
    j = (HashJoiner if joiner_cls == "hash" else BroadcastJoiner)(mesh)
    k, lv, rv = j.join(fk, fv, dk, dv)
    got = sorted(zip(k.tolist(), lv.tolist(), rv.tolist()))
    assert got == [(1, 2**24 + 1, 0.5), (2, 7, 1.5)]
    assert lv.dtype == np.int32


def test_join_rejects_silent_int64_truncation(mesh, devices):
    from sparkrdma_tpu.models.join import HashJoiner
    import jax as _jax

    if _jax.config.jax_enable_x64:
        pytest.skip("x64 enabled: int64 is exact, nothing to reject")
    fk = np.array([2**33 + 1, 5], np.int64)
    fv = np.array([10, 20], np.int32)
    dk = np.array([1], np.int64)
    dv = np.array([99], np.int32)
    with pytest.raises(ValueError, match="int64 keys"):
        HashJoiner(mesh).join(fk, fv, dk, dv)


def test_external_sort_streaming_chunks(mesh, devices):
    from sparkrdma_tpu.models.external_sort import ExternalTeraSorter

    rng = np.random.default_rng(50)
    all_k, all_v = [], []

    def chunks():
        for _ in range(10):
            n = int(rng.integers(1000, 5000))
            k = rng.integers(0, 1 << 30, n).astype(np.int32)
            v = rng.integers(0, 1 << 30, n).astype(np.int32)
            all_k.append(k)
            all_v.append(v)
            yield k, v

    ext = ExternalTeraSorter(mesh, num_buckets=8, sample_per_chunk=512)
    outs = list(ext.sort_chunks(chunks()))
    got_k = np.concatenate([k for k, _ in outs])
    got_v = np.concatenate([v for _, v in outs])
    keys = np.concatenate(all_k)
    vals = np.concatenate(all_v)
    order = np.argsort(keys, kind="stable")
    np.testing.assert_array_equal(got_k, keys[order])
    assert sorted(zip(got_k.tolist(), got_v.tolist())) == sorted(
        zip(keys.tolist(), vals.tolist())
    )
    assert ext.chunks_in == 10
    assert ext.bytes_spilled == keys.nbytes + vals.nbytes
    # memory bound: no bucket anywhere near the whole dataset
    assert ext.max_bucket_records < len(keys) // 2


def test_external_sort_empty_and_single(mesh, devices):
    from sparkrdma_tpu.models.external_sort import ExternalTeraSorter

    ext = ExternalTeraSorter(mesh, num_buckets=4)
    k, v = ext.sort(np.zeros(0, np.int32), np.zeros(0, np.int32))
    assert len(k) == 0 and len(v) == 0
    k, v = ExternalTeraSorter(mesh, num_buckets=4).sort(
        np.array([5], np.int32), np.array([7], np.int32)
    )
    assert k.tolist() == [5] and v.tolist() == [7]


def test_external_sort_resplits_sorted_input(mesh, devices):
    """Adversarial (already sorted) input freezes the first-chunk
    splitters on an unrepresentative sample; pass 2 must re-split the
    oversized bucket instead of loading it whole (advisor finding)."""
    from sparkrdma_tpu.models.external_sort import ExternalTeraSorter

    n_chunk, n_chunks = 2000, 8
    keys = np.arange(n_chunk * n_chunks, dtype=np.int32)
    vals = keys[::-1].copy()

    def chunks():
        for c in range(n_chunks):
            sl = slice(c * n_chunk, (c + 1) * n_chunk)
            yield keys[sl], vals[sl]

    ext = ExternalTeraSorter(mesh, num_buckets=8, sample_per_chunk=256)
    outs = list(ext.sort_chunks(chunks()))
    got_k = np.concatenate([k for k, _ in outs])
    got_v = np.concatenate([v for _, v in outs])
    np.testing.assert_array_equal(got_k, keys)
    np.testing.assert_array_equal(got_v, vals)
    # sorted input routes chunks 2..N into the last range bucket; the
    # re-split must both trigger and restore the working-set bound
    assert ext.buckets_resplit >= 1
    assert ext.max_bucket_records <= n_chunk


def test_external_sort_balanced_input_no_resplit(mesh, devices):
    """Balanced buckets larger than one chunk must NOT trigger the
    re-split path (the bound is max(chunk, balanced bucket))."""
    from sparkrdma_tpu.models.external_sort import ExternalTeraSorter

    rng = np.random.default_rng(51)
    # 16 chunks of 1000 into 4 buckets: balanced buckets hold ~4000
    # records, well over one chunk — still no re-split
    ext = ExternalTeraSorter(mesh, num_buckets=4, sample_per_chunk=512)
    ks = rng.integers(0, 1 << 30, (16, 1000)).astype(np.int32)
    outs = list(ext.sort_chunks((k, k.copy()) for k in ks))
    got = np.concatenate([k for k, _ in outs])
    np.testing.assert_array_equal(got, np.sort(ks.reshape(-1)))
    assert ext.buckets_resplit == 0


def test_external_sort_duplicate_heavy_bucket(mesh, devices):
    """An all-one-key bucket cannot be split by key; the re-split must
    detect no-progress and fall back to a whole load instead of
    recursing max_split_depth times over the same file."""
    from sparkrdma_tpu.models.external_sort import ExternalTeraSorter

    keys = np.concatenate([
        np.arange(2000, dtype=np.int32),          # chunk 1: spread
        np.full(14000, 7_000_000, np.int32),      # chunks 2..8: one key
    ])
    vals = np.arange(len(keys), dtype=np.int32)
    ext = ExternalTeraSorter(mesh, num_buckets=8, sample_per_chunk=128)
    outs = list(ext.sort_chunks(
        (keys[i:i + 2000], vals[i:i + 2000])
        for i in range(0, len(keys), 2000)
    ))
    got_k = np.concatenate([k for k, _ in outs])
    got_v = np.concatenate([v for _, v in outs])
    order = np.argsort(keys, kind="stable")
    np.testing.assert_array_equal(got_k, keys[order])
    assert sorted(got_v.tolist()) == sorted(vals.tolist())
    # the degenerate bucket loaded whole exactly once (no useless churn)
    assert ext.buckets_resplit == 0


def test_join_int64_keys_under_x64():
    """64-bit keys/values must survive the packed transport when
    jax_enable_x64 is on: keys differing only in their high 32 bits
    must NOT collide (regression: the uint32 transport collapsed
    2**32+1 onto 1).  Runs in a subprocess because x64 is a global
    startup flag."""
    import subprocess
    import sys

    code = """
import os
os.environ["JAX_ENABLE_X64"] = "1"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
from sparkrdma_tpu.models.join import BroadcastJoiner, HashJoiner
from sparkrdma_tpu.parallel.mesh import make_mesh

mesh = make_mesh(1)
fact_keys = np.array([1, 2**32 + 1, 5], dtype=np.int64)
fact_vals = np.array([10, 20, 30], dtype=np.int64)
dim_keys = np.array([1, 5], dtype=np.int64)
dim_vals = np.array([100, 2**33 + 7], dtype=np.int64)
for joiner in (HashJoiner(mesh), BroadcastJoiner(mesh)):
    k, fv, dv = joiner.join(fact_keys, fact_vals, dim_keys, dim_vals)
    rows = sorted(zip(k.tolist(), fv.tolist(), dv.tolist()))
    assert rows == [(1, 10, 100), (5, 30, 2**33 + 7)], (
        type(joiner).__name__, rows)
print("OK")
"""
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240,
    )
    assert out.returncode == 0 and "OK" in out.stdout, (
        out.stdout + out.stderr
    )


def _join_aggregate_oracle(fk, fv, dk, dv, gk_fn, val_fn):
    """numpy oracle for the fused broadcast-join + aggregate."""
    lookup = dict(zip(dk.tolist(), dv.tolist()))
    groups = {}
    for k, pv in zip(fk.tolist(), fv.tolist()):
        if k not in lookup:
            continue
        g = gk_fn(k)
        v = val_fn(k, pv, lookup[k])
        s, c, mn, mx = groups.get(g, (0, 0, None, None))
        groups[g] = (
            s + v, c + 1,
            v if mn is None else min(mn, v),
            v if mx is None else max(mx, v),
        )
    return groups


def test_broadcast_join_aggregate_fused(mesh, devices):
    import jax.numpy as jnp

    from sparkrdma_tpu.models.join_aggregate import BroadcastJoinAggregator

    fk, fv, dk, dv, _ = _join_case(11, 4096, 300, 1000)
    # negative dim values exercise min/max over the signed decode
    dv = dv - (1 << 29)

    def gk_fn(ku):
        return ku % jnp.asarray(17, ku.dtype)

    def val_fn(ku, fact_pay_u, dim_val_u):
        import jax.lax as lax

        return lax.bitcast_convert_type(
            fact_pay_u, jnp.int32
        ) ^ lax.bitcast_convert_type(dim_val_u, jnp.int32)

    agg = BroadcastJoinAggregator(mesh)
    got = agg.join_aggregate(fk, fv, dk, dv, gk_fn, val_fn)
    want = _join_aggregate_oracle(
        fk, fv, dk, dv, lambda k: k % 17, lambda k, a, b: a ^ b
    )
    assert set(got) == set(want)
    for g, (s, c, mn, mx) in want.items():
        st = got[g]
        # sums wrap in int32 (JVM Int parity, models/aggregate.py)
        assert (st.sum - s) % (1 << 32) == 0, (g, st, s)
        assert (st.count, st.min, st.max) == (c, mn, mx), (g, st)


def test_broadcast_join_aggregate_defaults_and_edge_keys(mesh, devices):
    from sparkrdma_tpu.models.join_aggregate import BroadcastJoinAggregator

    imax = np.iinfo(np.int32).max
    # default hooks: group by the join key, aggregate the dim value;
    # imax fact key must not match padding, unmatched key 9 drops out
    fk = np.array([1, 1, 2, imax, 9], np.int32)
    fv = np.array([10, 11, 20, 30, 90], np.int32)
    dk = np.array([1, 2], np.int32)
    dv = np.array([-5, 7], np.int32)
    agg = BroadcastJoinAggregator(mesh)
    got = agg.join_aggregate(fk, fv, dk, dv)
    assert set(got) == {1, 2}
    assert got[1] == (-10, 2, -5, -5)
    assert got[2] == (7, 1, 7, 7)


def test_broadcast_join_aggregate_negative_keys(mesh, devices):
    # group keys must come back in the signed join-key domain, not the
    # unsigned transport view (code-review finding)
    from sparkrdma_tpu.models.join_aggregate import BroadcastJoinAggregator

    fk = np.array([-5, -5, 3], np.int32)
    fv = np.array([1, 2, 3], np.int32)
    dk = np.array([-5, 3], np.int32)
    dv = np.array([100, 200], np.int32)
    got = BroadcastJoinAggregator(mesh).join_aggregate(fk, fv, dk, dv)
    assert set(got) == {-5, 3}
    assert got[-5] == (200, 2, 100, 100)
    assert got[3] == (200, 1, 200, 200)


@pytest.mark.parametrize("joiner_cls", ["hash", "broadcast"])
def test_join_variants_semi_anti_outer(joiner_cls, mesh, devices):
    """left-semi (TPC-DS q16), left-anti (q94), and left-outer joins
    against dict oracles."""
    from sparkrdma_tpu.models.join import BroadcastJoiner, HashJoiner

    fk, fv, dk, dv, _ = _join_case(23, 5000, 250, 900)
    lut = dict(zip(dk.tolist(), dv.tolist()))
    j = (HashJoiner if joiner_cls == "hash" else BroadcastJoiner)(mesh)

    matched = sorted(
        (int(k), int(v)) for k, v in zip(fk, fv) if int(k) in lut
    )
    unmatched = sorted(
        (int(k), int(v)) for k, v in zip(fk, fv) if int(k) not in lut
    )

    k, lv = j.join(fk, fv, dk, dv, how="semi")
    assert sorted(zip(k.tolist(), lv.tolist())) == matched

    k, lv = j.join(fk, fv, dk, dv, how="anti")
    assert sorted(zip(k.tolist(), lv.tolist())) == unmatched

    k, lv, rv, m = j.join(fk, fv, dk, dv, how="left_outer")
    assert len(k) == len(fk)
    got = sorted(
        ((int(kk), int(vv), int(rr) if mm else None)
         for kk, vv, rr, mm in zip(k, lv, rv, m)),
        key=lambda t: (t[0], t[1]),
    )
    want = sorted(
        ((int(kk), int(vv), lut.get(int(kk)))
         for kk, vv in zip(fk, fv)),
        key=lambda t: (t[0], t[1]),
    )
    assert got == want

    with pytest.raises(ValueError, match="how"):
        j.join(fk, fv, dk, dv, how="full_outer")


def test_keyed_models_single_device_fast_path(devices):
    """D == 1 with no padding engages the validity-free sort fast path
    (with_validity=False); results must match the padded general path."""
    from sparkrdma_tpu.models import KeyedAggregator, WordCounter

    m1 = make_mesh(1)
    rng = np.random.default_rng(55)
    keys = rng.integers(0, 97, 4096, dtype=np.int32)  # even n: unpadded
    vals = rng.integers(-500, 500, 4096, dtype=np.int32)
    got = WordCounter(m1).count(keys, vals)
    u = np.unique(keys)
    assert got == {
        int(k): int(vals[keys == k].sum()) for k in u
    }
    stats = KeyedAggregator(m1).aggregate(keys, vals)
    for k in u:
        sel = vals[keys == k]
        st = stats[int(k)]
        assert (st.sum, st.count, st.min, st.max) == (
            int(sel.sum()), len(sel), int(sel.min()), int(sel.max())
        )
    # dtype-max key is a REAL key on the fast path too (no sentinel
    # confusion when every slot is valid)
    imax = np.iinfo(np.int32).max
    keys2 = np.array([imax, imax, 7, 8], np.int32)
    vals2 = np.array([1, 2, 3, 4], np.int32)
    assert WordCounter(m1).count(keys2, vals2) == {imax: 3, 7: 3, 8: 4}


def test_quantized_padded_lengths_collapse_shapes(mesh, devices):
    """Arbitrary input sizes collapse onto the 8-steps-per-octave
    compile-shape ladder (≤12.5% padding), and results stay exact."""
    from sparkrdma_tpu.models._base import quantize_padded_length
    from sparkrdma_tpu.models import WordCounter

    sizes = {quantize_padded_length(n, 8) for n in range(1000, 100_000, 97)}
    # ~1000 distinct sizes collapse to ~16 per octave over ~7 octaves
    assert len(sizes) <= 130, len(sizes)
    for n in range(1000, 100_000, 97):
        m = quantize_padded_length(n, 8)
        assert m >= n and m % 8 == 0 and m <= n * 1.125 + 8, (n, m)

    wc = WordCounter(mesh)
    rng = np.random.default_rng(77)
    keys = rng.integers(0, 31, 12_345, dtype=np.int32)  # off-ladder n
    got = wc.count(keys)
    u, c = np.unique(keys, return_counts=True)
    assert got == dict(zip(u.tolist(), c.tolist()))


def test_grouped_topk(mesh, devices):
    """Grouped top-k (the q67 rank/LIMIT-per-group shape) vs a dict
    oracle, including ties, k larger than a group, and negatives."""
    from sparkrdma_tpu.models.topk import GroupedTopK

    rng = np.random.default_rng(42)
    n = 20011
    keys = rng.integers(0, 67, n, dtype=np.int32)
    vals = rng.integers(-1000, 1000, n, dtype=np.int32)
    for k in (1, 3, 500):
        got = GroupedTopK(mesh).top_k(keys, vals, k)
        for kk in np.unique(keys):
            sel = np.sort(vals[keys == kk])[::-1][:k]
            assert got[int(kk)] == sel.tolist(), (k, kk)
        assert set(got) == set(np.unique(keys).tolist())
    import pytest as _pytest

    with _pytest.raises(ValueError, match="k must be positive"):
        GroupedTopK(mesh).top_k(keys, vals, 0)


def test_terasort_wide_records_match_numpy(devices):
    """Wide-record sort (HiBench 10B+90B shape): payload rows follow
    their keys through sample/window/all_to_all/merge exactly."""
    import jax.numpy as jnp
    import numpy as np

    from sparkrdma_tpu.models.terasort import TeraSorter
    from sparkrdma_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(17)
    W = 24  # 96B payload
    for n in (8 * 512, 8 * 2048):
        keys = rng.integers(0, 1 << 31, n).astype(np.int32)
        payload = rng.integers(0, 1 << 31, (n, W)).astype(np.int32)
        # make payload row 0 a fingerprint of the key so row identity
        # survives duplicate keys
        payload[:, 0] = keys
        sorter = TeraSorter(make_mesh())
        (sk, sp, n_valid, max_fill), cap = sorter.sort_device_wide(
            jnp.asarray(keys), jnp.asarray(payload)
        )
        assert int(np.max(np.asarray(max_fill))) <= cap
        D = sorter.n_devices
        sk_h = np.asarray(sk).reshape(D, -1)
        sp_h = np.asarray(sp).reshape(D, D * cap, W)
        nv = np.asarray(n_valid).reshape(-1)
        out_k = np.concatenate([sk_h[d, : nv[d]] for d in range(D)])
        out_p = np.concatenate([sp_h[d, : nv[d]] for d in range(D)])
        assert out_k.shape[0] == n
        np.testing.assert_array_equal(out_k, np.sort(keys))
        # every payload row still sits next to its key...
        np.testing.assert_array_equal(out_p[:, 0], out_k)
        # ...and the multiset of payload rows is exactly preserved
        order_in = np.lexsort(payload.T[::-1])
        order_out = np.lexsort(out_p.T[::-1])
        np.testing.assert_array_equal(
            payload[order_in], out_p[order_out]
        )
