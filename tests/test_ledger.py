"""Runtime resource ledger (utils/ledger.py, conf resourceDebug):

- with the conf OFF, every acquire hands out the shared no-op ticket
  (identity-checked — zero overhead on the default path);
- with it ON, leaks are reported at stop with their acquisition-site
  stacks, double releases raise, ownership transfers hand over
  exactly once, and stale-epoch tickets (late GC finalizers) settle
  as silent no-ops;
- the acceptance stress runs striped-read shuffles, tier churn and
  hot QoS brokers under resourceDebug + lockDebug together: zero
  leaks, zero double releases, zero rank violations."""

import gc
import threading
import time
from collections import defaultdict

import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.transport import LoopbackNetwork
from sparkrdma_tpu.utils.dbglock import get_lock_factory
from sparkrdma_tpu.utils.ledger import (
    NOOP_TICKET,
    DoubleReleaseError,
    ResourceLeakError,
    ResourceLedger,
    get_resource_ledger,
    ledger_acquire,
)

BASE_PORT = 39700


@pytest.fixture()
def ledger():
    """Save/restore the process-global ledger + registry state."""
    led = get_resource_ledger()
    prev = led.enabled
    prev_lock = get_lock_factory().enabled
    prev_reg = GLOBAL_REGISTRY.enabled
    led.reset()
    yield led
    led.enabled = prev
    led.reset()
    get_lock_factory().enabled = prev_lock
    GLOBAL_REGISTRY.enabled = prev_reg
    GLOBAL_REGISTRY.reset()


# -- identity: disabled path is one shared no-op ticket -----------------------


def test_disabled_acquire_returns_the_shared_noop_ticket(ledger):
    ledger.enabled = False
    t1 = ledger_acquire("x.tokens", 5)
    t2 = ledger_acquire("y.bytes", 1 << 20)
    assert t1 is NOOP_TICKET and t2 is NOOP_TICKET
    t1.release()
    t1.release(3)       # settled tickets stay no-ops: nothing raises
    assert t1.transfer() is NOOP_TICKET
    assert ledger.outstanding() == {}


def test_conf_flips_the_global_ledger():
    assert TpuShuffleConf().resource_debug is False
    on = TpuShuffleConf({"spark.shuffle.tpu.resourceDebug": "true"})
    assert on.resource_debug is True


# -- enabled: lifecycle enforcement -------------------------------------------


def test_partial_release_composes_to_zero(ledger):
    ledger.enabled = True
    t = ledger_acquire("x.bytes", 100)
    t.release(60)
    assert ledger.outstanding() == {"x.bytes": 40}
    t.release(0)        # always a no-op
    t.release(40)
    assert ledger.outstanding() == {}
    # partial drain leaves the ticket OPEN for its exactly-once final
    # settle (the per-stripe progress + settle() pairing) ...
    t.release()
    # ... and only the SECOND settle is a double release
    with pytest.raises(DoubleReleaseError):
        t.release()
    with pytest.raises(DoubleReleaseError):
        t.release(1)    # over-release past zero is caught either way


def test_release_none_settles_all_remaining(ledger):
    ledger.enabled = True
    t = ledger_acquire("x.bytes", 100)
    t.release()
    assert ledger.outstanding() == {}
    # a zero-amount acquisition still settles cleanly (0-cost serves)
    z = ledger_acquire("x.bytes", 0)
    z.release()
    assert ledger.double_releases() == 0


def test_over_and_negative_release_raise(ledger):
    ledger.enabled = True
    t = ledger_acquire("x.bytes", 10)
    with pytest.raises(DoubleReleaseError):
        t.release(11)
    with pytest.raises(DoubleReleaseError):
        t.release(-1)
    assert ledger.double_releases() == 2
    t.release(10)       # the failed attempts did not corrupt the count
    assert ledger.outstanding() == {}


def test_double_release_raises_with_site(ledger):
    ledger.enabled = True
    t = ledger_acquire("x.tokens")
    t.release()
    with pytest.raises(DoubleReleaseError) as ei:
        t.release()
    assert "x.tokens" in str(ei.value)
    assert "test_ledger.py" in str(ei.value)  # the acquisition site


def test_transfer_hands_over_exactly_once(ledger):
    ledger.enabled = True
    t = ledger_acquire("x.tokens", 7)
    nt = t.transfer()
    assert ledger.outstanding() == {"x.tokens": 7}
    with pytest.raises(DoubleReleaseError):
        t.release()     # the old ticket is dead
    with pytest.raises(DoubleReleaseError):
        t.transfer()    # and cannot be handed over again
    nt.release(7)       # the new owner settles
    assert ledger.outstanding() == {}


def test_leak_reported_at_stop_with_site_stack(ledger):
    ledger.enabled = True
    ledger_acquire("x.pins", 3)
    report = ledger.leak_report()
    assert len(report) == 1 and "x.pins" in report[0]
    assert "test_ledger.py" in report[0]
    with pytest.raises(ResourceLeakError) as ei:
        ledger.stop(raise_on_leak=True)
    assert "x.pins" in str(ei.value)
    assert "test_ledger.py" in str(ei.value)
    assert ledger.outstanding() == {}  # the epoch closed


def test_stale_epoch_ticket_is_a_silent_noop(ledger):
    """A GC-tied finalizer can fire after the manager stopped the
    ledger; its release must not raise or touch the new epoch."""
    ledger.enabled = True
    old = ledger_acquire("x.pins", 2)
    ledger.stop(raise_on_leak=False)
    old.release()                   # late finalizer: silent no-op
    assert old.transfer() is NOOP_TICKET
    fresh = ledger_acquire("x.pins", 1)
    assert ledger.outstanding() == {"x.pins": 1}
    fresh.release()
    assert ledger.double_releases() == 0


def test_retained_ledger_flushes_only_at_the_last_owner_stop(ledger):
    """Three managers sharing the process-global ledger: the first two
    stops must not flush (the others' channels are still legitimately
    open); the last one renders the report."""
    ledger.enabled = True
    ledger.retain()
    ledger.retain()
    ledger.retain()
    t = ledger_acquire("x.fds", 2)  # a still-live manager's sockets
    assert ledger.stop(raise_on_leak=True) == {}   # owner 1: no flush
    assert ledger.stop(raise_on_leak=True) == {}   # owner 2: no flush
    t.release()                     # the owning manager closes them
    assert ledger.stop(raise_on_leak=True) == {}   # last owner flushes
    # the epoch closed: a fresh unowned ledger stop flushes directly
    leftover = ledger_acquire("x.fds", 1)
    assert ledger.stop(raise_on_leak=False) == {"x.fds": 1}
    leftover.release()              # stale epoch: silent no-op


def test_stop_counts_leaks_into_the_metrics_registry(ledger):
    GLOBAL_REGISTRY.enabled = True
    led = ResourceLedger(enabled=True)
    led.acquire("x.fds", 2)
    leaked = led.stop(raise_on_leak=False)
    assert leaked == {"x.fds": 2}
    vals = {
        dict(inst.labels).get("resource"): inst.value
        for _k, inst in GLOBAL_REGISTRY.instruments()
        if getattr(inst, "name", "") == "resource_leaked_total"
    }
    assert vals.get("x.fds") == 2


# -- the acceptance stress ----------------------------------------------------


def _run_shuffle(driver, executors, shuffle_id, errors):
    """One full write→publish→resolve→striped-fetch→read cycle (the
    lock-sanitizer stress shape); block sizes exceed the stripe
    threshold so remote fetches ride the multi-lane scatter path."""
    try:
        num_maps, num_parts = 2, 4
        part = HashPartitioner(num_parts)
        handle = driver.register_shuffle(shuffle_id, num_maps, part)
        payload = "v" * 2000
        records = [
            [(f"k{j % num_parts}", payload) for j in range(200)]
            for _m in range(num_maps)
        ]
        maps_by_host = defaultdict(list)
        for map_id, recs in enumerate(records):
            ex = executors[map_id % len(executors)]
            w = ex.get_writer(handle, map_id)
            w.write(recs)
            w.stop(True)
            maps_by_host[ex.local_smid].append(map_id)
        reader = executors[0].get_reader(
            handle, 0, num_parts, dict(maps_by_host)
        )
        got = sum(len(v) for _k, v in reader.read())
        assert got == num_maps * 200 * len(payload), got
        driver.unregister_shuffle(shuffle_id)
    except BaseException as e:  # propagate to the main thread
        errors.append(e)


def test_stress_shuffles_tier_churn_qos_zero_leaks(ledger):
    """Two concurrent striped-read shuffles + tier churn (tiny hot
    budget forces promote/demote traffic) + hot QoS brokers, all under
    resourceDebug AND lockDebug: every tracked resource drains to zero
    outstanding, with zero double releases and zero rank violations."""
    get_lock_factory().enabled = False
    GLOBAL_REGISTRY.reset()
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.resourceDebug": True,
        "spark.shuffle.tpu.lockDebug": True,
        "spark.shuffle.tpu.metrics": True,
        "spark.shuffle.tpu.qosEnabled": True,
        "spark.shuffle.tpu.transportNumStripes": 2,
        "spark.shuffle.tpu.transportStripeThreshold": "4k",
        "spark.shuffle.tpu.tierHotBytes": "64k",  # force churn
        "spark.shuffle.tpu.driverPort": BASE_PORT,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "20s",
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=BASE_PORT + 10 + i * 10, executor_id=str(i),
        )
        for i in range(2)
    ]
    assert ledger.enabled  # the conf flipped it on
    errors: list = []
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(len(e._peers) == 2 for e in executors):
                break
            time.sleep(0.01)
        shufflers = [
            threading.Thread(
                target=_run_shuffle,
                args=(driver, executors, sid, errors),
            )
            for sid in range(2)
        ]
        for t in shufflers:
            t.start()
        for t in shufflers:
            t.join(60)
            assert not t.is_alive(), "stress thread hung"
        assert not errors, errors

        # the system is up but idle: everything acquired during the
        # run must have drained (GC-tied pins settle via finalizers)
        gc.collect()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            left = {r: n for r, n in ledger.outstanding().items() if n}
            if not left:
                break
            time.sleep(0.05)
        assert not left, (left, ledger.leak_report())
        assert ledger.double_releases() == 0, ledger.leak_report()
    finally:
        for m in executors + [driver]:
            m.stop()
    # the managers' own stops found nothing left to leak...
    leaked = [
        (dict(inst.labels), inst.value)
        for _k, inst in GLOBAL_REGISTRY.instruments()
        if getattr(inst, "name", "") == "resource_leaked_total"
        and inst.value > 0
    ]
    assert not leaked, leaked
    doubles = [
        inst.value for _k, inst in GLOBAL_REGISTRY.instruments()
        if getattr(inst, "name", "") == "resource_double_release_total"
    ]
    assert all(v == 0 for v in doubles), doubles
    # ...and lockDebug saw zero rank violations alongside
    viol = [
        inst for _k, inst in GLOBAL_REGISTRY.instruments()
        if getattr(inst, "name", "") == "lock_rank_violations_total"
    ]
    assert all(v.value == 0 for v in viol), [v.value for v in viol]
    # the ledger actually watched the planes: the census populated
    acquired = {
        dict(inst.labels).get("resource")
        for _k, inst in GLOBAL_REGISTRY.instruments()
        if getattr(inst, "name", "") == "resource_acquires_total"
        and inst.value > 0
    }
    assert acquired, "resourceDebug recorded no acquisitions"
