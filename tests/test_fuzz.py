"""Seeded property tests: every device model vs a numpy oracle across
randomized sizes, key ranges, skews, and paddings (the systematic test
strategy SURVEY.md §4 notes the reference never had)."""

import collections

import numpy as np
import pytest

from sparkrdma_tpu.parallel import make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


# size pool instead of arbitrary sizes: every distinct (n, capacity)
# pair is a fresh XLA compile, which would dominate the suite's runtime
_SIZES = (1, 7, 8, 9, 64, 1000, 2048, 4999)


def _cases(seed, n_cases):
    rng = np.random.default_rng(seed)
    for i in range(n_cases):
        n = int(rng.choice(_SIZES))
        if rng.random() < 0.3:
            # heavy skew: a few hot keys
            keys = rng.choice(
                rng.integers(0, 1 << 30, size=max(1, n // 100 + 1)),
                size=n,
            ).astype(np.int32)
        else:
            keys = rng.integers(
                0, int(rng.integers(2, 1 << 30)), size=n
            ).astype(np.int32)
        if rng.random() < 0.1:
            # include dtype-max keys (the sentinel hazard)
            keys[rng.integers(0, n, size=max(1, n // 50))] = np.iinfo(
                np.int32
            ).max
        vals = rng.integers(-1000, 1000, size=n).astype(np.int32)
        yield i, keys, vals


def test_fuzz_terasort(mesh, devices):
    from sparkrdma_tpu.models import TeraSorter

    sorter = TeraSorter(mesh)
    for i, keys, vals in _cases(100, 12):
        sk, sv = sorter.sort(keys, vals)
        order = np.argsort(keys, kind="stable")
        np.testing.assert_array_equal(sk, keys[order], err_msg=f"case {i}")
        assert sorted(zip(sk.tolist(), sv.tolist())) == sorted(
            zip(keys.tolist(), vals.tolist())
        ), f"case {i}: pairs broken"


def test_fuzz_wordcount(mesh, devices):
    from sparkrdma_tpu.models import WordCounter

    wc = WordCounter(mesh)
    for i, keys, _vals in _cases(200, 12):
        got = wc.count(keys)
        assert got == dict(collections.Counter(keys.tolist())), f"case {i}"


def test_fuzz_aggregate(mesh, devices):
    from sparkrdma_tpu.models import KeyedAggregator

    agg = KeyedAggregator(mesh)
    for i, keys, vals in _cases(300, 10):
        got = agg.aggregate(keys, vals)
        assert set(got) == set(np.unique(keys).tolist()), f"case {i}"
        for k in np.unique(keys):
            sel = vals[keys == k]
            st = got[int(k)]
            assert (st.sum, st.count, st.min, st.max) == (
                int(sel.sum()), len(sel), int(sel.min()), int(sel.max())
            ), f"case {i} key {k}"


def test_fuzz_joins(mesh, devices):
    from sparkrdma_tpu.models import BroadcastJoiner, HashJoiner
    from tests.test_models import _join_case  # shared case/oracle builder

    rng = np.random.default_rng(400)
    joiners = [HashJoiner(mesh), BroadcastJoiner(mesh)]
    for i in range(8):
        n_dim = int(rng.choice((1, 8, 100, 1999)))
        n_fact = int(rng.choice((1, 9, 1000, 4096)))
        fk, fv, dk, dv, expect = _join_case(
            seed=400 + i, n_fact=n_fact, n_dim=n_dim, key_space=3 * n_dim
        )
        for j in joiners:
            k, lv, rv = j.join(fk, fv, dk, dv)
            got = sorted(zip(k.tolist(), lv.tolist(), rv.tolist()))
            assert got == expect, f"case {i} {type(j).__name__}"


def test_fuzz_join_variants(mesh, devices):
    """semi/anti/left_outer joins fuzzed vs dict oracles across skew,
    tiny sides, and key spaces with/without full dim coverage."""
    from sparkrdma_tpu.models import BroadcastJoiner, HashJoiner
    from tests.test_models import _join_case

    rng = np.random.default_rng(900)
    joiners = [HashJoiner(mesh), BroadcastJoiner(mesh)]
    for i in range(6):
        n_dim = int(rng.choice((1, 17, 500)))
        n_fact = int(rng.choice((1, 64, 2048)))
        fk, fv, dk, dv, _ = _join_case(
            seed=900 + i, n_fact=n_fact, n_dim=n_dim, key_space=2 * n_dim
        )
        lut = set(dk.tolist())
        matched = sorted(
            (int(k), int(v)) for k, v in zip(fk, fv) if int(k) in lut
        )
        unmatched = sorted(
            (int(k), int(v)) for k, v in zip(fk, fv) if int(k) not in lut
        )
        for j in joiners:
            name = f"case {i} {type(j).__name__}"
            k, lv = j.join(fk, fv, dk, dv, how="semi")
            assert sorted(zip(k.tolist(), lv.tolist())) == matched, name
            k, lv = j.join(fk, fv, dk, dv, how="anti")
            assert sorted(zip(k.tolist(), lv.tolist())) == unmatched, name
            k, lv, rv, m = j.join(fk, fv, dk, dv, how="left_outer")
            assert len(k) == len(fk), name
            assert int(m.sum()) == len(matched), name


def test_fuzz_join_aggregate(mesh, devices):
    """Fused broadcast-join+aggregate fuzzed vs a dict oracle (group
    key = join key % P for random P, value = dim ^ fact)."""
    import jax.numpy as jnp

    from sparkrdma_tpu.models.join_aggregate import BroadcastJoinAggregator
    from tests.test_models import _join_aggregate_oracle, _join_case

    agg = BroadcastJoinAggregator(mesh)
    rng = np.random.default_rng(1200)
    for i in range(5):
        n_dim = int(rng.choice((3, 50, 700)))
        n_fact = int(rng.choice((8, 512, 3000)))
        P = int(rng.choice((1, 7, 64)))
        fk, fv, dk, dv, _ = _join_case(
            seed=1200 + i, n_fact=n_fact, n_dim=n_dim, key_space=2 * n_dim
        )

        def gk_fn(ku, _P=P):
            return ku % jnp.asarray(_P, ku.dtype)

        def val_fn(ku, fp, dvu):
            import jax.lax as lax

            return lax.bitcast_convert_type(
                fp, jnp.int32
            ) ^ lax.bitcast_convert_type(dvu, jnp.int32)

        got = agg.join_aggregate(fk, fv, dk, dv, gk_fn, val_fn)
        want = _join_aggregate_oracle(
            fk, fv, dk, dv, lambda k, _P=P: k % _P, lambda k, a, b: a ^ b
        )
        assert set(got) == set(want), f"case {i}"
        for g, (s, c, mn, mx) in want.items():
            st = got[g]
            assert (st.sum - s) % (1 << 32) == 0, (i, g)
            assert (st.count, st.min, st.max) == (c, mn, mx), (i, g)


def test_fuzz_grouped_topk(mesh, devices):
    """Grouped top-k fuzzed vs numpy: random k, cardinality, skew."""
    from sparkrdma_tpu.models.topk import GroupedTopK

    model = GroupedTopK(mesh)
    rng = np.random.default_rng(2100)
    for i in range(5):
        n = int(rng.choice((16, 999, 4096)))
        card = int(rng.choice((1, 13, 300)))
        k = int(rng.choice((1, 2, 7, 64)))
        keys = rng.integers(0, card, n, dtype=np.int32)
        vals = rng.integers(-(1 << 20), 1 << 20, n, dtype=np.int32)
        got = model.top_k(keys, vals, k)
        assert set(got) == set(np.unique(keys).tolist()), f"case {i}"
        for kk in np.unique(keys):
            want = np.sort(vals[keys == kk])[::-1][:k].tolist()
            assert got[int(kk)] == want, (i, kk, k)


def test_fuzz_windowed_plane_random_topologies(devices):
    """Property test for the unified windowed plane: random executor
    counts, window sizes, partition counts, and per-map record loads —
    reducer-issued per-partition reads must recover every record
    exactly once, whatever the plan cut."""
    import threading

    from tests.test_bulk_shuffle import _windowed_plane_cluster

    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner

    rng = np.random.default_rng(17)
    for trial in range(4):
        E = int(rng.integers(2, 5))
        num_maps = int(rng.integers(1, 7))
        num_parts = int(rng.integers(E, 3 * E + 1))
        window_maps = int(rng.integers(0, 4))
        net, conf, driver, executors = _windowed_plane_cluster(
            window_maps, 49700 + trial * 200, n_exec=E
        )
        try:
            part = HashPartitioner(num_parts)
            handle = driver.register_shuffle(77, num_maps, part)
            expect = []
            for m in range(num_maps):
                n = int(rng.integers(0, 300))
                recs = [
                    (int(rng.integers(0, 40)), (m, j)) for j in range(n)
                ]
                expect.extend(recs)
                w = executors[m % E].get_writer(handle, m)
                w.write(recs)
                w.stop(True)
            for e in executors:
                e.windowed_plane.join(77)
            results = {}
            errors = {}

            def reduce_task(pid):
                try:
                    r = executors[pid % E].get_reader(
                        handle, pid, pid + 1, {}
                    )
                    results[pid] = list(r.read())
                except BaseException as err:
                    errors[pid] = err

            threads = [
                threading.Thread(target=reduce_task, args=(p,),
                                 daemon=True)
                for p in range(num_parts)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=90)
            assert not any(t.is_alive() for t in threads), (
                "hung reducer", trial, E, num_maps, num_parts,
                window_maps,
            )
            assert not errors, (trial, E, num_maps, num_parts,
                                window_maps, errors)
            got = [kv for recs in results.values() for kv in recs]
            assert sorted(map(repr, got)) == sorted(map(repr, expect)), (
                trial, E, num_maps, num_parts, window_maps,
                len(got), len(expect),
            )
        finally:
            for m in executors + [driver]:
                m.stop()
