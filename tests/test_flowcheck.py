"""Tier-1 wrapper + unit fixtures for the resource-lifecycle gate
(tools/flowcheck.py): the real tree must be clean with a nonempty
resource census, and seeded lifecycle bugs must each produce exactly
their FC finding."""

import importlib.util
import pathlib
import textwrap

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_flowcheck():
    spec = importlib.util.spec_from_file_location(
        "sparkrdma_tpu_flowcheck", REPO / "tools" / "flowcheck.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _analyze_src(tmp_path, src: str):
    fc = _load_flowcheck()
    f = tmp_path / "fixture.py"
    f.write_text(textwrap.dedent(src))
    return fc.analyze([f], root=tmp_path)


def _codes(findings):
    return sorted({code for _rel, _line, code, _msg in findings})


# -- tier-1: the real tree ----------------------------------------------------


def test_library_is_flowcheck_clean():
    fc = _load_flowcheck()
    findings = fc.analyze([REPO / "sparkrdma_tpu"])
    assert not findings, "\n".join(
        f"{rel}:{line}: {code} {msg}" for rel, line, code, msg in findings
    )


def test_library_census_is_nonempty():
    """Clean AND nonempty: the analyzer actually discovered the
    resource population (a discovery regression would pass
    vacuously).  The census floor is the annotated sweep: credits,
    tokens, pins, registered bytes, fds, send descriptors."""
    fc = _load_flowcheck()
    an = fc.Analyzer()
    findings = an.analyze_paths([REPO / "sparkrdma_tpu"])
    assert not findings
    assert len(an.decls) >= 10, sorted(an.decls)
    n_acq = sum(len(f.acquires) for f in an.fns)
    n_rel = sum(len(f.releases) for f in an.fns)
    assert n_acq >= 10 and n_rel >= 10, (n_acq, n_rel)


# -- FC01: acquire without release on all paths -------------------------------


def test_fc01_acquire_without_release(tmp_path):
    findings = _analyze_src(tmp_path, """
        POOL = []  # resource: fix.tokens

        def leaky():
            tok = POOL.pop()  # acquires: fix.tokens
            return tok
    """)
    assert _codes(findings) == ["FC01"]


def test_fc01_plain_release_is_not_all_paths(tmp_path):
    """A release outside any finally does not run when the code
    between acquire and release raises — still FC01."""
    findings = _analyze_src(tmp_path, """
        POOL = []  # resource: fix.tokens

        def racy():
            tok = POOL.pop()  # acquires: fix.tokens
            work(tok)
            POOL.append(tok)  # releases: fix.tokens
    """)
    assert _codes(findings) == ["FC01"]


def test_fc01_finally_release_is_clean(tmp_path):
    findings = _analyze_src(tmp_path, """
        POOL = []  # resource: fix.tokens

        def safe():
            tok = POOL.pop()  # acquires: fix.tokens
            try:
                work(tok)
            finally:
                POOL.append(tok)  # releases: fix.tokens
    """)
    assert findings == []


def test_fc01_finalizer_release_is_clean(tmp_path):
    findings = _analyze_src(tmp_path, """
        import weakref
        POOL = []  # resource: fix.pins

        def pin(view, blk):
            POOL.append(blk)  # acquires: fix.pins
            weakref.finalize(view, unpin, blk)  # releases: fix.pins
            return view
    """)
    assert findings == []


def test_fc01_ownership_transfer_is_clean(tmp_path):
    """An acquire whose release duty is handed to another function is
    clean here; the receiver's release is then FC03-clean too."""
    findings = _analyze_src(tmp_path, """
        POOL = []  # resource: fix.tokens

        def borrow():
            # owns: fix.tokens -> give_back
            tok = POOL.pop()  # acquires: fix.tokens
            return tok

        def give_back(tok):
            POOL.append(tok)  # releases: fix.tokens
    """)
    assert findings == []


def test_fc01_noqa_escape(tmp_path):
    findings = _analyze_src(tmp_path, """
        POOL = []  # resource: fix.tokens

        def deliberate():
            tok = POOL.pop()  # acquires: fix.tokens  # noqa: FC01
            return tok
    """)
    assert findings == []


# -- FC02: double release -----------------------------------------------------


def test_fc02_double_release_same_suite(tmp_path):
    findings = _analyze_src(tmp_path, """
        POOL = []  # resource: fix.tokens

        def double(tok):
            # owns: fix.tokens -> double
            POOL.append(tok)  # releases: fix.tokens
            POOL.append(tok)  # releases: fix.tokens
    """)
    assert _codes(findings) == ["FC02"]


def test_fc02_body_and_finally(tmp_path):
    findings = _analyze_src(tmp_path, """
        POOL = []  # resource: fix.tokens

        def double(tok):
            # owns: fix.tokens -> double
            try:
                POOL.append(tok)  # releases: fix.tokens
            finally:
                POOL.append(tok)  # releases: fix.tokens
    """)
    assert _codes(findings) == ["FC02"]


def test_fc02_one_shot_guard_accepted(tmp_path):
    """The swap-and-release idiom settles at most once per site even
    when both sites run — `# one-shot` records the guard."""
    findings = _analyze_src(tmp_path, """
        POOL = []  # resource: fix.tokens

        def settle(state):
            # owns: fix.tokens -> settle
            try:
                tok, state.tok = state.tok, None
                if tok:
                    POOL.append(tok)  # releases: fix.tokens  # one-shot
            finally:
                tok, state.tok = state.tok, None
                if tok:
                    POOL.append(tok)  # releases: fix.tokens  # one-shot
    """)
    assert findings == []


def test_fc02_branches_are_not_one_path(tmp_path):
    """Releases on mutually exclusive branches are fine."""
    findings = _analyze_src(tmp_path, """
        POOL = []  # resource: fix.tokens

        def either(tok, fast):
            # owns: fix.tokens -> either
            if fast:
                POOL.append(tok)  # releases: fix.tokens
            else:
                POOL.append(tok)  # releases: fix.tokens
    """)
    assert findings == []


# -- FC03: release without acquire or transfer --------------------------------


def test_fc03_release_never_acquired(tmp_path):
    findings = _analyze_src(tmp_path, """
        POOL = []  # resource: fix.tokens

        def who_gave_me_this(tok):
            POOL.append(tok)  # releases: fix.tokens
    """)
    assert _codes(findings) == ["FC03"]


def test_fc03_class_method_receiver(tmp_path):
    findings = _analyze_src(tmp_path, """
        POOL = []  # resource: fix.tokens

        class Pool:
            def borrow(self):
                # owns: fix.tokens -> Pool.put_back
                tok = POOL.pop()  # acquires: fix.tokens
                return tok

            def put_back(self, tok):
                POOL.append(tok)  # releases: fix.tokens
    """)
    assert findings == []


# -- FC04: undeclared resources -----------------------------------------------


def test_fc04_undeclared_resource(tmp_path):
    findings = _analyze_src(tmp_path, """
        def mystery():
            tok = grab()  # acquires: fix.ghost  # noqa: FC01
            return tok
    """)
    assert _codes(findings) == ["FC04"]


def test_fc04_undeclared_owns_target(tmp_path):
    findings = _analyze_src(tmp_path, """
        def mystery():
            # owns: fix.ghost -> elsewhere
            return grab()
    """)
    assert _codes(findings) == ["FC04"]


def test_grammar_examples_in_docstrings_are_ignored(tmp_path):
    findings = _analyze_src(tmp_path, '''
        def documented():
            """Example annotation, not a live site::

                x = grab()  # acquires: fix.not_a_resource
                # owns: fix.not_a_resource -> elsewhere
            """
            return None
    ''')
    assert findings == []
