"""End-to-end shuffle over loopback: the minimum slice of SURVEY.md §7 —
write → publish → resolve → fetch → read across multiple executors."""

import time
from collections import defaultdict

import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import Aggregator, TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner, RangePartitioner
from sparkrdma_tpu.shuffle.reader import (
    FetchFailedError,
    MetadataFetchFailedError,
)
from sparkrdma_tpu.transport import LoopbackNetwork


@pytest.fixture()
def cluster(devices):
    """Driver + 3 executors sharing one loopback network and conf."""
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.collectShuffleReaderStats": True,
        "spark.shuffle.tpu.driverPort": 37000,
        # keep failure tests fast; production default is 120s
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "5s",
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=38000 + i * 10, executor_id=str(i),
        )
        for i in range(3)
    ]
    # wait until announce reached everyone (control plane is async)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == 3 for e in executors):
            break
        time.sleep(0.01)
    yield net, conf, driver, executors
    for m in executors + [driver]:
        m.stop()


def run_maps(handle, executors, records_per_map):
    """Job-layer stand-in: run map tasks round-robin over executors.
    Returns maps_by_host (the MapOutputTracker analog)."""
    maps_by_host = defaultdict(list)
    for map_id, records in enumerate(records_per_map):
        ex = executors[map_id % len(executors)]
        w = ex.get_writer(handle, map_id)
        w.write(records)
        w.stop(True)
        maps_by_host[ex.local_smid].append(map_id)
    return dict(maps_by_host)


def test_membership_and_announce(cluster):
    net, conf, driver, executors = cluster
    assert len(driver.executors) == 3
    for e in executors:
        assert len(e._peers) == 3


def test_group_by_key_e2e(cluster):
    net, conf, driver, executors = cluster
    num_maps, num_parts = 4, 6
    part = HashPartitioner(num_parts)
    handle = driver.register_shuffle(0, num_maps, part)

    records_per_map = [
        [(f"k{j}", (m, j)) for j in range(50)] for m in range(num_maps)
    ]
    maps_by_host = run_maps(handle, executors, records_per_map)

    expected = defaultdict(list)
    for recs in records_per_map:
        for k, v in recs:
            expected[k].append(v)

    got = {}
    for i, ex in enumerate(executors):
        # executor i reads partitions [i*2, i*2+2)
        reader = ex.get_reader(handle, i * 2, i * 2 + 2, maps_by_host)
        for k, v in reader.read():
            got.setdefault(k, []).append(v)
        assert reader.metrics.records_read > 0
        assert reader.metrics.remote_blocks > 0  # cross-executor traffic
        assert reader.metrics.local_blocks > 0

    assert set(got) == set(expected)
    for k in expected:
        assert sorted(got[k]) == sorted(expected[k]), k


def test_reduce_by_key_with_map_side_combine(cluster):
    net, conf, driver, executors = cluster
    agg = Aggregator(
        create_combiner=lambda v: v,
        merge_value=lambda c, v: c + v,
        merge_combiners=lambda a, b: a + b,
    )
    part = HashPartitioner(4)
    handle = driver.register_shuffle(
        1, 3, part, aggregator=agg, map_side_combine=True
    )
    records_per_map = [
        [(j % 10, 1) for j in range(100)] for _ in range(3)
    ]
    maps_by_host = run_maps(handle, executors, records_per_map)

    got = {}
    for ex in executors[:2]:
        reader = ex.get_reader(handle, 0 if ex is executors[0] else 2,
                               2 if ex is executors[0] else 4, maps_by_host)
        got.update(dict(reader.read()))
    assert got == {k: 30 for k in range(10)}


def test_sort_by_key_e2e(cluster):
    net, conf, driver, executors = cluster
    import random

    rng = random.Random(0)
    all_keys = [rng.randrange(10**6) for _ in range(600)]
    part = RangePartitioner(6, rng.sample(all_keys, 100))
    handle = driver.register_shuffle(2, 3, part, key_ordering=True)
    records_per_map = [
        [(k, k * 2) for k in all_keys[m * 200 : (m + 1) * 200]]
        for m in range(3)
    ]
    maps_by_host = run_maps(handle, executors, records_per_map)

    out = []
    for pid in range(6):
        reader = executors[pid % 3].get_reader(handle, pid, pid + 1, maps_by_host)
        chunk = list(reader.read())
        # each partition comes out key-sorted
        assert chunk == sorted(chunk, key=lambda kv: kv[0])
        assert all(v == k * 2 for k, v in chunk)
        out.extend(k for k, _ in chunk)
    # concatenating the range partitions in order gives the global sort
    assert out == sorted(all_keys)


def test_empty_partitions(cluster):
    net, conf, driver, executors = cluster
    part = HashPartitioner(8)
    handle = driver.register_shuffle(3, 2, part)
    # map 0 writes nothing at all; map 1 writes one record
    maps_by_host = run_maps(handle, executors, [[], [("x", 1)]])
    total = []
    for pid in range(8):
        r = executors[0].get_reader(handle, pid, pid + 1, maps_by_host)
        total.extend(r.read())
    assert total == [("x", 1)]


def test_metadata_fetch_timeout(cluster):
    net, conf, driver, executors = cluster
    fast_conf_ms = 300
    conf.set("partitionLocationFetchTimeout", f"{fast_conf_ms}ms")
    part = HashPartitioner(2)
    handle = driver.register_shuffle(4, 2, part)
    # claim executor 1 hosts map 0, but never run the map task: locations
    # can never resolve and the reader's timer must fire
    maps_by_host = {executors[1].local_smid: [0]}
    reader = executors[0].get_reader(handle, 0, 1, maps_by_host)
    with pytest.raises(MetadataFetchFailedError):
        list(reader.read())
    conf.set("partitionLocationFetchTimeout", "120s")


def test_executor_loss_fails_fetch(cluster):
    net, conf, driver, executors = cluster
    part = HashPartitioner(2)
    handle = driver.register_shuffle(5, 2, part)
    maps_by_host = run_maps(handle, executors[:2], [[("a", 1)], [("b", 2)]])
    # wait until both async publishes landed on the driver, THEN kill the
    # executor — isolates the data-plane failure from the publish race
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if sum(len(v) for v in driver.maps_by_host(5).values()) == 2:
            break
        time.sleep(0.01)
    victim = executors[1]
    net.partition(victim.node.address)
    reader = executors[0].get_reader(handle, 0, 2, maps_by_host)
    with pytest.raises(FetchFailedError):
        list(reader.read())
    net.heal(victim.node.address)
    # driver-side pruning (elastic membership)
    driver.remove_executor(victim.local_smid)
    assert victim.local_smid not in driver.executors


def test_unregister_shuffle_releases_segments(cluster):
    net, conf, driver, executors = cluster
    part = HashPartitioner(2)
    handle = driver.register_shuffle(6, 2, part)
    run_maps(handle, executors[:1], [[("a", 1)], [("b", 2)]])
    ex = executors[0]
    assert ex.arena.stats()["segments"] == 2
    ex.unregister_shuffle(6)
    assert ex.arena.stats()["segments"] == 0


def test_stable_hash_cross_process():
    # reviewer finding: builtin hash() is interpreter-salted; the
    # partitioner must agree across executor processes
    import subprocess
    import sys

    from sparkrdma_tpu.shuffle.partitioner import stable_hash

    keys = ["k1", 42, -7, 3.5, (1, "a"), b"raw", True, "日本語"]
    here = [stable_hash(k) for k in keys]
    code = (
        "from sparkrdma_tpu.shuffle.partitioner import stable_hash\n"
        f"print([stable_hash(k) for k in {keys!r}])"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd="/root/repo", env={"PATH": "/usr/local/bin:/usr/bin:/bin",
                               "PYTHONHASHSEED": "random",
                               "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    assert eval(out.stdout) == here


def test_map_task_retry_releases_old_segment(cluster):
    # reviewer finding: re-committing a map output (speculation/retry)
    # must release the superseded HBM segment
    net, conf, driver, executors = cluster
    part = HashPartitioner(2)
    handle = driver.register_shuffle(7, 1, part)
    ex = executors[0]
    w1 = ex.get_writer(handle, 0)
    w1.write([("a", 1)])
    w1.stop(True)
    assert ex.arena.stats()["segments"] == 1
    w2 = ex.get_writer(handle, 0)  # speculative re-run of map 0
    w2.write([("a", 1)])
    w2.stop(True)
    s = ex.arena.stats()
    assert s["segments"] == 1 and s["released_ever"] == 1


def test_abandoned_reader_cleans_up(cluster):
    # reviewer finding: abandoning the iterator mid-read must not leak
    # callbacks or timers
    net, conf, driver, executors = cluster
    part = HashPartitioner(2)
    handle = driver.register_shuffle(8, 2, part)
    maps_by_host = run_maps(
        handle, executors[:2],
        [[(f"k{i}", i) for i in range(500)], [(f"j{i}", i) for i in range(500)]],
    )
    ex = executors[0]
    before = len(ex._callbacks)
    it = ex.get_reader(handle, 0, 2, maps_by_host).read()
    next(it)  # take one record, abandon the rest
    del it
    import gc
    gc.collect()
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline and len(ex._callbacks) > before:
        time.sleep(0.05)
    assert len(ex._callbacks) == before


def test_writer_spill_roundtrip(devices, tmp_path):
    """Spilled + in-memory chunks merge into the same read results."""
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": 37300,
        "spark.shuffle.tpu.shuffleSpillRecordThreshold": "100",
        "spark.shuffle.tpu.spillDir": str(tmp_path),
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    ex = TpuShuffleManager(conf, is_driver=False, network=net,
                           port=38300, executor_id="0")
    try:
        handle = driver.register_shuffle(0, 1, HashPartitioner(4))
        w = ex.get_writer(handle, 0)
        records = [(i % 37, i) for i in range(1000)]
        w.write(records)
        assert w.metrics.spills >= 9  # 1000 records / 100 threshold
        assert w.metrics.bytes_spilled > 0
        w.stop(True)
        assert not list(tmp_path.glob("sparkrdma_tpu_spill_*")), (
            "spill file must be deleted after commit"
        )
        # a spilled commit routes to the mmap (file-backed) path so peak
        # memory stays bounded by the spill threshold
        assert ex.arena.stats()["file_bytes"] > 0, (
            "spilled commit should be file-backed"
        )
        got = []
        for pid in range(4):
            r = ex.get_reader(handle, pid, pid + 1, {ex.local_smid: [0]})
            got.extend(r.read())
        assert sorted(got) == sorted(records)
    finally:
        ex.stop()
        driver.stop()


def test_writer_spill_with_map_side_combine(devices, tmp_path):
    """Spilled combiner chunks re-merge through merge_combiners on read."""
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": 37310,
        "spark.shuffle.tpu.shuffleSpillRecordThreshold": "10",
        "spark.shuffle.tpu.spillDir": str(tmp_path),
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    ex = TpuShuffleManager(conf, is_driver=False, network=net,
                           port=38310, executor_id="0")
    try:
        agg = Aggregator(lambda v: v, lambda c, v: c + v, lambda a, b: a + b)
        handle = driver.register_shuffle(
            0, 1, HashPartitioner(2), aggregator=agg, map_side_combine=True
        )
        w = ex.get_writer(handle, 0)
        # 20 distinct keys, threshold 10 -> at least one spill; every key
        # appears in 2+ chunks so the reader must merge across chunks
        w.write([(i % 20, 1) for i in range(400)])
        w.stop(True)
        assert w.metrics.spills >= 1
        got = {}
        for pid in range(2):
            r = ex.get_reader(handle, pid, pid + 1, {ex.local_smid: [0]})
            got.update(dict(r.read()))
        assert got == {k: 20 for k in range(20)}
    finally:
        ex.stop()
        driver.stop()


def test_file_backed_commit(devices, tmp_path):
    """Commits above the threshold land in an mmapped file segment that
    serves reads and is unlinked on shuffle unregister."""
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": 37320,
        "spark.shuffle.tpu.fileBackedCommitBytes": "1k",
        "spark.shuffle.tpu.spillDir": str(tmp_path),
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    ex = TpuShuffleManager(conf, is_driver=False, network=net,
                           port=38320, executor_id="0")
    try:
        handle = driver.register_shuffle(0, 1, HashPartitioner(3))
        w = ex.get_writer(handle, 0)
        records = [(i, "x" * 50) for i in range(500)]  # well over 1k
        w.write(records)
        w.stop(True)
        files = list(tmp_path.glob("sparkrdma_tpu_shuffle_*"))
        assert files, "file-backed commit must write a data file"
        got = []
        for pid in range(3):
            r = ex.get_reader(handle, pid, pid + 1, {ex.local_smid: [0]})
            got.extend(r.read())
        assert sorted(got) == sorted(records)
        ex.unregister_shuffle(0)
        assert not list(tmp_path.glob("sparkrdma_tpu_shuffle_*")), (
            "data file must be unlinked when the shuffle is released"
        )
    finally:
        ex.stop()
        driver.stop()


def test_writer_spill_with_compression(devices, tmp_path):
    """Spilled compressed chunks concatenate into valid framed streams."""
    net = LoopbackNetwork()
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": 37330,
        "spark.shuffle.tpu.shuffleSpillRecordThreshold": "64",
        "spark.shuffle.tpu.spillDir": str(tmp_path / "newdir"),  # not yet created
        "spark.shuffle.tpu.compress": "true",
    })
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    ex = TpuShuffleManager(conf, is_driver=False, network=net,
                           port=38330, executor_id="0")
    try:
        handle = driver.register_shuffle(0, 1, HashPartitioner(3))
        w = ex.get_writer(handle, 0)
        records = [(i % 91, "v" * (i % 17)) for i in range(700)]
        w.write(records)
        w.stop(True)
        assert w.metrics.spills >= 2
        got = []
        for pid in range(3):
            r = ex.get_reader(handle, pid, pid + 1, {ex.local_smid: [0]})
            got.extend(r.read())
        assert sorted(got) == sorted(records)
    finally:
        ex.stop()
        driver.stop()


def test_concurrent_shuffles_stress(cluster):
    """Many shuffles in flight at once over one manager set: exercises
    the control plane's locking (driver maps, resolver registry, arena,
    callbacks) the way overlapping Spark stages would."""
    import concurrent.futures

    net, conf, driver, executors = cluster
    N_SHUFFLES, N_MAPS, N_PARTS = 6, 4, 3

    def run_one(sid):
        handle = driver.register_shuffle(
            100 + sid, N_MAPS, HashPartitioner(N_PARTS)
        )
        records_per_map = [
            [((m * 31 + i) % 50, (sid, m, i)) for i in range(200)]
            for m in range(N_MAPS)
        ]
        maps_by_host = run_maps(handle, executors, records_per_map)
        got = []
        for pid in range(N_PARTS):
            ex = executors[pid % len(executors)]
            reader = ex.get_reader(handle, pid, pid + 1, maps_by_host)
            got.extend(reader.read())
        expect = [kv for recs in records_per_map for kv in recs]
        assert sorted(got) == sorted(expect), f"shuffle {sid} corrupted"
        driver.unregister_shuffle(100 + sid)
        for ex in executors:
            ex.unregister_shuffle(100 + sid)
        return sid

    with concurrent.futures.ThreadPoolExecutor(max_workers=N_SHUFFLES) as p:
        done = sorted(p.map(run_one, range(N_SHUFFLES)))
    assert done == list(range(N_SHUFFLES))
    # no segment leaks across any executor after unregisters
    for ex in executors:
        assert ex.arena.stats()["segments"] == 0
