"""Worker for the 2-process multi-controller test (test_multihost.py).

Run as: python multihost_worker.py <process_id> <coordinator_port>

Validates the DCN-analog path on two CPU processes: rendezvous via
``multihost.initialize``, a global 8-device mesh spanning both
processes, a psum and a tiled all_to_all (the shuffle collective)
crossing the process boundary.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from sparkrdma_tpu.parallel import multihost
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]
    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=pid,
    )
    assert jax.process_count() == 2, jax.process_count()
    assert multihost.is_multihost()

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = multihost.global_mesh()
    D = len(list(mesh.devices.flat))
    assert D == 8, D
    local = multihost.host_local_indices(mesh)
    assert len(local) == 4, local
    sharding = NamedSharding(mesh, P(EXCHANGE_AXIS))

    # cross-process psum: every shard sees the global total
    def body(x):
        return jnp.full_like(x, jax.lax.psum(jnp.sum(x), EXCHANGE_AXIS))

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P(EXCHANGE_AXIS),
                              out_specs=P(EXCHANGE_AXIS)))
    arr = jax.make_array_from_process_local_data(
        sharding, np.ones(D * 4, np.int32) * (pid + 1), (D * 4,)
    )
    out = f(arr)
    for s in out.addressable_shards:
        got = int(np.asarray(s.data)[0])
        assert got == 16 * 1 + 16 * 2, got

    # cross-process all_to_all: the shuffle exchange collective.
    # x[src, dst] = src * D + dst; after the exchange each device d
    # holds row d of every source
    def a2a(x):  # local [1, D]
        y = jax.lax.all_to_all(
            x, EXCHANGE_AXIS, split_axis=1, concat_axis=0, tiled=True
        )
        return y  # [D, 1]

    g = jax.jit(jax.shard_map(
        a2a, mesh=mesh, in_specs=P(EXCHANGE_AXIS, None),
        out_specs=P(None, EXCHANGE_AXIS),
    ))
    mat = np.arange(D * D, dtype=np.int32).reshape(D, D)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(EXCHANGE_AXIS, None)),
        mat[np.array(local)], (D, D),
    )
    got = g(garr)
    for s in got.addressable_shards:
        d = s.index[1].start
        col = np.asarray(s.data).reshape(-1)
        expect = mat[:, d]
        assert (col == expect).all(), (d, col, expect)

    # the byte-exchange engine itself across the process boundary: this
    # process supplies data only for ITS sources (remote rows empty),
    # every process agrees on the lengths matrix, and the host-local
    # result guards remote destination rows
    from sparkrdma_tpu.parallel.exchange import (
        HostLocalStreams,
        NonAddressableStreamError,
        TileExchange,
    )

    def payload(s, d):
        return bytes([(7 * s + 3 * d + 1) % 251]) * (100 * (s + d + 1))

    lengths = np.array(
        [[100 * (s + d + 1) for d in range(D)] for s in range(D)],
        dtype=np.int64,
    )
    streams = [
        [payload(s, d) if s in local else b"" for d in range(D)]
        for s in range(D)
    ]
    ex = TileExchange(mesh, tile_bytes=1 << 10)
    res = ex.exchange_bytes(streams, lengths=lengths)
    assert isinstance(res, HostLocalStreams), type(res)
    assert res.addressable == frozenset(local), res.addressable
    for d, row in res.items():
        for s in range(D):
            assert row[s] == payload(s, d), (s, d)
    remote = next(i for i in range(D) if i not in local)
    try:
        res[remote]
    except NonAddressableStreamError:
        pass
    else:
        raise AssertionError("remote destination row did not raise")

    print(f"proc {pid}: multihost collectives OK", flush=True)


if __name__ == "__main__":
    main()
