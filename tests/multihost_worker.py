"""Worker for the 2-process multi-controller test (test_multihost.py).

Run as: python multihost_worker.py <process_id> <coordinator_port>

Validates the DCN-analog path on two CPU processes: rendezvous via
``multihost.initialize``, a global 8-device mesh spanning both
processes, a psum and a tiled all_to_all (the shuffle collective)
crossing the process boundary.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from sparkrdma_tpu.parallel import multihost
from sparkrdma_tpu.parallel.mesh import EXCHANGE_AXIS


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]

    # ---- bulk-shuffle control plane (REAL sockets, created before the
    # jax rendezvous so the driver is listening when executors hello)
    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
    from sparkrdma_tpu.transport import TcpNetwork

    driver_port = int(port) + 31
    conf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": driver_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "60s",
        "spark.shuffle.tpu.connectTimeout": "10s",
    })
    # windowed-plan conf (shuffle 71): 4 maps / window of 2 — reducers
    # exchange window 0 while each process's straggler map is unwritten
    wconf = TpuShuffleConf({
        "spark.shuffle.tpu.driverPort": driver_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "60s",
        "spark.shuffle.tpu.connectTimeout": "10s",
        "spark.shuffle.tpu.bulkWindowMaps": "2",
    })
    NUM_PARTS = 8
    part = HashPartitioner(NUM_PARTS)
    driver = None
    if pid == 0:
        driver = TpuShuffleManager(
            wconf, is_driver=True, network=TcpNetwork(), port=driver_port,
        )
        driver.register_shuffle(70, 2, part)
        driver.register_shuffle(71, 4, part)
        driver.register_shuffle(72, 4, part)

    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=pid,
    )
    assert jax.process_count() == 2, jax.process_count()
    assert multihost.is_multihost()

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = multihost.global_mesh()
    D = len(list(mesh.devices.flat))
    assert D == 8, D
    local = multihost.host_local_indices(mesh)
    assert len(local) == 4, local
    sharding = NamedSharding(mesh, P(EXCHANGE_AXIS))

    # cross-process psum: every shard sees the global total
    def body(x):
        return jnp.full_like(x, jax.lax.psum(jnp.sum(x), EXCHANGE_AXIS))

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P(EXCHANGE_AXIS),
                              out_specs=P(EXCHANGE_AXIS)))
    arr = jax.make_array_from_process_local_data(
        sharding, np.ones(D * 4, np.int32) * (pid + 1), (D * 4,)
    )
    out = f(arr)
    for s in out.addressable_shards:
        got = int(np.asarray(s.data)[0])
        assert got == 16 * 1 + 16 * 2, got

    # cross-process all_to_all: the shuffle exchange collective.
    # x[src, dst] = src * D + dst; after the exchange each device d
    # holds row d of every source
    def a2a(x):  # local [1, D]
        y = jax.lax.all_to_all(
            x, EXCHANGE_AXIS, split_axis=1, concat_axis=0, tiled=True
        )
        return y  # [D, 1]

    g = jax.jit(jax.shard_map(
        a2a, mesh=mesh, in_specs=P(EXCHANGE_AXIS, None),
        out_specs=P(None, EXCHANGE_AXIS),
    ))
    mat = np.arange(D * D, dtype=np.int32).reshape(D, D)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(EXCHANGE_AXIS, None)),
        mat[np.array(local)], (D, D),
    )
    got = g(garr)
    for s in got.addressable_shards:
        d = s.index[1].start
        col = np.asarray(s.data).reshape(-1)
        expect = mat[:, d]
        assert (col == expect).all(), (d, col, expect)

    # the byte-exchange engine itself across the process boundary: this
    # process supplies data only for ITS sources (remote rows empty),
    # every process agrees on the lengths matrix, and the host-local
    # result guards remote destination rows
    from sparkrdma_tpu.parallel.exchange import (
        HostLocalStreams,
        NonAddressableStreamError,
        TileExchange,
    )

    def payload(s, d):
        return bytes([(7 * s + 3 * d + 1) % 251]) * (100 * (s + d + 1))

    lengths = np.array(
        [[100 * (s + d + 1) for d in range(D)] for s in range(D)],
        dtype=np.int64,
    )
    streams = [
        [payload(s, d) if s in local else b"" for d in range(D)]
        for s in range(D)
    ]
    ex = TileExchange(mesh, tile_bytes=1 << 10)
    res = ex.exchange_bytes(streams, lengths=lengths)
    assert isinstance(res, HostLocalStreams), type(res)
    assert res.addressable == frozenset(local), res.addressable
    for d, row in res.items():
        for s in range(D):
            assert row[s] == payload(s, d), (s, d)
    remote = next(i for i in range(D) if i not in local)
    try:
        res[remote]
    except NonAddressableStreamError:
        pass
    else:
        raise AssertionError("remote destination row did not raise")

    # ---- the FULL bulk-synchronous shuffle across processes: TCP
    # control plane (hello/publish/plan) + one cross-process collective
    # (shuffle/bulk.py) — one executor per process, mesh = one device
    # per process
    import time

    from jax.sharding import Mesh as _Mesh

    from sparkrdma_tpu.shuffle.bulk import BulkExchangeReader

    ex_mgr = TpuShuffleManager(
        conf, is_driver=False, network=TcpNetwork(),
        port=driver_port + 10 + pid, executor_id=str(pid),
    )
    deadline = time.time() + 30
    while time.time() < deadline and len(ex_mgr._peers) < 2:
        time.sleep(0.02)
    assert len(ex_mgr._peers) == 2, "announce did not reach both executors"

    from sparkrdma_tpu.shuffle.manager import ShuffleHandle

    handle = ShuffleHandle(70, 2, part)
    records = [(f"p{pid}-k{j}", (pid, j)) for j in range(60)]
    w = ex_mgr.get_writer(handle, pid)
    w.write(records)
    w.stop(True)

    # one mesh device per process, ordered by process index — both
    # processes derive the identical mesh
    per_proc = {}
    for dev in jax.devices():
        per_proc.setdefault(dev.process_index, dev)
    mesh2 = _Mesh(
        np.array([per_proc[i] for i in sorted(per_proc)]), (EXCHANGE_AXIS,)
    )
    reader = BulkExchangeReader(
        ex_mgr, TileExchange(mesh2, tile_bytes=1 << 12)
    )
    mine = list(reader.read(70))

    # my canonical index: executors sorted by (host, port) — ports are
    # driver_port+10+pid, so index == pid
    all_records = [
        (f"p{q}-k{j}", (q, j)) for q in range(2) for j in range(60)
    ]
    expect = [
        (k, v) for k, v in all_records
        if part.partition(k) % 2 == pid
    ]
    assert sorted(mine) == sorted(expect), (
        f"proc {pid}: got {len(mine)} records, want {len(expect)}"
    )

    # ---- windowed bulk across processes (shuffle 71): each process
    # writes map `pid`, starts reading, PROVES window 0's collective
    # completed, then writes its straggler map `pid + 2` — the
    # incremental-plan overlap crossing a real process boundary
    import threading

    conf.set("bulkWindowMaps", "2")
    handle71 = ShuffleHandle(71, 4, part)
    rec71 = {
        m: [(f"w{m}-k{j}", (m, j)) for j in range(40)] for m in range(4)
    }
    w = ex_mgr.get_writer(handle71, pid)
    w.write(rec71[pid])
    w.stop(True)

    reader71 = BulkExchangeReader(
        ex_mgr, TileExchange(mesh2, tile_bytes=1 << 12)
    )
    box = {}

    def read71():
        try:
            box["got"] = list(reader71.read(71))
        except BaseException as e:  # surfaced after join
            box["err"] = e

    th = threading.Thread(target=read71, daemon=True)
    th.start()
    deadline = time.time() + 30
    while time.time() < deadline and not reader71.window_events:
        time.sleep(0.02)
    assert reader71.window_events, (
        f"proc {pid}: window 0 never exchanged before the straggler"
    )
    assert "got" not in box, "read returned before the straggler map"

    w = ex_mgr.get_writer(handle71, pid + 2)
    w.write(rec71[pid + 2])
    w.stop(True)
    th.join(timeout=60)
    assert "err" not in box, f"proc {pid}: {box.get('err')!r}"
    wins = [wn for wn, _t, _b in reader71.window_events]
    assert wins == [0, 1], f"proc {pid}: windows {wins}"
    all71 = [kv for m in range(4) for kv in rec71[m]]
    expect71 = [
        (k, v) for k, v in all71 if part.partition(k) % 2 == pid
    ]
    assert sorted(box["got"]) == sorted(expect71), (
        f"proc {pid}: windowed got {len(box['got'])} records, "
        f"want {len(expect71)}"
    )

    # ---- the UNIFIED reactive device plane across processes (shuffle
    # 72, VERDICT r3 item 3): reducers issue per-partition reads through
    # manager.get_reader (readPlane=windowed) and driver-planned window
    # collectives move the bytes — window 0 reaches the READERS while
    # each process's straggler map is still unwritten
    from sparkrdma_tpu.shuffle.bulk import WindowedReadPlane

    conf.set("readPlane", "windowed")  # bulkWindowMaps already 2
    ex_mgr.windowed_plane = WindowedReadPlane(
        ex_mgr, exchange=TileExchange(mesh2, tile_bytes=1 << 12)
    )
    handle72 = ShuffleHandle(72, 4, part)
    rec72 = {
        m: [(f"u{m}-k{j}", (m, j)) for j in range(50)] for m in range(4)
    }
    w = ex_mgr.get_writer(handle72, pid)
    w.write(rec72[pid])
    w.stop(True)

    my_parts = [r for r in range(NUM_PARTS) if r % 2 == pid]
    results72 = {}
    errors72 = {}

    def reduce72(p):
        try:
            r = ex_mgr.get_reader(handle72, p, p + 1, {})
            results72[p] = list(r.read())
        except BaseException as e:
            errors72[p] = e

    threads72 = [
        threading.Thread(target=reduce72, args=(p,), daemon=True)
        for p in my_parts
    ]
    for t in threads72:
        t.start()
    deadline = time.time() + 30
    while time.time() < deadline and not ex_mgr.windowed_plane.window_events(72):
        time.sleep(0.02)
    assert ex_mgr.windowed_plane.window_events(72), (
        f"proc {pid}: no reactive window landed before the straggler"
    )
    assert not results72, (
        f"proc {pid}: a reducer finished before the straggler map"
    )

    w = ex_mgr.get_writer(handle72, pid + 2)
    w.write(rec72[pid + 2])
    w.stop(True)
    for t in threads72:
        t.join(timeout=60)
    assert not errors72, f"proc {pid}: {errors72!r}"
    wins72 = [wn for wn, _t, _b in ex_mgr.windowed_plane.window_events(72)]
    assert wins72 == [0, 1], f"proc {pid}: windows {wins72}"
    all72 = [kv for m in range(4) for kv in rec72[m]]
    for p in my_parts:
        expect = [(k, v) for k, v in all72 if part.partition(k) == p]
        assert sorted(results72.get(p, [])) == sorted(expect), (
            f"proc {pid}: partition {p} got "
            f"{len(results72.get(p, []))} records, want {len(expect)}"
        )

    ex_mgr.stop()
    if driver is not None:
        driver.stop()

    print(f"proc {pid}: multihost collectives OK", flush=True)


if __name__ == "__main__":
    main()
