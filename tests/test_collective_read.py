"""The north-star integration: record-plane shuffles whose bulk fetches
ride all_to_all tile rounds over the device mesh.

Covers the write → publish → resolve → exchange(a2a) → read path the
reference realizes as commit → publish → FetchMapStatus → scatter RDMA
READ (RdmaShuffleFetcherIterator.scala:162-171, RdmaChannel.java:441-474)
— here the fetches between mesh-attached executors execute as collective
pack+all_to_all rounds (tests/collective_read_fixture.py) with zero per-block
host round-trips.
"""

import numpy as np
import pytest

from sparkrdma_tpu.api import TpuShuffleContext
from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.memory.device_arena import WRITE_ALIGN, DeviceArena
from collective_read_fixture import CollectiveNetwork
from sparkrdma_tpu.parallel.mesh import make_mesh


def _collective_conf(**extra):
    conf = TpuShuffleConf()
    conf.set("readPlane", "collective")
    conf.set("deviceArenaBytes", 8 << 20)
    for k, v in extra.items():
        conf.set(k, v)
    return conf


def _collective_ctx(num_executors, conf, base_port):
    """The coordinator plane is a TEST FIXTURE now (superseded by the
    windowed plane): contexts opt in by passing the network explicitly,
    exactly what production configs can no longer reach."""
    return TpuShuffleContext(
        num_executors=num_executors, conf=conf, base_port=base_port,
        network=CollectiveNetwork(
            mesh=make_mesh(num_executors),
            tile_bytes=conf.exchange_tile_bytes,
            flush_ms=conf.exchange_flush_ms,
        ),
    )


# -- DeviceArena unit coverage ----------------------------------------------

def test_arena_alloc_write_read_roundtrip(devices):
    arena = DeviceArena(1 << 20, devices[0])
    span = arena.alloc(1000)
    assert span.offset % WRITE_ALIGN == 0
    data = np.arange(1000, dtype=np.uint8) % 251
    arena.write(span, data)
    out = np.frombuffer(arena.read(span.offset, 1000), np.uint8)
    np.testing.assert_array_equal(out, data)
    span.free()


def test_arena_free_coalesces(devices):
    arena = DeviceArena(1 << 20, devices[0])
    spans = [arena.alloc(WRITE_ALIGN) for _ in range(4)]
    # free out of order: 1, 3, 0, 2 → one extent at the end
    for i in (1, 3, 0, 2):
        spans[i].free()
    assert arena.stats()["free_extents"] == 1
    assert arena.allocated_bytes == 0
    # double free is a no-op
    spans[0].free()
    assert arena.allocated_bytes == 0


def test_arena_exhaustion_raises(devices):
    arena = DeviceArena(64 << 10, devices[0])
    arena.alloc(60 << 10)
    with pytest.raises(MemoryError):
        arena.alloc(32 << 10)


def test_arena_writes_are_isolated(devices):
    """Two spans: writing one must not disturb the other."""
    arena = DeviceArena(1 << 20, devices[0])
    a, b = arena.alloc(WRITE_ALIGN), arena.alloc(WRITE_ALIGN)
    da = np.full(WRITE_ALIGN, 7, np.uint8)
    db = np.full(WRITE_ALIGN, 9, np.uint8)
    arena.write(a, da)
    arena.write(b, db)
    np.testing.assert_array_equal(
        np.frombuffer(arena.read(a.offset, WRITE_ALIGN), np.uint8), da
    )
    np.testing.assert_array_equal(
        np.frombuffer(arena.read(b.offset, WRITE_ALIGN), np.uint8), db
    )


# -- integrated shuffle over the collective plane ---------------------------

def test_collective_group_by_key(devices):
    """Full shuffle on 4 mesh-attached executors: results correct AND the
    bulk plane actually ran collective rounds with no host fallbacks."""
    with _collective_ctx(4, _collective_conf(), 41000) as ctx:
        assert isinstance(ctx.network, CollectiveNetwork)
        data = [(i % 37, i) for i in range(4000)]
        out = (
            ctx.parallelize(data, num_slices=8)
            .group_by_key(num_partitions=8)
            .collect()
        )
        got = {k: sorted(vs) for k, vs in out}
        expect = {}
        for k, v in data:
            expect.setdefault(k, []).append(v)
        assert got == {k: sorted(vs) for k, vs in expect.items()}
        stats = ctx.network.coordinator.stats()
    assert stats["rounds_executed"] > 0
    assert stats["batches_executed"] > 0
    assert stats["fallback_blocks"] == 0
    assert stats["payload_bytes_moved"] > 0


def test_collective_matches_host_plane(devices):
    data = [(i % 11, i * 3) for i in range(2500)]

    def run(conf, port):
        maker = (
            _collective_ctx if conf.read_plane == "collective"
            else lambda n, c, p: TpuShuffleContext(
                num_executors=n, conf=c, base_port=p
            )
        )
        with maker(3, conf, port) as ctx:
            return sorted(
                ctx.parallelize(data, num_slices=6)
                .reduce_by_key(lambda a, b: a + b, num_partitions=6)
                .collect()
            )

    host = run(TpuShuffleConf(), 42000)
    coll = run(_collective_conf(), 43000)
    assert host == coll


def test_collective_sort_by_key(devices):
    with _collective_ctx(4, _collective_conf(), 44000) as ctx:
        rng = np.random.default_rng(7)
        keys = rng.integers(0, 1 << 30, 3000).tolist()
        out = (
            ctx.parallelize([(k, 1) for k in keys], num_slices=8)
            .sort_by_key(num_partitions=8)
            .collect()
        )
        assert [k for k, _ in out] == sorted(keys)
        assert ctx.network.coordinator.rounds_executed > 0


def test_collective_columnar_shuffle(devices):
    """Columnar serializer + collective bulk plane: the two round-2 perf
    paths composed."""
    conf = _collective_conf(serializer="columnar")
    with _collective_ctx(4, conf, 45000) as ctx:
        n = 6000
        keys = np.arange(n, dtype=np.int64) % 101
        vals = np.arange(n, dtype=np.int64)
        out = (
            ctx.parallelize_columns(keys, vals, num_slices=8)
            .reduce_by_key("sum", num_partitions=8)
            .collect()
        )
        got = dict(out)
        expect = {}
        for k, v in zip(keys.tolist(), vals.tolist()):
            expect[k] = expect.get(k, 0) + v
        assert got == expect
        stats = ctx.network.coordinator.stats()
    assert stats["rounds_executed"] > 0
    assert stats["fallback_blocks"] == 0


def test_collective_more_executors_than_devices_rejected(devices):
    import jax

    too_many = len(jax.devices()) + 1
    with pytest.raises(ValueError, match="mesh devices"):
        TpuShuffleContext(
            num_executors=too_many, conf=_collective_conf(), base_port=46000
        )


def test_unattached_executor_falls_back_to_host(devices):
    """An executor beyond the attached set still shuffles correctly via
    the host fallback path (lazy membership: the reference's executors
    join the mesh lazily, RdmaShuffleManager.scala:277-318)."""
    conf = _collective_conf()
    with _collective_ctx(3, conf, 47000) as ctx:
        # executor 2 leaves the mesh: its commits stay arena-resident but
        # fetches touching it must take the one-sided host path
        ctx.network.coordinator.detach(2)
        data = [(i % 13, i) for i in range(1500)]
        out = (
            ctx.parallelize(data, num_slices=6)
            .reduce_by_key(lambda a, b: a + b, num_partitions=6)
            .collect()
        )
        expect = {}
        for k, v in data:
            expect[k] = expect.get(k, 0) + v
        assert dict(out) == expect


def test_coordinator_stop_fails_pending(devices):
    """Pending (unflushed) fetches are failed on stop, like channel
    teardown failing outstanding listeners (RdmaChannel.java:788-869)."""
    from collective_read_fixture import ExchangeCoordinator
    from sparkrdma_tpu.transport.channel import (
        FnCompletionListener,
        TransportError,
    )

    from types import SimpleNamespace

    coord = ExchangeCoordinator(make_mesh(), flush_ms=10_000.0)
    failures = []
    ok = []

    # drive stop() with a manually queued request
    from collective_read_fixture import _Request

    req = _Request(0, 1, [(0, 128)], FnCompletionListener(
        lambda r: ok.append(r), lambda e: failures.append(e)
    ))
    with coord._lock:
        coord._pending.append(req)
    coord.stop()
    assert len(failures) == 1 and isinstance(failures[0], TransportError)
    assert not ok
    with pytest.raises(TransportError):
        coord.submit(
            SimpleNamespace(device_index=0), SimpleNamespace(device_index=1),
            [], FnCompletionListener(), lambda locs: [],
        )


def test_shuffle_larger_than_arena_completes(devices):
    """Shuffle bigger than the HBM arena budget: segments that don't
    fit stay host-resident and fall back to the host read path, the
    rest ride the collective plane — results exact either way (the
    larger-than-HBM shuffle contract, SURVEY §5 long-context note)."""
    # conf clamps the arena to >=1 MiB; ~6 MiB of payload across 4
    # executors (~1.5 MiB committed each) must overflow it
    conf = _collective_conf(deviceArenaBytes=1 << 20)
    data = [(i % 23, bytes(1000) + i.to_bytes(4, "big"))
            for i in range(6000)]
    with _collective_ctx(4, conf, 45500) as ctx:
        out = (
            ctx.parallelize(data, num_slices=8)
            .group_by_key(num_partitions=8)
            .collect()
        )
        got = {k: sorted(vs) for k, vs in out}
        stats = ctx.network.coordinator.stats()
    expect = {}
    for k, v in data:
        expect.setdefault(k, []).append(v)
    assert got == {k: sorted(vs) for k, vs in expect.items()}
    # the tiny arena forced at least part of the traffic off-plane
    assert stats["fallback_blocks"] > 0


def test_write_block_size_splits_commits(devices):
    """shuffleWriteBlockSize bounds arena span sizes: one map output
    splits across several registered segments (the reference's chunked
    mmap+MR registration, RdmaMappedFile.java:95-171) and every block
    reads back exactly, single and batched."""
    from sparkrdma_tpu.memory.arena import ArenaManager
    from sparkrdma_tpu.shuffle.resolver import ShuffleBlockResolver

    arena = ArenaManager()
    res = ShuffleBlockResolver(
        arena, node=None, stage_to_device=True,
        write_block_size=64 << 10,
    )
    rng = np.random.default_rng(3)
    parts = [rng.integers(0, 256, 9000, dtype=np.uint8).tobytes()
             for _ in range(32)]  # ~288 KiB total, 64 KiB blocks
    res.device_arena = None  # host/jnp path: no arena attached
    # arena path: attach a device arena so splitting engages
    from sparkrdma_tpu.memory.device_arena import DeviceArena

    res.device_arena = DeviceArena(8 << 20, devices[0])
    mto = res.commit_map_output(7, 0, parts)
    _mto, segs = res._shuffles[7].outputs[0]
    assert len(segs) > 1, "expected a multi-segment commit"
    mkeys = {mto.get_location(p).mkey for p in range(32)}
    assert mkeys == set(segs), "locations must cover every segment"
    for p in range(32):
        assert res.get_local_block(7, 0, p) == parts[p]
    got = res.get_local_blocks(7, 0, range(32))
    assert [bytes(b) for b in got] == parts
    # retry/speculation replaces ALL prior segments
    mto2 = res.commit_map_output(7, 0, parts)
    _mto2, segs2 = res._shuffles[7].outputs[0]
    assert set(segs2).isdisjoint(set(segs))
    res.remove_shuffle(7)
    assert res.device_arena.allocated_bytes == 0
