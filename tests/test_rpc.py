"""RPC wire formats: round-trips, dispatch, segmentation
(SURVEY.md §2, RdmaRpcMsg)."""

import pytest

from sparkrdma_tpu.rpc import (
    AnnounceShuffleManagersMsg,
    FetchMapStatusMsg,
    FetchMapStatusResponseMsg,
    HelloMsg,
    PublishMapTaskOutputMsg,
    decode_msg,
)
from sparkrdma_tpu.shuffle.map_output import MapTaskOutput
from sparkrdma_tpu.utils.types import BlockLocation, BlockManagerId, ShuffleManagerId


def smid(i: int) -> ShuffleManagerId:
    return ShuffleManagerId(
        f"host{i}", 9000 + i, BlockManagerId(str(i), f"host{i}", 7000 + i)
    )


def test_hello_roundtrip():
    msg = HelloMsg(smid(1), channel_port=4242)
    out = decode_msg(msg.encode())
    assert isinstance(out, HelloMsg)
    assert out.shuffle_manager_id == msg.shuffle_manager_id
    assert out.channel_port == 4242


def test_announce_roundtrip_and_segmentation():
    msg = AnnounceShuffleManagersMsg([smid(i) for i in range(100)])
    # single-frame round trip
    out = decode_msg(msg.encode())
    assert out.shuffle_manager_ids == msg.shuffle_manager_ids
    # segmentation into small frames: union of decoded segments == original
    frames = msg.encode_segments(max_segment_size=256)
    assert len(frames) > 1
    assert all(len(f) <= 256 for f in frames)
    collected = []
    for f in frames:
        collected.extend(decode_msg(f).shuffle_manager_ids)
    assert tuple(collected) == msg.shuffle_manager_ids


def test_publish_roundtrip_and_segmented_install():
    src = MapTaskOutput(64)
    for p in range(64):
        src.put(p, BlockLocation(p * 4096, 4096, 17))
    msg = PublishMapTaskOutputMsg(
        smid(2), shuffle_id=5, map_id=9, total_num_partitions=64,
        first_reduce_id=0, last_reduce_id=63, entries=src.get_range_bytes(0, 63),
    )
    frames = msg.encode_segments(max_segment_size=300)
    assert len(frames) > 1
    # driver side: install each segment independently via put_range
    dst = MapTaskOutput(64)
    for f in frames:
        seg = decode_msg(f)
        assert isinstance(seg, PublishMapTaskOutputMsg)
        assert seg.shuffle_id == 5 and seg.map_id == 9
        dst.put_range(seg.first_reduce_id, seg.last_reduce_id, seg.entries)
    assert dst.is_complete
    for p in range(64):
        assert dst.get_location(p) == src.get_location(p)


def test_fetch_map_status_roundtrip():
    blocks = [(m, r) for m in range(3) for r in range(4)]
    msg = FetchMapStatusMsg(smid(3), smid(4), shuffle_id=1, callback_id=77,
                            block_ids=blocks)
    out = decode_msg(msg.encode())
    assert out.requester == msg.requester
    assert out.host == msg.host
    assert out.callback_id == 77
    assert out.block_ids == tuple(tuple(b) for b in blocks)


def test_fetch_response_roundtrip_and_segmentation():
    locs = [BlockLocation(i * 100, i + 1, 3) for i in range(50)]
    msg = FetchMapStatusResponseMsg(callback_id=8, total=50, index=0, locations=locs)
    frames = msg.encode_segments(max_segment_size=200)
    assert len(frames) > 1
    # reassemble by index
    got = [None] * 50
    for f in frames:
        seg = decode_msg(f)
        assert seg.callback_id == 8 and seg.total == 50
        for j, loc in enumerate(seg.locations):
            got[seg.index + j] = loc
    assert got == locs


def test_decode_rejects_garbage():
    with pytest.raises(ValueError):
        decode_msg(b"\x01")
    with pytest.raises(ValueError):
        decode_msg(b"\x10\x00\x00\x00\x63\x00\x00\x00" + b"\x00" * 8)  # type 99
    # length mismatch
    good = HelloMsg(smid(5), 1).encode()
    with pytest.raises(ValueError):
        decode_msg(good + b"trailing")


def test_unsegmentable_oversize_raises():
    msg = HelloMsg(smid(6), 1)
    with pytest.raises(ValueError):
        msg.encode_segments(max_segment_size=10)


def test_fetch_map_status_segmentation():
    # reviewer finding: wide fetches (1000+ blocks) must split across frames
    blocks = [(m, 7) for m in range(1000)]
    msg = FetchMapStatusMsg(smid(7), smid(8), shuffle_id=2, callback_id=5,
                            block_ids=blocks)
    frames = msg.encode_segments(max_segment_size=512)
    assert len(frames) > 1
    got = [None] * 1000
    for f in frames:
        seg = decode_msg(f)
        assert seg.total == 1000 and seg.callback_id == 5
        for j, b in enumerate(seg.block_ids):
            got[seg.index + j] = b
    assert got == [tuple(b) for b in blocks]


def test_oversized_atomic_element_raises_not_recurses():
    # reviewer finding: a single id larger than the segment must raise
    # ValueError, not recurse forever
    big = ShuffleManagerId("h" * 300, 1, BlockManagerId("e", "h" * 300, 2))
    msg = AnnounceShuffleManagersMsg([big, smid(1)])
    with pytest.raises(ValueError, match="exceeds segment size"):
        msg.encode_segments(max_segment_size=256)


def test_payload_size_estimates_match_actual():
    # the no-serialize split decision must agree with real payload sizes
    locs = [BlockLocation(i, i, 1) for i in range(10)]
    msgs = [
        HelloMsg(smid(1), 7),
        AnnounceShuffleManagersMsg([smid(i) for i in range(5)]),
        PublishMapStub := PublishMapTaskOutputMsg(
            smid(2), 1, 2, 4, 0, 3, b"\x00" * 64),
        FetchMapStatusMsg(smid(3), smid(4), 1, 2, [(0, 1), (2, 3)]),
        FetchMapStatusResponseMsg(1, 10, 0, locs),
    ]
    for m in msgs:
        assert m._payload_size() == len(m._payload()), type(m).__name__


def test_malformed_frame_raises_valueerror_not_struct_error():
    # reviewer finding: truncated string payloads must surface as ValueError
    import struct as _s
    bogus_payload = _s.pack("<H", 1000) + b"ab"  # claims 1000-byte string
    frame = _s.pack("<ii", 8 + len(bogus_payload), 1) + bogus_payload
    with pytest.raises(ValueError):
        decode_msg(frame)


def test_compressed_serializer_concatenation_safe():
    # reviewer finding: spill-merge concatenates serialize() outputs;
    # the compressed framing must decode ALL frames, not just the first
    from sparkrdma_tpu.utils.serde import CompressedSerializer

    for codec in ("zlib", "lzma"):
        s = CompressedSerializer(codec=codec, min_size=64)
        big = [(i, "x" * 50) for i in range(100)]    # compressed frame
        small = [(999, "y")]                          # raw frame
        blob = s.serialize(big) + s.serialize(small) + s.serialize(big)
        got = list(s.deserialize(blob))
        assert got == big + small + big


def test_compressed_serializer_truncation_detected():
    from sparkrdma_tpu.utils.serde import CompressedSerializer
    import pytest as _pytest

    s = CompressedSerializer(min_size=16)
    blob = s.serialize([(1, "aaaa" * 50)])
    with _pytest.raises(ValueError, match="truncated"):
        list(s.deserialize(blob[:-3]))


def test_compressed_serializer_multi_frame_roundtrip():
    # large record streams split into multiple frames (bounding each
    # frame far below the 4 GiB length-field ceiling)
    from sparkrdma_tpu.utils.serde import CompressedSerializer

    s = CompressedSerializer(min_size=64)
    s.frame_records = 100
    records = [(i, i * 3) for i in range(1050)]  # 11 frames
    blob = s.serialize(records)
    assert list(s.deserialize(blob)) == records


def test_fetch_failed_roundtrip():
    from sparkrdma_tpu.rpc.messages import FetchMapStatusFailedMsg

    msg = FetchMapStatusFailedMsg(77, "executor host3:9003 was removed")
    out = decode_msg(msg.encode())
    assert out == msg
    # reasons are clamped to 1 KiB on the wire
    long = FetchMapStatusFailedMsg(1, "x" * 5000)
    got = decode_msg(long.encode())
    assert got.callback_id == 1 and len(got.reason) == 1024


def test_heartbeat_roundtrip():
    from sparkrdma_tpu.rpc.messages import HeartbeatMsg

    ping = HeartbeatMsg(smid(4), seq=12, is_ack=False)
    ack = HeartbeatMsg(smid(5), seq=12, is_ack=True)
    assert decode_msg(ping.encode()) == ping
    assert decode_msg(ack.encode()) == ack


def test_exchange_plan_roundtrip_windowed():
    from sparkrdma_tpu.rpc.messages import (
        ExchangePlanMsg,
        FetchExchangePlanMsg,
    )

    # fetch side: legacy default window=-1 and an explicit window
    legacy = FetchExchangePlanMsg(smid(1), 5, 33)
    out = decode_msg(legacy.encode())
    assert out == legacy and out.window == -1
    win = FetchExchangePlanMsg(smid(2), 5, 34, window=3)
    assert decode_msg(win.encode()) == win

    # plan side: window metadata + the requester's map set round-trip
    hosts = [smid(i) for i in range(3)]
    lengths = list(range(9))
    manifest = [
        ((0, 1, 100), (2, 4, 50)),
        (),
        ((1, 0, 7),),
    ]
    plan = ExchangePlanMsg(
        9, hosts, lengths, manifest,
        window=2, final=False, my_maps=(4, 7, 9),
    )
    got = decode_msg(plan.encode())
    assert got == plan
    assert got.window == 2 and got.final is False
    assert got.my_maps == (4, 7, 9)
    # defaults decode as the legacy full-barrier plan
    full = ExchangePlanMsg(9, hosts, lengths, manifest)
    got2 = decode_msg(full.encode())
    assert got2.window == -1 and got2.final is True and got2.my_maps == ()
    # size estimate stays exact with the new tail fields
    assert len(plan._payload()) == plan._payload_size()


def test_clean_shuffle_roundtrip():
    from sparkrdma_tpu.rpc.messages import CleanShuffleMsg

    msg = CleanShuffleMsg(417)
    out = decode_msg(msg.encode())
    assert isinstance(out, CleanShuffleMsg)
    assert out == msg
    assert len(msg._payload()) == msg._payload_size()
