"""Columnar record plane: serializer, vectorized partitioning, plane
consistency, and end-to-end wide ops (VERDICT round-1 item 3 — the
unsafe-row analog, RdmaWrapperShuffleWriter.scala:85-101)."""

import numpy as np
import pytest

from sparkrdma_tpu.api import TpuShuffleContext
from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.shuffle.manager import ColumnarAggregator
from sparkrdma_tpu.shuffle.partitioner import (
    HashPartitioner,
    RangePartitioner,
    stable_hash,
    stable_hash_array,
)
from sparkrdma_tpu.utils.columns import (
    ColumnBatch,
    combine_columns,
    group_columns,
    stable_key_order,
    take_rows,
)
from sparkrdma_tpu.utils.serde import ColumnarSerializer, CompressedSerializer


def _columnar_conf(extra=None):
    conf = {"spark.shuffle.tpu.serializer": "columnar"}
    conf.update(extra or {})
    return TpuShuffleConf(conf)


# -- hash / partition consistency (the cross-plane contract) ----------------

def test_stable_hash_scalar_array_agree_ints():
    ks = np.array([0, 1, -1, 5, -(2**63), 2**63 - 1, 12345678901], np.int64)
    assert [stable_hash(int(k)) for k in ks] == stable_hash_array(ks).tolist()
    ku = np.array([0, 1, 2**64 - 1, 2**63], np.uint64)
    assert [stable_hash(int(k)) for k in ku] == stable_hash_array(ku).tolist()
    k32 = np.array([-5, 7, 2**31 - 1], np.int32)
    assert [stable_hash(int(k)) for k in k32] == stable_hash_array(k32).tolist()


def test_stable_hash_scalar_array_agree_floats():
    kf = np.array([0.0, -0.0, 1.5, -3.25, 1e300], np.float64)
    assert [stable_hash(float(k)) for k in kf] == stable_hash_array(kf).tolist()
    k32 = np.array([1.5, -2.25], np.float32)
    # float32 promotes to float64 bits, matching the scalar float path
    assert [stable_hash(float(k)) for k in k32] == stable_hash_array(k32).tolist()


def test_partitioners_scalar_array_agree():
    ks = np.array([0, 1, -1, 977, -(2**62), 41, 2**63 - 1], np.int64)
    hp = HashPartitioner(7)
    assert [hp.partition(int(k)) for k in ks] == hp.partition_array(ks).tolist()
    hp8 = HashPartitioner(8)  # power-of-two branch if added later
    assert [hp8.partition(int(k)) for k in ks] == hp8.partition_array(ks).tolist()
    rp = RangePartitioner(4, [3, 9, 200, 5, 7])
    assert [rp.partition(int(k)) for k in ks] == rp.partition_array(ks).tolist()
    rp0 = RangePartitioner(4, [])
    assert rp0.partition_array(ks).tolist() == [0] * len(ks)


# -- serializer --------------------------------------------------------------

def test_columnar_serializer_roundtrip_and_concat():
    rng = np.random.default_rng(0)
    b = ColumnBatch(
        np.arange(1000, dtype=np.int64),
        np.frombuffer(rng.bytes(64000), dtype="S64"),
    )
    s = ColumnarSerializer()
    data = s.serialize(b) + s.serialize(b)  # concatenation-safe
    outs = list(s.deserialize_columns(data))
    assert len(outs) == 2
    np.testing.assert_array_equal(outs[0].keys, b.keys)
    assert outs[1].vals.tolist() == b.vals.tolist()
    # tuple-iterable input packs into one batch
    recs = list(s.deserialize(s.serialize([(1, 2.5), (3, 4.5)])))
    assert recs == [(1, 2.5), (3, 4.5)]
    # empty serialize
    assert s.serialize([]) == b""
    assert list(s.deserialize(b"")) == []


def test_columnar_serializer_key_sorted_flag_rides_wire():
    s = ColumnarSerializer()
    b = ColumnBatch(np.array([1, 2, 3]), np.array([9, 8, 7]), key_sorted=True)
    (out,) = s.deserialize_columns(s.serialize(b))
    assert out.key_sorted
    b2 = ColumnBatch(np.array([3, 1]), np.array([1, 2]))
    (out2,) = s.deserialize_columns(s.serialize(b2))
    assert not out2.key_sorted


def test_columnar_serializer_through_compression():
    rng = np.random.default_rng(1)
    cs = CompressedSerializer(ColumnarSerializer())
    assert cs.supports_columns
    b = ColumnBatch(
        rng.integers(0, 50, 5000).astype(np.int64),
        rng.integers(0, 9, 5000).astype(np.int64),
    )
    data = cs.serialize(b) + cs.serialize(b)
    outs = list(cs.deserialize_columns(data))
    assert len(outs) == 2
    np.testing.assert_array_equal(outs[0].keys, b.keys)
    np.testing.assert_array_equal(outs[1].vals, b.vals)
    # pickle-backed compression must NOT advertise columns
    assert not CompressedSerializer().supports_columns


def test_columnar_serializer_rejects_bad_magic():
    s = ColumnarSerializer()
    with pytest.raises(ValueError, match="magic"):
        list(s.deserialize_columns(b"\x00garbage"))


def test_column_batch_rejects_object_dtype():
    with pytest.raises(TypeError, match="object-dtype"):
        ColumnBatch(np.array([1, "a"], object), np.array([1, 2], object))


# -- kernels -----------------------------------------------------------------

def test_take_rows_matches_numpy():
    rng = np.random.default_rng(2)
    for dtype in (np.int64, "S64", np.float32, "S24"):
        col = (
            np.frombuffer(rng.bytes(1000 * np.dtype(dtype).itemsize),
                          dtype=dtype)
        )
        idx = rng.permutation(1000)
        np.testing.assert_array_equal(take_rows(col, idx), col[idx])
    # into an unaligned destination view (the direct-commit case)
    col = np.arange(100, dtype=np.int64)
    idx = rng.permutation(100)
    buf = np.zeros(3 + 800, np.uint8)
    out = buf[3:803].view(np.int64)
    take_rows(col, idx, out=out)
    np.testing.assert_array_equal(out, col[idx])


def test_stable_key_order_radix_path_matches():
    rng = np.random.default_rng(3)
    small = rng.integers(1000, 1800, 10000).astype(np.int64)  # narrow range
    wide = rng.integers(-(2**60), 2**60, 10000).astype(np.int64)
    for keys in (small, wide):
        np.testing.assert_array_equal(
            keys[stable_key_order(keys)], np.sort(keys, kind="stable")
        )


def test_combine_and_group_columns_oracle():
    rng = np.random.default_rng(4)
    keys = rng.integers(0, 37, 5000).astype(np.int64)
    vals = rng.integers(0, 100, 5000).astype(np.int64)
    b = ColumnBatch(keys, vals)
    out = combine_columns(b, "sum")
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect[k] = expect.get(k, 0) + v
    assert dict(zip(out.keys.tolist(), out.vals.tolist())) == expect
    assert out.key_sorted
    uk, groups = group_columns(b)
    got = {k: sorted(g.tolist()) for k, g in zip(uk.tolist(), groups)}
    want = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        want.setdefault(k, []).append(v)
    assert got == {k: sorted(v) for k, v in want.items()}


# -- end-to-end through the shuffle stack ------------------------------------

def test_columnar_group_by_key_e2e(devices):
    rng = np.random.default_rng(5)
    N, NK = 40_000, 97
    keys = rng.integers(0, NK, N).astype(np.int64)
    vals = np.frombuffer(rng.bytes(N * 16), dtype="S16")
    with TpuShuffleContext(num_executors=3, conf=_columnar_conf(),
                           base_port=47100, stage_to_device=False) as ctx:
        out = (
            ctx.parallelize_columns(keys, vals, num_slices=6)
            .group_by_key(num_partitions=5)
            .collect()
        )
    assert len(out) == NK
    assert sum(len(g) for _, g in out) == N
    # exact-byte oracle: S payloads ride as void rows, so trailing NULs
    # survive (the S dtype's tolist would strip them)
    exact = vals.view("V16")
    for k0, grp in out[:5]:
        assert sorted(grp.tolist()) == sorted(exact[keys == k0].tolist())


def test_columnar_reduce_by_key_e2e(devices):
    rng = np.random.default_rng(6)
    keys = rng.integers(0, 100, 30000).astype(np.int64)
    vals = rng.integers(0, 1000, 30000).astype(np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=47200, stage_to_device=False) as ctx:
        out = dict(
            ctx.parallelize_columns(keys, vals, num_slices=4)
            .reduce_by_key("sum").collect()
        )
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect[k] = expect.get(k, 0) + v
    assert out == expect


def test_columnar_sort_by_key_e2e(devices):
    rng = np.random.default_rng(7)
    keys = rng.integers(-(2**40), 2**40, 20000).astype(np.int64)
    vals = np.arange(20000, dtype=np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=47300, stage_to_device=False) as ctx:
        flat = (
            ctx.parallelize_columns(keys, vals, num_slices=4)
            .sort_by_key(num_partitions=4).collect()
        )
    assert [k for k, _ in flat] == sorted(keys.tolist())
    assert sorted(v for _, v in flat) == vals.tolist()


def test_columnar_spill_roundtrip(devices, tmp_path):
    """Columnar writes above the spill threshold materialize, spill, and
    re-merge through the concatenation-safe framing."""
    rng = np.random.default_rng(8)
    keys = rng.integers(0, 23, 5000).astype(np.int64)
    vals = rng.integers(0, 9, 5000).astype(np.int64)
    conf = _columnar_conf({
        "spark.shuffle.tpu.shuffleSpillRecordThreshold": "400",
        "spark.shuffle.tpu.spillDir": str(tmp_path),
    })
    with TpuShuffleContext(num_executors=2, conf=conf, base_port=47400,
                           stage_to_device=False) as ctx:
        ds = ctx.parallelize_columns(keys, vals, num_slices=4)
        # several write batches per map task force repeated spills
        out = dict(ds.reduce_by_key("sum").collect())
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect[k] = expect.get(k, 0) + v
    assert out == expect
    assert not list(tmp_path.glob("sparkrdma_tpu_spill_*"))


def test_columnar_with_compression_e2e(devices):
    rng = np.random.default_rng(9)
    keys = rng.integers(0, 50, 20000).astype(np.int64)
    vals = rng.integers(0, 5, 20000).astype(np.int64)
    conf = _columnar_conf({"spark.shuffle.tpu.compress": "true"})
    with TpuShuffleContext(num_executors=2, conf=conf, base_port=47500,
                           stage_to_device=False) as ctx:
        out = dict(
            ctx.parallelize_columns(keys, vals, num_slices=4)
            .reduce_by_key("sum").collect()
        )
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect[k] = expect.get(k, 0) + v
    assert out == expect


def test_columnar_device_staged_e2e(devices):
    """Columnar plane with HBM staging on (the default device path)."""
    rng = np.random.default_rng(10)
    keys = rng.integers(0, 29, 10000).astype(np.int64)
    vals = rng.integers(0, 7, 10000).astype(np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=47600, stage_to_device=True) as ctx:
        out = dict(
            ctx.parallelize_columns(keys, vals, num_slices=4)
            .reduce_by_key("sum").collect()
        )
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect[k] = expect.get(k, 0) + v
    assert out == expect


def test_writer_rejects_mixed_planes(devices):
    with TpuShuffleContext(num_executors=1, conf=_columnar_conf(),
                           base_port=47700, stage_to_device=False) as ctx:
        handle = ctx.driver.register_shuffle(0, 1, HashPartitioner(2))
        w = ctx.executors[0].get_writer(handle, 0)
        w.write(ColumnBatch(np.array([1, 2]), np.array([3, 4])))
        with pytest.raises(TypeError, match="single record plane"):
            w.write([(1, 2)])
        w.stop(False)
        w2 = ctx.executors[0].get_writer(handle, 1)
        w2.write([(1, 2)])
        with pytest.raises(TypeError, match="single record plane"):
            w2.write(ColumnBatch(np.array([1]), np.array([2])))
        w2.stop(False)


def test_columnar_aggregator_tuple_plane_interop(devices):
    """A ColumnarAggregator's scalar callables keep the tuple plane
    working — mixed tuple-mode map tasks in a columnar shuffle."""
    agg = ColumnarAggregator.reduce("sum")
    assert agg.create_combiner(5) == 5
    assert agg.merge_value(2, 3) == 5
    assert agg.merge_combiners(2, 3) == 5
    g = ColumnarAggregator.group()
    assert g.merge_value(g.create_combiner(1), 2) == [1, 2]
    with pytest.raises(ValueError, match="unknown columnar reduction"):
        ColumnarAggregator.reduce("mean")


def test_s_dtype_payload_trailing_nulls_survive(devices):
    """Reviewer finding: 'S' payload bytes ending in \\x00 must round
    trip exactly (they ride as void rows)."""
    keys = np.array([1, 2, 1], np.int64)
    vals = np.array([b"ab\x00\x00", b"cdef", b"\x00\x00\x00\x00"], "S4")
    with TpuShuffleContext(num_executors=1, conf=_columnar_conf(),
                           base_port=47800, stage_to_device=False) as ctx:
        out = dict(
            ctx.parallelize_columns(keys, vals, 2).group_by_key(2).collect()
        )
    assert sorted(out[1].tolist()) == [b"\x00\x00\x00\x00", b"ab\x00\x00"]
    assert out[2].tolist() == [b"cdef"]


def test_columnar_dataset_under_pickle_serializer_falls_back(devices):
    """Reviewer finding: a columnar dataset with the default (pickle)
    serializer must degrade to the tuple plane, not crash."""
    keys = np.arange(100, dtype=np.int64) % 7
    vals = np.arange(100, dtype=np.int64)
    with TpuShuffleContext(num_executors=2, base_port=47900,
                           stage_to_device=False) as ctx:
        ds = ctx.parallelize_columns(keys, vals, 4)
        out = {k: sorted(np.asarray(g).tolist())
               for k, g in ds.group_by_key(3).collect()}
        srt = ds.sort_by_key(3).collect()
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect.setdefault(k, []).append(v)
    assert out == {k: sorted(v) for k, v in expect.items()}
    assert [k for k, _ in srt] == sorted(keys.tolist())


def test_tuple_group_by_key_under_columnar_serializer(devices):
    """Reviewer finding: tuple-plane group_by_key (ragged list
    combiners) must survive a manager-global columnar serializer via
    the pickle-fallback frame."""
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=48000, stage_to_device=False) as ctx:
        ds = ctx.parallelize([(i % 5, i) for i in range(200)], 4)
        out = {k: sorted(v) for k, v in ds.group_by_key(3).collect()}
    expect = {}
    for i in range(200):
        expect.setdefault(i % 5, []).append(i)
    assert out == {k: sorted(v) for k, v in expect.items()}


def test_range_partitioner_one_sort_fast_path_routes_like_scalar():
    """The RangePartitioner columnar fast path (one key sort + binary-
    searched counts) must route every record exactly like the scalar
    bisect path, including keys EQUAL to a splitter."""
    import numpy as np

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.shuffle.partitioner import RangePartitioner

    rng = np.random.default_rng(11)
    keys = np.concatenate([
        rng.integers(-(1 << 40), 1 << 40, 30_000).astype(np.int64),
        np.full(100, 12345, np.int64),  # exact splitter hits
    ])
    vals = np.arange(len(keys), dtype=np.int64)
    part = RangePartitioner(5, [-(1 << 39), 0, 12345, 1 << 39])
    conf = TpuShuffleConf({"spark.shuffle.tpu.serializer": "columnar"})
    from sparkrdma_tpu.shuffle.manager import (
        ShuffleHandle,
        TpuShuffleManager,
    )
    from sparkrdma_tpu.transport import LoopbackNetwork
    from sparkrdma_tpu.utils.columns import ColumnBatch

    net = LoopbackNetwork()
    mgr = TpuShuffleManager(conf, is_driver=True, network=net,
                            stage_to_device=False)
    try:
        handle = ShuffleHandle(99, 1, part)
        mgr.register_shuffle(99, 1, part)
        w = mgr.get_writer(handle, 0)
        w.write_columns(ColumnBatch(keys, vals))
        batch, order, counts = w._col_pending[-1]
        expect = np.bincount(
            np.fromiter((part.partition(int(k)) for k in keys), np.int64),
            minlength=5,
        )
        assert np.array_equal(counts, expect)
        sk = keys[order]
        bounds = np.cumsum(counts)
        for p in range(5):
            lo = 0 if p == 0 else bounds[p - 1]
            seg = sk[lo:bounds[p]]
            assert (np.diff(seg) >= 0).all()  # key-sorted within pid
            for k in (seg[:1], seg[-1:]):
                if len(k):
                    assert part.partition(int(k[0])) == p
        w.stop(True)
    finally:
        mgr.stop()


def test_hash_fast_path_skips_uint64_overflow_keys():
    """uint64 keys past int64.max have a small range but cannot ride the
    int64-rebased fast path (native ctypes arg / astype both break) —
    they must fall through to the generic partition_array path and still
    route every record like the scalar partitioner."""
    from sparkrdma_tpu.shuffle.manager import ShuffleHandle, TpuShuffleManager
    from sparkrdma_tpu.transport import LoopbackNetwork

    keys = np.uint64(1 << 63) + (
        np.arange(5000, dtype=np.uint64) % np.uint64(7)
    )
    vals = np.arange(len(keys), dtype=np.int64)
    P = 4
    part = HashPartitioner(P)
    net = LoopbackNetwork()
    mgr = TpuShuffleManager(_columnar_conf(), is_driver=True, network=net,
                            stage_to_device=False)
    try:
        handle = ShuffleHandle(98, 1, part)
        mgr.register_shuffle(98, 1, part)
        w = mgr.get_writer(handle, 0)
        w.write_columns(ColumnBatch(keys, vals))
        batch, order, counts = w._col_pending[-1]
        expect = np.bincount(part.partition_array(keys), minlength=P)
        assert np.array_equal(counts, expect)
        if order is not None:
            pids = part.partition_array(keys)[order]
            assert (np.diff(pids) >= 0).all()  # pid-major order
        w.stop(True)
    finally:
        mgr.stop()


# -- vectorized narrow plane (map_values / filter / sample) ------------------

def test_columnar_map_values_filter_stay_columnar(devices):
    rng = np.random.default_rng(21)
    N = 50_000
    keys = rng.integers(0, 64, N).astype(np.int64)
    vals = rng.integers(-100, 100, N).astype(np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=47300, stage_to_device=False) as ctx:
        ds = (
            ctx.parallelize_columns(keys, vals, num_slices=4)
            .map_values(lambda v: v * 2)
            .filter(lambda kv: kv[1] > 10)
        )
        assert ds._is_columnar  # the chain did NOT de-columnarize
        got = dict(ds.reduce_by_key("sum", num_partitions=4).collect())
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        if v * 2 > 10:
            expect[k] = expect.get(k, 0) + v * 2
    assert got == expect


def test_columnar_narrow_fallback_matches_vectorized(devices):
    """A non-vectorizable callable (str payloads) produces the same
    records through the per-record fallback."""
    rng = np.random.default_rng(22)
    N = 5_000
    keys = rng.integers(0, 16, N).astype(np.int64)
    vals = rng.integers(0, 50, N).astype(np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=47350, stage_to_device=False) as ctx:
        ds_vec = (
            ctx.parallelize_columns(keys, vals, num_slices=3)
            .map_values(lambda v: v + 1)
        )
        # same op, defeats vectorization (returns a list per element)
        ds_slow = (
            ctx.parallelize_columns(keys, vals, num_slices=3)
            .map_values(lambda v: (v + 1) if np.ndim(v) == 0 else _no(v))
        )
        def _no(v):
            raise TypeError("not vectorizable")
        a = sorted(ds_vec.collect())
        b = sorted(ds_slow.collect())
    assert [(k, int(v)) for k, v in a] == [(k, int(v)) for k, v in b]


def test_columnar_sample_deterministic(devices):
    rng = np.random.default_rng(23)
    N = 40_000
    keys = rng.integers(0, 8, N).astype(np.int64)
    vals = np.arange(N, dtype=np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=47400, stage_to_device=False) as ctx:
        s = ctx.parallelize_columns(keys, vals, num_slices=4).sample(
            0.25, seed=3
        )
        assert s._is_columnar
        c1, r1 = s.count(), sorted(v for _k, v in s.collect())
        c2, r2 = s.count(), sorted(v for _k, v in s.collect())
    assert c1 == c2 and r1 == r2
    assert 0.2 < c1 / N < 0.3


def test_columnar_map_stays_columnar(devices):
    """Key+value producing map (VERDICT r3 item 5): the chain stays
    columnar end to end and matches the per-record semantics."""
    rng = np.random.default_rng(31)
    N = 40_000
    keys = rng.integers(0, 100, N).astype(np.int64)
    vals = rng.integers(-50, 50, N).astype(np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=47700, stage_to_device=False) as ctx:
        ds = (
            ctx.parallelize_columns(keys, vals, num_slices=4)
            .map(lambda kv: (kv[0] % 10, kv[1] * 3))
            .filter(lambda kv: kv[1] != 0)
        )
        assert ds._is_columnar
        got = dict(ds.reduce_by_key("sum", num_partitions=4).collect())
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        if v * 3 != 0:
            expect[k % 10] = expect.get(k % 10, 0) + v * 3
    assert got == expect


def test_columnar_map_scalar_broadcast(devices):
    """A map producing a constant column broadcasts the scalar
    (wordcount's (key, 1) shape stays columnar)."""
    keys = np.arange(9000, dtype=np.int64) % 23
    vals = np.arange(9000, dtype=np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=47800, stage_to_device=False) as ctx:
        ds = ctx.parallelize_columns(keys, vals, num_slices=4).map(
            lambda kv: (kv[0], 1)
        )
        assert ds._is_columnar
        got = dict(ds.reduce_by_key("sum", num_partitions=4).collect())
    expect = {}
    for k in keys.tolist():
        expect[k] = expect.get(k, 0) + 1
    assert got == expect


def test_columnar_flat_map_stays_columnar(devices):
    """A ColumnBatch-producing flat_map stays columnar (the ONE return
    shape whose per-record fallback — iterating the batch's records —
    flattens to the same stream)."""
    keys = np.arange(5000, dtype=np.int64) % 13
    vals = np.arange(5000, dtype=np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=47900, stage_to_device=False) as ctx:
        ds = ctx.parallelize_columns(keys, vals, num_slices=4).flat_map(
            lambda kv: ColumnBatch(np.repeat(kv[0], 2),
                                   np.repeat(kv[1], 2))
        )
        assert ds._is_columnar
        got = dict(ds.reduce_by_key("sum", num_partitions=4).collect())
    expect = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        expect[k] = expect.get(k, 0) + 2 * v
    assert got == expect


def test_columnar_flat_map_tuple_return_flattens_per_record(devices):
    """A flat_map returning a plain tuple is NOT a column pair: the
    fallback flattens it into its elements on every plane (the
    semantics divergence the ColumnBatch-only contract prevents)."""
    keys = np.arange(200, dtype=np.int64) % 3
    vals = np.arange(200, dtype=np.int64) + 1000
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=47950, stage_to_device=False) as ctx:
        got = sorted(
            ctx.parallelize_columns(keys, vals, num_slices=4)
            .flat_map(lambda kv: (int(kv[0]), int(kv[1])))
            .collect()
        )
    expect = sorted(
        y for k, v in zip(keys.tolist(), vals.tolist()) for y in (k, v)
    )
    assert got == expect


def test_columnar_map_rejects_reduction_broadcast(devices):
    """A map whose value side is a column REDUCTION (numpy scalar)
    must NOT broadcast the partition aggregate over every row: the
    vectorized path rejects numpy scalars, and the per-record fallback
    fails LOUDLY (records carry plain Python scalars) instead of
    silently corrupting the column."""
    keys = np.arange(1000, dtype=np.int64) % 11
    vals = np.arange(1000, dtype=np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=48250, stage_to_device=False) as ctx:
        with pytest.raises(AttributeError, match="max"):
            (
                ctx.parallelize_columns(keys, vals, num_slices=4)
                .map(lambda kv: (kv[0], kv[1].max()))
                .collect()
            )


def test_columnar_map_nonpair_falls_back(devices):
    """keys()/values() style maps (non-pair records) de-columnarize but
    stay correct."""
    keys = np.arange(3000, dtype=np.int64) % 7
    vals = np.arange(3000, dtype=np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=48050, stage_to_device=False) as ctx:
        got = sorted(
            ctx.parallelize_columns(keys, vals, num_slices=4)
            .keys()
            .collect()
        )
    assert got == sorted(keys.tolist())


def test_columnar_map_python_only_falls_back(devices):
    """A map that cannot vectorize (string formatting) still runs
    correctly per record."""
    keys = np.arange(2000, dtype=np.int64) % 5
    vals = np.arange(2000, dtype=np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=48150, stage_to_device=False) as ctx:
        got = sorted(
            ctx.parallelize_columns(keys, vals, num_slices=4)
            .map(lambda kv: (f"k{int(kv[0])}", int(kv[1])))
            .collect()
        )
    expect = sorted(
        (f"k{k}", v) for k, v in zip(keys.tolist(), vals.tolist())
    )
    assert got == expect


def test_native_kway_merge_matches_stable_argsort():
    """The native loser-tree merge order over concatenated key-sorted
    runs is bit-exact with numpy's stable argsort (ties across runs
    resolve to the lower concat position)."""
    from sparkrdma_tpu.memory.staging import native_kway_merge

    rng = np.random.default_rng(9)
    for _trial in range(30):
        K = int(rng.integers(1, 10))
        runs = [
            np.sort(rng.integers(0, int(rng.integers(2, 40)),
                                 int(rng.integers(0, 80))).astype(np.int64))
            for _ in range(K)
        ]
        concat = (np.concatenate(runs) if runs
                  else np.zeros(0, np.int64))
        offs = np.zeros(K + 1, np.int64)
        np.cumsum([len(r) for r in runs], out=offs[1:])
        order = native_kway_merge(concat, offs)
        if order is None:
            pytest.skip("native lib unavailable")
        assert np.array_equal(order, np.argsort(concat, kind="stable"))


def test_sorted_read_uses_merge_path(devices, monkeypatch):
    """sort_by_key over key-sorted blocks returns the exact stable
    order AND actually exercises the native merge fast path (the test
    fails if the eligibility guard regresses to the fallback)."""
    from sparkrdma_tpu.memory import staging

    if staging._NATIVE is None or not hasattr(
        staging._NATIVE, "kway_merge_i64"
    ):
        pytest.skip("native lib unavailable")
    calls = []
    real = staging.native_kway_merge

    def spy(keys, offs):
        out = real(keys, offs)
        calls.append(out is not None)
        return out

    monkeypatch.setattr(staging, "native_kway_merge", spy)
    rng = np.random.default_rng(10)
    keys = rng.integers(0, 1 << 40, 20000).astype(np.int64)
    vals = np.arange(20000, dtype=np.int64)
    with TpuShuffleContext(num_executors=2, conf=_columnar_conf(),
                           base_port=48400, stage_to_device=False) as ctx:
        out = (
            ctx.parallelize_columns(keys, vals, num_slices=4)
            .sort_by_key(num_partitions=4)
            .collect()
        )
    assert [k for k, _v in out] == sorted(keys.tolist())
    # values ride with their keys
    kv = dict(zip(vals.tolist(), keys.tolist()))
    for k, v in out:
        assert kv[v] == k
    assert calls and all(calls), (
        f"native merge path never ran / fell back: {calls}"
    )


def test_wide_range_low_card_composite_order_matches_generic(monkeypatch):
    """The rank-compress composite path (wide-RANGE, low-CARDINALITY
    hash keys → ONE uint16 radix argsort) must produce the exact
    pid-major stable key order of the generic two-sort chain; the
    kernel's cardinality abort (>65536 distinct) must route to the
    generic path, and the composite must actually RUN for the shapes
    that advertise it."""
    import numpy as np

    import sparkrdma_tpu.memory.staging as staging
    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.shuffle.manager import (
        ShuffleHandle,
        TpuShuffleManager,
    )
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
    from sparkrdma_tpu.transport import LoopbackNetwork
    from sparkrdma_tpu.utils.columns import ColumnBatch, stable_key_order

    calls = {"ok": 0, "abort": 0}
    real = staging.native_rank_compress

    def counting(keys):
        res = real(keys)
        calls["ok" if res is not None else "abort"] += 1
        return res

    monkeypatch.setattr(staging, "native_rank_compress", counting)

    rng = np.random.default_rng(13)
    conf = TpuShuffleConf({"spark.shuffle.tpu.serializer": "columnar"})
    net = LoopbackNetwork()
    mgr = TpuShuffleManager(conf, is_driver=True, network=net,
                            stage_to_device=False)
    try:
        # (cardinality, P, rows, all_unique): the last trial's keys are
        # ALL distinct (100k > 65536) so the kernel's abort path — not
        # just the P*nr guard — routes to the generic chain
        for trial, (card, P, n, uniq) in enumerate(
            [(512, 8, 50_000, False), (65536 // 8, 8, 30_000, False),
             (60_000, 2, 80_000, False), (0, 8, 100_000, True)]
        ):
            if uniq:
                keys = rng.permutation(
                    np.arange(-(n // 2), n - n // 2, dtype=np.int64)
                    * np.int64(1 << 40)
                )
            else:
                pool = rng.integers(
                    -(1 << 62), 1 << 62, card, dtype=np.int64
                )
                keys = pool[rng.integers(0, card, n)]
            vals = np.arange(n, dtype=np.int64)
            part = HashPartitioner(P)
            sid = 120 + trial
            handle = ShuffleHandle(sid, 1, part)
            mgr.register_shuffle(sid, 1, part)
            w = mgr.get_writer(handle, 0)
            w.write_columns(ColumnBatch(keys, vals))
            _b, order, counts = w._col_pending[-1]
            pids = part.partition_array(keys)
            korder = stable_key_order(keys)
            porder = np.argsort(
                pids[korder].astype(np.uint16), kind="stable"
            )
            ref_order = korder[porder]
            ref_counts = np.bincount(pids, minlength=P).astype(np.int64)
            assert np.array_equal(counts, ref_counts), trial
            assert np.array_equal(order, ref_order), trial
    finally:
        mgr.stop()
    # the composite/rank path ran for the low-card trials and the
    # all-unique trial hit the kernel's abort
    assert calls["ok"] >= 3, calls
    assert calls["abort"] >= 1, calls


def test_rank_compress_exactly_65536_distinct_single_partition():
    """Boundary case: rank_compress_i64 returns nr==65536 for exactly
    2**16 distinct keys (its abort gate is strictly-greater).  No
    reachable writer path feeds that into ``np.uint16`` today — P==1
    short-circuits before the rank-compress branch, and P>=2 bounds
    nr<=32768 via the P*nr guard — but the composite gate carries a
    defensive ``nr < 2**16`` so a future P==1 path can't overflow
    under numpy>=2.  This pins the kernel's boundary behavior and the
    writer's P==1 semantics."""
    import numpy as np

    import sparkrdma_tpu.memory.staging as staging
    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.shuffle.manager import (
        ShuffleHandle,
        TpuShuffleManager,
    )
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
    from sparkrdma_tpu.transport import LoopbackNetwork
    from sparkrdma_tpu.utils.columns import ColumnBatch, stable_key_order

    if staging.native_rank_compress(
        np.arange(4, dtype=np.int64)
    ) is None:
        pytest.skip("native lib unavailable")

    rng = np.random.default_rng(65536)
    pool = rng.permutation(
        rng.integers(-(1 << 62), 1 << 62, 1 << 16, dtype=np.int64)
    )
    assert len(np.unique(pool)) == 1 << 16
    # every pool key appears at least once => exactly 65536 distinct
    keys = np.concatenate(
        [pool, pool[rng.integers(0, 1 << 16, 20_000)]]
    )
    keys = keys[rng.permutation(len(keys))]
    vals = np.arange(len(keys), dtype=np.int64)
    conf = TpuShuffleConf({"spark.shuffle.tpu.serializer": "columnar"})
    net = LoopbackNetwork()
    mgr = TpuShuffleManager(conf, is_driver=True, network=net,
                            stage_to_device=False)
    try:
        part = HashPartitioner(1)
        handle = ShuffleHandle(140, 1, part)
        mgr.register_shuffle(140, 1, part)
        w = mgr.get_writer(handle, 0)
        w.write_columns(ColumnBatch(keys, vals))  # must not raise
        _b, order, counts = w._col_pending[-1]
        assert counts.sum() == len(keys)
        # P==1 short-circuits to (order=None, original order); any
        # non-None order must equal the stable key order
        assert order is None or np.array_equal(
            order, stable_key_order(keys)
        )
        # the composite gate itself must reject nr==65536 even at P==1
        # (np.uint16(65536) overflows under numpy>=2)
        res = staging.native_rank_compress(keys)
        assert res is not None
        _ranks, nr = res
        assert nr == 1 << 16
        assert not (nr < (1 << 16) and 1 * nr <= (1 << 16))
    finally:
        mgr.stop()
