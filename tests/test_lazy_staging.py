"""Lazy staging — the ODP analog made real (VERDICT round-1 item 5).

Reference: ``useOdp`` registers memory on demand with an optional
prefetch advise (RdmaShuffleConf.scala:68-83,
RdmaBufferManager.java:103-110, RdmaMappedFile.java:158-168).  Here
``lazyStaging=true`` keeps commits in host memory; the first collective
(device-plane) touch faults the segment into the HBM arena under its
original mkey, and ``prefetch_shuffle`` sweeps a whole shuffle ahead of
the reads.
"""

import numpy as np

from sparkrdma_tpu.api import TpuShuffleContext
from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.memory.arena import ArenaSpanSegment
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner


def _fixture_ctx(num_executors, conf, base_port):
    """Coordinator plane = test fixture: pass the network explicitly
    (production readPlane=collective now routes to the windowed plane)."""
    from collective_read_fixture import CollectiveNetwork
    from sparkrdma_tpu.parallel.mesh import make_mesh

    return TpuShuffleContext(
        num_executors=num_executors, conf=conf, base_port=base_port,
        network=CollectiveNetwork(
            mesh=make_mesh(num_executors),
            tile_bytes=conf.exchange_tile_bytes,
            flush_ms=conf.exchange_flush_ms,
        ),
    )


def _conf(lazy: bool):
    conf = TpuShuffleConf()
    conf.set("readPlane", "collective")
    conf.set("deviceArenaBytes", 8 << 20)
    conf.set("serializer", "columnar")
    if lazy:
        conf.set("lazyStaging", "true")
    return conf


def _segments(ex):
    with ex.arena._lock:
        return [s for s in ex.arena._segments.values()]


def _run_one_map(ctx, shuffle_id, ex_index=0):
    """Commit one map output on one executor, no reads."""
    part = HashPartitioner(4)
    handle = ctx.driver.register_shuffle(shuffle_id, 1, part)
    ex = ctx.executors[ex_index]
    w = ex.get_writer(handle, 0)
    w.write([(i % 7, i) for i in range(400)])
    w.stop(True)
    return handle, ex


def test_eager_commit_is_arena_resident(devices):
    with _fixture_ctx(2, _conf(lazy=False), 51000) as ctx:
        _, ex = _run_one_map(ctx, 0)
        segs = _segments(ex)
        assert segs and all(
            isinstance(s, ArenaSpanSegment) for s in segs
        ), "eager staging must commit straight into the device arena"


def test_lazy_commit_stays_on_host_then_faults_in(devices):
    with _fixture_ctx(2, _conf(lazy=True), 52000) as ctx:
        part = HashPartitioner(4)
        handle = ctx.driver.register_shuffle(7, 2, part)
        from collections import defaultdict

        maps_by_host = defaultdict(list)
        for map_id in range(2):
            ex = ctx.executors[map_id]
            w = ex.get_writer(handle, map_id)
            w.write([(i % 5, i) for i in range(300)])
            w.stop(True)
            maps_by_host[ex.local_smid].append(map_id)

        # BEFORE any read: committed segments are host numpy, NOT arena
        for ex in ctx.executors:
            segs = _segments(ex)
            assert segs
            assert all(
                not isinstance(s, ArenaSpanSegment)
                and isinstance(getattr(s, "array", None), np.ndarray)
                for s in segs
            ), "lazy commit must stay in host memory until first touch"

        # cross-executor read: the collective plane faults segments in
        got = {}
        for pid in range(4):
            ex = ctx.executors[pid % 2]
            reader = ex.get_reader(handle, pid, pid + 1, dict(maps_by_host))
            for k, v in reader.read():
                got[k] = got.get(k, 0) + (
                    len(v) if hasattr(v, "__len__") else 1
                )
        assert sum(got.values()) == 600

        stats = ctx.network.coordinator.stats()
        assert stats["rounds_executed"] > 0, "reads must ride the collective"
        assert stats["fallback_blocks"] == 0, (
            "lazy segments must fault into the arena, not fall back"
        )
        # AFTER the reads: remotely-touched segments are arena-resident
        staged = [
            s for ex in ctx.executors for s in _segments(ex)
            if isinstance(s, ArenaSpanSegment)
        ]
        assert staged, "first device-plane touch must stage segments"


def test_prefetch_sweep_stages_everything(devices):
    with _fixture_ctx(2, _conf(lazy=True), 53000) as ctx:
        _, ex = _run_one_map(ctx, 3)
        assert not any(
            isinstance(s, ArenaSpanSegment) for s in _segments(ex)
        )
        n = ex.resolver.prefetch_shuffle(3)
        assert n == 1
        assert all(
            isinstance(s, ArenaSpanSegment) for s in _segments(ex)
        ), "prefetch sweep must stage every segment of the shuffle"
        # segment content survives the swap (same mkey, same bytes)
        data = ex.resolver.get_local_block(3, 0, 0)
        assert isinstance(data, bytes)


def test_lazy_without_device_arena_is_host_only(devices):
    """lazyStaging on the plain host plane: commits stay host, reads
    work, ensure_staged is a no-op."""
    conf = TpuShuffleConf()
    conf.set("lazyStaging", "true")
    with TpuShuffleContext(
        num_executors=2, conf=conf, base_port=54000
    ) as ctx:
        handle, ex = _run_one_map(ctx, 0)
        assert ex.resolver.ensure_staged(
            _segments(ex)[0].mkey
        ) is None
        assert ex.resolver.prefetch_shuffle(0) == 0
        data = ex.resolver.get_local_block(0, 0, 0)
        assert isinstance(data, (bytes, np.ndarray, memoryview))


def test_lazy_staging_on_windowed_plane(devices):
    """The ODP analog on the PRODUCTION plane (readPlane=windowed):
    lazy commits stay host-resident, ``prefetch_shuffle`` stages them
    into the device arena under their original mkeys, and the windowed
    read serves the arena-resident segments exactly.  (This coverage
    used to live only behind the collective fixture,
    tests/collective_read_fixture.py — VERDICT r4 item 5.)"""
    conf = _conf(lazy=True)
    conf.set("readPlane", "windowed")
    with TpuShuffleContext(
        num_executors=2, conf=conf, base_port=57000
    ) as ctx:
        part = HashPartitioner(4)
        handle = ctx.driver.register_shuffle(9, 2, part)
        from collections import defaultdict

        maps_by_host = defaultdict(list)
        for map_id in range(2):
            ex = ctx.executors[map_id]
            w = ex.get_writer(handle, map_id)
            w.write([(i % 5, i) for i in range(300)])
            w.stop(True)
            maps_by_host[ex.local_smid].append(map_id)
        # lazy: committed segments are host numpy, NOT arena spans
        for ex in ctx.executors:
            segs = _segments(ex)
            assert segs
            assert not any(
                isinstance(s, ArenaSpanSegment) for s in segs
            ), "lazy commit must stay in host memory until prefetched"
        # the ODP prefetch sweep stages every segment, keeping mkeys
        for ex in ctx.executors:
            n = ex.resolver.prefetch_shuffle(9)
            assert n >= 1
            assert all(
                isinstance(s, ArenaSpanSegment) for s in _segments(ex)
            ), "prefetch sweep must stage every segment of the shuffle"
        # windowed-plane read over the arena-resident segments is
        # exact.  Every host must join the window collectives
        # (symmetric participation) before any sequential read blocks.
        for ex in ctx.executors:
            ex.windowed_plane.join(9)
        got = {}
        for pid in range(4):
            ex = ctx.executors[pid % 2]
            reader = ex.get_reader(handle, pid, pid + 1,
                                   dict(maps_by_host))
            for k, v in reader.read():
                got[k] = got.get(k, 0) + (
                    len(v) if hasattr(v, "__len__") else 1
                )
        assert sum(got.values()) == 600


def test_lazy_read_result_matches_eager(devices):
    data = [(i % 11, i) for i in range(2000)]

    def run(lazy, port):
        with TpuShuffleContext(
            num_executors=2, conf=_conf(lazy=lazy), base_port=port
        ) as ctx:
            return sorted(
                ctx.parallelize(data, num_slices=4)
                .reduce_by_key(lambda a, b: a + b, num_partitions=4)
                .collect()
            )

    assert run(False, 55000) == run(True, 56000)
