"""Wire-format round-trips for ids and locations (SURVEY.md §2, RdmaUtils)."""


from sparkrdma_tpu.utils.types import (
    LOCATION_ENTRY_SIZE,
    BlockLocation,
    BlockManagerId,
    ShuffleManagerId,
    get_cached_shuffle_manager_id,
)


def test_block_location_roundtrip():
    loc = BlockLocation(address=0x1234_5678_9ABC, length=65536, mkey=42)
    raw = loc.pack()
    assert len(raw) == LOCATION_ENTRY_SIZE == 16
    assert BlockLocation.read(memoryview(raw)) == loc


def test_block_location_empty():
    assert BlockLocation.EMPTY.is_empty
    assert BlockLocation.EMPTY.length == 0
    assert not BlockLocation(0, 10, 1).is_empty


def test_block_location_negative_address_roundtrip():
    # i64 address must survive the sign bit (raw 64-bit offsets).
    loc = BlockLocation(address=-1, length=1, mkey=7)
    assert BlockLocation.read(memoryview(loc.pack())) == loc


def test_block_manager_id_roundtrip():
    bmid = BlockManagerId("exec-7", "host-α.example", 7337)
    buf = bytearray()
    bmid.write(buf)
    assert len(buf) == bmid.serialized_length()
    out, consumed = BlockManagerId.read(memoryview(bytes(buf)))
    assert out == bmid
    assert consumed == len(buf)


def test_shuffle_manager_id_roundtrip_and_interning():
    bmid = BlockManagerId("1", "10.0.0.1", 4000)
    smid = ShuffleManagerId("10.0.0.1", 9999, bmid)
    buf = bytearray()
    smid.write(buf)
    assert len(buf) == smid.serialized_length()
    out1, _ = ShuffleManagerId.read(memoryview(bytes(buf)))
    out2, _ = ShuffleManagerId.read(memoryview(bytes(buf)))
    assert out1 == smid
    assert out1 is out2  # interning cache returns one object per peer


def test_interning_cache_identity():
    bmid = BlockManagerId("2", "h", 1)
    a = get_cached_shuffle_manager_id(ShuffleManagerId("h", 1, bmid))
    b = get_cached_shuffle_manager_id(ShuffleManagerId("h", 1, bmid))
    assert a is b
