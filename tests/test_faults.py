"""Fault-injection plane + in-task fetch retry (faults/, conf
``faultInject`` / ``fetchRetryCount``):

- spec parsing: named points, ``p=``/``nth=``/``ms=`` knobs, seeded,
  typos rejected at arm time;
- determinism: the schedule is a pure function of (spec, per-point
  call index) — two injectors armed alike agree call for call;
- RetryPolicy: exponential backoff with equal jitter under a deadline
  budget anchored at the FIRST failure;
- CircuitBreaker / StripeHealth: trip → open → half-open probe →
  close, and repeated lane failures demoting striped reads;
- reader integration over loopback: transient read failures absorbed
  in-task (bit-exact result), ``fetchRetryCount=0`` restoring the
  reference first-failure conversion, breaker fast-fail, stripe
  demotion completing unstriped;
- the seeded chaos soak: loopback / tcp-threaded / tcp-async ×
  decodeThreads {0,4} × skew on/off under a mixed fault spec — every
  run is bit-exact or a clean FetchFailedError, with zero ledger
  leaks, zero double releases and zero lock-rank violations.
"""

import errno
import gc
import threading
import time
from collections import defaultdict

import pytest

from sparkrdma_tpu.conf import TpuShuffleConf
from sparkrdma_tpu.faults.breaker import CircuitBreaker, StripeHealth
from sparkrdma_tpu.faults.injector import (
    FAULTS,
    FaultInjectedError,
    FaultInjector,
    FaultSpecError,
    KNOWN_POINTS,
    parse_fault_spec,
)
from sparkrdma_tpu.faults.retry import RetryPolicy, is_transient
from sparkrdma_tpu.metrics import GLOBAL_REGISTRY
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
from sparkrdma_tpu.shuffle.reader import (
    FetchFailedError,
    MetadataFetchFailedError,
)
from sparkrdma_tpu.transport import LoopbackNetwork, TcpNetwork
from sparkrdma_tpu.transport.channel import (
    FatalTransportError,
    TransportError,
    decode_remote_error,
    encode_remote_error,
)
from sparkrdma_tpu.utils.dbglock import get_lock_factory
from sparkrdma_tpu.utils.ledger import get_resource_ledger
from sparkrdma_tpu.utils.statemachine import shake_confs_from_env

BASE_PORT = 42400


@pytest.fixture()
def faults_env():
    """Save/restore every process-global the fault plane touches."""
    led = get_resource_ledger()
    prev_led = led.enabled
    prev_lock = get_lock_factory().enabled
    prev_reg = GLOBAL_REGISTRY.enabled
    FAULTS.reset()
    led.reset()
    GLOBAL_REGISTRY.reset()
    yield
    FAULTS.reset()
    led.enabled = prev_led
    led.reset()
    get_lock_factory().enabled = prev_lock
    GLOBAL_REGISTRY.enabled = prev_reg
    GLOBAL_REGISTRY.reset()


def _metric_total(name):
    """Sum of one counter across all label sets."""
    return sum(
        inst.value for _k, inst in GLOBAL_REGISTRY.instruments()
        if getattr(inst, "name", "") == name
    )


# -- spec parsing -------------------------------------------------------------


def test_parse_spec_points_knobs_and_seed():
    seed, clauses = parse_fault_spec(
        "connect:p=0.1;read_resp:p=0.05;serve_delay:ms=30;"
        "lane_kill:nth=7;seed=42"
    )
    assert seed == 42
    assert set(clauses) == {"connect", "read_resp", "serve_delay",
                            "lane_kill"}
    assert clauses["connect"].p == 0.1
    assert clauses["serve_delay"].ms == 30
    assert clauses["lane_kill"].nth == 7
    # empty/whitespace specs arm nothing
    assert parse_fault_spec("") == (0, {})
    assert parse_fault_spec(" ; ") == (0, {})


@pytest.mark.parametrize("bad", [
    "frobnicate:p=0.5",          # unknown point
    "connect",                   # no knobs
    "connect:p",                 # not key=value
    "connect:q=1",               # unknown key
    "connect:p=1.5",             # p out of range
    "connect:p=banana",          # unparsable
    "connect:nth=0",             # nth must be >= 1
    "serve_delay:ms=-3",         # negative delay
    "seed=xyz",                  # bad seed
])
def test_parse_spec_rejects(bad):
    with pytest.raises(FaultSpecError):
        parse_fault_spec(bad)


def test_every_known_point_parses():
    spec = ";".join(f"{p}:nth=3" for p in KNOWN_POINTS)
    _seed, clauses = parse_fault_spec(spec)
    assert set(clauses) == set(KNOWN_POINTS)


# -- determinism --------------------------------------------------------------


def test_probability_schedule_is_deterministic():
    spec = "recv:p=0.3;seed=17"
    a, b = FaultInjector(), FaultInjector()
    a.arm(spec)
    b.arm(spec)
    assert [a.fires("recv") for _ in range(300)] == \
           [b.fires("recv") for _ in range(300)]
    assert a.fired_counts() == b.fired_counts()
    assert 0 < a.fired_counts()["recv"] < 300


def test_nth_schedule_fires_on_exact_multiples():
    inj = FaultInjector()
    inj.arm("send:nth=4")
    hits = [inj.fires("send") for _ in range(12)]
    assert hits == [False, False, False, True] * 3


def test_points_draw_independent_streams():
    """Interleaving calls to another point must not perturb a point's
    own schedule (per-point rng + counter)."""
    spec = "recv:p=0.5;send:p=0.5;seed=9"
    solo, mixed = FaultInjector(), FaultInjector()
    solo.arm(spec)
    mixed.arm(spec)
    want = [solo.fires("recv") for _ in range(100)]
    got = []
    for _ in range(100):
        mixed.fires("send")
        got.append(mixed.fires("recv"))
    assert got == want


def test_ms_clause_sleeps_instead_of_raising():
    inj = FaultInjector()
    inj.arm("serve_delay:ms=20")
    t0 = time.monotonic()
    inj.check("serve_delay")    # must NOT raise
    assert time.monotonic() - t0 >= 0.015
    assert inj.fired_counts() == {"serve_delay": 1}


def test_check_raises_transient_fault():
    inj = FaultInjector()
    inj.arm("recv:nth=1")
    with pytest.raises(FaultInjectedError) as ei:
        inj.check("recv")
    assert ei.value.point == "recv"
    assert is_transient(ei.value)


def test_owner_counting_keeps_schedule_until_last_stop():
    inj = FaultInjector()
    inj.arm("recv:nth=2;seed=1")
    inj.arm("recv:nth=2;seed=1")    # second manager, same spec
    assert inj.enabled
    assert [inj.fires("recv") for _ in range(4)] == \
           [False, True, False, True]
    inj.stop()
    assert inj.enabled              # one owner still armed
    # re-arming kept the LIVE schedule: counters carried on above
    inj.stop()
    assert not inj.enabled
    assert not inj.fires("recv")    # disarmed: nothing fires


def test_unarmed_point_never_fires():
    inj = FaultInjector()
    inj.arm("recv:nth=1")
    assert not inj.fires("connect")
    inj.check("connect")            # no clause: returns silently


# -- retry policy -------------------------------------------------------------


def test_retry_policy_disabled_at_count_zero():
    rp = RetryPolicy(0, 50, 10_000)
    assert not rp.enabled
    assert rp.next_delay_ms(1, 0) is None


def test_retry_backoff_doubles_with_equal_jitter():
    import random as _random
    rp = RetryPolicy(5, 100, 60_000, rng=_random.Random(7))
    for attempts in (1, 2, 3, 4, 5):
        base = 100 * 2 ** (attempts - 1)
        for _ in range(20):
            d = rp.next_delay_ms(attempts, 0)
            assert base / 2 <= d <= base, (attempts, d)
    assert rp.next_delay_ms(6, 0) is None     # attempts exhausted
    assert rp.next_delay_ms(0, 0) is None     # not a failure count


def test_retry_deadline_budget():
    rp = RetryPolicy(10, 1000, 500)
    assert rp.next_delay_ms(1, 500) is None   # budget gone
    assert rp.next_delay_ms(1, 501) is None
    d = rp.next_delay_ms(1, 400)              # clamped to what's left
    assert d is not None and d <= 100


# -- breaker + stripe health --------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_breaker_trips_half_opens_and_recovers():
    clk = _Clock()
    br = CircuitBreaker(failures=3, reset_ms=2_000, name="p", clock=clk)
    assert br.state == "closed"
    for _ in range(2):
        br.record_failure()
    assert br.allow() and br.state == "closed"
    br.record_failure()                       # third strike
    assert br.state == "open" and br.trips == 1
    assert not br.allow()
    clk.t += 1.0
    assert not br.allow()                     # still inside reset_ms
    clk.t += 1.5
    assert br.allow()                         # the half-open probe
    assert br.state == "half-open"
    assert not br.allow()                     # probe already out
    br.record_success()
    assert br.state == "closed" and br.allow()


def test_breaker_halfopen_failure_reopens_and_restarts_clock():
    clk = _Clock()
    br = CircuitBreaker(failures=1, reset_ms=1_000, name="p", clock=clk)
    br.record_failure()
    assert br.state == "open"
    clk.t += 1.0
    assert br.allow()                         # probe admitted
    br.record_failure()                       # probe failed
    assert br.state == "open"
    clk.t += 0.5
    assert not br.allow()                     # clock restarted
    clk.t += 0.5
    assert br.allow()


def test_breaker_disabled_at_failures_zero():
    br = CircuitBreaker(failures=0, reset_ms=1_000)
    for _ in range(50):
        br.record_failure()
    assert br.allow() and br.trips == 0


def test_stripe_health_demotes_and_expires():
    clk = _Clock()
    sh = StripeHealth(failures=2, demote_ms=5_000, name="p", clock=clk)
    sh.note_lane_failure()
    assert not sh.demoted()
    sh.note_lane_failure()
    assert sh.demoted()
    clk.t += 4.9
    assert sh.demoted()
    clk.t += 0.2
    assert not sh.demoted()                   # window expired
    # a success while healthy clears accumulated strikes
    sh.note_lane_failure()
    sh.note_success()
    sh.note_lane_failure()
    assert not sh.demoted()


def test_stripe_health_disabled_at_failures_zero():
    sh = StripeHealth(failures=0, demote_ms=5_000)
    for _ in range(10):
        sh.note_lane_failure()
    assert not sh.demoted()


# -- error taxonomy -----------------------------------------------------------


def test_transient_classification_and_wire_roundtrip():
    assert is_transient(TransportError("blip"))
    assert not is_transient(FatalTransportError("gone"))
    assert not is_transient(ValueError("nope"))
    # fatal survives the status!=0 reason string; transient stays plain
    wire = encode_remote_error(FatalTransportError("no block store"))
    assert wire.startswith("FATAL:")
    back = decode_remote_error(wire)
    assert isinstance(back, FatalTransportError)
    assert not is_transient(back)
    plain = decode_remote_error(encode_remote_error(TransportError("x")))
    assert is_transient(plain)


# -- reader integration over loopback -----------------------------------------


def _loop_cluster(extra, driver_port, n_exec=2):
    net = LoopbackNetwork()
    d = {
        "spark.shuffle.tpu.driverPort": driver_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "10s",
        "spark.shuffle.tpu.metrics": True,
    }
    d.update(extra)
    conf = TpuShuffleConf(d)
    driver = TpuShuffleManager(conf, is_driver=True, network=net)
    executors = [
        TpuShuffleManager(
            conf, is_driver=False, network=net,
            port=driver_port + 100 + i * 10, executor_id=str(i),
        )
        for i in range(n_exec)
    ]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if all(len(e._peers) == n_exec for e in executors):
            break
        time.sleep(0.01)
    return net, conf, driver, executors


def _write_maps(driver, executors, sid, num_maps=2, num_parts=4,
                rows=200, vbytes=600):
    """Deterministic records; returns (handle, maps_by_host, expected)."""
    part = HashPartitioner(num_parts)
    handle = driver.register_shuffle(sid, num_maps, part)
    expected = defaultdict(list)
    maps_by_host = defaultdict(list)
    for m in range(num_maps):
        recs = [
            (f"s{sid}m{m}r{j}", bytes([(m + j) % 251]) * vbytes)
            for j in range(rows)
        ]
        for k, v in recs:
            expected[k].append(v)
        ex = executors[m % len(executors)]
        w = ex.get_writer(handle, m)
        w.write(recs)
        w.stop(True)
        maps_by_host[ex.local_smid].append(m)
    return handle, dict(maps_by_host), expected


def _read_all(reader, expected):
    got = defaultdict(list)
    for k, v in reader.read():
        got[k].append(bytes(v) if not isinstance(v, bytes) else v)
    assert set(got) == set(expected)
    for k in expected:
        assert sorted(got[k]) == sorted(expected[k]), k


def test_reader_absorbs_transient_read_faults_bit_exact(faults_env):
    """Every second read response is cut; with in-task retries the
    read completes BIT-EXACT and the retry counters moved."""
    net, conf, driver, executors = _loop_cluster({
        "spark.shuffle.tpu.faultInject": "read_resp:nth=2;seed=3",
        "spark.shuffle.tpu.fetchRetryCount": 10,
        "spark.shuffle.tpu.fetchRetryWaitMs": "2ms",
        "spark.shuffle.tpu.fetchRetryMaxMs": "30s",
    }, BASE_PORT, n_exec=3)
    try:
        # three hosts -> two remote fetch groups: the nth=2 schedule
        # cuts the second group's response, the retry lands it
        handle, maps_by_host, expected = _write_maps(
            driver, executors, 0, num_maps=3)
        reader = executors[0].get_reader(handle, 0, 4, maps_by_host)
        _read_all(reader, expected)
        fired = FAULTS.fired_counts()
        assert fired.get("read_resp", 0) > 0, fired
        assert _metric_total("shuffle_fetch_retries_total") > 0
        assert _metric_total("fault_injected_total") > 0
    finally:
        for m in executors + [driver]:
            m.stop()


def test_retry_disabled_converts_first_failure(faults_env):
    """fetchRetryCount=0: the reference posture — the FIRST transport
    failure converts to FetchFailedError, no retries, no recording."""
    net, conf, driver, executors = _loop_cluster({
        "spark.shuffle.tpu.faultInject": "read_resp:nth=1",
        "spark.shuffle.tpu.fetchRetryCount": 0,
    }, BASE_PORT + 60)
    try:
        handle, maps_by_host, expected = _write_maps(
            driver, executors, 0)
        reader = executors[0].get_reader(handle, 0, 4, maps_by_host)
        with pytest.raises(FetchFailedError):
            for _ in reader.read():
                pass
        assert _metric_total("shuffle_fetch_retries_total") == 0
        assert _metric_total("transport_breaker_trips_total") == 0
    finally:
        for m in executors + [driver]:
            m.stop()


def test_breaker_trips_on_persistent_peer_failure(faults_env):
    """Every read response fails: strikes trip the per-peer breaker,
    the fetch converts cleanly, and the trip is counted."""
    net, conf, driver, executors = _loop_cluster({
        "spark.shuffle.tpu.faultInject": "read_resp:nth=1",
        "spark.shuffle.tpu.fetchRetryCount": 3,
        "spark.shuffle.tpu.fetchRetryWaitMs": "1ms",
        "spark.shuffle.tpu.fetchBreakerFailures": 2,
        "spark.shuffle.tpu.fetchBreakerResetMs": "60s",
    }, BASE_PORT + 120)
    try:
        handle, maps_by_host, expected = _write_maps(
            driver, executors, 0)
        reader = executors[0].get_reader(handle, 0, 4, maps_by_host)
        with pytest.raises(FetchFailedError):
            for _ in reader.read():
                pass
        assert _metric_total("transport_breaker_trips_total") >= 1
        assert _metric_total("shuffle_fetch_failures_total") >= 1
    finally:
        for m in executors + [driver]:
            m.stop()


def test_fresh_reader_probes_open_breaker_after_heal(faults_env):
    """The breaker is node-resident and outlives the task — but a
    stage retry's FRESH reader must not be fast-failed on stale state
    when the peer healed: its first fetch per peer goes out as the
    probe, succeeds, and closes the breaker (the lineage contract:
    heal + re-register + rerun must complete)."""
    net, conf, driver, executors = _loop_cluster({
        "spark.shuffle.tpu.faultInject": "read_resp:nth=1",
        "spark.shuffle.tpu.fetchRetryCount": 2,
        "spark.shuffle.tpu.fetchRetryWaitMs": "1ms",
        "spark.shuffle.tpu.fetchBreakerFailures": 2,
        # far past the test: only the probe path can get through
        "spark.shuffle.tpu.fetchBreakerResetMs": "600s",
    }, BASE_PORT + 140)
    try:
        handle, maps_by_host, expected = _write_maps(
            driver, executors, 0)
        reader = executors[0].get_reader(handle, 0, 4, maps_by_host)
        with pytest.raises(FetchFailedError):
            for _ in reader.read():
                pass
        assert _metric_total("transport_breaker_trips_total") >= 1
        # the peer heals (fault plane disarmed) and the stage retries:
        # a new shuffle, a new reader, the same open breaker
        FAULTS.reset()
        handle2, maps2, expected2 = _write_maps(
            driver, executors, 2)
        reader2 = executors[0].get_reader(handle2, 0, 4, maps2)
        _read_all(reader2, expected2)
        # the successful probe closed it: a third read sails through
        reader3 = executors[0].get_reader(handle2, 0, 4, maps2)
        _read_all(reader3, expected2)
    finally:
        for m in executors + [driver]:
            m.stop()


def test_striped_lane_kill_demotes_to_unstriped(faults_env):
    """Lane kills fail the striped attempt; health demotes the peer to
    the unstriped small-read lane and the retry completes bit-exact
    (the degradation ladder: striped -> unstriped -> FetchFailed)."""
    net, conf, driver, executors = _loop_cluster({
        "spark.shuffle.tpu.faultInject": "lane_kill:nth=2;seed=5",
        "spark.shuffle.tpu.fetchRetryCount": 8,
        "spark.shuffle.tpu.fetchRetryWaitMs": "2ms",
        "spark.shuffle.tpu.fetchRetryMaxMs": "30s",
        "spark.shuffle.tpu.transportNumStripes": 2,
        # the threshold clamps at its 64k floor: blocks must beat THAT
        "spark.shuffle.tpu.transportStripeThreshold": "64k",
        "spark.shuffle.tpu.stripeDemoteFailures": 1,
        "spark.shuffle.tpu.stripeDemoteMs": "60s",
        "spark.shuffle.tpu.fetchBreakerFailures": 0,
    }, BASE_PORT + 180)
    try:
        handle, maps_by_host, expected = _write_maps(
            driver, executors, 0, rows=240, vbytes=1500)
        reader = executors[0].get_reader(handle, 0, 4, maps_by_host)
        _read_all(reader, expected)
        fired = FAULTS.fired_counts()
        assert fired.get("lane_kill", 0) >= 1, fired
        assert _metric_total("transport_stripe_demotions_total") >= 1
    finally:
        for m in executors + [driver]:
            m.stop()


def test_late_stripe_progress_release_races_settle_clean(
        faults_env, monkeypatch):
    """Regression (found by the shaken tcp-async chaos soak): the
    reader's per-stripe progress callback claims its n bytes under the
    pending lock but releases the window ticket AFTER dropping it,
    while settle() used to close the ticket with a no-arg release — a
    settle overtaking that claim->release window turned the late
    release(n) into a DoubleReleaseError.  settle() now releases the
    explicit remainder, so the releases sum exactly in any order.

    The interleaving is forced deterministically: every group read
    fires one injected progress report from a side thread, the ticket
    release under it parks on an event inside the claim->release
    window, and only then does the completion (and thus settle) run."""
    from sparkrdma_tpu.transport import stripe as stripe_mod
    from sparkrdma_tpu.transport.channel import (
        FnCompletionListener as FnCL,
    )
    from sparkrdma_tpu.utils import ledger as ledger_mod

    parked = threading.Event()
    orig_release = ledger_mod.ResourceTicket.release

    def parking_release(self, amount=None):
        if self.resource == "reader.inflight_bytes" and amount:
            parked.set()  # the claim happened; now park in the window
            time.sleep(0.05)
        return orig_release(self, amount)

    monkeypatch.setattr(
        ledger_mod.ResourceTicket, "release", parking_release)

    orig_rb = stripe_mod.ReadGroup.read_blocks

    def racing_rb(self, locations, listener, on_progress=None,
                  tenant=None, ctx=None):
        if on_progress is None:
            return orig_rb(self, locations, listener, tenant=tenant,
                           ctx=ctx)
        total = sum(loc.length for loc in locations)
        racer = threading.Thread(target=on_progress, args=(total // 2,))

        def on_success(blocks):
            parked.clear()
            racer.start()
            # wait until the progress claim is parked inside its
            # claim->release window, THEN let completion settle
            assert parked.wait(5), "progress release never parked"
            listener.on_success(blocks)
            racer.join(10)

        # the real per-stripe progress stays suppressed (on_progress
        # None below) — the injected racer is the only window release
        # besides settle, so the arithmetic stays exact
        return orig_rb(self, locations, FnCL(on_success,
                                             listener.on_failure),
                       tenant=tenant, ctx=ctx)

    monkeypatch.setattr(stripe_mod.ReadGroup, "read_blocks", racing_rb)

    net, conf, driver, executors = _loop_cluster({
        "spark.shuffle.tpu.resourceDebug": True,
        "spark.shuffle.tpu.transportNumStripes": 2,
        "spark.shuffle.tpu.transportStripeThreshold": "64k",
    }, BASE_PORT + 340)
    ledger = get_resource_ledger()
    assert ledger.enabled
    got = defaultdict(list)
    try:
        handle, maps_by_host, expected = _write_maps(
            driver, executors, 0, rows=240, vbytes=1500)
        for pid in range(4):
            rd = executors[pid % 2].get_reader(
                handle, pid, pid + 1, dict(maps_by_host))
            for k, v in rd.read():
                got[k].append(bytes(v) if not isinstance(v, bytes) else v)
    finally:
        for m in executors + [driver]:
            m.stop()
    assert set(got) == set(expected)
    for k in expected:
        assert sorted(got[k]) == sorted(expected[k]), k
    assert ledger.double_releases() == 0, ledger.leak_report()


def test_location_rpc_fault_is_a_clean_metadata_failure(faults_env):
    net, conf, driver, executors = _loop_cluster({
        "spark.shuffle.tpu.faultInject": "location_rpc:nth=1",
    }, BASE_PORT + 240)
    try:
        handle, maps_by_host, expected = _write_maps(
            driver, executors, 0)
        reader = executors[0].get_reader(handle, 0, 4, maps_by_host)
        with pytest.raises(MetadataFetchFailedError):
            for _ in reader.read():
                pass
    finally:
        for m in executors + [driver]:
            m.stop()


def test_dropped_publish_fails_clean_not_wrong(faults_env):
    """Every publish run is 'lost': the reader must time out with a
    clean metadata failure (stage retry), never a wrong answer — and
    the drop re-marked the runs dirty for the next publish."""
    net, conf, driver, executors = _loop_cluster({
        "spark.shuffle.tpu.faultInject": "publish:nth=1",
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "2s",
    }, BASE_PORT + 300)
    try:
        handle, maps_by_host, expected = _write_maps(
            driver, executors, 0, rows=20, vbytes=64)
        assert FAULTS.fired_counts().get("publish", 0) >= 1
        reader = executors[0].get_reader(handle, 0, 4, maps_by_host)
        with pytest.raises(MetadataFetchFailedError):
            for _ in reader.read():
                pass
    finally:
        for m in executors + [driver]:
            m.stop()


def test_dropped_heartbeats_do_not_prune_live_executors(faults_env):
    """Probe drops model lost packets: acks from the surviving probes
    keep last_ack fresh, so nobody is pruned."""
    net, conf, driver, executors = _loop_cluster({
        "spark.shuffle.tpu.faultInject": "heartbeat:nth=2",
        "spark.shuffle.tpu.heartbeatInterval": "100ms",
        "spark.shuffle.tpu.heartbeatTimeout": "2s",
    }, BASE_PORT + 360)
    try:
        time.sleep(0.8)
        assert len(driver.executors) == 2
        assert FAULTS.fired_counts().get("heartbeat", 0) >= 1
    finally:
        for m in executors + [driver]:
            m.stop()


def test_accept_paths_survive_transient_errors(faults_env):
    """ECONNABORTED from accept() (a peer that reset mid-handshake —
    routine when an injected connect fault kills a client) must not
    take the LISTENER down: that would refuse every future peer on
    the node forever.  Only listener-is-gone errnos are fatal."""
    GLOBAL_REGISTRY.enabled = True  # fixture restores

    class _Disp:
        def __init__(self):
            self.unregistered = []

        def sel_register(self, *a):
            pass

        def sel_unregister(self, s):
            self.unregistered.append(s)

    class _Srv:
        def __init__(self, errs):
            self._errs = list(errs)

        def fileno(self):
            return 99

        def accept(self):
            raise self._errs.pop(0)

        def close(self):
            pass

    from sparkrdma_tpu.transport.dispatcher import Acceptor

    d = _Disp()
    acc = Acceptor(d, None, _Srv([OSError(errno.ECONNABORTED, "aborted"),
                                  OSError(errno.EMFILE, "fd pressure")]))
    acc.on_readable()  # transient: listener survives
    acc.on_readable()
    assert not acc._closed and not d.unregistered
    acc_dead = Acceptor(d, None, _Srv([OSError(errno.EBADF, "closed")]))
    acc_dead.on_readable()  # fatal: unregisters and closes
    assert acc_dead._closed and len(d.unregistered) == 1

    # the threaded analog: survives the abort, returns on EBADF
    net = TcpNetwork()
    srv = _Srv([OSError(errno.ECONNABORTED, "aborted"),
                OSError(errno.EBADF, "closed")])
    net._accept_forever(srv, None)
    assert not srv._errs  # consumed the abort, returned on EBADF
    assert _metric_total("transport_accept_transient_errors_total") >= 3


# -- the seeded chaos soak ----------------------------------------------------

_SOAK_SPEC = (
    "connect:p=0.04;read_resp:p=0.06;serve_delay:ms=2,p=0.3;"
    "lane_kill:nth=9;stripe:p=0.03;send:p=0.015;disk_read:p=0.04;"
    "heartbeat:p=0.2;seed={seed}"
)


def _soak_shuffle(driver, executors, sid, outcomes, errors):
    """One shuffle under chaos: record 'exact' or 'failed-clean'."""
    try:
        # per-partition blocks beat the 64k stripe-threshold floor, so
        # the lane_kill/stripe points actually see striped traffic
        handle, maps_by_host, expected = _write_maps(
            driver, executors, sid, rows=160, vbytes=2000)
        try:
            reader = executors[sid % len(executors)].get_reader(
                handle, 0, 4, maps_by_host)
            _read_all(reader, expected)
            outcomes.append("exact")
        except FetchFailedError:
            # clean, stage-retriable — the allowed degraded outcome
            outcomes.append("failed-clean")
        finally:
            driver.unregister_shuffle(sid)
    except BaseException as e:  # anything else is a soak failure
        errors.append(e)


@pytest.mark.parametrize("transport", ["loopback", "tcp-threaded",
                                       "tcp-async"])
@pytest.mark.parametrize("decode_threads", [0, 4])
@pytest.mark.parametrize("skew", [False, True])
def test_chaos_soak_exact_or_clean_zero_leaks(
        faults_env, transport, decode_threads, skew):
    """The acceptance soak: a mixed seeded fault spec over the full
    engine matrix, under resourceDebug + lockDebug.  Contract: every
    shuffle is bit-exact or a clean FetchFailedError — never a hang,
    wrong answer, ledger leak, double release or rank violation."""
    get_lock_factory().enabled = False
    idx = (["loopback", "tcp-threaded", "tcp-async"].index(transport) * 4
           + decode_threads // 4 * 2 + int(skew))
    driver_port = BASE_PORT + 500 + idx * 60
    extra = {
        "spark.shuffle.tpu.faultInject": _SOAK_SPEC.format(seed=100 + idx),
        "spark.shuffle.tpu.resourceDebug": True,
        "spark.shuffle.tpu.lockDebug": True,
        "spark.shuffle.tpu.metrics": True,
        "spark.shuffle.tpu.fetchRetryCount": 4,
        "spark.shuffle.tpu.fetchRetryWaitMs": "5ms",
        "spark.shuffle.tpu.fetchRetryMaxMs": "3s",
        "spark.shuffle.tpu.decodeThreads": decode_threads,
        "spark.shuffle.tpu.skewEnabled": skew,
        "spark.shuffle.tpu.transportNumStripes": 2,
        "spark.shuffle.tpu.transportStripeThreshold": "64k",
        "spark.shuffle.tpu.tierHotBytes": "64k",  # force disk reads
        "spark.shuffle.tpu.driverPort": driver_port,
        "spark.shuffle.tpu.partitionLocationFetchTimeout": "8s",
        "spark.shuffle.tpu.connectTimeout": "5s",
    }
    # make chaos-shake: SCHED_SHAKE=<seed> layers the deterministic
    # schedule shaker + state validator onto the same soak
    extra.update(shake_confs_from_env())
    if transport != "loopback":
        extra["spark.shuffle.tpu.transportAsyncDispatcher"] = (
            transport == "tcp-async")

    def mk_conf():
        return TpuShuffleConf(dict(extra))

    if transport == "loopback":
        net = LoopbackNetwork()
        driver = TpuShuffleManager(
            mk_conf(), is_driver=True, network=net)
        executors = [
            TpuShuffleManager(
                mk_conf(), is_driver=False, network=net,
                port=driver_port + 100 + i * 10, executor_id=str(i),
            )
            for i in range(2)
        ]
    else:
        driver = TpuShuffleManager(
            mk_conf(), is_driver=True, network=TcpNetwork(),
            port=driver_port, stage_to_device=False,
        )
        # the test ports sit inside the kernel's ephemeral range, so a
        # leaked outgoing connection from an earlier test can occupy
        # driver_port and _bind_node moves the driver up a port —
        # executors must dial the port it ACTUALLY bound (the
        # conf-broadcast analog), not the one we asked for
        extra["spark.shuffle.tpu.driverPort"] = driver.node.address[1]
        executors = [
            TpuShuffleManager(
                mk_conf(), is_driver=False, network=TcpNetwork(),
                port=driver_port + 100 + i * 10, executor_id=str(i),
                stage_to_device=False,
            )
            for i in range(2)
        ]
    ledger = get_resource_ledger()
    assert ledger.enabled  # the conf flipped it on
    outcomes: list = []
    errors: list = []
    try:
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline:
            if all(len(e._peers) == 2 for e in executors):
                break
            time.sleep(0.01)
        threads = [
            threading.Thread(
                target=_soak_shuffle,
                args=(driver, executors, sid, outcomes, errors),
            )
            for sid in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
            assert not t.is_alive(), "chaos soak hung"
        assert not errors, errors
        assert len(outcomes) == 2 and set(outcomes) <= {
            "exact", "failed-clean"}, outcomes

        # idle now: every TASK-lifetime resource must drain.  Open
        # sockets (tcp.fds) are CONNECTION-lifetime — legitimately
        # held while the cluster is up; the managers' own stops below
        # audit those via resource_leaked_total.
        gc.collect()
        deadline = time.monotonic() + 10
        left = {}
        while time.monotonic() < deadline:
            left = {r: n for r, n in ledger.outstanding().items()
                    if n and r != "tcp.fds"}
            if not left:
                break
            time.sleep(0.05)
        assert not left, (left, ledger.leak_report())
        assert ledger.double_releases() == 0, ledger.leak_report()
        assert FAULTS.fired_counts(), "the chaos spec never fired"
    finally:
        for m in executors + [driver]:
            m.stop()
    # the last manager's stop flushed the ledger epoch: nothing —
    # including the sockets — survived teardown
    leaked = [
        (dict(inst.labels), inst.value)
        for _k, inst in GLOBAL_REGISTRY.instruments()
        if getattr(inst, "name", "") == "resource_leaked_total"
        and inst.value > 0
    ]
    assert not leaked, leaked
    viol = [
        inst for _k, inst in GLOBAL_REGISTRY.instruments()
        if getattr(inst, "name", "") == "lock_rank_violations_total"
    ]
    assert all(v.value == 0 for v in viol), [v.value for v in viol]
    doubles = [
        inst.value for _k, inst in GLOBAL_REGISTRY.instruments()
        if getattr(inst, "name", "") == "resource_double_release_total"
    ]
    assert all(v == 0 for v in doubles), doubles
    # under stateDebug/schedShake (make chaos-shake) every lifecycle
    # transition was validated against its declared table: zero
    # illegal-transition attempts allowed anywhere in the soak
    illegal = [
        (dict(inst.labels), inst.value)
        for _k, inst in GLOBAL_REGISTRY.instruments()
        if getattr(inst, "name", "") == "state_transitions_illegal_total"
        and inst.value > 0
    ]
    assert not illegal, illegal
