"""Ring exchange + ring attention on the 8-device CPU mesh
(SURVEY.md §5 long-context / sequence parallelism)."""

import numpy as np
import pytest

import jax.numpy as jnp

from sparkrdma_tpu.models.ring_attention import ring_attention
from sparkrdma_tpu.parallel import make_mesh
from sparkrdma_tpu.parallel.ring import RingExchange


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def test_ring_all_shards(mesh, devices):
    ring = RingExchange(mesh)
    D = ring.n_devices
    x = jnp.arange(D * 16, dtype=jnp.int32).reshape(D, 16)
    out = np.asarray(ring.all_shards(x))  # [D, D, 16]
    for i in range(D):
        for j in range(D):
            src = (i - j) % D
            np.testing.assert_array_equal(out[i, j], np.asarray(x[src]))


def test_ring_reduce_streaming_sum(mesh, devices):
    ring = RingExchange(mesh)
    D = ring.n_devices
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 100, size=(D, 32), dtype=np.int32))

    # fold: sum of all shards, computed one hop at a time
    acc = ring.ring_reduce(
        x,
        init_fn=lambda shard: jnp.zeros_like(shard),
        consume=lambda acc, src, shard: acc + shard,
    )
    total = np.asarray(x).sum(axis=0)
    out = np.asarray(acc)  # [D, 32] — every device holds the full sum
    for d in range(D):
        np.testing.assert_array_equal(out[d], total)


def reference_attention(q, k, v, causal):
    s = (q @ k.T) / np.sqrt(q.shape[-1])
    if causal:
        n = q.shape[0]
        mask = np.tril(np.ones((n, n), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return p @ v


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(mesh, devices, causal):
    rng = np.random.default_rng(1)
    S, d = 128, 32  # 8 devices x 16 local
    q = rng.standard_normal((S, d)).astype(np.float32)
    k = rng.standard_normal((S, d)).astype(np.float32)
    v = rng.standard_normal((S, d)).astype(np.float32)
    out = np.asarray(
        ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                       mesh=mesh, causal=causal)
    )
    expect = reference_attention(q, k, v, causal)
    np.testing.assert_allclose(out, expect, rtol=2e-4, atol=2e-5)


def test_ring_attention_validation(mesh, devices):
    q = jnp.zeros((100, 8), jnp.float32)  # 100 not divisible by 8
    with pytest.raises(ValueError):
        ring_attention(q, q, q, mesh=mesh)


def test_ring_reduce_caches_compilation(mesh, devices):
    """Same (mesh, shape, dtype, fns) → the jitted program is reused."""
    from sparkrdma_tpu.parallel.ring import RingExchange, _ring_reduce_fn

    ring = RingExchange(mesh)
    init_fn = lambda shard: jnp.zeros_like(shard)  # noqa: E731
    consume = lambda acc, src, cur: acc + cur  # noqa: E731
    x = jnp.arange(8 * 4, dtype=jnp.int32).reshape(8, 4)
    a = ring.ring_reduce(x, init_fn, consume)
    before = _ring_reduce_fn.cache_info().hits
    b = ring.ring_reduce(x + 1, init_fn, consume)
    assert _ring_reduce_fn.cache_info().hits == before + 1
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b) - 8)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_multihead(mesh, devices, causal):
    # [B, H, S, d] leading dims: each (b, h) attends independently
    rng = np.random.default_rng(2)
    B, H, S, d = 2, 4, 64, 16
    q = rng.standard_normal((B, H, S, d)).astype(np.float32)
    k = rng.standard_normal((B, H, S, d)).astype(np.float32)
    v = rng.standard_normal((B, H, S, d)).astype(np.float32)
    out = np.asarray(
        ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                       mesh=mesh, causal=causal)
    )
    assert out.shape == (B, H, S, d)
    for b in range(B):
        for h in range(H):
            expect = reference_attention(q[b, h], k[b, h], v[b, h], causal)
            np.testing.assert_allclose(
                out[b, h], expect, rtol=2e-4, atol=2e-5
            )


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(mesh, devices, causal):
    from sparkrdma_tpu.models.ring_attention import ulysses_attention

    rng = np.random.default_rng(3)
    H, S, d = 8, 64, 16  # H == D: one head per device after the a2a
    q = rng.standard_normal((H, S, d)).astype(np.float32)
    k = rng.standard_normal((H, S, d)).astype(np.float32)
    v = rng.standard_normal((H, S, d)).astype(np.float32)
    out = np.asarray(
        ulysses_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          mesh=mesh, causal=causal)
    )
    assert out.shape == (H, S, d)
    for h in range(H):
        expect = reference_attention(q[h], k[h], v[h], causal)
        np.testing.assert_allclose(out[h], expect, rtol=2e-4, atol=2e-5)


def test_ulysses_attention_batched_heads(mesh, devices):
    # [B, H, S, d] with B*H divisible by D
    from sparkrdma_tpu.models.ring_attention import ulysses_attention

    rng = np.random.default_rng(4)
    B, H, S, d = 2, 4, 64, 16
    q = rng.standard_normal((B, H, S, d)).astype(np.float32)
    k = rng.standard_normal((B, H, S, d)).astype(np.float32)
    v = rng.standard_normal((B, H, S, d)).astype(np.float32)
    out = np.asarray(
        ulysses_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          mesh=mesh, causal=True)
    )
    for b in range(B):
        for h in range(H):
            expect = reference_attention(q[b, h], k[b, h], v[b, h], True)
            np.testing.assert_allclose(
                out[b, h], expect, rtol=2e-4, atol=2e-5
            )


def test_ulysses_attention_head_validation(mesh, devices):
    from sparkrdma_tpu.models.ring_attention import ulysses_attention

    q = jnp.zeros((3, 64, 8), jnp.float32)  # 3 heads not divisible by 8
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention(q, q, q, mesh=mesh)


def test_ring_ulysses_agree(mesh, devices):
    from sparkrdma_tpu.models.ring_attention import ulysses_attention

    rng = np.random.default_rng(5)
    H, S, d = 8, 64, 16
    q = rng.standard_normal((H, S, d)).astype(np.float32)
    k = rng.standard_normal((H, S, d)).astype(np.float32)
    v = rng.standard_normal((H, S, d)).astype(np.float32)
    a = np.asarray(ring_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh=mesh, causal=True
    ))
    b = np.asarray(ulysses_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh=mesh, causal=True
    ))
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
