"""Memory layer: native staging pool + device arenas
(SURVEY.md §2 rows RdmaBufferManager/RdmaBuffer/RdmaMappedFile)."""

import numpy as np
import pytest

from sparkrdma_tpu.memory import ArenaManager, StagingPool
from sparkrdma_tpu.memory.staging import MIN_BLOCK_SIZE
from sparkrdma_tpu.transport.channel import TransportError
from sparkrdma_tpu.utils.types import BlockLocation


@pytest.fixture(params=["native", "python"])
def pool(request):
    p = StagingPool(max_bytes=4 << 20, force_python=(request.param == "python"))
    if request.param == "native":
        assert p.is_native, "native _staging.so should be built (make -C native)"
    yield p
    p.close()


def test_alloc_rounds_to_size_class(pool):
    buf = pool.alloc(100)
    assert buf.capacity == MIN_BLOCK_SIZE
    buf2 = pool.alloc(MIN_BLOCK_SIZE + 1)
    assert buf2.capacity == MIN_BLOCK_SIZE * 2
    buf.free()
    buf2.free()


def test_view_is_writable_and_reusable(pool):
    with pool.alloc(1024) as buf:
        buf.view[:4] = [1, 2, 3, 4]
        addr1 = buf.address
        assert list(buf.view[:4]) == [1, 2, 3, 4]
    # freed block returns to its class stack; next alloc reuses it
    with pool.alloc(1024) as buf2:
        assert buf2.address == addr1


def test_budget_and_stats(pool):
    stats0 = pool.stats()
    bufs = [pool.alloc(1 << 20) for _ in range(3)]  # 3 MiB in 1 MiB classes
    s = pool.stats()
    assert s["in_use"] == 3 * (1 << 20)
    assert s["owned"] >= s["in_use"]
    bufs.append(pool.alloc(1 << 20))  # 4th fits the 4 MiB budget exactly
    with pytest.raises(MemoryError):
        pool.alloc(1 << 20)  # 5th exceeds it
    for b in bufs:
        b.free()
    s2 = pool.stats()
    assert s2["in_use"] == 0
    assert s2["failed_allocs"] >= 1


def test_trim_frees_idle(pool):
    bufs = [pool.alloc(1 << 20) for _ in range(3)]
    for b in bufs:
        b.free()
    assert pool.stats()["idle"] >= 3 * (1 << 20) * 0.9 or pool.stats()["idle"] == 0
    pool.trim(0)
    assert pool.stats()["idle"] == 0
    assert pool.stats()["owned"] == pool.stats()["in_use"] == 0


def test_double_free_is_safe(pool):
    buf = pool.alloc(64)
    buf.free()
    buf.free()  # no-op, no crash
    assert pool.stats()["in_use"] == 0


def test_auto_trim_keeps_idle_below_budget():
    # fill to the budget, free everything: idle > 90% triggers trim to 65%
    p = StagingPool(max_bytes=2 << 20)
    bufs = [p.alloc(256 << 10) for _ in range(8)]  # 2 MiB
    for b in bufs:
        b.free()
    idle = p.stats()["idle"]
    assert idle <= 0.66 * (2 << 20)
    p.close()


# -- arenas -----------------------------------------------------------------


def test_arena_register_read_release(devices):
    import jax.numpy as jnp

    mgr = ArenaManager()
    data = np.arange(4096, dtype=np.uint8)
    seg = mgr.register(jnp.asarray(data), shuffle_id=3)
    assert seg.mkey >= 1
    loc = BlockLocation(address=100, length=16, mkey=seg.mkey)
    assert mgr.read_block(loc) == bytes(data[100:116])
    assert mgr.total_bytes == 4096
    mgr.release(seg.mkey)
    with pytest.raises(TransportError):
        mgr.read_block(loc)
    assert mgr.total_bytes == 0


def test_arena_release_by_shuffle(devices):
    import jax.numpy as jnp

    mgr = ArenaManager()
    for sid in (1, 1, 2):
        mgr.register(jnp.zeros(1024, dtype=jnp.uint8), shuffle_id=sid)
    assert mgr.stats()["segments"] == 3
    freed = mgr.release_shuffle(1)
    assert freed == 2
    assert mgr.stats()["segments"] == 1
    assert mgr.total_bytes == 1024


def test_arena_budget_and_validation(devices):
    import jax.numpy as jnp

    mgr = ArenaManager(max_bytes=2048)
    mgr.register(jnp.zeros(2048, dtype=jnp.uint8))
    with pytest.raises(MemoryError):
        mgr.register(jnp.zeros(1, dtype=jnp.uint8))
    with pytest.raises(ValueError):
        mgr.register(jnp.zeros((2, 2), dtype=jnp.uint8))
    with pytest.raises(ValueError):
        mgr.register(jnp.zeros(4, dtype=jnp.float32))


def test_arena_out_of_bounds_read(devices):
    import jax.numpy as jnp

    mgr = ArenaManager()
    seg = mgr.register(jnp.zeros(64, dtype=jnp.uint8))
    with pytest.raises(TransportError):
        mgr.read_block(BlockLocation(60, 8, seg.mkey))


def test_staging_prealloc_warms_pool():
    p = StagingPool(max_bytes=16 << 20)
    n = p.prealloc(4 << 20, 1 << 20)
    assert n == 4
    s = p.stats()
    assert s["idle"] >= 4 << 20 and s["in_use"] == 0
    # subsequent allocs reuse warm blocks (owned stays flat)
    owned = s["owned"]
    b = p.alloc(1 << 20)
    assert p.stats()["owned"] == owned
    b.free()
    p.close()


def test_segment_keepalive_released_with_segment(devices):
    import jax.numpy as jnp

    class FakeBuf:
        freed = 0

        def free(self):
            FakeBuf.freed += 1

    mgr = ArenaManager()
    seg = mgr.register(jnp.zeros(64, dtype=jnp.uint8), shuffle_id=1,
                       keepalive=FakeBuf())
    assert FakeBuf.freed == 0
    mgr.release(seg.mkey)
    assert FakeBuf.freed == 1
    # release by shuffle and stop also free keepalives exactly once
    s2 = mgr.register(jnp.zeros(64, dtype=jnp.uint8), shuffle_id=2,
                      keepalive=FakeBuf())
    mgr.release_shuffle(2)
    assert FakeBuf.freed == 2


def test_arena_unbudgeted_file_segment():
    # reviewer finding: file-backed segments must not consume the arena
    # byte budget (they live in the OS page cache, not HBM)
    import numpy as np
    from sparkrdma_tpu.memory.arena import ArenaManager

    arena = ArenaManager(max_bytes=1024)
    big = np.zeros(4096, np.uint8)
    seg = arena.register(big, budgeted=False)
    assert arena.total_bytes == 0
    assert arena.stats()["file_bytes"] == 4096
    # budgeted registration still enforced
    arena.register(np.zeros(512, np.uint8))
    try:
        arena.register(np.zeros(1024, np.uint8))
        assert False, "budget must still apply to budgeted segments"
    except MemoryError:
        pass
    arena.release(seg.mkey)
    assert arena.stats()["file_bytes"] == 0


def test_mapped_file_empty_input(tmp_path):
    # advisor finding: a zero-byte chunk stream must still map (the
    # segment serves only EMPTY locations, but construction can't raise)
    from sparkrdma_tpu.memory.mapped_file import MappedFile

    mf = MappedFile([], directory=str(tmp_path))
    try:
        assert mf.array.shape == (1,)
        assert mf.array[0] == 0
    finally:
        mf.free()
    assert not list(tmp_path.iterdir()), "file must be unlinked on free"


def test_mapped_file_direct_write_parity(tmp_path):
    """The O_DIRECT commit write path must produce byte-identical
    files to the buffered path across chunk shapes (odd sizes around
    the 4096 alignment, empty chunks, >1 MiB chunks that span bounce
    buffers) — readers mmap the result either way."""
    import numpy as np

    from sparkrdma_tpu.memory.mapped_file import MappedFile

    rng = np.random.default_rng(5)
    sizes = [0, 1, 4095, 4096, 4097, 1 << 20, (1 << 20) + 13, 3]
    chunks = [rng.bytes(s) for s in sizes]
    mfs = {}
    for direct in (True, False):
        mfs[direct] = MappedFile(
            list(chunks), directory=str(tmp_path), direct_write=direct
        )
    try:
        a, b = mfs[True].array, mfs[False].array
        assert a.nbytes == b.nbytes == sum(sizes)
        assert a.tobytes() == b.tobytes() == b"".join(chunks)
    finally:
        for mf in mfs.values():
            mf.free()
    assert not list(tmp_path.iterdir()), "files must be unlinked on free"


def test_alloc_gc_returns_on_collection():
    """alloc_gc ties pool release to GC of the view and its slices
    (the BufferReleasingInputStream analog)."""
    import gc

    from sparkrdma_tpu.memory.staging import StagingPool

    for force_python in (True, False):
        pool = StagingPool(1 << 22, force_python=force_python)
        arr = pool.alloc_gc(100_000)
        arr[:4] = [1, 2, 3, 4]
        sl = arr[:4].copy()  # consumer data survives buffer release
        view = arr[1:3]  # a consumer slice keeps the buffer alive
        before = pool.stats()
        assert before["in_use"] > 0
        del arr
        gc.collect()
        # slice still alive -> buffer must NOT have returned
        assert pool.stats()["in_use"] == before["in_use"]
        assert bytes(view) == b"\x02\x03"
        del view
        gc.collect()
        after = pool.stats()
        assert after["in_use"] == 0, (force_python, after)
        assert bytes(sl) == b"\x01\x02\x03\x04"
        pool.close()


def test_alloc_gc_native_reuses_block():
    from sparkrdma_tpu.memory.staging import StagingPool

    import gc

    pool = StagingPool(1 << 22)
    if not pool.is_native:
        pool.close()
        import pytest

        pytest.skip("native staging allocator not built")
    a = pool.alloc_gc(50_000)
    addr_a = a.ctypes.data
    del a
    gc.collect()
    b = pool.alloc_gc(50_000)
    assert b.ctypes.data == addr_a, "freed block must be reused"
    del b
    gc.collect()
    pool.close()


def test_alloc_gc_close_with_outstanding_views_is_safe():
    import gc

    from sparkrdma_tpu.memory.staging import StagingPool

    pool = StagingPool(1 << 22)
    arr = pool.alloc_gc(10_000)
    arr[:3] = [9, 8, 7]
    pool.close()
    # view stays readable after close (leak, not use-after-free)
    assert bytes(arr[:3]) == b"\x09\x08\x07"
    del arr
    gc.collect()


def test_read_spans_clustered_skips_large_gaps():
    """Sparse batches must not materialize the gap between far-apart
    blocks (code-review finding): clusters split above the gap cap."""
    from sparkrdma_tpu.memory.arena import (
        READ_MANY_MAX_GAP,
        _read_spans_clustered,
    )

    fetched = []
    backing = bytes(range(256)) * 4  # 1 KiB pattern

    def fetch(lo, hi):
        fetched.append((lo, hi))
        # synthesize content: offset modulo pattern
        return bytes((i % 251 for i in range(lo, hi)))

    far = READ_MANY_MAX_GAP * 3
    spans = [(far + 100, 50), (0, 10), (far + 500, 20), (40, 5)]
    out = _read_spans_clustered(spans, fetch)
    assert len(fetched) == 2, fetched  # two clusters, gap skipped
    total = sum(hi - lo for lo, hi in fetched)
    assert total < READ_MANY_MAX_GAP, "gap was materialized"
    for (o, ln), b in zip(spans, out):
        assert b == bytes((i % 251 for i in range(o, o + ln)))
    assert _read_spans_clustered([], fetch) == []


def test_native_hash_partition_order_matches_numpy():
    """The fused native kernel must agree BIT-EXACTLY with the numpy
    reference (partition_array + stable composite order) across skew,
    negatives, and partition counts — cross-plane routing depends on
    it."""
    import numpy as np

    from sparkrdma_tpu.memory.staging import native_hash_partition_order
    from sparkrdma_tpu.shuffle.partitioner import HashPartitioner
    from sparkrdma_tpu.utils.columns import stable_key_order

    rng = np.random.default_rng(7)
    for trial in range(20):
        n = int(rng.integers(1, 5000))
        P = int(rng.choice([1, 2, 3, 7, 8, 64]))
        kind = trial % 3
        if kind == 0:
            keys = rng.integers(-50, 50, n).astype(np.int64)
        elif kind == 1:
            keys = rng.integers(0, 3, n).astype(np.int64)  # heavy skew
        else:
            keys = rng.zipf(1.5, n).clip(0, 500).astype(np.int64)
        kmin = int(keys.min())
        krange = int(keys.max()) - kmin + 1
        if krange * P > (1 << 16):
            continue
        got = native_hash_partition_order(keys, P, kmin, krange)
        if got is None:  # native lib absent: numpy fallback covers it
            import pytest

            pytest.skip("native staging lib not built")
        order, counts = got
        part = HashPartitioner(P)
        pids = part.partition_array(keys)
        korder = stable_key_order(keys)
        porder = stable_key_order(pids[korder])
        ref_order = korder[porder]
        ref_counts = np.bincount(pids, minlength=P).astype(np.int64)
        assert np.array_equal(counts, ref_counts), (trial, n, P)
        assert np.array_equal(order, ref_order), (trial, n, P)


def test_native_merge_runs_groups_matches_python_merge():
    """The fused streaming group-merge must agree with the per-key
    Python merge (merge_sorted_groups) as a mapping: same key set,
    and per key the same value bytes in the same (run-major) order —
    the read side's groupByKey correctness rests on it."""
    import numpy as np

    from sparkrdma_tpu.memory.staging import native_merge_runs_groups
    from sparkrdma_tpu.utils.columns import (
        ColumnBatch,
        group_columns,
        merge_sorted_groups,
    )

    rng = np.random.default_rng(11)
    ran = 0
    for trial in range(120):
        nruns = int(rng.integers(1, 6))
        itemsize = int(rng.choice([8, 16, 64]))
        batches, per = [], []
        for _ in range(nruns):
            n = int(rng.integers(0, 60))
            ks = np.sort(rng.integers(-5, 15, n)).astype(np.int64)
            vs = np.frombuffer(rng.bytes(n * itemsize), dtype=f"S{itemsize}")
            b = ColumnBatch(ks, vs, key_sorted=True)
            if n:
                batches.append(b)
                per.append(group_columns(b))
        res = native_merge_runs_groups(
            [b.keys for b in batches], [b.vals for b in batches]
        )
        ref = {k: v for k, v in merge_sorted_groups(per)}
        if res is None:
            if batches:
                import pytest

                pytest.skip("native staging lib not built")
            assert not ref
            continue
        ran += 1
        uk, mv, offs = res
        assert list(uk) == sorted(ref), trial
        # offsets partition the merged values exactly
        assert offs[0] == 0 and offs[-1] == len(mv)
        for i, k in enumerate(uk.tolist()):
            got = mv[offs[i]:offs[i + 1]]
            assert got.tobytes() == ref[k].tobytes(), (trial, k)
    assert ran > 50  # the fuzz actually exercised the kernel


def test_native_radix_argsort_matches_numpy_stable():
    import numpy as np

    from sparkrdma_tpu.memory.staging import native_radix_argsort

    rng = np.random.default_rng(3)
    cases = [
        rng.integers(-(1 << 62), 1 << 62, 100_000).astype(np.int64),
        rng.integers(-5, 5, 50_000).astype(np.int64),  # heavy ties
        np.zeros(1000, np.int64),
        np.array([np.iinfo(np.int64).min, np.iinfo(np.int64).max, 0, -1, 1],
                 np.int64),
        np.arange(70_000, dtype=np.int64)[::-1].copy(),
    ]
    for keys in cases:
        got = native_radix_argsort(keys)
        if got is None:
            import pytest

            pytest.skip("native staging lib not built")
        ref = np.argsort(keys, kind="stable")
        assert np.array_equal(got, ref), keys[:8]


# -- O_DIRECT spill/commit path (memory/direct_io.py, round 4) ---------------

def test_direct_appender_roundtrip(tmp_path):
    """Appends of every alignment shape land byte-exact; the file is
    trimmed to the logical size."""
    import os
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from sparkrdma_tpu.memory.direct_io import DirectAppender

    rng = np.random.default_rng(0)
    for use_direct in (True, False):
        with ThreadPoolExecutor(1) as ex:
            app = DirectAppender(
                str(tmp_path / f"f_{use_direct}"), use_direct=use_direct,
                buf_bytes=1 << 14, executor=ex,
            )
            chunks = [
                rng.bytes(n)
                for n in (1, 4095, 4096, 40000, 13, 0, 16384, 99999)
            ]
            offs = [app.append(c) for c in chunks]
            size = app.finish()
        assert size == sum(len(c) for c in chunks)
        assert os.path.getsize(app.path) == size
        data = open(app.path, "rb").read()
        pos = 0
        for c, (off, n) in zip(chunks, offs):
            assert off == pos and data[off : off + n] == c
            pos += n
        os.unlink(app.path)


def test_direct_appender_numpy_views(tmp_path):
    """Column views (any dtype) append without an intermediate bytes
    join — the spill streaming contract."""
    import numpy as np

    from sparkrdma_tpu.memory.direct_io import DirectAppender

    app = DirectAppender(str(tmp_path / "cols"), use_direct=True)
    keys = np.arange(10000, dtype=np.int64)
    vals = np.frombuffer(
        np.random.default_rng(1).bytes(10000 * 24), dtype="V24"
    )
    app.append(b"hdr")
    app.append(keys.view(np.uint8))
    app.append(vals.view(np.uint8).reshape(-1))
    size = app.finish()
    data = open(app.path, "rb").read()
    assert size == 3 + keys.nbytes + vals.nbytes
    assert data[:3] == b"hdr"
    assert data[3 : 3 + keys.nbytes] == keys.tobytes()
    assert data[3 + keys.nbytes :] == vals.tobytes()


def test_mapped_file_pread_matches_mmap(tmp_path):
    """O_DIRECT pread serves exactly the mmap view's bytes for every
    alignment of offset and length."""
    import numpy as np

    from sparkrdma_tpu.memory.mapped_file import MappedFile

    payload = np.random.default_rng(2).bytes(300_000)
    mf = MappedFile(payload, directory=str(tmp_path))
    try:
        for off, n in [(0, 300_000), (1, 5000), (4096, 4096),
                       (4095, 2), (123, 299_000), (299_999, 1)]:
            got = mf.pread(off, n)
            if got is None:  # O_DIRECT unsupported here: fallback ok
                continue
            assert bytes(got) == payload[off : off + n], (off, n)
            assert not got.flags.writeable
    finally:
        mf.free()


def test_commit_spilled_files_zero_copy(tmp_path, devices):
    """Per-partition spill files register AS the shuffle files: blocks
    read back exactly, empty/zero-length partitions come back empty,
    and every file is unlinked when the shuffle unregisters."""
    import glob
    import os

    import numpy as np

    from sparkrdma_tpu.conf import TpuShuffleConf
    from sparkrdma_tpu.memory.direct_io import DirectAppender
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.transport import LoopbackNetwork

    conf = TpuShuffleConf({"spark.shuffle.tpu.spillDir": str(tmp_path)})
    mgr = TpuShuffleManager(
        conf, is_driver=True, network=LoopbackNetwork(),
        stage_to_device=False,
    )
    try:
        payloads = {0: b"a" * 100_000, 2: b"xyz" * 33}
        entries = []
        for pid in range(4):
            if pid == 3:
                entries.append(None)
                continue
            app = DirectAppender(str(tmp_path / f"p{pid}"))
            if pid in payloads:
                app.append(payloads[pid])
            n = app.finish()
            entries.append((app.path, n))
        mto = mgr.resolver.commit_spilled_files(7, 0, entries)
        assert mto.get_location(1).is_empty  # zero-length file
        assert mto.get_location(3).is_empty  # None entry
        assert not os.path.exists(str(tmp_path / "p1")), (
            "zero-length spill file not unlinked"
        )
        for pid, want in payloads.items():
            got = mgr.resolver.get_local_block(7, 0, pid)
            assert bytes(got) == want
        mgr.resolver.remove_shuffle(7)
        assert not glob.glob(str(tmp_path / "p*")), "files leaked"
    finally:
        mgr.stop()


# -- ISSUE 17 hot-path kernels: frame walk / CRC batch / gather -------------


def test_native_frame_spans_matches_python_walkers(monkeypatch):
    """The native frame walkers must agree span-for-span with the
    serde Python loops on REAL serialized payloads, and the serializers
    must return the identical answer with the native hook disabled
    (the pure-Python fallback path, tested both ways)."""
    from sparkrdma_tpu.memory import staging
    from sparkrdma_tpu.utils.columns import ColumnBatch
    from sparkrdma_tpu.utils.serde import (
        ColumnarSerializer,
        CompressedSerializer,
        PickleSerializer,
    )

    if staging._NATIVE is None:
        pytest.skip("native staging lib not built")
    rng = np.random.default_rng(3)
    pick = PickleSerializer(batch_size=16)
    comp = CompressedSerializer(PickleSerializer(batch_size=16))
    col = ColumnarSerializer()
    payloads = []
    for n in (0, 1, 15, 16, 17, 300):
        records = [(int(k), bytes(rng.bytes(8))) for k in range(n)]
        payloads.append((pick, pick.serialize(records)))
        payloads.append((comp, comp.serialize(records)))
        if n:
            batch = ColumnBatch(
                rng.integers(0, 99, n).astype(np.int64),
                np.frombuffer(rng.bytes(n * 16), dtype="S16"),
            )
            payloads.append((col, col.serialize(batch)))
    for ser, blob in payloads:
        native = ser.frame_spans(blob)
        with monkeypatch.context() as m:
            m.setattr(staging, "native_frame_spans",
                      lambda *a, **k: None)
            m.setattr(staging, "native_columnar_frame_spans",
                      lambda *a, **k: None)
            python = ser.frame_spans(blob)
        assert native == python, type(ser).__name__
        if blob:
            assert native, type(ser).__name__


def test_native_frame_spans_rejects_garbage():
    """Truncated/garbage buffers must come back None (negative native
    rc) so the Python walker stays the authority for error text."""
    from sparkrdma_tpu.memory import staging

    if staging._NATIVE is None:
        pytest.skip("native staging lib not built")
    # truncated: header promises more bytes than the buffer holds
    bad = (1000).to_bytes(4, "little") + b"xy"
    assert staging.native_frame_spans(bad, 0) is None
    assert staging.native_columnar_frame_spans(b"\xc2" + b"\x00" * 3) is None
    # empty payloads walk to zero spans, not None
    assert staging.native_frame_spans(b"", 0).shape == (0, 2)


def test_native_crc32_spans_bit_exact_and_bounds_checked():
    import zlib

    from sparkrdma_tpu.memory import staging

    if staging._NATIVE is None or not hasattr(staging._NATIVE,
                                              "crc32_spans"):
        pytest.skip("native staging lib not built")
    rng = np.random.default_rng(5)
    buf = rng.bytes(100_000)
    view = memoryview(buf)
    for trial in range(30):
        n = int(rng.integers(1, 200))
        a = rng.integers(0, len(buf) - 1, n)
        b = a + rng.integers(0, 4096, n)
        spans = np.stack([a, np.minimum(b, len(buf))], axis=1)
        got = staging.native_crc32_spans(buf, spans)
        assert got is not None
        want = [zlib.crc32(view[x:y]) for x, y in spans.tolist()]
        assert got.tolist() == want, trial
    # bounds violations and shape mismatches fall back (None)
    assert staging.native_crc32_spans(buf, [(0, len(buf) + 1)]) is None
    assert staging.native_crc32_spans(buf, [(-1, 4)]) is None
    assert staging.native_crc32_spans(buf, [(8, 4)]) is None
    assert staging.native_crc32_spans(buf, [(1, 2, 3)]) is None
    assert staging.native_crc32_spans(buf, np.empty((0, 2), np.int64)) \
        .shape == (0,)


def test_native_gather_blocks_matches_slice_assignment():
    from sparkrdma_tpu.memory import staging

    if staging._NATIVE is None or not hasattr(staging._NATIVE,
                                              "gather_blocks"):
        pytest.skip("native staging lib not built")
    rng = np.random.default_rng(9)
    for trial in range(20):
        n_blocks = int(rng.integers(0, 60))
        srcs = [
            np.frombuffer(rng.bytes(int(rng.integers(1, 2000))), np.uint8)
            for _ in range(n_blocks)
        ]
        lens = [len(s) for s in srcs]
        offs, acc = [], 0
        for ln in lens:
            offs.append(acc)
            acc += ln
        want = np.empty(acc, np.uint8)
        for s, off, ln in zip(srcs, offs, lens):
            want[off:off + ln] = s
        got = np.zeros(acc, np.uint8)
        ok = staging.native_gather_blocks(
            got, [int(s.ctypes.data) for s in srcs], lens, offs)
        assert ok
        assert np.array_equal(got, want), trial
    # ineligible shapes refuse (caller keeps the numpy loop)
    dst = np.zeros(16, np.uint8)
    src = np.arange(8, dtype=np.uint8)
    addr = int(src.ctypes.data)
    assert not staging.native_gather_blocks(dst, [addr], [8], [9])  # overrun
    assert not staging.native_gather_blocks(dst, [addr], [-1], [0])
    assert not staging.native_gather_blocks(dst, [addr], [8], [0, 8])
    assert not staging.native_gather_blocks(
        np.zeros((4, 4), np.uint8), [addr], [8], [0])
