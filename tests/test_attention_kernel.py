"""Pallas blockwise-attention kernel vs the XLA reference, and ring
attention end-to-end through both implementations."""

import numpy as np
import pytest

import jax.numpy as jnp

from sparkrdma_tpu.models.ring_attention import ring_attention
from sparkrdma_tpu.ops.attention import block_attention
from sparkrdma_tpu.parallel import make_mesh


def reference_attention(q, k, v, causal=False):
    s = (q.astype(np.float64) @ k.astype(np.float64).T) / np.sqrt(q.shape[-1])
    if causal:
        mask = np.tril(np.ones(s.shape, bool))
        s = np.where(mask, s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return p @ v.astype(np.float64)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_matches_xla_block(causal, devices):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((64, 64), dtype=np.float32))
    k = jnp.asarray(rng.standard_normal((96, 64), dtype=np.float32))
    v = jnp.asarray(rng.standard_normal((96, 64), dtype=np.float32))
    args = dict(q_offset=32, k_offset=0, causal=causal)
    mx, lx, ox = block_attention(q, k, v, impl="xla", **args)
    mp, lp, op = block_attention(
        q, k, v, impl="pallas", block_q=32, block_k=32, **args
    )
    np.testing.assert_allclose(np.asarray(mx), np.asarray(mp), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(lx), np.asarray(lp), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ox), np.asarray(op),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact(impl, causal, devices):
    mesh = make_mesh(8)
    rng = np.random.default_rng(1)
    S, d = 8 * 32, 64
    q = rng.standard_normal((S, d), dtype=np.float32)
    k = rng.standard_normal((S, d), dtype=np.float32)
    v = rng.standard_normal((S, d), dtype=np.float32)
    out = ring_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        mesh=mesh, causal=causal, impl=impl,
    )
    expected = reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), expected,
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ring_attention_bfloat16(impl, devices):
    # bf16 inputs ride the MXU's native path (no f32 up-cast in the
    # kernel); accumulate in f32, so accuracy stays bf16-input-bounded
    mesh = make_mesh(8)
    rng = np.random.default_rng(2)
    S, d = 8 * 32, 64
    q = rng.standard_normal((S, d), dtype=np.float32)
    k = rng.standard_normal((S, d), dtype=np.float32)
    v = rng.standard_normal((S, d), dtype=np.float32)
    qb = jnp.asarray(q, jnp.bfloat16)
    kb = jnp.asarray(k, jnp.bfloat16)
    vb = jnp.asarray(v, jnp.bfloat16)
    out = ring_attention(qb, kb, vb, mesh=mesh, causal=True, impl=impl)
    assert out.dtype == jnp.bfloat16
    expected = reference_attention(
        np.asarray(qb, np.float32), np.asarray(kb, np.float32),
        np.asarray(vb, np.float32), causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), expected, rtol=0.06, atol=0.06
    )
